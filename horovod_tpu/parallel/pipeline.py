"""Pipeline parallelism — schedule-driven microbatch pipelining over 'pp'.

No reference equivalent (SURVEY.md §2.1: PP absent). TPU-first design: each
schedule is ONE jitted SPMD program. Every 'pp' rank holds the parameters
of its stage (or, interleaved, of V non-contiguous stage chunks);
activations and cotangents move between neighboring ranks with ``ppermute``
(collective-permute rides ICI), and XLA overlaps the permute with the stage
compute exactly as the original forward-only scan did.

Three schedules (docs/pipeline.md; the exemplar is "Scaling Deep Learning
Training with MPMD Pipeline Parallelism", arXiv 2412.14374, recast onto the
single-SPMD-program collective-permute pattern):

``gpipe``
    The baseline. Forward sweep (skewed ``lax.scan``, ``m + n - 1`` ticks)
    stashes only the per-microbatch stage INPUT — O(m) small activations —
    then a backward sweep re-linearizes each stage from the stash
    (recompute, the GPipe paper's rematerialization design) and flows
    cotangents last→first. Static tick budget:
    ``(m+n-1)·cF + (m+n-1)·(cF+cB)``.

``1f1b``
    One-forward-one-backward. Three scans — warmup (forward-only ticks),
    steady state (one F and one B per tick), drain (backward-only) — so the
    in-flight window is O(n) microbatches, which makes it affordable to
    stash the stage's VJP RESIDUALS in a ring buffer instead of
    recomputing: budget ``(m+n-1)·(cF+cB)``, strictly below gpipe's. The
    ring holds ``2n - 1`` slots (the maximum ticks between a microbatch's
    F and its B on any stage).

``interleaved``
    Virtual stages: each rank holds V non-contiguous chunks (chunk-stage
    ``c = v·n + r`` lives on rank ``r = c mod n``), a microbatch loops the
    rank ring V times, and each tick moves one CHUNK (cost/V). The fill
    skew stays ``n - 1`` chunk-ticks while the useful work per rank grows
    to ``m·V`` chunk computes: budget ``(mV+n-1)·(cF+cB)/V``, bubble
    ``(n-1)/(mV+n-1)`` — the gpipe/1f1b bubble shrunk by ~1/V.

``zb-h1``
    Zero-bubble H1 (the ZB-H1 point of arXiv 2412.14374): the backward is
    SPLIT into an input-grad tick (Bx, cost cBx) that unblocks the
    upstream stage immediately, and a weight-grad tick (W, cost cBw) that
    has no inter-stage dependency and is pushed into what would otherwise
    be drain bubble. F and Bx keep the 1f1b tiling (``F_j`` at
    ``j + idx``, ``Bx_j`` at ``j + 2n - 2 - idx``); ``W_j`` runs at the
    UNIFORM tick ``2n - 2 + j`` on every rank — by then rank ``idx``'s
    cotangent for microbatch ``j`` arrived at its Bx tick
    ``2n - 2 + j - idx ≤ 2n - 2 + j``, so no W slot is ever masked.
    Budget ``(m+n-1)·(cF+cBx) + m·cBw`` with bubble cost
    ``(n-1)·(cF+cBx)`` — strictly below 1f1b's ``(n-1)·(cF+cB)`` because
    only the input-grad half of the backward stays on the critical fill
    path. Needs ``m >= n`` (W ticks start only once every rank is in
    steady state). The cotangents awaiting their W tick live in an
    ``n``-slot ring keyed ``j mod n`` next to the usual ``2n - 1``-slot
    residual ring.

Bubble accounting is STATIC (``PipelineSchedule.bubble_share``): every tick
of the scan costs real wall time on every rank (masked computes are wasted
work, not idle time, in SPMD), so the bubble share is the exact fraction of
the schedule's compute-cost budget not spent on useful microbatch work. It
feeds the ``hvdtpu_pipeline_bubble_share`` gauge and BENCH_PIPELINE.json.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

SCHEDULES = ("gpipe", "1f1b", "interleaved", "zb-h1")


# ---------------------------------------------------------------------------
# Static schedule accounting
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PipelineSchedule:
    """Static tick/cost budget of one pipelined step.

    Costs are in forward-compute units per FULL stage (``cost_fwd`` for a
    stage forward, ``cost_bwd`` for a stage backward — the conventional
    backward:forward ratio is 2). Interleaved ticks move one chunk, i.e.
    1/V of a stage, and are costed accordingly. ``bubble_share`` is
    ``1 - useful_cost / total_cost`` — the fraction of the program's
    compute budget spent on masked (bubble) work, including gpipe's
    backward recompute."""

    name: str
    num_stages: int
    num_microbatches: int
    num_virtual: int = 1
    cost_fwd: float = 1.0
    cost_bwd: float = 2.0

    @property
    def ticks(self) -> dict:
        """Scan trip counts per phase. gpipe phases are its two sweeps
        (warmup = forward sweep, steady = 0, drain = backward sweep);
        1f1b/interleaved are warmup/steady/drain of the fused schedule;
        zb-h1's steady merges its F+Bx and F+Bx+W spans (m ticks) and
        its drain is the Bx+W tail."""
        n, m, v = self.num_stages, self.num_microbatches, self.num_virtual
        if self.name == "gpipe":
            return {"warmup": m + n - 1, "steady": 0, "drain": m + n - 1}
        if self.name == "zb-h1":
            return {"warmup": n - 1, "steady": m, "drain": n - 1}
        warmup = n * v - 1
        steady = (m - n) * v + n
        drain = n * v - 1
        return {"warmup": warmup, "steady": steady, "drain": drain}

    @property
    def total_cost(self) -> float:
        n, m, v = self.num_stages, self.num_microbatches, self.num_virtual
        cf, cb = self.cost_fwd, self.cost_bwd
        if self.name == "gpipe":
            # Forward sweep at cF a tick; backward sweep re-linearizes
            # from the activation stash (recompute), cF + cB a tick.
            return (m + n - 1) * cf + (m + n - 1) * (cf + cb)
        if self.name == "zb-h1":
            # Backward split cB = cBx + cBw (even halves by convention):
            # only cBx rides the fill/drain skew, cBw fills the bubble.
            cbx = cbw = cb / 2.0
            return (m + n - 1) * (cf + cbx) + m * cbw
        t = self.ticks
        per = 1.0 / v
        return (t["warmup"] * cf * per + t["steady"] * (cf + cb) * per
                + t["drain"] * cb * per)

    @property
    def useful_cost(self) -> float:
        return self.num_microbatches * (self.cost_fwd + self.cost_bwd)

    @property
    def bubble_share(self) -> float:
        return 1.0 - self.useful_cost / self.total_cost


def schedule_info(schedule: str, num_stages: int, num_microbatches: int,
                  *, num_virtual: int = 1, cost_fwd: float = 1.0,
                  cost_bwd: float = 2.0) -> PipelineSchedule:
    """Static budget of a pipelined step — the numbers behind the
    ``hvdtpu_pipeline_bubble_share`` gauge and ``bench_engine.py
    --pipeline`` (docs/pipeline.md)."""
    if schedule not in SCHEDULES:
        raise ValueError(f"unknown pipeline schedule {schedule!r}; "
                         f"expected one of {SCHEDULES}")
    v = num_virtual if schedule == "interleaved" else 1
    _validate(schedule, num_stages, num_microbatches, v)
    return PipelineSchedule(schedule, num_stages, num_microbatches, v,
                            cost_fwd, cost_bwd)


def _validate(schedule: str, n: int, m: int, v: int) -> None:
    if m < 1:
        raise ValueError("need at least one microbatch")
    if v < 1:
        raise ValueError("num_virtual must be >= 1")
    if schedule == "interleaved":
        if v < 2:
            raise ValueError("interleaved needs num_virtual >= 2 "
                             "(num_virtual=1 IS the 1f1b schedule)")
        if m < n or m % n:
            raise ValueError(
                f"interleaved needs num_microbatches ({m}) to be a "
                f"multiple of the stage count ({n}) at least as large "
                "as it — the circular schedule streams microbatches in "
                "rounds of one per stage")
    if schedule == "zb-h1" and m < n:
        raise ValueError(
            f"zb-h1 needs num_microbatches ({m}) >= num_stages ({n}): "
            "the uniform weight-grad tick W_j = 2n-2+j assumes every "
            "rank reached steady state before the first W fires")


# ---------------------------------------------------------------------------
# Observability (docs/metrics.md + the flight recorder, docs/postmortem.md)
# ---------------------------------------------------------------------------


class _PipelineMetrics:
    _instance = None

    def __init__(self):
        from ..observability import registry as _obs
        r = _obs.registry()
        self.bubble = r.gauge(
            "hvdtpu_pipeline_bubble_share",
            "Static bubble share of the most recently built pipeline "
            "program per schedule: the fraction of the schedule's "
            "compute-cost budget spent on masked (non-microbatch) work, "
            "from the tick budget — compare against the measured step "
            "phases to see how much of a comm-bound verdict is schedule "
            "bubble (docs/pipeline.md)")
        self.ticks = r.gauge(
            "hvdtpu_pipeline_ticks",
            "Scan trip counts of the most recently built pipeline "
            "program, by schedule and phase (warmup/steady/drain)")

    @classmethod
    def get(cls) -> "_PipelineMetrics":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance


def _record_schedule(sched: PipelineSchedule) -> None:
    """Trace-time (python-side) bookkeeping for a freshly built pipeline
    program: the static-bubble gauge plus a flight-recorder event so a
    post-mortem can attribute a death phase inside a pipelined step
    (tools/postmortem)."""
    try:
        metrics = _PipelineMetrics.get()
        metrics.bubble.labels(schedule=sched.name).set(
            round(sched.bubble_share, 6))
        for phase, count in sched.ticks.items():
            metrics.ticks.labels(schedule=sched.name, phase=phase).set(
                float(count))
        from ..observability import flight_recorder as _fr
        _fr.recorder().note("pipeline", (
            sched.name, sched.num_stages, sched.num_microbatches,
            sched.num_virtual, sched.ticks["warmup"],
            sched.ticks["steady"], sched.ticks["drain"],
            round(sched.bubble_share, 6)))
    except Exception:  # pragma: no cover — telemetry must never break jit
        pass


# ---------------------------------------------------------------------------
# Forward-only pipeline (the seed API, kept)
# ---------------------------------------------------------------------------


def pipeline_apply(stage_fn: Callable, params, x_microbatches, *,
                   axis_name: str = "pp",
                   replicate_output: str = "relay"):
    """Run a pipelined forward pass inside shard_map.

    Args:
      stage_fn: ``stage_fn(params, x) -> y`` with ``y.shape == x.shape`` —
        one stage's computation (e.g. a group of transformer blocks); every
        'pp' rank runs it with its own stage's params.
      params: this rank's stage parameters (pytree).
      x_microbatches: [num_micro, micro_batch, ...] input, meaningful on
        stage 0 (other ranks' copies are ignored).
      replicate_output: how the last stage's outputs reach every rank.
        ``"relay"`` (default) rides each finished microbatch around the
        ring ONE HOP PER TICK on a second ppermute channel overlapped
        with the remaining compute (plus an ``n - 1``-tick permute-only
        drain) — each output crosses each link exactly once.
        ``"psum"`` is the original path: a full ``[m, ...]``-buffer
        allreduce of the masked outputs after the scan (~2x the wire
        bytes, one extra unoverlapped collective), kept for comparison.

    Returns: [num_micro, micro_batch, ...] outputs of the LAST stage,
      replicated to all 'pp' ranks.
    """
    if replicate_output not in ("relay", "psum"):
        raise ValueError("replicate_output must be 'relay' or 'psum'")
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = x_microbatches.shape[0]
    ticks = m + n - 1

    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    state0 = jnp.zeros_like(x_microbatches[0])
    outs0 = jnp.zeros_like(x_microbatches)

    def compute(state, t):
        """One pipeline tick: feed/consume, run the stage, hand off."""
        mb_idx = jnp.clip(t, 0, m - 1)
        fed = jnp.where(t < m, x_microbatches[mb_idx],
                        jnp.zeros_like(state0))
        inp = jnp.where(idx == 0, fed, state)
        y = stage_fn(params, inp)
        return y

    if replicate_output == "psum":
        def tick(carry, t):
            state, outs = carry
            y = compute(state, t)
            out_idx = t - (n - 1)
            record = jnp.logical_and(out_idx >= 0, idx == n - 1)
            safe_idx = jnp.clip(out_idx, 0, m - 1)
            outs = jnp.where(
                record,
                outs.at[safe_idx].set(y.astype(outs.dtype)),
                outs)
            state = lax.ppermute(y, axis_name, fwd_perm)
            return (state, outs), None

        (_, outs), _ = lax.scan(tick, (state0, outs0), jnp.arange(ticks))
        # Replicate the last stage's outputs to every 'pp' rank.
        return lax.psum(
            jnp.where(idx == n - 1, outs, jnp.zeros_like(outs)), axis_name)

    # "relay": a second ppermute channel carries finished outputs around
    # the ring n-1 → 0 → 1 → ... → n-2, one hop per tick. The last stage
    # records its own y at compute time and originates the relay; every
    # other rank records the value arriving at tick t as microbatch
    # t - n - idx and forwards it unchanged (masked select).
    def relay_record(outs, relay, t):
        j_in = t - n - idx
        rec_in = jnp.logical_and(
            jnp.logical_and(j_in >= 0, j_in < m), idx != n - 1)
        jc = jnp.clip(j_in, 0, m - 1)
        val = jnp.where(rec_in, relay.astype(outs.dtype), outs[jc])
        return lax.dynamic_update_index_in_dim(outs, val, jc, 0)

    def tick(carry, t):
        state, relay, outs = carry
        outs = relay_record(outs, relay, t)
        y = compute(state, t)
        out_idx = t - (n - 1)
        own = jnp.logical_and(out_idx >= 0, idx == n - 1)
        oc = jnp.clip(out_idx, 0, m - 1)
        val = jnp.where(own, y.astype(outs.dtype), outs[oc])
        outs = lax.dynamic_update_index_in_dim(outs, val, oc, 0)
        # Originate at the last stage, forward everywhere else.
        relay = lax.ppermute(jnp.where(idx == n - 1, y, relay),
                             axis_name, fwd_perm)
        state = lax.ppermute(y, axis_name, fwd_perm)
        return (state, relay, outs), None

    def drain_tick(carry, t):
        relay, outs = carry
        outs = relay_record(outs, relay, t)
        relay = lax.ppermute(relay, axis_name, fwd_perm)
        return (relay, outs), None

    relay0 = jnp.zeros_like(state0)
    (state, relay, outs), _ = lax.scan(
        tick, (state0, relay0, outs0), jnp.arange(ticks))
    if n > 1:
        (_, outs), _ = lax.scan(drain_tick, (relay, outs),
                                jnp.arange(ticks, ticks + n - 1))
    return outs


# ---------------------------------------------------------------------------
# Training schedules: loss + gradients in one SPMD program
# ---------------------------------------------------------------------------


def _tree_add(a, b):
    return jax.tree_util.tree_map(lambda x, y: x + y, a, b)


def _pipeline_result(loss, grads, lp_grads, xg, axis_name, want_lp,
                     want_xg):
    """Assemble the (loss, grads[, extras]) return: extras appear only
    when asked for, so the legacy 2-tuple contract is untouched."""
    if not (want_lp or want_xg):
        return loss, grads
    extras = {}
    if want_lp:
        extras["loss_params_grads"] = lp_grads
    if want_xg:
        # Only stage 0 wrote its slots; everyone else holds zeros.
        extras["input_grads"] = lax.psum(xg, axis_name)
    return loss, grads, extras


def _vjp_template(stage_fn, params, x0):
    """Residual-stash plumbing: capture the TREEDEF and leaf avals of
    ``jax.vjp(stage_fn, params, x)`` via ``eval_shape`` (no FLOPs
    staged). The treedef embeds the pullback jaxpr — rebuilt later with
    leaves read from a ring buffer, it runs the stage backward from
    stashed residuals without recomputing the forward. Structure and
    shapes are identical across ticks because stage_fn and the
    activation shape are fixed."""
    _, vjp_aval = jax.eval_shape(
        lambda p, x: jax.vjp(stage_fn, p, x), params, x0)
    leaves, treedef = jax.tree_util.tree_flatten(vjp_aval)
    return leaves, treedef


def _make_loss_caller(loss_fn, loss_aux):
    """Normalize the loss call across the aux/params variants: returns
    ``call(lp, y, jc) -> scalar`` where ``lp`` (trainable loss params)
    may be None and ``jc`` indexes the microbatch axis of ``loss_aux``
    (per-microbatch targets), when given."""
    def call(lp, y, jc):
        args = [] if lp is None else [lp]
        args.append(y)
        if loss_aux is not None:
            args.append(jax.tree_util.tree_map(
                lambda l: lax.dynamic_index_in_dim(l, jc, 0,
                                                   keepdims=False),
                loss_aux))
        return loss_fn(*args)
    return call


def pipeline_value_and_grad(stage_fn: Callable, loss_fn: Callable, params,
                            x_microbatches, *, axis_name: str = "pp",
                            schedule: str = "1f1b",
                            num_virtual: int = 1,
                            cost_backward: float = 2.0,
                            loss_aux=None, loss_params=None,
                            return_input_grads: bool = False):
    """Pipelined loss AND stage-parameter gradients inside shard_map.

    The pipelined model is the composition of every rank's
    ``stage_fn(params, x)`` along the 'pp' ring (interleaved: of all
    ``n·V`` chunk applications in chunk-stage order ``c = v·n + r``);
    the total loss is ``mean_j loss_fn(y_j)`` over the ``m``
    microbatches' last-stage outputs.

    Args:
      stage_fn: ``stage_fn(params, x) -> y``, ``y.shape == x.shape``.
      loss_fn: ``loss_fn(y) -> scalar`` per microbatch output. With
        ``loss_params`` the signature becomes ``loss_fn(lp, y)``; with
        ``loss_aux`` the microbatch's aux slice is appended as the last
        positional arg.
      params: this rank's stage parameters. For ``interleaved``, a pytree
        whose leaves carry a leading ``num_virtual`` axis — chunk slot
        ``v`` on rank ``r`` is chunk-stage ``v·n + r``.
      x_microbatches: [num_micro, micro_batch, ...], read on stage 0.
      schedule: ``"gpipe"`` | ``"1f1b"`` | ``"interleaved"`` | ``"zb-h1"``
        (docs/pipeline.md: memory/bubble tradeoffs).
      num_virtual: chunk count V for ``interleaved`` (ignored otherwise).
      cost_backward: backward:forward cost ratio used for the static
        bubble accounting only (never changes the program).
      loss_aux: optional pytree of per-microbatch loss inputs, leaves
        ``[num_micro, ...]`` (e.g. next-token targets), replicated over
        'pp'.
      loss_params: optional pytree of TRAINABLE loss-side parameters
        (e.g. a final layernorm + tied softmax head), replicated over
        'pp'; their gradient is accumulated at the last stage and psum'd.
      return_input_grads: also return ``d loss / d x_microbatches``
        (collected at stage 0's backward ticks and psum'd) — the hook an
        outer embedding pullback needs.

    Returns ``(loss, grads)`` — the scalar total loss (replicated) and
    the gradient w.r.t. THIS rank's ``params`` — or, when
    ``loss_params``/``return_input_grads`` are used,
    ``(loss, grads, extras)`` with ``extras`` holding
    ``"loss_params_grads"`` and/or ``"input_grads"`` (both replicated
    over 'pp').
    """
    n = lax.axis_size(axis_name)
    m = x_microbatches.shape[0]
    if schedule not in SCHEDULES:
        raise ValueError(f"unknown pipeline schedule {schedule!r}; "
                         f"expected one of {SCHEDULES}")
    v = num_virtual if schedule == "interleaved" else 1
    _validate(schedule, n, m, v)
    sched = PipelineSchedule(schedule, n, m, v, 1.0, float(cost_backward))
    _record_schedule(sched)
    if schedule == "gpipe":
        return _gpipe_value_and_grad(stage_fn, loss_fn, params,
                                     x_microbatches, axis_name,
                                     loss_aux, loss_params,
                                     return_input_grads)
    if schedule == "zb-h1":
        return _zb_value_and_grad(stage_fn, loss_fn, params,
                                  x_microbatches, axis_name,
                                  loss_aux, loss_params,
                                  return_input_grads)
    return _fused_value_and_grad(stage_fn, loss_fn, params,
                                 x_microbatches, axis_name, v,
                                 loss_aux, loss_params,
                                 return_input_grads)


def _gpipe_value_and_grad(stage_fn, loss_fn, params, x_mb, axis_name,
                          loss_aux=None, loss_params=None,
                          return_input_grads=False):
    """Forward sweep + backward sweep with full flush. The stash holds
    only each microbatch's stage INPUT; the backward sweep re-linearizes
    (recomputes) the stage — GPipe's rematerialization, which is what
    keeps its memory O(m · activation) instead of O(m · residuals)."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = x_mb.shape[0]
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    rev_perm = [((i + 1) % n, i) for i in range(n)]

    state0 = jnp.zeros_like(x_mb[0])
    outs0 = jnp.zeros_like(x_mb)
    stash0 = jnp.zeros_like(x_mb)

    def fwd_tick(carry, t):
        state, outs, stash = carry
        j = t - idx
        valid = jnp.logical_and(j >= 0, j < m)
        jc = jnp.clip(j, 0, m - 1)
        fed = jnp.where(t < m, x_mb[jnp.clip(t, 0, m - 1)],
                        jnp.zeros_like(state0))
        inp = jnp.where(idx == 0, fed, state)
        stash = lax.dynamic_update_index_in_dim(
            stash, jnp.where(valid, inp, stash[jc]), jc, 0)
        y = stage_fn(params, inp)
        out_j = t - (n - 1)
        rec = jnp.logical_and(out_j >= 0, idx == n - 1)
        oc = jnp.clip(out_j, 0, m - 1)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(rec, y.astype(outs.dtype), outs[oc]), oc, 0)
        state = lax.ppermute(y, axis_name, fwd_perm)
        return (state, outs, stash), None

    (_, outs, stash), _ = lax.scan(
        fwd_tick, (state0, outs0, stash0), jnp.arange(m + n - 1))

    # Per-microbatch losses + cotangent seeds, all on the last stage
    # (other ranks compute on garbage outs; every use below is masked).
    def total_loss(lp, o):
        if loss_aux is None:
            per_mb = loss_fn if lp is None else (lambda y: loss_fn(lp, y))
            return jnp.mean(jax.vmap(per_mb)(o))
        per_mb = (loss_fn if lp is None
                  else (lambda y, a: loss_fn(lp, y, a)))
        return jnp.mean(jax.vmap(per_mb)(o, loss_aux))

    if loss_params is None:
        loss_local, loss_vjp = jax.vjp(lambda o: total_loss(None, o),
                                       outs)
        (seeds,) = loss_vjp(jnp.ones((), loss_local.dtype))
        lp_grads = None
    else:
        loss_local, loss_vjp = jax.vjp(total_loss, loss_params, outs)
        d_lp, seeds = loss_vjp(jnp.ones((), loss_local.dtype))
        lp_grads = jax.tree_util.tree_map(
            lambda d: lax.psum(jnp.where(idx == n - 1, d, 0), axis_name),
            d_lp)

    grad0 = jax.tree_util.tree_map(jnp.zeros_like, params)
    xg0 = jnp.zeros_like(x_mb) if return_input_grads else None

    def bwd_tick(carry, u):
        g_state, gacc, xg = carry
        j = u - (n - 1 - idx)
        valid = jnp.logical_and(j >= 0, j < m)
        jc = jnp.clip(j, 0, m - 1)
        g_in = jnp.where(idx == n - 1, seeds[jnp.clip(u, 0, m - 1)],
                         g_state)
        g_in = jnp.where(valid, g_in, jnp.zeros_like(g_in))
        # Re-linearize the stage at the stashed input (the recompute).
        _, vjp_fn = jax.vjp(stage_fn, params, stash[jc])
        dp, dx = vjp_fn(g_in)
        gacc = _tree_add(gacc, dp)   # masked ticks contribute exact zeros
        if xg is not None:
            take = jnp.logical_and(valid, idx == 0)
            xg = lax.dynamic_update_index_in_dim(
                xg, jnp.where(take, dx.astype(xg.dtype), xg[jc]), jc, 0)
        g_state = lax.ppermute(dx, axis_name, rev_perm)
        return (g_state, gacc, xg), None

    (_, grads, xg), _ = lax.scan(
        bwd_tick, (jnp.zeros_like(x_mb[0]), grad0, xg0),
        jnp.arange(m + n - 1))
    loss = lax.psum(jnp.where(idx == n - 1, loss_local, 0.0), axis_name)
    return _pipeline_result(loss, grads, lp_grads, xg, axis_name,
                            loss_params is not None, return_input_grads)


def _fused_value_and_grad(stage_fn, loss_fn, params, x_mb, axis_name, V,
                          loss_aux=None, loss_params=None,
                          return_input_grads=False):
    """The 1F1B engine (V = 1) and its interleaved generalization
    (V >= 2): warmup / steady / drain scans over global tick indices.

    Chunk-stage ``c = v·n + r`` of microbatch ``j`` (group ``g = j // n``,
    in-group index ``jr = j % n``) runs its FORWARD at tick

        t_F = g·nV + v·n + r + jr

    and its BACKWARD at ``t_B = t_F + 2·(nV - 1 - c)`` — the mirror
    schedule that retires the last chunk-stage's backward in the same
    tick as its forward. Both tilings are conflict-free per rank, the
    forward ring permute serves intra-slot hops and the n-1 → 0
    wrap-around alike, and the reverse permute carries cotangents. The
    VJP residuals of each forward live in a ring of ``2nV - 1`` slots
    keyed by ``t_F mod W`` — the in-flight window is O(n·V), never O(m),
    which is what lets this schedule stash residuals instead of
    recomputing the forward (contrast ``_gpipe_value_and_grad``)."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = x_mb.shape[0]
    nV = n * V
    W = 2 * nV - 1
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    rev_perm = [((i + 1) % n, i) for i in range(n)]

    # Virtual-chunk plumbing: params leaves carry a leading V axis; V=1
    # callers pass plain stage params and we add the axis here.
    stacked = V > 1
    p_stacked = params if stacked else jax.tree_util.tree_map(
        lambda l: l[None], params)

    def chunk_params(vc):
        return jax.tree_util.tree_map(
            lambda l: lax.dynamic_index_in_dim(l, vc, 0, keepdims=False),
            p_stacked)

    res_avals, res_treedef = _vjp_template(
        stage_fn, chunk_params(jnp.int32(0)), x_mb[0])
    ring0 = [jnp.zeros((W,) + tuple(a.shape), a.dtype) for a in res_avals]
    loss_call = _make_loss_caller(loss_fn, loss_aux)

    def f_sched(t):
        """(valid, j, v) of this rank's forward work at tick t."""
        u = t - idx
        g = jnp.maximum(u, 0) // nV
        w = jnp.maximum(u, 0) % nV
        vv = w // n
        jr = w % n
        j = g * n + jr
        valid = jnp.logical_and(u >= 0, j < m)
        return valid, j, vv

    def b_sched(t):
        """(valid, j, v) of this rank's backward work at tick t."""
        q = t - (2 * nV - 2) + idx + (V - 1) * n
        g = jnp.maximum(q, 0) // nV
        w = jnp.maximum(q, 0) % nV
        vv = (V - 1) - w // n
        jr = w % n
        j = g * n + jr
        valid = jnp.logical_and(q >= 0, j < m)
        return valid, j, vv

    def f_part(t, fwd_state, ring, loss_acc, lp_acc, with_loss):
        validF, jF, vF = f_sched(t)
        jc = jnp.clip(jF, 0, m - 1)
        vc = jnp.clip(vF, 0, V - 1)
        fresh = jnp.logical_and(idx == 0, vF == 0)
        inp = jnp.where(fresh, x_mb[jc], fwd_state)
        y, vjp_fn = jax.vjp(stage_fn, chunk_params(vc), inp)
        slot = (g_tF(jc, vc)) % W
        leaves = jax.tree_util.tree_leaves(vjp_fn)
        ring = [lax.dynamic_update_index_in_dim(
                    r, jnp.where(validF, l,
                                 lax.dynamic_index_in_dim(
                                     r, slot, 0, keepdims=False)),
                    slot, 0)
                for r, l in zip(ring, leaves)]
        seed = jnp.zeros_like(y)
        if with_loss:
            # Per-microbatch loss + cotangent seed at the last
            # chunk-stage, in the same tick as its forward.
            last = jnp.logical_and(idx == n - 1, vF == V - 1)
            if loss_params is None:
                mb_loss, loss_vjp = jax.vjp(
                    lambda yy: loss_call(None, yy, jc), y)
                (seed,) = loss_vjp(jnp.ones((), mb_loss.dtype) / m)
            else:
                mb_loss, loss_vjp = jax.vjp(
                    lambda lp, yy: loss_call(lp, yy, jc), loss_params, y)
                d_lp, seed = loss_vjp(jnp.ones((), mb_loss.dtype) / m)
                use = jnp.logical_and(validF, last)
                lp_acc = jax.tree_util.tree_map(
                    lambda a, d: a + jnp.where(use, d, 0), lp_acc, d_lp)
            loss_acc = loss_acc + jnp.where(
                jnp.logical_and(validF, last),
                mb_loss.astype(loss_acc.dtype), 0.0)
        fwd_state = lax.ppermute(y, axis_name, fwd_perm)
        return fwd_state, ring, loss_acc, lp_acc, seed

    def g_tF(j, vv):
        """Forward tick of (microbatch j, chunk slot vv) on THIS rank."""
        return (j // n) * nV + vv * n + idx + (j % n)

    def b_part(t, bwd_state, ring, gacc, xg, seed):
        validB, jB, vB = b_sched(t)
        jc = jnp.clip(jB, 0, m - 1)
        vc = jnp.clip(vB, 0, V - 1)
        slot = g_tF(jc, vc) % W
        stashed = [lax.dynamic_index_in_dim(r, slot, 0, keepdims=False)
                   for r in ring]
        vjp_fn = jax.tree_util.tree_unflatten(res_treedef, stashed)
        last = jnp.logical_and(idx == n - 1, vB == V - 1)
        g_in = jnp.where(last, seed, bwd_state)
        g_in = jnp.where(validB, g_in, jnp.zeros_like(g_in))
        dp, dx = vjp_fn(g_in)    # zero cotangent -> exact zero dp/dx
        gacc = jax.tree_util.tree_map(
            lambda a, d: lax.dynamic_update_index_in_dim(
                a, lax.dynamic_index_in_dim(a, vc, 0, keepdims=False) + d,
                vc, 0),
            gacc, dp)
        if xg is not None:
            take = jnp.logical_and(
                validB, jnp.logical_and(idx == 0, vB == 0))
            xg = lax.dynamic_update_index_in_dim(
                xg, jnp.where(take, dx.astype(xg.dtype), xg[jc]), jc, 0)
        bwd_state = lax.ppermute(dx, axis_name, rev_perm)
        return bwd_state, gacc, xg

    grad0 = jax.tree_util.tree_map(jnp.zeros_like, p_stacked)
    fwd0 = jnp.zeros_like(x_mb[0])
    bwd0 = jnp.zeros_like(x_mb[0])
    lp0 = (None if loss_params is None
           else jax.tree_util.tree_map(jnp.zeros_like, loss_params))
    xg0 = jnp.zeros_like(x_mb) if return_input_grads else None

    def warmup_tick(carry, t):
        fwd_state, bwd_state, ring, gacc, loss_acc, lp_acc, xg = carry
        fwd_state, ring, loss_acc, lp_acc, _ = f_part(
            t, fwd_state, ring, loss_acc, lp_acc, with_loss=False)
        return (fwd_state, bwd_state, ring, gacc, loss_acc, lp_acc,
                xg), None

    def steady_tick(carry, t):
        fwd_state, bwd_state, ring, gacc, loss_acc, lp_acc, xg = carry
        fwd_state, ring, loss_acc, lp_acc, seed = f_part(
            t, fwd_state, ring, loss_acc, lp_acc, with_loss=True)
        bwd_state, gacc, xg = b_part(t, bwd_state, ring, gacc, xg, seed)
        return (fwd_state, bwd_state, ring, gacc, loss_acc, lp_acc,
                xg), None

    def drain_tick(carry, t):
        fwd_state, bwd_state, ring, gacc, loss_acc, lp_acc, xg = carry
        bwd_state, gacc, xg = b_part(t, bwd_state, ring, gacc, xg,
                                     jnp.zeros_like(bwd_state))
        return (fwd_state, bwd_state, ring, gacc, loss_acc, lp_acc,
                xg), None

    warmup = nV - 1
    steady_end = m * V + n - 1          # one past the last F tick
    drain_end = steady_end + nV - 1     # one past the last B tick

    carry = (fwd0, bwd0, ring0, grad0, jnp.zeros((), jnp.float32),
             lp0, xg0)
    if warmup:
        carry, _ = lax.scan(warmup_tick, carry, jnp.arange(warmup))
    carry, _ = lax.scan(steady_tick, carry,
                        jnp.arange(warmup, steady_end))
    if nV > 1:
        carry, _ = lax.scan(drain_tick, carry,
                            jnp.arange(steady_end, drain_end))
    _, _, _, grads, loss_acc, lp_acc, xg = carry
    loss = lax.psum(jnp.where(idx == n - 1, loss_acc / m, 0.0), axis_name)
    if not stacked:
        grads = jax.tree_util.tree_map(lambda l: l[0], grads)
    lp_grads = (None if lp_acc is None else jax.tree_util.tree_map(
        lambda d: lax.psum(d, axis_name), lp_acc))
    return _pipeline_result(loss, grads, lp_grads, xg, axis_name,
                            loss_params is not None, return_input_grads)


def _zb_value_and_grad(stage_fn, loss_fn, params, x_mb, axis_name,
                       loss_aux=None, loss_params=None,
                       return_input_grads=False):
    """The ZB-H1 engine (V = 1, m >= n): 1f1b's F/B tiling with the
    backward split into an input-grad tick (Bx) and a weight-grad tick
    (W) — arXiv 2412.14374's zero-bubble H1 point recast onto the
    single-SPMD-program collective-permute pattern.

    Tick map on rank ``idx`` (global tick t, microbatch j):

        F_j   at  t = j + idx                (same as 1f1b)
        Bx_j  at  t = j + 2n - 2 - idx       (same slot as 1f1b's B)
        W_j   at  t = 2n - 2 + j             (UNIFORM across ranks)

    Bx rebuilds the stage VJP from the residual ring (``2n - 1`` slots
    keyed ``(j + idx) mod W``, exactly as 1f1b), emits only ``dx`` down
    the reverse ring, and stashes its incoming cotangent into an n-slot
    COTANGENT ring keyed ``j mod n``. W rebuilds the same VJP later and
    emits only ``dp``. Because ``W_j``'s tick ``2n-2+j`` is at or after
    every rank's ``Bx_j`` tick ``2n-2+j-idx``, no W slot is ever masked:
    the four scans are warmup (F), steady-A (F+Bx), steady-B (F+Bx+W)
    and drain (Bx+W), and every steady-B/drain tick does useful W work.

    Ring safety: the residual slot of ``W_j`` (``(j+idx) mod (2n-1)``)
    is next overwritten by ``F_{j+2n-1}`` at tick ``j+idx+2n-1``, after
    W's read at ``2n-2+j``; a same-tick F write collides with the W read
    only at ``idx = 2n-2`` (impossible) or n = 1 (same microbatch —
    f-before-w ordering makes the read correct). The cotangent slot
    ``j mod n`` is next overwritten by ``Bx_{j+n}`` at tick
    ``j+3n-2-idx > 2n-2+j``; rank 0's same-tick Bx_j -> W_j handoff is
    ordered bx-before-w.

    In this SPMD emulation both Bx and W stage the full ``vjp_fn`` call;
    the unused half of each (``dp`` at Bx, ``dx`` at W) is dead code for
    XLA to eliminate. Numerics are exactly the microbatch-summed VJP
    either way — only the static cost model asserts the cBx/cBw split.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = x_mb.shape[0]
    W = 2 * n - 1
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    rev_perm = [((i + 1) % n, i) for i in range(n)]

    res_avals, res_treedef = _vjp_template(stage_fn, params, x_mb[0])
    ring0 = [jnp.zeros((W,) + tuple(a.shape), a.dtype) for a in res_avals]
    cring0 = jnp.zeros((n,) + x_mb.shape[1:], x_mb.dtype)
    loss_call = _make_loss_caller(loss_fn, loss_aux)

    def rebuild_vjp(jc):
        slot = (jc + idx) % W
        stashed = [lax.dynamic_index_in_dim(r, slot, 0, keepdims=False)
                   for r in ring_ref[0]]
        return jax.tree_util.tree_unflatten(res_treedef, stashed)

    # rebuild_vjp closes over a one-element list so f/bx/w parts all see
    # the CURRENT ring of the tick being traced.
    ring_ref = [ring0]

    def f_part(t, fwd_state, ring, loss_acc, lp_acc, with_loss):
        j = t - idx
        validF = jnp.logical_and(j >= 0, j < m)
        jc = jnp.clip(j, 0, m - 1)
        inp = jnp.where(idx == 0, x_mb[jc], fwd_state)
        y, vjp_fn = jax.vjp(stage_fn, params, inp)
        slot = (jc + idx) % W
        leaves = jax.tree_util.tree_leaves(vjp_fn)
        ring = [lax.dynamic_update_index_in_dim(
                    r, jnp.where(validF, l,
                                 lax.dynamic_index_in_dim(
                                     r, slot, 0, keepdims=False)),
                    slot, 0)
                for r, l in zip(ring, leaves)]
        seed = jnp.zeros_like(y)
        if with_loss:
            last = idx == n - 1
            if loss_params is None:
                mb_loss, loss_vjp = jax.vjp(
                    lambda yy: loss_call(None, yy, jc), y)
                (seed,) = loss_vjp(jnp.ones((), mb_loss.dtype) / m)
            else:
                mb_loss, loss_vjp = jax.vjp(
                    lambda lp, yy: loss_call(lp, yy, jc), loss_params, y)
                d_lp, seed = loss_vjp(jnp.ones((), mb_loss.dtype) / m)
                use = jnp.logical_and(validF, last)
                lp_acc = jax.tree_util.tree_map(
                    lambda a, d: a + jnp.where(use, d, 0), lp_acc, d_lp)
            loss_acc = loss_acc + jnp.where(
                jnp.logical_and(validF, last),
                mb_loss.astype(loss_acc.dtype), 0.0)
        fwd_state = lax.ppermute(y, axis_name, fwd_perm)
        return fwd_state, ring, loss_acc, lp_acc, seed

    def bx_part(t, bwd_state, cring, xg, seed):
        j = t - (2 * n - 2) + idx
        validB = jnp.logical_and(j >= 0, j < m)
        jc = jnp.clip(j, 0, m - 1)
        vjp_fn = rebuild_vjp(jc)
        g_in = jnp.where(idx == n - 1, seed, bwd_state)
        g_in = jnp.where(validB, g_in, jnp.zeros_like(g_in))
        # Park the cotangent for this microbatch's deferred W tick.
        cslot = jc % n
        cring = lax.dynamic_update_index_in_dim(
            cring,
            jnp.where(validB, g_in.astype(cring.dtype),
                      lax.dynamic_index_in_dim(cring, cslot, 0,
                                               keepdims=False)),
            cslot, 0)
        dp, dx = vjp_fn(g_in)   # dp is the W tick's job — dead here
        if xg is not None:
            take = jnp.logical_and(validB, idx == 0)
            xg = lax.dynamic_update_index_in_dim(
                xg, jnp.where(take, dx.astype(xg.dtype), xg[jc]), jc, 0)
        bwd_state = lax.ppermute(dx, axis_name, rev_perm)
        return bwd_state, cring, xg

    def w_part(t, cring, gacc):
        # W_j at the uniform tick 2n-2+j: always a valid microbatch in
        # the steady-B/drain spans (that is the zero-bubble property).
        jc = jnp.clip(t - (2 * n - 2), 0, m - 1)
        vjp_fn = rebuild_vjp(jc)
        g = lax.dynamic_index_in_dim(cring, jc % n, 0, keepdims=False)
        dp, dx = vjp_fn(g)      # dx already shipped at the Bx tick
        return _tree_add(gacc, dp)

    grad0 = jax.tree_util.tree_map(jnp.zeros_like, params)
    lp0 = (None if loss_params is None
           else jax.tree_util.tree_map(jnp.zeros_like, loss_params))
    xg0 = jnp.zeros_like(x_mb) if return_input_grads else None
    fwd0 = jnp.zeros_like(x_mb[0])
    bwd0 = jnp.zeros_like(x_mb[0])

    def tick(carry, t, *, do_f, do_bx, do_w):
        (fwd_state, bwd_state, ring, cring, gacc, loss_acc, lp_acc,
         xg) = carry
        ring_ref[0] = ring
        seed = jnp.zeros_like(bwd_state)
        if do_f:
            fwd_state, ring, loss_acc, lp_acc, seed = f_part(
                t, fwd_state, ring, loss_acc, lp_acc,
                with_loss=do_bx)
            ring_ref[0] = ring
        if do_bx:
            bwd_state, cring, xg = bx_part(t, bwd_state, cring, xg, seed)
        if do_w:
            gacc = w_part(t, cring, gacc)
        return (fwd_state, bwd_state, ring, cring, gacc, loss_acc,
                lp_acc, xg), None

    def warmup_tick(c, t):
        return tick(c, t, do_f=True, do_bx=False, do_w=False)

    def steady_a_tick(c, t):
        return tick(c, t, do_f=True, do_bx=True, do_w=False)

    def steady_b_tick(c, t):
        return tick(c, t, do_f=True, do_bx=True, do_w=True)

    def drain_tick(c, t):
        return tick(c, t, do_f=False, do_bx=True, do_w=True)

    carry = (fwd0, bwd0, ring0, cring0, grad0,
             jnp.zeros((), jnp.float32), lp0, xg0)
    if n > 1:
        carry, _ = lax.scan(warmup_tick, carry, jnp.arange(n - 1))
        carry, _ = lax.scan(steady_a_tick, carry,
                            jnp.arange(n - 1, 2 * n - 2))
    carry, _ = lax.scan(steady_b_tick, carry,
                        jnp.arange(2 * n - 2, m + n - 1))
    if n > 1:
        carry, _ = lax.scan(drain_tick, carry,
                            jnp.arange(m + n - 1, m + 2 * n - 2))
    _, _, _, _, grads, loss_acc, lp_acc, xg = carry
    loss = lax.psum(jnp.where(idx == n - 1, loss_acc / m, 0.0), axis_name)
    lp_grads = (None if lp_acc is None else jax.tree_util.tree_map(
        lambda d: lax.psum(d, axis_name), lp_acc))
    return _pipeline_result(loss, grads, lp_grads, xg, axis_name,
                            loss_params is not None, return_input_grads)
