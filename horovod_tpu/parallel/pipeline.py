"""Pipeline parallelism — GPipe-style microbatching over the 'pp' axis.

No reference equivalent (SURVEY.md §2.1: PP absent). TPU-first design: the
whole pipeline is ONE jitted SPMD program. Each 'pp' rank holds the
parameters of its stage; activations move between neighboring ranks with
``ppermute`` (collective-permute rides ICI); the microbatch schedule is a
``lax.scan`` with a static trip count of (num_microbatches + num_stages - 1)
ticks — the classic skewed schedule where tick t has stage s working on
microbatch t - s (bubbles at the ends).

This is the "collective permute pipeline" pattern (cf. praxis/t5x-style
pipelining): no host control flow, no per-stage programs, and XLA overlaps
the permute with the stage compute.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn: Callable, params, x_microbatches, *,
                   axis_name: str = "pp"):
    """Run a pipelined forward pass inside shard_map.

    Args:
      stage_fn: ``stage_fn(params, x) -> y`` with ``y.shape == x.shape`` —
        one stage's computation (e.g. a group of transformer blocks); every
        'pp' rank runs it with its own stage's params.
      params: this rank's stage parameters (pytree).
      x_microbatches: [num_micro, micro_batch, ...] input, meaningful on
        stage 0 (other ranks' copies are ignored).

    Returns: [num_micro, micro_batch, ...] outputs of the LAST stage,
      replicated to all 'pp' ranks (one masked psum at the end).
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = x_microbatches.shape[0]
    ticks = m + n - 1

    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    state0 = jnp.zeros_like(x_microbatches[0])
    outs0 = jnp.zeros_like(x_microbatches)

    def tick(carry, t):
        state, outs = carry
        # Stage 0 feeds microbatch t while they last; later stages consume
        # the activations handed over on the previous tick.
        mb_idx = jnp.clip(t, 0, m - 1)
        fed = jnp.where(t < m, x_microbatches[mb_idx],
                        jnp.zeros_like(state0))
        inp = jnp.where(idx == 0, fed, state)
        y = stage_fn(params, inp)
        # The last stage finishes microbatch t-(n-1) at tick t.
        out_idx = t - (n - 1)
        record = jnp.logical_and(out_idx >= 0, idx == n - 1)
        safe_idx = jnp.clip(out_idx, 0, m - 1)
        outs = jnp.where(
            record,
            outs.at[safe_idx].set(y.astype(outs.dtype)),
            outs)
        state = lax.ppermute(y, axis_name, fwd_perm)
        return (state, outs), None

    (_, outs), _ = lax.scan(tick, (state0, outs0), jnp.arange(ticks))
    # Replicate the last stage's outputs to every 'pp' rank.
    outs = lax.psum(jnp.where(idx == n - 1, outs, jnp.zeros_like(outs)),
                    axis_name)
    return outs
