"""Ulysses attention — all-to-all sequence parallelism.

No reference equivalent (SURVEY.md §5.7: sequence parallelism is
green-field for the rebuild); this is the DeepSpeed-Ulysses formulation
(Jacobs et al. 2023), the all-to-all complement to
:mod:`.ring_attention`:

  - Inputs arrive sequence-sharded over the 'sp' axis: each device holds
    [B, S/n, H, D] for ALL heads.
  - An all-to-all reshards to head-sharded [B, S, H/n, D]: each device now
    holds the FULL sequence for a subset of heads, so plain (flash)
    attention runs locally with no communication inside the softmax.
  - A second all-to-all reshards the output back to sequence-sharded.

Communication: 2 all-to-alls of the activations per attention call —
O(B·S·H·D/n) per device, constant in sequence length per hop, riding the
ICI all-to-all bandwidth. Ring attention instead sends K/V blocks n times;
Ulysses wins when head count >= n and the all-to-all fabric is strong
(TPU ICI is), ring wins for head counts smaller than the shard count.

Constraint: n_heads must be divisible by the 'sp' axis size.

All ops are static-shape einsum/reshape/all_to_all — one fused XLA
program, MXU-friendly.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

from .ring_attention import full_attention


def _seq_to_heads(x, axis_name: str):
    """[B, S/n, H, D] sequence-sharded -> [B, S, H/n, D] head-sharded.

    lax.all_to_all splits axis ``split_axis`` across the mesh axis and
    concatenates received blocks along ``concat_axis``.
    """
    # split heads (axis 2) across devices, gather sequence (axis 1)
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def _heads_to_seq(x, axis_name: str):
    """[B, S, H/n, D] head-sharded -> [B, S/n, H, D] sequence-sharded."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q, k, v, *, axis_name: str = "sp",
                      causal: bool = True,
                      scale: Optional[float] = None,
                      use_flash: bool = False,
                      flash_block: Optional[int] = None,
                      flash_interpret: bool = False):
    """Attention over a sequence sharded on ``axis_name`` via two
    all-to-alls (DeepSpeed-Ulysses).

    Args (per-shard views inside shard_map):
      q, k, v: [batch, seq_shard, heads, head_dim], heads % axis_size == 0
      use_flash: run the local (full-sequence) attention through the
        Pallas flash kernel — O(S) memory instead of the [S, S] score
        matrix; essential at long global sequence lengths.
      flash_block: flash kernel block size (None = tuned default) —
        forwarded so long-sequence block sweeps reach the kernel on
        this path too.
    Returns: [batch, seq_shard, heads, head_dim], exact (up to fp) vs
    full attention over the global sequence.
    """
    n = lax.axis_size(axis_name)
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(
            f"Ulysses needs n_heads ({h}) divisible by the '{axis_name}' "
            f"axis size ({n}); use ring_attention for fewer heads than "
            "shards")
    # Reshard: full sequence, subset of heads.
    q = _seq_to_heads(q, axis_name)
    k = _seq_to_heads(k, axis_name)
    v = _seq_to_heads(v, axis_name)
    # Local attention over the full sequence — no comm inside softmax.
    if use_flash:
        from ..ops.flash_attention import flash_attention
        # block sizes None -> tuned defaults (512 compiled / 128 interp)
        out = flash_attention(q, k, v, causal, scale, flash_block,
                              flash_block, flash_interpret)
    else:
        out = full_attention(q, k, v, causal=causal, scale=scale)
    # Reshard back: full heads, sequence shard.
    return _heads_to_seq(out, axis_name)
