"""SPMD training-step builder — composes dp/tp/sp/ep into one jitted
program over the mesh.

This is the jit-native counterpart of the reference's DistributedOptimizer
(torch/__init__.py:42-151) generalized beyond data parallelism. The whole
step — forward (ring attention over 'sp', Megatron column/row splits over
'tp', MoE all_to_all over 'ep'), backward, gradient cross-shard reduction,
and the optimizer update — is ONE shard_map'ed, jitted program; XLA
schedules every collective on ICI.

Gradient reduction rule (manual SPMD). shard_map-of-grad computes the VJP
of the per-shard outputs with a cotangent seed of 1 on EVERY shard, i.e.
the gradient of sum-over-shards of the returned scalar, treating each
shard's copy of a replicated parameter as independent. To make that sum
equal the global batch-mean loss exactly once:

  - each data shard returns local_mean / n_data_shards, and
  - the value is masked to zero except on model-rank 0 (tp/ep index 0),
    so duplicated outputs across model axes don't overcount (the masked
    ranks still receive their cotangent shares through the transposes of
    the model's own collectives — row-parallel psum, ring ppermute,
    expert all_to_all).

Then the true gradient of a parameter sharded with spec S is a plain psum
of the per-shard gradients over every mesh axis NOT in S (the chain rule
for tied parameters), with no extra scaling anywhere.
"""

from __future__ import annotations

import functools
import itertools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import transformer as tfm

DATA_AXES = ("dp", "sp")
MODEL_AXES = ("tp", "ep")


def _spec_axes(spec) -> set:
    from .zero import _spec_axes_ordered
    return set(_spec_axes_ordered(spec))


def reduce_gradients(grads, specs, mesh: Mesh, skip=(),
                     hierarchical=None, dcn_wire=None):
    """Apply the reduction rule leaf-by-leaf (see module docstring).
    ``skip`` omits axes whose reduction happens elsewhere (ZeRO-1 sums
    over 'dp' inside its psum_scatter).

    ``hierarchical=(ici_axis, dcn_axis)`` routes leaves that reduce
    over BOTH axes through the two-stage in-slice-then-cross-slice
    reduction (collectives.hierarchical_psum: reduce-scatter on ICI,
    1/ici_size-sized — optionally ``dcn_wire``-quantized — psum on DCN,
    all-gather back), instead of one flat psum over the pair. Leaves
    missing only one of the two keep the plain psum."""
    mesh_axes = [a for a in mesh.axis_names if a not in skip]

    def red(g, spec):
        have = _spec_axes(spec)
        missing = [ax for ax in mesh_axes if ax not in have]
        if hierarchical is not None:
            ici_ax, dcn_ax = hierarchical
            if ici_ax in missing and dcn_ax in missing:
                from .collectives import hierarchical_psum
                g = hierarchical_psum(g, ici_ax, dcn_ax, wire=dcn_wire)
                missing = [ax for ax in missing
                           if ax not in (ici_ax, dcn_ax)]
        if missing:
            g = lax.psum(g, tuple(missing))
        return g

    return jax.tree_util.tree_map(red, grads, specs,
                                  is_leaf=lambda x: isinstance(x, P))


def build_train_step(cfg: tfm.TransformerConfig, mesh: Mesh, optimizer,
                     *, dcn_axis: Optional[str] = None,
                     dcn_wire: Optional[str] = None,
                     dcn_hierarchical: bool = True):
    """Returns ``(step_fn, shard_params, shard_batch)``.

    step_fn(params, opt_state, tokens, targets) -> (params, opt_state, loss)
    — jitted over the mesh; tokens/targets are [B, S] global arrays sharded
    batch-over-'dp', sequence-over-'sp'.

    ``dcn_axis`` names an OUTER data-parallel mesh axis that crosses
    slice/host boundaries (``"auto"`` discovers one via
    :func:`horovod_tpu.parallel.mesh.dcn_axes`): the batch shards over
    ``(dcn_axis, 'dp')`` jointly and the gradient reduction runs
    hierarchically — in-slice reduce-scatter over 'dp' first, then the
    1/dp-sized (optionally ``dcn_wire``-block-quantized, docs/compression.md)
    cross-slice psum, then the in-slice all-gather (docs/pipeline.md).
    ``dcn_hierarchical=False`` keeps the identical data layout but
    reduces with one flat psum over the axis pair — the A/B baseline
    the bench measures bytes against. ZeRO-1 states keep their own
    dp-space reduction and are not supported together with
    ``dcn_axis``."""
    specs = tfm.param_specs(cfg)
    axis_names = set(mesh.axis_names)

    if dcn_axis == "auto":
        from .mesh import dcn_axes as _dcn_axes
        found = [a for a in _dcn_axes(mesh) if a not in
                 (cfg.tp_axis, cfg.sp_axis, cfg.ep_axis)]
        dcn_axis = found[0] if found else None
    if dcn_axis is not None:
        if dcn_axis not in axis_names:
            raise ValueError(f"dcn_axis {dcn_axis!r} is not a mesh axis "
                             f"(axes: {sorted(axis_names)})")
        if "dp" not in axis_names:
            raise ValueError("hierarchical reduction needs an in-slice "
                             "'dp' axis under dcn_axis "
                             f"{dcn_axis!r}")

    batch_axes = ((dcn_axis, "dp") if dcn_axis is not None
                  else ("dp" if "dp" in axis_names else None))
    data_spec = P(batch_axes, cfg.sp_axis if cfg.sp_axis else None)

    world = 1
    for _ax in mesh.axis_names:
        world *= int(mesh.shape[_ax])

    def _dedup_sq(tree):
        """Global squared L2 norm contribution of this shard: per-leaf
        local sum-of-squares divided by the leaf's replication factor
        (product of mesh axes NOT in its spec), so a psum over every
        axis counts each unique element exactly once."""
        def leaf_sq(x, s):
            d = 1
            have = _spec_axes(s)
            for ax in mesh.axis_names:
                if ax not in have:
                    d *= int(mesh.shape[ax])
            return jnp.sum(jnp.square(x.astype(jnp.float32))) / d
        parts = jax.tree_util.tree_map(
            leaf_sq, tree, specs, is_leaf=lambda x: isinstance(x, P))
        return sum(jax.tree_util.tree_leaves(parts))

    def _numerics_aux(g_for_norm, updates, params, nf_local):
        """In-graph numerics telemetry (docs/numerics.md): ONE small
        psum of a [3 + world] vector piggybacked on the step — global
        grad/update/param squared norms plus a per-device nonfinite
        vector (each shard deposits its LOCAL pre-reduction count at
        its linear mesh index, so the host alert can name the producing
        rank)."""
        idx = jnp.int32(0)
        for ax in mesh.axis_names:
            idx = idx * int(mesh.shape[ax]) + lax.axis_index(ax)
        nf_vec = jnp.zeros((world,), jnp.float32).at[idx].set(
            nf_local.astype(jnp.float32))
        packed = jnp.concatenate([
            jnp.stack([_dedup_sq(g_for_norm), _dedup_sq(updates),
                       _dedup_sq(params)]), nf_vec])
        packed = lax.psum(packed, tuple(mesh.axis_names))
        return {
            "grad_norm": jnp.sqrt(packed[0]),
            "update_ratio": jnp.sqrt(packed[1])
            / jnp.maximum(jnp.sqrt(packed[2]), 1e-12),
            "nonfinite_by_rank": packed[3:],
        }

    def _per_shard_step(zero1_mode, with_numerics=False):
        from .zero import zero1_update

        def per_shard_step(params, opt_state, tokens, targets):
            n_data = 1
            for ax in DATA_AXES:
                if ax in axis_names:
                    n_data *= mesh.shape[ax]
            if dcn_axis is not None:
                n_data *= mesh.shape[dcn_axis]

            def local_loss(p):
                loss = tfm.loss_fn(p, tokens, targets, cfg) / n_data
                # Mask to model-rank 0 so sum-over-shards counts each
                # data shard's loss exactly once (module docstring).
                for ax in MODEL_AXES:
                    if ax in axis_names:
                        loss = jnp.where(lax.axis_index(ax) == 0,
                                         loss, 0.0)
                return loss

            loss, grads = jax.value_and_grad(local_loss)(params)
            if with_numerics:
                # Count on the LOCAL, pre-reduction gradients — after
                # the psum a NaN has spread to every shard and the
                # producer is unidentifiable.
                nf_local = sum(
                    jnp.sum(~jnp.isfinite(g)) for g in
                    jax.tree_util.tree_leaves(grads))
            if zero1_mode:
                # ZeRO-1 (parallel/zero.py): reduce over every missing
                # axis EXCEPT 'dp' — the wrapper's psum_scatter does the
                # dp-sum and the sharding in one collective; moments
                # live as 1/dp flat shards.
                grads = reduce_gradients(grads, specs, mesh,
                                         skip=("dp",))
                updates, opt_state = zero1_update(
                    optimizer, grads, opt_state, params, axis="dp")
            else:
                hier = (("dp", dcn_axis)
                        if dcn_axis is not None and dcn_hierarchical
                        else None)
                grads = reduce_gradients(grads, specs, mesh,
                                         hierarchical=hier,
                                         dcn_wire=dcn_wire)
                updates, opt_state = optimizer.update(grads, opt_state,
                                                      params)
            aux = None
            if with_numerics:
                g_for_norm = grads
                if zero1_mode:
                    # ZeRO-1 grads skipped the 'dp' sum (the wrapper's
                    # psum_scatter owns it) — finish it here so the
                    # telemetry norm is the true global gradient norm.
                    g_for_norm = jax.tree_util.tree_map(
                        lambda g, s: g if "dp" in _spec_axes(s)
                        else lax.psum(g, "dp"),
                        grads, specs,
                        is_leaf=lambda x: isinstance(x, P))
                aux = _numerics_aux(g_for_norm, updates, params,
                                    nf_local)
            import optax
            params = optax.apply_updates(params, updates)
            # Reported loss: global mean (sum of masked, scaled shards).
            loss = lax.psum(loss, tuple(mesh.axis_names))
            if with_numerics:
                return params, opt_state, loss, aux
            return params, opt_state, loss

        return per_shard_step

    def make(params, opt_state):
        from .zero import Zero1State, zero1_state_specs

        zero1_mode = isinstance(opt_state, Zero1State)
        if zero1_mode:
            if dcn_axis is not None:
                raise ValueError(
                    "ZeRO-1 optimizer state and dcn_axis hierarchical "
                    "reduction are mutually exclusive: ZeRO-1's "
                    "psum_scatter already owns the 'dp'-space "
                    "reduction (docs/pipeline.md)")
            if "dp" not in axis_names:
                raise ValueError(
                    "Zero1State optimizer state requires a 'dp' mesh "
                    "axis to shard over")
            # The flat-shard layout (padding, per-shard sizes) is baked
            # in at zero1_init time; a mismatched dp size would surface
            # as an opaque jit sharding failure deep inside shard_map.
            # Reject it here with the actual numbers instead.
            if opt_state.n_shards is not None:
                recorded = int(opt_state.n_shards)
                dp = int(mesh.shape["dp"])
                if recorded != dp:
                    raise ValueError(
                        f"Zero1State was built for n_shards={recorded} "
                        f"but this mesh's 'dp' axis has {dp} shards; "
                        "the flat-shard padding depends on the shard "
                        "count, so rebuild the state with "
                        f"zero1_init(..., n_shards={dp}) for this mesh")
            for s in jax.tree_util.tree_leaves(
                    specs, is_leaf=lambda x: isinstance(x, P)):
                if "dp" in _spec_axes(s):
                    raise ValueError(
                        "ZeRO-1 shards moments over 'dp' and requires "
                        f"dp-replicated parameters; spec {s} already "
                        "uses 'dp'")
            opt_specs = zero1_state_specs(opt_state, params, specs,
                                          mesh, axis="dp")
        else:
            # Opt-state specs by STRUCTURE (shared helper — optax
            # moment subtrees get the param specs wholesale, counts
            # replicate; shape-based matching would be ambiguous since
            # wq and wo share shapes with transposed specs).
            from .zero import state_specs_by_structure
            opt_specs = state_specs_by_structure(opt_state, params,
                                                 specs)
        from ..observability import numerics as _numerics
        numerics_on = _numerics.enabled()
        out_specs = (specs, opt_specs, P())
        if numerics_on:
            # Aux leaves are psum'ed over every axis inside the step —
            # replicated outputs, so plain P() specs.
            out_specs = out_specs + ({"grad_norm": P(),
                                      "update_ratio": P(),
                                      "nonfinite_by_rank": P()},)
        step = jax.jit(jax.shard_map(
            _per_shard_step(zero1_mode, with_numerics=numerics_on),
            mesh=mesh,
            in_specs=(specs, opt_specs, data_spec, data_spec),
            out_specs=out_specs,
            check_vma=False))
        if numerics_on:
            step = _wrap_numerics_step(step)
        return step, opt_specs

    def shard_params(params):
        return _put_tree(params, specs, mesh)

    def shard_batch(batch):
        return jax.device_put(batch, NamedSharding(mesh, data_spec))

    return make, shard_params, shard_batch


def _wrap_numerics_step(inner):
    """Host-side shell of the numerics aux channel (docs/numerics.md):
    keeps the public ``(params, opt_state, loss)`` contract while
    feeding the deferred :class:`~horovod_tpu.observability.numerics
    .StepStats` sink (step N's device scalars materialize while step
    N+1 runs — no added host sync), running the periodic cross-rank
    fingerprint probe, and honoring an armed ``bitflip_param`` fault
    clause."""
    from ..observability import numerics as _numerics
    counter = itertools.count()

    def step(params, opt_state, tokens, targets):
        i = next(counter)
        params = _numerics.maybe_bitflip(params, i)
        params, opt_state, loss, aux = inner(params, opt_state,
                                             tokens, targets)
        _numerics.step_stats().note(i, loss, aux)
        _numerics.maybe_send_fingerprint(params, i)
        return params, opt_state, loss

    return step


def _put_tree(tree, specs, mesh: Mesh):
    flat_t, treedef = jax.tree_util.tree_flatten(tree)
    flat_s = jax.tree_util.tree_flatten(
        specs, is_leaf=lambda x: isinstance(x, P))[0]
    out = [jax.device_put(x, NamedSharding(mesh, s))
           for x, s in zip(flat_t, flat_s)]
    return jax.tree_util.tree_unflatten(treedef, out)


# --------------------------------------------------------------------------
# Pipeline-parallel training step — cuts the flagship transformer into
# stage_fns over 'pp' automatically (docs/pipeline.md, docs/autotune.md).
# --------------------------------------------------------------------------

_DENSE_LAYER_KEYS = ("ln1", "ln2", "wq", "wk", "wv", "wo", "wi", "wo_mlp")


def _check_pipeline_cfg(cfg: tfm.TransformerConfig, mesh: Mesh,
                        num_virtual: int) -> int:
    if "pp" not in mesh.axis_names:
        raise ValueError("build_pipeline_train_step needs a 'pp' mesh "
                         f"axis (axes: {sorted(mesh.axis_names)})")
    for ax, name in ((cfg.tp_axis, "tp"), (cfg.sp_axis, "sp"),
                     (cfg.ep_axis, "ep")):
        if ax:
            raise ValueError(
                f"pipeline train step does not compose with {name} "
                "parallelism yet; build the config with "
                f"{name}_axis=None")
    if cfg.num_experts:
        raise ValueError("pipeline train step supports dense layers "
                         "only (num_experts=0): MoE layer dicts are not "
                         "homogeneous across the stage stack")
    n = int(mesh.shape["pp"])
    extra = [a for a in mesh.axis_names
             if a != "pp" and int(mesh.shape[a]) > 1]
    if extra:
        raise ValueError("pipeline train step shards over 'pp' only; "
                         f"fold or drop mesh axes {extra}")
    if cfg.n_layers % (n * num_virtual):
        raise ValueError(
            f"n_layers ({cfg.n_layers}) must divide evenly into "
            f"pp ({n}) x num_virtual ({num_virtual}) stage chunks")
    return n


def to_pipeline_params(cfg: tfm.TransformerConfig, params, num_stages: int,
                       num_virtual: int = 1):
    """Re-pack ``init_params`` layout into the pipeline layout:
    ``{"embed", "pos", "ln_f", "stages"}`` where each stages leaf is
    ``[n_pp, V, layers_per_chunk, ...]`` — slot ``[r, v]`` holds
    chunk-stage ``v·n + r``'s layers in order (the interleaved
    chunk-stage convention; V=1 collapses to contiguous stages)."""
    nV = num_stages * num_virtual
    lpc = cfg.n_layers // nV
    layers = params["layers"]
    chunks = [jax.tree_util.tree_map(
                  lambda *ls: jnp.stack(ls), *layers[c * lpc:(c + 1) * lpc])
              for c in range(nV)]
    stages = jax.tree_util.tree_map(
        lambda *cs: jnp.stack(cs).reshape(
            (num_virtual, num_stages) + cs[0].shape).swapaxes(0, 1),
        *chunks)
    return {"embed": params["embed"], "pos": params["pos"],
            "ln_f": params["ln_f"], "stages": stages}


def from_pipeline_params(cfg: tfm.TransformerConfig, pparams,
                         num_stages: int, num_virtual: int = 1):
    """Inverse of :func:`to_pipeline_params` (checkpoint interop)."""
    nV = num_stages * num_virtual
    lpc = cfg.n_layers // nV
    flat = jax.tree_util.tree_map(
        lambda l: l.swapaxes(0, 1).reshape((nV * lpc,) + l.shape[3:]),
        pparams["stages"])
    layers = [jax.tree_util.tree_map(lambda l: l[i], flat)
              for i in range(nV * lpc)]
    return {"embed": pparams["embed"], "pos": pparams["pos"],
            "ln_f": pparams["ln_f"], "layers": layers}


def pipeline_param_specs(cfg: tfm.TransformerConfig):
    """PartitionSpecs for the pipeline layout: stage stacks shard their
    leading n_pp axis over 'pp'; embed/pos/ln_f replicate (they are the
    loss head + embedding, applied on every rank)."""
    stage_spec = {k: P("pp") for k in _DENSE_LAYER_KEYS}
    return {"embed": P(), "pos": P(), "ln_f": P(), "stages": stage_spec}


def build_pipeline_train_step(cfg: tfm.TransformerConfig, mesh: Mesh,
                              optimizer, *, schedule: str = "1f1b",
                              num_virtual: int = 1,
                              cost_backward: float = 2.0):
    """Returns ``(make, shard_params, shard_batch)`` for a
    pipeline-parallel train step over a 'pp' mesh.

    ``step(params, opt_state, tokens_mb, targets_mb) ->
    (params, opt_state, loss)`` where ``tokens_mb``/``targets_mb`` are
    ``[num_micro, micro_batch, S]`` int32 (replicated — 'pp' shards
    layers, not data) and ``params`` is the
    :func:`to_pipeline_params` layout. The flagship transformer is cut
    automatically: every rank's stage_fn scans its
    ``n_layers / (pp · V)`` decoder blocks, the embedding runs
    replicated on every rank with its gradient recovered from the
    pipeline's stage-0 input grads, and the final layernorm + tied
    softmax head ride the schedule's ``loss_params`` channel. The
    microbatch count is whatever leading axis the batch carries — the
    autotuner varies it (and ``schedule``) per trial by rebuilding this
    step (docs/autotune.md)."""
    from ..models.transformer import _block, _layernorm, _project_logits
    from .pipeline import pipeline_value_and_grad

    n = _check_pipeline_cfg(cfg, mesh, num_virtual)
    interleaved = schedule == "interleaved"
    if interleaved and num_virtual < 2:
        raise ValueError("interleaved needs num_virtual >= 2")
    if not interleaved and num_virtual != 1:
        raise ValueError(f"schedule {schedule!r} uses num_virtual=1")
    specs = pipeline_param_specs(cfg)
    dt = cfg.dtype

    block = _block
    if cfg.remat:
        policy = None
        if cfg.remat_policy == "dots":
            policy = (jax.checkpoint_policies
                      .checkpoint_dots_with_no_batch_dims)
        block = jax.checkpoint(_block, static_argnums=(2, 3),
                               policy=policy)

    def stage_fn(p, x):
        def body(h, layer):
            return block(layer, h, cfg, 0), None
        h, _ = lax.scan(body, x, p)
        return h

    def embed_all(ep, tokens_mb):
        s = tokens_mb.shape[-1]
        pos = ep["pos"][jnp.arange(s)]
        return (ep["embed"].astype(dt)[tokens_mb]
                + pos.astype(dt)[None, None])

    def head_loss(lp, y, targets):
        h = _layernorm(y, lp["ln_f"])
        logits = _project_logits({"embed": lp["embed"]}, h, cfg)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return -ll.mean()

    def per_shard_step(params, opt_state, tokens_mb, targets_mb):
        ep = {"embed": params["embed"], "pos": params["pos"]}
        x_mb, emb_vjp = jax.vjp(lambda e: embed_all(e, tokens_mb), ep)
        lp = {"ln_f": params["ln_f"], "embed": params["embed"]}
        # Local stage stack [1, V, lpc, ...] -> the engine's view.
        p_stage = jax.tree_util.tree_map(lambda l: l[0],
                                         params["stages"])
        if not interleaved:
            p_stage = jax.tree_util.tree_map(lambda l: l[0], p_stage)
        loss, g_stage, extras = pipeline_value_and_grad(
            stage_fn, head_loss, p_stage, x_mb, axis_name="pp",
            schedule=schedule, num_virtual=num_virtual,
            cost_backward=cost_backward, loss_aux=targets_mb,
            loss_params=lp, return_input_grads=True)
        (d_ep,) = emb_vjp(extras["input_grads"])
        lp_g = extras["loss_params_grads"]
        if not interleaved:
            g_stage = jax.tree_util.tree_map(lambda l: l[None], g_stage)
        grads = {
            # Tied embedding: input-path pullback + softmax-head path.
            "embed": d_ep["embed"] + lp_g["embed"],
            "pos": d_ep["pos"],
            "ln_f": lp_g["ln_f"],
            "stages": jax.tree_util.tree_map(lambda l: l[None], g_stage),
        }
        updates, opt_state = optimizer.update(grads, opt_state, params)
        import optax
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def make(params, opt_state):
        from .zero import state_specs_by_structure
        opt_specs = state_specs_by_structure(opt_state, params, specs)
        data_spec = P()
        step = jax.jit(jax.shard_map(
            per_shard_step, mesh=mesh,
            in_specs=(specs, opt_specs, data_spec, data_spec),
            out_specs=(specs, opt_specs, P()),
            check_vma=False))
        return step, opt_specs

    def shard_params(params):
        return _put_tree(params, specs, mesh)

    def shard_batch(batch):
        return jax.device_put(batch, NamedSharding(mesh, P()))

    return make, shard_params, shard_batch
