"""SPMD training-step builder — composes dp/tp/sp/ep into one jitted
program over the mesh.

This is the jit-native counterpart of the reference's DistributedOptimizer
(torch/__init__.py:42-151) generalized beyond data parallelism. The whole
step — forward (ring attention over 'sp', Megatron column/row splits over
'tp', MoE all_to_all over 'ep'), backward, gradient cross-shard reduction,
and the optimizer update — is ONE shard_map'ed, jitted program; XLA
schedules every collective on ICI.

Gradient reduction rule (manual SPMD). shard_map-of-grad computes the VJP
of the per-shard outputs with a cotangent seed of 1 on EVERY shard, i.e.
the gradient of sum-over-shards of the returned scalar, treating each
shard's copy of a replicated parameter as independent. To make that sum
equal the global batch-mean loss exactly once:

  - each data shard returns local_mean / n_data_shards, and
  - the value is masked to zero except on model-rank 0 (tp/ep index 0),
    so duplicated outputs across model axes don't overcount (the masked
    ranks still receive their cotangent shares through the transposes of
    the model's own collectives — row-parallel psum, ring ppermute,
    expert all_to_all).

Then the true gradient of a parameter sharded with spec S is a plain psum
of the per-shard gradients over every mesh axis NOT in S (the chain rule
for tied parameters), with no extra scaling anywhere.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import transformer as tfm

DATA_AXES = ("dp", "sp")
MODEL_AXES = ("tp", "ep")


def _spec_axes(spec) -> set:
    from .zero import _spec_axes_ordered
    return set(_spec_axes_ordered(spec))


def reduce_gradients(grads, specs, mesh: Mesh, skip=(),
                     hierarchical=None, dcn_wire=None):
    """Apply the reduction rule leaf-by-leaf (see module docstring).
    ``skip`` omits axes whose reduction happens elsewhere (ZeRO-1 sums
    over 'dp' inside its psum_scatter).

    ``hierarchical=(ici_axis, dcn_axis)`` routes leaves that reduce
    over BOTH axes through the two-stage in-slice-then-cross-slice
    reduction (collectives.hierarchical_psum: reduce-scatter on ICI,
    1/ici_size-sized — optionally ``dcn_wire``-quantized — psum on DCN,
    all-gather back), instead of one flat psum over the pair. Leaves
    missing only one of the two keep the plain psum."""
    mesh_axes = [a for a in mesh.axis_names if a not in skip]

    def red(g, spec):
        have = _spec_axes(spec)
        missing = [ax for ax in mesh_axes if ax not in have]
        if hierarchical is not None:
            ici_ax, dcn_ax = hierarchical
            if ici_ax in missing and dcn_ax in missing:
                from .collectives import hierarchical_psum
                g = hierarchical_psum(g, ici_ax, dcn_ax, wire=dcn_wire)
                missing = [ax for ax in missing
                           if ax not in (ici_ax, dcn_ax)]
        if missing:
            g = lax.psum(g, tuple(missing))
        return g

    return jax.tree_util.tree_map(red, grads, specs,
                                  is_leaf=lambda x: isinstance(x, P))


def build_train_step(cfg: tfm.TransformerConfig, mesh: Mesh, optimizer,
                     *, dcn_axis: Optional[str] = None,
                     dcn_wire: Optional[str] = None,
                     dcn_hierarchical: bool = True):
    """Returns ``(step_fn, shard_params, shard_batch)``.

    step_fn(params, opt_state, tokens, targets) -> (params, opt_state, loss)
    — jitted over the mesh; tokens/targets are [B, S] global arrays sharded
    batch-over-'dp', sequence-over-'sp'.

    ``dcn_axis`` names an OUTER data-parallel mesh axis that crosses
    slice/host boundaries (``"auto"`` discovers one via
    :func:`horovod_tpu.parallel.mesh.dcn_axes`): the batch shards over
    ``(dcn_axis, 'dp')`` jointly and the gradient reduction runs
    hierarchically — in-slice reduce-scatter over 'dp' first, then the
    1/dp-sized (optionally ``dcn_wire``-block-quantized, docs/compression.md)
    cross-slice psum, then the in-slice all-gather (docs/pipeline.md).
    ``dcn_hierarchical=False`` keeps the identical data layout but
    reduces with one flat psum over the axis pair — the A/B baseline
    the bench measures bytes against. ZeRO-1 states keep their own
    dp-space reduction and are not supported together with
    ``dcn_axis``."""
    specs = tfm.param_specs(cfg)
    axis_names = set(mesh.axis_names)

    if dcn_axis == "auto":
        from .mesh import dcn_axes as _dcn_axes
        found = [a for a in _dcn_axes(mesh) if a not in
                 (cfg.tp_axis, cfg.sp_axis, cfg.ep_axis)]
        dcn_axis = found[0] if found else None
    if dcn_axis is not None:
        if dcn_axis not in axis_names:
            raise ValueError(f"dcn_axis {dcn_axis!r} is not a mesh axis "
                             f"(axes: {sorted(axis_names)})")
        if "dp" not in axis_names:
            raise ValueError("hierarchical reduction needs an in-slice "
                             "'dp' axis under dcn_axis "
                             f"{dcn_axis!r}")

    batch_axes = ((dcn_axis, "dp") if dcn_axis is not None
                  else ("dp" if "dp" in axis_names else None))
    data_spec = P(batch_axes, cfg.sp_axis if cfg.sp_axis else None)

    def _per_shard_step(zero1_mode):
        from .zero import zero1_update

        def per_shard_step(params, opt_state, tokens, targets):
            n_data = 1
            for ax in DATA_AXES:
                if ax in axis_names:
                    n_data *= mesh.shape[ax]
            if dcn_axis is not None:
                n_data *= mesh.shape[dcn_axis]

            def local_loss(p):
                loss = tfm.loss_fn(p, tokens, targets, cfg) / n_data
                # Mask to model-rank 0 so sum-over-shards counts each
                # data shard's loss exactly once (module docstring).
                for ax in MODEL_AXES:
                    if ax in axis_names:
                        loss = jnp.where(lax.axis_index(ax) == 0,
                                         loss, 0.0)
                return loss

            loss, grads = jax.value_and_grad(local_loss)(params)
            if zero1_mode:
                # ZeRO-1 (parallel/zero.py): reduce over every missing
                # axis EXCEPT 'dp' — the wrapper's psum_scatter does the
                # dp-sum and the sharding in one collective; moments
                # live as 1/dp flat shards.
                grads = reduce_gradients(grads, specs, mesh,
                                         skip=("dp",))
                updates, opt_state = zero1_update(
                    optimizer, grads, opt_state, params, axis="dp")
            else:
                hier = (("dp", dcn_axis)
                        if dcn_axis is not None and dcn_hierarchical
                        else None)
                grads = reduce_gradients(grads, specs, mesh,
                                         hierarchical=hier,
                                         dcn_wire=dcn_wire)
                updates, opt_state = optimizer.update(grads, opt_state,
                                                      params)
            import optax
            params = optax.apply_updates(params, updates)
            # Reported loss: global mean (sum of masked, scaled shards).
            loss = lax.psum(loss, tuple(mesh.axis_names))
            return params, opt_state, loss

        return per_shard_step

    def make(params, opt_state):
        from .zero import Zero1State, zero1_state_specs

        zero1_mode = isinstance(opt_state, Zero1State)
        if zero1_mode:
            if dcn_axis is not None:
                raise ValueError(
                    "ZeRO-1 optimizer state and dcn_axis hierarchical "
                    "reduction are mutually exclusive: ZeRO-1's "
                    "psum_scatter already owns the 'dp'-space "
                    "reduction (docs/pipeline.md)")
            if "dp" not in axis_names:
                raise ValueError(
                    "Zero1State optimizer state requires a 'dp' mesh "
                    "axis to shard over")
            # The flat-shard layout (padding, per-shard sizes) is baked
            # in at zero1_init time; a mismatched dp size would surface
            # as an opaque jit sharding failure deep inside shard_map.
            # Reject it here with the actual numbers instead.
            if opt_state.n_shards is not None:
                recorded = int(opt_state.n_shards)
                dp = int(mesh.shape["dp"])
                if recorded != dp:
                    raise ValueError(
                        f"Zero1State was built for n_shards={recorded} "
                        f"but this mesh's 'dp' axis has {dp} shards; "
                        "the flat-shard padding depends on the shard "
                        "count, so rebuild the state with "
                        f"zero1_init(..., n_shards={dp}) for this mesh")
            for s in jax.tree_util.tree_leaves(
                    specs, is_leaf=lambda x: isinstance(x, P)):
                if "dp" in _spec_axes(s):
                    raise ValueError(
                        "ZeRO-1 shards moments over 'dp' and requires "
                        f"dp-replicated parameters; spec {s} already "
                        "uses 'dp'")
            opt_specs = zero1_state_specs(opt_state, params, specs,
                                          mesh, axis="dp")
        else:
            # Opt-state specs by STRUCTURE (shared helper — optax
            # moment subtrees get the param specs wholesale, counts
            # replicate; shape-based matching would be ambiguous since
            # wq and wo share shapes with transposed specs).
            from .zero import state_specs_by_structure
            opt_specs = state_specs_by_structure(opt_state, params,
                                                 specs)
        step = jax.jit(jax.shard_map(
            _per_shard_step(zero1_mode), mesh=mesh,
            in_specs=(specs, opt_specs, data_spec, data_spec),
            out_specs=(specs, opt_specs, P()),
            check_vma=False))
        return step, opt_specs

    def shard_params(params):
        return _put_tree(params, specs, mesh)

    def shard_batch(batch):
        return jax.device_put(batch, NamedSharding(mesh, data_spec))

    return make, shard_params, shard_batch


def _put_tree(tree, specs, mesh: Mesh):
    flat_t, treedef = jax.tree_util.tree_flatten(tree)
    flat_s = jax.tree_util.tree_flatten(
        specs, is_leaf=lambda x: isinstance(x, P))[0]
    out = [jax.device_put(x, NamedSharding(mesh, s))
           for x, s in zip(flat_t, flat_s)]
    return jax.tree_util.tree_unflatten(treedef, out)
