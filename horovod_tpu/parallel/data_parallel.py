"""Data parallelism — the reference's one-and-only strategy
(SURVEY.md §2.1), recast as shardings.

In the reference, data parallelism is explicit allreduce calls on gradients
(DistributedOptimizer, torch/__init__.py:42-151). On TPU the same program
is expressed by sharding the batch over 'dp' and letting the loss-mean
insert the psum, or — when writing shard_map-style SPMD by hand — calling
:func:`allreduce_gradients_in_jit`.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_batch(batch, mesh: Mesh, axis: str = "dp"):
    """Place a host batch with its leading dim sharded over ``axis`` —
    the DistributedSampler pattern (examples/pytorch_mnist.py:43-64)
    without the sampler: every chip sees its own slice of one global
    array."""
    spec = P(axis)
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, NamedSharding(mesh, spec)), batch)


def allreduce_gradients_in_jit(grads, axis: str = "dp",
                               average: bool = True):
    """psum/pmean a gradient pytree over the mesh axis — the in-jit
    equivalent of the reference's per-gradient allreduce hooks
    (torch/__init__.py:106-130). XLA's collective combiner performs the
    tensor-fusion role here (SURVEY.md §5.8)."""
    op = lax.pmean if average else lax.psum
    return jax.tree_util.tree_map(lambda g: op(g, axis), grads)
