"""Ring attention — sequence/context parallelism for long sequences.

No reference equivalent (SURVEY.md §5.7: "Absent — ... For the TPU rebuild
this is green-field"); built on the same mesh-axis collective layer as
everything else, per the survey's guidance that sequence-dimension sharding
rides the comm layer.

Algorithm (Liu et al., "Ring Attention with Blockwise Transformers", and
the blockwise-parallel formulation): the sequence is sharded over the 'sp'
axis; each device holds one query block Q_i and one key/value block
(K_i, V_i). K/V blocks rotate around the ring via ``ppermute`` while each
device accumulates its attention output *online* with the numerically
stable streaming softmax (running max m, normalizer l, weighted numerator):

    for step in 0..n-1:
        scores   = Q_i @ K_j^T          # j = (i - step) mod n
        m_new    = max(m, rowmax(scores))
        corr     = exp(m - m_new)
        p        = exp(scores - m_new)
        num      = num * corr + p @ V_j
        l        = l * corr + rowsum(p)
        (K, V)  <- ring_shift(K, V)

    out = num / l

Communication (one K/V block per step, overlappable with the matmul) rides
the ICI ring — bandwidth-optimal for sequence lengths that do not fit one
chip. Causal masking uses global position offsets per block.

The loop is a ``lax.fori_loop`` (compiler-friendly static trip count); each
step is two MXU matmuls over full blocks — no dynamic shapes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _ring_step(carry, _, axis_name: str, causal: bool, scale: float,
               q_index, n_shards: int, block_q: int, block_k: int):
    (q, k, v, m, l, num, step) = carry
    # Block j currently resident = (q_index - step) mod n.
    j = (q_index - step) % n_shards

    # scores: [B, H, block_q, block_k] in fp32 for a stable softmax.
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale

    if causal:
        q_pos = q_index * block_q + jnp.arange(block_q)[:, None]
        k_pos = j * block_k + jnp.arange(block_k)[None, :]
        mask = q_pos >= k_pos  # attend to self and the past
        scores = jnp.where(mask[None, None], scores, -jnp.inf)

    m_new = jnp.maximum(m, scores.max(axis=-1))
    # Blocks fully masked out produce -inf rowmax; keep the old statistics.
    m_new = jnp.where(jnp.isfinite(m_new), m_new, m)
    # corr would be exp(-inf - -inf) = nan for rows with no mass yet; they
    # carry zero numerator/normalizer, so force corr to 0 there.
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 0.0)
    p = jnp.exp(scores - m_new[..., None])
    p = jnp.where(jnp.isfinite(scores), p, 0.0)

    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    num = num * corr.transpose(0, 2, 1)[..., None] + pv
    l = l * corr + p.sum(axis=-1)

    # Rotate K/V to the next rank (ring_shift): each device passes its
    # resident block along, receiving the previous rank's.
    n = n_shards
    perm = [(i, (i + 1) % n) for i in range(n)]
    k = lax.ppermute(k, axis_name, perm)
    v = lax.ppermute(v, axis_name, perm)
    return (q, k, v, m_new, l, num, step + 1), None


def ring_attention(q, k, v, *, axis_name: str = "sp", causal: bool = True,
                   scale: Optional[float] = None):
    """Blockwise ring attention over the ``axis_name`` mesh axis.

    Args (per-shard views inside shard_map):
      q, k, v: [batch, seq_shard, heads, head_dim]
    Returns: [batch, seq_shard, heads, head_dim] attention output for this
    device's query block, exact (up to fp) vs full attention.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    if scale is None:
        scale = d ** -0.5

    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    num0 = jnp.zeros((b, sq, h, d), jnp.float32)

    step_fn = functools.partial(
        _ring_step, axis_name=axis_name, causal=causal, scale=scale,
        q_index=idx, n_shards=n, block_q=sq, block_k=sk)

    (q, k, v, m, l, num, _), _ = lax.scan(
        step_fn, (q, k, v, m0, l0, num0, jnp.zeros((), jnp.int32)),
        None, length=n)

    l = jnp.maximum(l, 1e-20)  # fully-masked rows (shouldn't occur causally)
    out = num / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def full_attention(q, k, v, *, causal: bool = True,
                   scale: Optional[float] = None):
    """Single-device reference attention (same layout) for tests."""
    b, sq, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        sk = k.shape[1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)
