"""Ring attention — sequence/context parallelism for long sequences.

No reference equivalent (SURVEY.md §5.7: "Absent — ... For the TPU rebuild
this is green-field"); built on the same mesh-axis collective layer as
everything else, per the survey's guidance that sequence-dimension sharding
rides the comm layer.

Algorithm (Liu et al., "Ring Attention with Blockwise Transformers", and
the blockwise-parallel formulation): the sequence is sharded over the 'sp'
axis; each device holds one query block Q_i and one key/value block
(K_i, V_i). K/V blocks rotate around the ring via ``ppermute`` while each
device accumulates its attention output *online* with the numerically
stable streaming softmax (running max m, normalizer l, weighted numerator):

    for step in 0..n-1:
        scores   = Q_i @ K_j^T          # j = (i - step) mod n
        m_new    = max(m, rowmax(scores))
        corr     = exp(m - m_new)
        p        = exp(scores - m_new)
        num      = num * corr + p @ V_j
        l        = l * corr + rowsum(p)
        (K, V)  <- ring_shift(K, V)

    out = num / l

Communication (one K/V block per step, overlappable with the matmul) rides
the ICI ring — bandwidth-optimal for sequence lengths that do not fit one
chip. Causal masking uses global position offsets per block.

The loop is a ``lax.fori_loop`` (compiler-friendly static trip count); each
step is two MXU matmuls over full blocks — no dynamic shapes.

Two inner-op variants: the plain einsum step above materializes the
[shard, shard] score tensor per step; :func:`ring_flash_attention`
(``use_flash=True``) runs each (q-shard, resident-kv-block) pair through
the Pallas flash kernels instead — O(shard) memory per device in forward
AND backward (the custom backward re-rotates K/V with traveling fp32
dK/dV accumulators and reuses the per-block flash backward kernels with
the global logsumexp/delta row statistics), which is what lets the
per-device shard itself be long.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _ring_step(carry, _, axis_name: str, causal: bool, scale: float,
               q_index, n_shards: int, block_q: int, block_k: int):
    (q, k, v, m, l, num, step) = carry
    # Block j currently resident = (q_index - step) mod n.
    j = (q_index - step) % n_shards

    # scores: [B, H, block_q, block_k] in fp32 for a stable softmax.
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale

    if causal:
        q_pos = q_index * block_q + jnp.arange(block_q)[:, None]
        k_pos = j * block_k + jnp.arange(block_k)[None, :]
        mask = q_pos >= k_pos  # attend to self and the past
        scores = jnp.where(mask[None, None], scores, -jnp.inf)

    m_new = jnp.maximum(m, scores.max(axis=-1))
    # Blocks fully masked out produce -inf rowmax; keep the old statistics.
    m_new = jnp.where(jnp.isfinite(m_new), m_new, m)
    # corr would be exp(-inf - -inf) = nan for rows with no mass yet; they
    # carry zero numerator/normalizer, so force corr to 0 there.
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 0.0)
    p = jnp.exp(scores - m_new[..., None])
    p = jnp.where(jnp.isfinite(scores), p, 0.0)

    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    num = num * corr.transpose(0, 2, 1)[..., None] + pv
    l = l * corr + p.sum(axis=-1)

    # Rotate K/V to the next rank (ring_shift): each device passes its
    # resident block along, receiving the previous rank's.
    n = n_shards
    perm = [(i, (i + 1) % n) for i in range(n)]
    k = lax.ppermute(k, axis_name, perm)
    v = lax.ppermute(v, axis_name, perm)
    return (q, k, v, m_new, l, num, step + 1), None


def ring_attention(q, k, v, *, axis_name: str = "sp", causal: bool = True,
                   scale: Optional[float] = None,
                   use_flash: bool = False,
                   flash_block: Optional[int] = None,
                   flash_interpret: bool = False):
    """Blockwise ring attention over the ``axis_name`` mesh axis.

    Args (per-shard views inside shard_map):
      q, k, v: [batch, seq_shard, heads, head_dim]
      use_flash: run each (q-shard, resident-kv-block) pair through the
        Pallas flash kernels (:func:`ring_flash_attention`) instead of
        materializing the [seq_shard, seq_shard] score tensor — O(shard)
        memory per step in forward AND backward, which is what lets the
        per-device shard itself be long.
    Returns: [batch, seq_shard, heads, head_dim] attention output for this
    device's query block, exact (up to fp) vs full attention.
    """
    if use_flash:
        return ring_flash_attention(
            q, k, v, axis_name=axis_name, causal=causal, scale=scale,
            block=flash_block, interpret=flash_interpret)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    if scale is None:
        scale = d ** -0.5

    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    num0 = jnp.zeros((b, sq, h, d), jnp.float32)

    step_fn = functools.partial(
        _ring_step, axis_name=axis_name, causal=causal, scale=scale,
        q_index=idx, n_shards=n, block_q=sq, block_k=sk)

    (q, k, v, m, l, num, _), _ = lax.scan(
        step_fn, (q, k, v, m0, l0, num0, jnp.zeros((), jnp.int32)),
        None, length=n)

    l = jnp.maximum(l, 1e-20)  # fully-masked rows (shouldn't occur causally)
    out = num / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _ring_perm(n: int):
    return [(i, (i + 1) % n) for i in range(n)]


def _rf_attend_cases(qb, kb, vb, sc, block, interpret, causal, branch):
    """Per-ring-step flash forward: 0 = skip (block entirely above the
    causal diagonal), 1 = diagonal block (intra-shard causal mask),
    2 = past block (every pair valid, no mask). The pallas grids are
    identical across branches, so lax.switch picks one per step without
    shape mismatch."""
    from ..ops.flash_attention import _flash_fwd

    bh, sq, d = qb.shape

    # fp32 per-block outputs: the merge accumulates across n blocks, and
    # per-block rounding to bf16 would stack n-fold (the plain flash
    # path rounds once over the whole sequence).
    def skip(_):
        # Sentinel contract: skip emits lse = -inf (true "no mass"),
        # while the flash kernels emit _NEG_INF (-1e30, finite) for
        # massless rows. The ring merge's isfinite() guards are pinned
        # to THIS -inf: they zero the weight of never-attended rows so
        # (-inf) - (-inf) can't produce NaN. _NEG_INF rows pass the
        # guard but their exp() underflows to 0 against any real mass.
        # Keep both facts in mind before editing the merge arithmetic.
        return (jnp.zeros((bh, sq, d), jnp.float32),
                jnp.full((bh, sq, 1), -jnp.inf, jnp.float32))

    def diag(_):
        return _flash_fwd(qb, kb, vb, sc, True, block, block, interpret,
                          out_dtype=jnp.float32)

    def past(_):
        return _flash_fwd(qb, kb, vb, sc, False, block, block, interpret,
                          out_dtype=jnp.float32)

    if not causal:
        return past(None)
    return lax.switch(branch, (skip, diag, past), None)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def ring_flash_attention(q, k, v, axis_name: str = "sp",
                         causal: bool = True,
                         scale: Optional[float] = None,
                         block: Optional[int] = None,
                         interpret: bool = False):
    """Ring attention with the Pallas flash kernels as the inner op.

    The plain :func:`ring_attention` materializes the
    [seq_shard, seq_shard] score tensor every ring step — O(shard²)
    memory inside a layer whose purpose is O(shard) scaling. This
    variant runs each (q-shard, resident-kv-block) pair through the
    compiled flash forward (returning the per-block output and
    logsumexp) and merges blocks with the streaming logaddexp rule; the
    custom backward re-rotates K/V around the ring and reuses the
    per-block flash backward kernels, which only need the block
    operands plus the GLOBAL per-row (lse, delta) statistics
    (ops/flash_attention._flash_bwd). Per-device memory is O(shard)
    in forward and backward; gradients for each K/V block accumulate
    in fp32 on the tuple that travels the ring and arrive home after
    the full rotation.
    """
    out, _ = _ring_flash_fwd(q, k, v, axis_name, causal, scale, block,
                             interpret)
    return out


def _ring_flash_fwd(q, k, v, axis_name, causal, scale, block, interpret):
    from ..ops.flash_attention import _from_bh, _to_bh

    b, sq, h, d = q.shape
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    sc = scale if scale is not None else d ** -0.5
    qb = _to_bh(q)
    bh = qb.shape[0]

    def step(carry, _):
        kb_cur, vb_cur, out_r, lse_r, t = carry
        j = (idx - t) % n
        # branch: 0 skip (j > idx), 1 diagonal (j == idx), 2 past
        branch = jnp.where(j > idx, 0, jnp.where(j == idx, 1, 2))
        o_j, lse_j = _rf_attend_cases(
            qb, kb_cur, vb_cur, sc, block, interpret, causal, branch)
        # Streaming merge of normalized per-block outputs: weights are
        # exp(lse_j - lse_tot). The isfinite guards are pinned to the
        # -inf sentinel (skip branch + lse0 init); flash's finite
        # _NEG_INF massless rows pass them and underflow to weight 0.
        lse_new = jnp.logaddexp(lse_r, lse_j)
        w_r = jnp.where(jnp.isfinite(lse_r), jnp.exp(lse_r - lse_new), 0.0)
        w_j = jnp.where(jnp.isfinite(lse_j), jnp.exp(lse_j - lse_new), 0.0)
        out_new = out_r * w_r + o_j * w_j
        k_nxt = lax.ppermute(kb_cur, axis_name, _ring_perm(n))
        v_nxt = lax.ppermute(vb_cur, axis_name, _ring_perm(n))
        return (k_nxt, v_nxt, out_new, lse_new, t + 1), None

    out0 = jnp.zeros((bh, sq, d), jnp.float32)
    lse0 = jnp.full((bh, sq, 1), -jnp.inf, jnp.float32)
    # K/V rotate in [bh, s, d] layout: the transpose to kernel layout
    # happens once here, not once per ring step (ppermute is
    # layout-agnostic).
    (k_fin, v_fin, out_r, lse_r, _), _ = lax.scan(
        step, (_to_bh(k), _to_bh(v), out0, lse0, jnp.zeros((), jnp.int32)),
        None, length=n)
    del k_fin, v_fin  # back at home position after n rotations
    out4 = _from_bh(out_r.astype(q.dtype), b, h)
    return out4, (q, k, v, out4, lse_r)


def _ring_flash_bwd(axis_name, causal, scale, block, interpret, res, g):
    from ..ops.flash_attention import _flash_bwd, _from_bh, _to_bh

    q, k, v, out, lse = res
    b, sq, h, d = q.shape
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    sc = scale if scale is not None else d ** -0.5
    qb, gb, ob = _to_bh(q), _to_bh(g), _to_bh(out)
    bh = qb.shape[0]

    # Global softmax-jacobian diagonal, same for every block pair.
    delta = jnp.sum(gb.astype(jnp.float32) * ob.astype(jnp.float32),
                    axis=-1, keepdims=True)                 # [bh, sq, 1]

    def bwd_cases(kb, vb, branch):
        def skip(_):
            return (jnp.zeros((bh, sq, d), jnp.float32),
                    jnp.zeros((bh, sq, d), jnp.float32),
                    jnp.zeros((bh, sq, d), jnp.float32))

        def run(is_diag):
            def f(_):
                # fp32 kernel outputs: each traveling accumulator sums n
                # per-pair contributions, so per-block bf16 rounding
                # would stack n-fold.
                return _flash_bwd(qb, kb, vb, gb, lse, delta, sc,
                                  is_diag, block, block, interpret,
                                  out_dtype=jnp.float32)
            return f

        if not causal:
            return run(False)(None)
        return lax.switch(branch, (skip, run(True), run(False)), None)

    def step(carry, _):
        kb_cur, vb_cur, dk_acc, dv_acc, dq_acc, t = carry
        j = (idx - t) % n
        branch = jnp.where(j > idx, 0, jnp.where(j == idx, 1, 2))
        dq_j, dk_j, dv_j = bwd_cases(kb_cur, vb_cur, branch)
        dq_acc = dq_acc + dq_j
        dk_acc = dk_acc + dk_j
        dv_acc = dv_acc + dv_j
        # dk/dv accumulators travel WITH their block around the ring.
        perm = _ring_perm(n)
        k_nxt = lax.ppermute(kb_cur, axis_name, perm)
        v_nxt = lax.ppermute(vb_cur, axis_name, perm)
        dk_nxt = lax.ppermute(dk_acc, axis_name, perm)
        dv_nxt = lax.ppermute(dv_acc, axis_name, perm)
        return (k_nxt, v_nxt, dk_nxt, dv_nxt, dq_acc, t + 1), None

    z = jnp.zeros((bh, sq, d), jnp.float32)
    (k_fin, v_fin, dk, dv, dq, _), _ = lax.scan(
        step, (_to_bh(k), _to_bh(v), z, z, z, jnp.zeros((), jnp.int32)),
        None, length=n)
    del k_fin, v_fin  # home again; dk/dv completed the full rotation too
    return (_from_bh(dq.astype(q.dtype), b, h),
            _from_bh(dk.astype(k.dtype), b, h),
            _from_bh(dv.astype(v.dtype), b, h))


ring_flash_attention.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def full_attention(q, k, v, *, causal: bool = True,
                   scale: Optional[float] = None):
    """Single-device reference attention (same layout) for tests."""
    b, sq, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        sk = k.shape[1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)
