"""ZeRO-1 optimizer-state sharding over the data axis.

Motivated by measurement (round 5, BENCH_LM.json wide1b_seq2048): at
1B params the binding constraint on a chip is optimizer-state memory —
fp32 AdamW moments are 2 x 4.1 GB of a 15.75 GB HBM, forcing
rematerialization that costs ~5-9 MFU points. The reference has no
analogue (its data parallelism replicates optimizer state per rank,
torch/__init__.py:42-151); this is the standard modern extension
(ZeRO stage 1) expressed TPU-natively: moments live sharded over
'dp' (stacked with the parameter's own model axes), gradients arrive
via ``psum_scatter`` (reduce+shard in one collective, riding ICI),
each rank updates only its 1/N shard, and the parameter updates
return by ``all_gather``.

Layout. Every moment leaf is a FLAT vector. For a parameter whose
spec uses model axes with combined size m (tp/ep blocks), the global
state leaf has length ``m * padded_local`` where ``padded_local`` is
the parameter's per-model-shard element count padded to a multiple of
dp, and it is sharded ``P((model_axes..., 'dp'))`` — each model shard
owns one contiguous ``padded_local`` block, split contiguously over
dp, which is exactly the block order ``psum_scatter(tiled=True)``
produces inside that model shard. Per-device the leaf is the
``[padded_local/dp]`` shard ``zero1_update`` works on. Values never
need to correspond ACROSS model shards, only within one, so the
flattening of a tp block vs the full matrix never matters.

Constraints: parameter specs must not already use the dp axis (this
framework's layouts never do), and the inner transformation must be
elementwise per parameter with a value-independent ``init``
(Adam/AdamW/SGD/momentum/rmsprop qualify — their init is
zeros/ones_like; global-norm clipping must be composed OUTSIDE the
wrapper since it needs the full gradient).

Use (see parallel/train.py::build_train_step, which wires this in
automatically when handed a Zero1State):

    state = zero1_init(opt, params, n_shards=dp,
                       param_specs=specs, mesh=mesh)
    step, _ = make(params, state)      # build_train_step's make
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

import optax


class Zero1State(NamedTuple):
    inner: Any          # inner optimizer state over flat sharded leaves
    # Shard count the state was built for (zero1_init's n_shards).
    # Recorded so build_train_step.make() can reject a state whose
    # padding/layout disagrees with the mesh's 'dp' size with a clear
    # error instead of an opaque jit sharding failure. A pytree LEAF
    # (NamedTuple fields always are), so it travels through jit as a
    # replicated scalar; None only for hand-built legacy states.
    n_shards: Any = None


def _spec_axes_ordered(spec):
    out = []
    if isinstance(spec, P):
        for entry in spec:
            if entry is None:
                continue
            if isinstance(entry, (tuple, list)):
                out.extend(entry)
            else:
                out.append(entry)
    return out


def _padded_size(n_elem: int, n_shards: int) -> int:
    return ((n_elem + n_shards - 1) // n_shards) * n_shards


def _model_factor(spec, mesh: Mesh) -> int:
    m = 1
    for ax in _spec_axes_ordered(spec):
        m *= int(mesh.shape[ax])
    return m


def _flat_pad(x, n_shards: int):
    flat = jnp.ravel(x)
    pad = _padded_size(flat.size, n_shards) - flat.size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat


def state_specs_by_structure(opt_state, params, param_like_specs):
    """Spec tree for an optax state by STRUCTURE: subtrees sharing the
    params' treedef (optax moment subtrees — mu/nu/trace) get
    ``param_like_specs`` wholesale; any other leaf (counts, scalars)
    replicates. Shared by build_train_step's replicated path and
    zero1_state_specs so the subtle matching rule lives once."""
    ptreedef = jax.tree_util.tree_structure(params)

    def is_param_like(x):
        try:
            return jax.tree_util.tree_structure(x) == ptreedef
        except Exception:
            return False

    return jax.tree_util.tree_map(
        lambda x: param_like_specs if is_param_like(x) else P(),
        opt_state, is_leaf=is_param_like)


def zero1_init(inner: optax.GradientTransformation, params,
               n_shards: int, param_specs=None,
               mesh: Mesh | None = None) -> Zero1State:
    """Host-side init. Builds the inner state over flat vectors shaped
    [m * padded_local] per parameter (see module docstring); requires
    the inner init to be value-independent (zeros/ones_like)."""
    if (param_specs is None) != (mesh is None):
        raise ValueError(
            "zero1_init needs BOTH param_specs and mesh to size "
            "model-sharded moments (or neither, for fully replicated "
            "parameters) — got only one of them")

    def flat_zero(p, spec):
        m = _model_factor(spec, mesh) if mesh is not None else 1
        assert p.size % m == 0, (p.shape, spec)
        local = p.size // m
        return jnp.zeros((m * _padded_size(local, n_shards),), p.dtype)

    if param_specs is None:
        flat_params = jax.tree_util.tree_map(
            lambda p: flat_zero(p, P()), params)
    else:
        flat_params = jax.tree_util.tree_map(
            flat_zero, params, param_specs,
            is_leaf=lambda x: isinstance(x, P))
        # tree_map over (params, specs) keys off params' structure; the
        # result has params' treedef, which is what optax init expects.
    return Zero1State(inner=inner.init(flat_params),
                      n_shards=int(n_shards))


def zero1_state_specs(state: Zero1State, params, param_specs,
                      mesh: Mesh, axis: str = "dp"):
    """PartitionSpec tree for the wrapper state: each moment subtree
    (params' treedef — the optax convention) gets, per parameter, the
    flat-leaf spec ``P((param's model axes..., axis))``; anything else
    (count scalars) replicates."""
    ptreedef = jax.tree_util.tree_structure(params)
    spec_leaves = [
        P(tuple(_spec_axes_ordered(s)) + (axis,))
        for s in jax.tree_util.tree_flatten(
            param_specs, is_leaf=lambda x: isinstance(x, P))[0]]
    per_param_specs = jax.tree_util.tree_unflatten(ptreedef, spec_leaves)
    # n_shards mirrors the state's structure: a replicated scalar spec
    # when recorded, None (empty subtree) for legacy states — the spec
    # tree must stay a structural match for shard_map's in/out_specs.
    return Zero1State(
        inner=state_specs_by_structure(state.inner, params,
                                       per_param_specs),
        n_shards=None if state.n_shards is None else P())


def zero1_update(inner: optax.GradientTransformation, grads,
                 state: Zero1State, params, axis: str = "dp"):
    """Per-shard update (call INSIDE shard_map, with ``grads`` already
    reduced over every mesh axis except ``axis`` — the psum_scatter
    here performs the ``axis`` reduction). ``grads``/``params`` are the
    per-shard (model-local) views. Returns ``(updates, new_state)``
    with updates in the per-shard param shapes."""
    n = lax.axis_size(axis)
    idx = lax.axis_index(axis)

    def to_shard(g):
        # Sum across data shards AND shard the result, one collective.
        return lax.psum_scatter(_flat_pad(g, n), axis, tiled=True)

    def param_shard(p):
        flat = _flat_pad(p, n)
        shard = flat.size // n
        return lax.dynamic_slice(flat, (idx * shard,), (shard,))

    g_shards = jax.tree_util.tree_map(to_shard, grads)
    p_shards = jax.tree_util.tree_map(param_shard, params)
    upd_shards, new_inner = inner.update(g_shards, state.inner, p_shards)

    def to_full(u, p):
        full = lax.all_gather(u, axis, tiled=True)
        return full[: p.size].reshape(p.shape).astype(p.dtype)

    updates = jax.tree_util.tree_map(to_full, upd_shards, params)
    return updates, Zero1State(inner=new_inner, n_shards=state.n_shards)
