"""Process & device topology layer — the TPU-native equivalent of Horovod's
process bring-up and rank/size API.

Reference parity (all paths relative to /root/reference):
  - ``hvd.init()`` / ``horovod_init`` / ``horovod_init_comm``
    (horovod/common/operations.cc:2384-2422, horovod/common/__init__.py:58-84)
  - ``rank/local_rank/size/local_size`` C API (operations.cc:2424-2460)
  - MPI communicator setup: world dup, node-local split via
    ``MPI_Comm_split_type(SHARED)``, cross-node split by local rank
    (operations.cc:1728-1797).

TPU-native redesign
-------------------
Horovod launches one *process per accelerator* and wires them with MPI. JAX
on TPU is single-controller-per-host SPMD: one process drives all local
chips, and ``jax.distributed`` + the XLA runtime replace MPI process wire-up.
We therefore map:

  =====================  =======================================================
  Horovod concept        TPU-native equivalent
  =====================  =======================================================
  rank                   *virtual rank* = global device index in the mesh.
                         ``rank()`` returns this process's first device's
                         index (the process "leads" its local devices).
  size                   ``jax.device_count()`` — total chips, matching
                         "number of GPUs" in the reference's benchmarks.
  local_rank/local_size  index/count of devices attached to this process.
  MPI world comm         a ``jax.sharding.Mesh`` over all devices with a flat
                         ``'dp'`` axis.
  local/cross comms      the same device set reshaped to ``('dcn', 'ici')``
                         axes (inter-host, intra-host) — the hierarchical
                         mesh used by hierarchical allreduce/allgather.
  =====================  =======================================================

Per-rank (per-device) data lives as a jax.Array sharded over the mesh's
``'dp'`` axis; host/replicated arrays mean "every local virtual rank
contributes this value", exactly as every Horovod rank passing the same
tensor.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh


class NotInitializedError(RuntimeError):
    """Raised when rank/size accessors are used before ``init()``.

    Mirrors the ``Horovod has not been initialized; use hvd.init()`` errors
    raised by the reference's ctypes basics layer
    (horovod/common/__init__.py:90-154).
    """


_NOT_INITIALIZED_MSG = (
    "Horovod-TPU has not been initialized; please call horovod_tpu.init()."
)


@dataclasses.dataclass
class Topology:
    """Immutable snapshot of the distributed topology created by ``init``."""

    devices: tuple            # all global devices, mesh order
    local_devices: tuple      # devices owned by this process
    mesh: Mesh                # flat mesh, axis 'dp'
    hier_mesh: Mesh           # ('dcn', 'ici') hierarchical mesh
    process_index: int
    process_count: int
    rank: int                 # first global device index of this process
    size: int                 # total device count
    local_rank: int           # == 0 for the leader virtual rank
    local_size: int           # local device count
    is_homogeneous: bool      # same local_size everywhere (operations.cc:1772-1790)
    # Elastic generation: 0 for the first launch (and all non-elastic
    # jobs); bumped by the elastic driver on every recovery relaunch
    # (HOROVOD_TPU_ELASTIC_GENERATION). A worker function uses it to
    # tell a cold start from a post-failure rejoin.
    generation: int = 0


_lock = threading.Lock()
_topology: Optional[Topology] = None


def _build_topology(devices: Sequence, process_index: int,
                    process_count: int) -> Topology:
    devices = tuple(devices)
    local_devices = tuple(d for d in devices if d.process_index == process_index)
    if not local_devices:
        # Single-process CPU emulation: every device is "local".
        local_devices = devices

    size = len(devices)
    local_size = len(local_devices)

    # Homogeneity check — reference allgathers local_sizes and compares
    # (operations.cc:1772-1790). Here the device list carries process ids.
    per_proc = {}
    for d in devices:
        per_proc[d.process_index] = per_proc.get(d.process_index, 0) + 1
    counts = set(per_proc.values())
    is_homogeneous = len(counts) <= 1

    mesh = Mesh(np.asarray(devices, dtype=object).reshape(size), ("dp",))
    # Hierarchical mesh: leading axis spans processes (DCN / inter-host),
    # trailing axis spans a process's chips (ICI / intra-host). This mirrors
    # the reference's cross_comm/local_comm split (operations.cc:1760-1797).
    if is_homogeneous and process_count >= 1 and size % max(local_size, 1) == 0:
        hier = np.asarray(devices, dtype=object).reshape(
            size // local_size, local_size)
    else:
        hier = np.asarray(devices, dtype=object).reshape(1, size)
    hier_mesh = Mesh(hier, ("dcn", "ici"))

    # Virtual-rank of this process's first device.
    first = devices.index(local_devices[0])
    return Topology(
        devices=devices,
        local_devices=local_devices,
        mesh=mesh,
        hier_mesh=hier_mesh,
        process_index=process_index,
        process_count=process_count,
        rank=first,
        size=size,
        local_rank=0,
        local_size=local_size,
        is_homogeneous=is_homogeneous,
        generation=_env_int("HOROVOD_TPU_ELASTIC_GENERATION") or 0,
    )


def init(*, coordinator_address: Optional[str] = None,
         num_processes: Optional[int] = None,
         process_id: Optional[int] = None,
         devices: Optional[Sequence] = None) -> Topology:
    """Initialize the Horovod-TPU runtime.

    Equivalent of ``hvd.init()`` (horovod/common/__init__.py:58-84 →
    operations.cc:2384-2422). Where the reference spawns the background
    coordinator thread and calls ``MPI_Init_thread``, we:

      1. optionally call ``jax.distributed.initialize`` (the MPI_Init
         equivalent — rendezvous of all host processes), driven either by
         explicit arguments or by the standard JAX env vars that our
         launcher (``horovod_tpu.runner``) exports;
      2. snapshot the device topology into meshes;
      3. start the native background runtime (done lazily by the ops layer).

    Safe to call multiple times (the reference's InitializeHorovodOnce uses
    an atomic guard, operations.cc:2388-2397).
    """
    global _topology
    with _lock:
        if _topology is not None:
            return _topology

        coord = coordinator_address or os.environ.get(
            "HOROVOD_TPU_COORDINATOR")
        nproc = num_processes or _env_int("HOROVOD_TPU_NUM_PROCESSES")
        pid = process_id if process_id is not None else _env_int(
            "HOROVOD_TPU_PROCESS_ID")
        if coord and (nproc or 0) > 1:
            # Multi-process CPU meshes (the pod-shape test/dev harness)
            # need a real CPU collectives implementation — without it,
            # some jaxlib versions build a CPU client that rejects
            # multi-process computations outright. Gloo is jaxlib's
            # bundled TCP implementation; the knob only affects CPU
            # client creation, so it is a no-op on TPU backends. Must
            # run before the first backend touch.
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except Exception:  # pragma: no cover - jax API drift
                pass
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=nproc,
                process_id=pid,
            )
            if jax.process_count() != nproc:
                # Split-brain guard: initialize() can "succeed" while the
                # platform plugin ignores the distributed config (seen
                # with a sitecustomize-pinned platform that was already
                # initialized). Every worker then believes it is rank 0
                # of 1 while the launcher env says N — rank-0-only work
                # (checkpoints, ETL) runs N times and races on shared
                # paths. Fail loudly instead.
                raise RuntimeError(
                    f"launcher requested {nproc} processes but the JAX "
                    f"backend initialized with process_count="
                    f"{jax.process_count()} — the platform plugin "
                    "ignored the distributed config. On hosts whose "
                    "sitecustomize pins a platform, set "
                    "jax.config.update('jax_platforms', ...) (or the "
                    "JAX_PLATFORMS env honored before first jax use) "
                    "ahead of hvd.init().")

        # Opt-in persistent XLA compilation cache: TPU compiles of a big
        # training step cost tens of seconds and are identical across
        # restarts of the same job — a restart-heavy workflow (the
        # rank-0-checkpoint convention, SURVEY.md §5.4) should not pay
        # them twice. Off by default: the cache directory must be
        # per-user/per-cluster policy, not a framework guess.
        cache_dir = os.environ.get("HOROVOD_TPU_COMPILE_CACHE")
        if cache_dir:
            try:
                jax.config.update("jax_compilation_cache_dir", cache_dir)
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 1.0)
            except Exception:  # pragma: no cover - jax API drift
                pass

        devs = tuple(devices) if devices is not None else tuple(jax.devices())
        _topology = _build_topology(
            devs, jax.process_index(), jax.process_count())
    # Telemetry exporters (docs/metrics.md): env-driven, idempotent,
    # no-op unless HOROVOD_TPU_METRICS_FILE / _PORT is set. Outside the
    # lock — the exporter reads topology through the public path.
    try:
        from .observability import maybe_start_exporters
        maybe_start_exporters()
    except Exception as e:  # never fail init over telemetry
        from .utils.logging import get_logger
        get_logger("topology").warning("metrics exporters not started: %s",
                                       e)
    # Flight recorder (docs/postmortem.md): stamp the process identity
    # on the always-on ring, arm the crash hooks (excepthook + SIGTERM
    # final gasp — only when a blackbox dir or metrics file is
    # configured), and record the init event itself.
    try:
        from .observability import flight_recorder as _flight
        _flight.recorder().configure(_topology.process_index,
                                     _topology.process_count,
                                     _topology.generation)
        _flight.recorder().note("init", (
            _topology.process_index, _topology.process_count,
            _topology.generation))
        _flight.maybe_install_hooks()
    except Exception as e:  # never fail init over telemetry
        from .utils.logging import get_logger
        get_logger("topology").warning("flight recorder not armed: %s", e)
    # Telemetry history + health detectors (docs/health.md): env-driven
    # (HOROVOD_TPU_HISTORY), idempotent, rides the shared telemetry
    # timer thread — the trend-aware plane the live gauges cannot be.
    try:
        from .observability import history as _history
        _history.maybe_start_sampler()
    except Exception as e:  # never fail init over telemetry
        from .utils.logging import get_logger
        get_logger("topology").warning("history sampler not started: %s",
                                       e)
    # Numerics plane (docs/numerics.md): env-driven single-flag arm —
    # nonfinite sentinels, gradient telemetry and fingerprint probes
    # all hang off this one module flag.
    try:
        from .observability import numerics as _numerics
        _numerics.maybe_enable_from_env()
    except Exception as e:  # never fail init over telemetry
        from .utils.logging import get_logger
        get_logger("topology").warning("numerics plane not armed: %s", e)
    return _topology


def shutdown() -> None:
    """Tear down the runtime (operations.cc:2425-2430 equivalent).

    Registered with ``atexit`` by the ops layer the same way the reference's
    Python basics register shutdown (horovod/common/__init__.py:69).
    """
    global _topology
    with _lock:
        _topology = None


def is_initialized() -> bool:
    return _topology is not None


def _get() -> Topology:
    if _topology is None:
        raise NotInitializedError(_NOT_INITIALIZED_MSG)
    return _topology


def topology() -> Topology:
    """The full topology snapshot (no reference equivalent — TPU extra)."""
    return _get()


def rank() -> int:
    """Global virtual rank of this process's leader device
    (operations.cc:2433-2438)."""
    return _get().rank


def local_rank() -> int:
    """Local rank within the host (operations.cc:2440-2445)."""
    return _get().local_rank


def size() -> int:
    """Total number of devices — the parity of "number of GPU ranks"
    (operations.cc:2447-2452)."""
    return _get().size


def local_size() -> int:
    """Number of devices driven by this process (operations.cc:2454-2460)."""
    return _get().local_size


def process_rank() -> int:
    """Host-process index (TPU-native extra; JAX ``process_index``)."""
    return _get().process_index


def process_count() -> int:
    """Host-process count (TPU-native extra; JAX ``process_count``)."""
    return _get().process_count


def mesh() -> Mesh:
    """The flat world mesh, axis name ``'dp'`` (the "world communicator")."""
    return _get().mesh


def hierarchical_mesh() -> Mesh:
    """The ``('dcn', 'ici')`` mesh (the local/cross communicator split)."""
    return _get().hier_mesh


def generation() -> int:
    """Elastic generation of this job (TPU-native extra): 0 on the first
    launch, incremented by the elastic driver on every recovery
    relaunch. See :mod:`horovod_tpu.elastic`."""
    return _get().generation


def mpi_threads_supported() -> bool:
    """Compatibility shim for ``hvd.mpi_threads_supported()``
    (operations.cc:2462-2468). There is no MPI on the TPU path; the JAX
    runtime is always safe to call from multiple Python threads, so this
    reports True after init."""
    _get()
    return True


def _env_int(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v else None
