"""Closed-loop adaptation policy — the rank-0 control loop that ACTS on
the straggler telemetry PR 5 only measured.

The coordinator's :class:`~horovod_tpu.ops.control_plane._SkewTracker`
already elects a straggler and quantifies its decay-weighted negotiate
lateness. This module turns that signal into graceful degradation: on
sustained lateness above a threshold the policy climbs a ladder of
tiers, each trading a little fidelity or fusion efficiency for less
time spent waiting on the slowest rank —

  ``shrink``      cut the fusion threshold (smaller fused groups →
                  shorter quanta → less head-of-line blocking behind a
                  late announce),
  ``bf16``        transport allreduce groups as bf16 casts,
  ``int8x256``    block-scaled int8 quantized wire (EQuARX-style,
                  riding the existing ``wire=`` fused path),
  ``fp8x256``     block-scaled fp8 wire — the most aggressive format,
  ``evict``       mark the straggler unhealthy: a ``slow_rank`` failure
                  event ships through the fetch side-channel, every
                  engine fails its pending handles with a typed
                  :class:`~horovod_tpu.elastic.failure.SlowRankFailure`,
                  and the elastic driver re-rendezvouses without the
                  host — a fleet-wide stall becomes a bounded
                  throughput dip.

Every transition is hysteresis-guarded: escalation requires the
lateness to stay above ``threshold_s`` for ``sustain_s`` (per step of
the ladder), de-escalation requires it below ``threshold_s *
deescalate_ratio`` for ``cooldown_s`` (per step, reverse order — the
ladder unwinds monotonically). Between the two bands the clocks reset,
so a borderline-slow rank produces NO flapping. Transitions are logged
as structured ``adaptation_event`` lines and exported as
``hvdtpu_adaptation_*`` metrics so the trace CLI and dashboards can
show *when* the system adapted.

The policy itself is a pure, deterministically-testable state machine
(:meth:`AdaptationPolicy.observe` takes the lateness map and a
timestamp and returns transition events); the coordinator glue that
applies the events lives in ops/control_plane.py.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ..observability import flight_recorder as _flight
from ..observability import registry as _obs
from ..utils import env as _env
from ..utils.logging import get_logger

_log = get_logger("adaptation.policy")

# Ladder entries that select a wire transport (vs structural actions).
_WIRE_TIERS = ("bf16", "int8x256", "fp8x256")
DEFAULT_TIERS = ("shrink", "bf16", "int8x256", "fp8x256", "evict")


@dataclasses.dataclass
class AdaptationConfig:
    """Knobs of the degradation ladder (env: HOROVOD_TPU_ADAPT_*)."""

    threshold_s: float = 0.1       # lateness that starts the sustain clock
    sustain_s: float = 5.0         # above threshold this long per escalation
    cooldown_s: float = 30.0       # below the low band this long per de-esc
    interval_s: float = 1.0        # evaluation cadence
    deescalate_ratio: float = 0.5  # low band = threshold * ratio
    shrink_factor: int = 4         # fusion-threshold divisor for 'shrink'
    alert_hold_s: float = 30.0     # how long a health alert keeps pressure
    tiers: Tuple[str, ...] = DEFAULT_TIERS

    @classmethod
    def from_env(cls) -> "AdaptationConfig":
        tiers = _env.adapt_tiers()
        return cls(
            threshold_s=_env.adapt_threshold_s(),
            sustain_s=_env.adapt_sustain_s(),
            cooldown_s=_env.adapt_cooldown_s(),
            interval_s=_env.adapt_interval_s(),
            alert_hold_s=_env.adapt_alert_hold_s(),
            tiers=tuple(t.strip() for t in tiers.split(",") if t.strip())
            if tiers else DEFAULT_TIERS)


class AdaptationPolicy:
    """Hysteresis-guarded tier ladder over the straggler-lateness signal.

    ``tier`` is 0 (baseline) .. len(tiers); tier k means tiers[:k] are
    active. ``observe(lateness_by_rank, now)`` advances the state
    machine and returns the transitions taken this call as event dicts
    (``{"action", "tier", "name", "rank", "lateness_s"}``) — the
    coordinator applies them; tests drive it with synthetic clocks."""

    def __init__(self, config: Optional[AdaptationConfig] = None,
                 allow_evict: bool = True):
        self.config = config or AdaptationConfig()
        # Eviction needs the elastic failure plane (it kills the job on
        # a fixed-world run); the coordinator passes allow_evict=False
        # when HOROVOD_TPU_FAILURE_TIMEOUT is not armed.
        self.allow_evict = allow_evict
        self.tier = 0
        self.evicted: set = set()
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None
        r = _obs.registry()
        self._m_tier = r.gauge(
            "hvdtpu_adaptation_tier",
            "Active degradation tier (0 = baseline; N = the first N "
            "ladder entries are active)").labels()
        self._m_transitions = r.counter(
            "hvdtpu_adaptation_transitions_total",
            "Degradation-ladder transitions, by direction and tier name")
        self._m_lateness = r.gauge(
            "hvdtpu_adaptation_lateness_seconds",
            "Worst-rank decayed lateness at the last policy "
            "evaluation").labels()
        self._m_straggler = r.gauge(
            "hvdtpu_adaptation_straggler_rank",
            "Rank the policy currently considers the straggler "
            "(-1: none)").labels()
        self._m_wire = r.gauge(
            "hvdtpu_adaptation_wire_active",
            "1 for the wire spec the policy currently imposes on fused "
            "allreduce groups (raw = no override)")
        self._m_evictions = r.counter(
            "hvdtpu_adaptation_evictions_total",
            "Slow-rank evictions requested by the policy, by rank")
        self._m_alert_inputs = r.counter(
            "hvdtpu_adaptation_alert_inputs_total",
            "Health alerts consumed as ladder inputs, by alert kind "
            "(docs/health.md#adaptation)")
        self._m_tier.set(0)
        self._m_straggler.set(-1)
        self._set_wire_gauge()
        # Health-alert pressure (docs/health.md#adaptation): a
        # regression/leak alert keeps the named rank's effective
        # lateness at the threshold for alert_hold_s — it can START
        # the sustain clock but never bypass the hysteresis.
        self._alert_until: Dict[Tuple[str, int], float] = {}
        # quantization_drift quality backoff (docs/numerics.md#drift):
        # while this clock runs, the ladder refuses to re-enter a wire
        # tier — the lossy transport stays off until drift clears.
        self._wire_block_until: float = 0.0

    # ----------------------------------------------------------- derived

    def active_tiers(self) -> Tuple[str, ...]:
        return self.config.tiers[: self.tier]

    def wire_spec(self) -> Optional[str]:
        """Wire transport the current tier imposes (the STRONGEST active
        wire entry), or None for raw."""
        spec = None
        for t in self.active_tiers():
            if t in _WIRE_TIERS:
                spec = t
        return spec

    def shrink_active(self) -> bool:
        return "shrink" in self.active_tiers()

    def _set_wire_gauge(self) -> None:
        self._m_wire.clear()
        self._m_wire.labels(spec=self.wire_spec() or "raw").set(1)

    # ------------------------------------------------------------- alerts

    def note_alert(self, kind: str, rank: int, now: float) -> None:
        """Record one health alert (docs/health.md#adaptation) as
        ladder pressure: for ``alert_hold_s`` after this call the named
        rank's effective lateness is clamped to at least
        ``threshold_s``, so a sustained regression/leak walks the same
        hysteresis-guarded ladder as measured negotiate lateness — and
        a one-off alert that is not renewed decays without ever
        escalating. Unknown kinds are accepted (forward compat) but
        only regression/leak kinds are ever forwarded here.

        ``quantization_drift`` is special-cased as the QUALITY
        direction (docs/numerics.md#drift): the quantized wire is the
        suspected *cause*, so instead of adding escalation pressure the
        policy unwinds every active wire tier back to the raw fp32
        transport and blocks wire re-escalation for ``alert_hold_s``."""
        if str(kind) == "quantization_drift":
            self._m_alert_inputs.labels(kind=str(kind)).inc()
            self._quality_backoff(int(rank), now)
            return
        self._alert_until[(str(kind), int(rank))] = \
            now + self.config.alert_hold_s
        self._m_alert_inputs.labels(kind=str(kind)).inc()
        _log.warning(
            "adaptation_event action=alert_input kind=%s rank=%d",
            kind, rank)
        _flight.recorder().note("adapt", (
            "alert_input", self.tier, str(kind), int(rank), 0.0))

    def _quality_backoff(self, rank: int, now: float) -> None:
        """Back the quantized wire off to raw fp32: drop ladder tiers
        until no wire entry is active (structural tiers such as
        ``shrink`` below the wire rungs survive), and refuse to
        re-enter a wire tier until the block window expires. Repeated
        drift alerts renew the window, so a genuinely lossy wire stays
        off as long as the detector keeps firing."""
        self._wire_block_until = now + self.config.alert_hold_s
        new_tier = self.tier
        while new_tier > 0 and any(
                t in _WIRE_TIERS for t in self.config.tiers[:new_tier]):
            new_tier -= 1
        if new_tier == self.tier:
            _log.warning(
                "adaptation_event action=quality_block rank=%d "
                "hold_s=%.1f", rank, self.config.alert_hold_s)
            return
        dropped = self.config.tiers[new_tier:self.tier]
        self.tier = new_tier
        self._m_tier.set(self.tier)
        for name in dropped:
            self._m_transitions.labels(
                action="quality_backoff", tier=name).inc()
        self._set_wire_gauge()
        _log.warning(
            "adaptation_event action=quality_backoff tier=%d dropped=%s "
            "rank=%d hold_s=%.1f", self.tier, ",".join(dropped), rank,
            self.config.alert_hold_s)
        _flight.recorder().note("adapt", (
            "quality_backoff", self.tier, ",".join(dropped), rank, 0.0))

    def _alert_pressure(self, now: float) -> Dict[int, float]:
        """Per-rank synthetic lateness from alerts still inside their
        hold window (expired entries are pruned)."""
        expired = [k for k, until in self._alert_until.items()
                   if until < now]
        for k in expired:
            del self._alert_until[k]
        out: Dict[int, float] = {}
        for (_, rank), _until in self._alert_until.items():
            if rank >= 0:
                out[rank] = self.config.threshold_s
        return out

    # ------------------------------------------------------------- clock

    def observe(self, lateness_by_rank: Dict[int, float],
                now: float) -> List[dict]:
        """Advance the ladder given the current per-rank decayed
        lateness; returns the transition events taken (possibly empty,
        never more than one per call — one hysteresis window per
        step keeps the escalation rate bounded and observable)."""
        cfg = self.config
        merged = dict(lateness_by_rank)
        for rank, floor in self._alert_pressure(now).items():
            merged[rank] = max(merged.get(rank, 0.0), floor)
        live = {r: v for r, v in merged.items()
                if r not in self.evicted}
        worst_rank = max(live, key=live.get) if live else -1
        lateness = live.get(worst_rank, 0.0)
        self._m_lateness.set(lateness)
        self._m_straggler.set(worst_rank if lateness > 0 else -1)

        events: List[dict] = []
        if lateness >= cfg.threshold_s:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
            elif now - self._above_since >= cfg.sustain_s:
                ev = self._escalate(worst_rank, lateness, now)
                if ev is not None:
                    events.append(ev)
                # Each further step needs its own full sustain window.
                self._above_since = now
        elif lateness < cfg.threshold_s * cfg.deescalate_ratio:
            self._above_since = None
            if self._below_since is None:
                self._below_since = now
            elif now - self._below_since >= cfg.cooldown_s:
                ev = self._deescalate(lateness, now)
                if ev is not None:
                    events.append(ev)
                self._below_since = now
        else:
            # Hysteresis band: hold state, restart both clocks — a
            # borderline-slow rank neither escalates nor unwinds.
            self._above_since = None
            self._below_since = None
        return events

    def _escalate(self, rank: int, lateness: float, now: float
                  ) -> Optional[dict]:
        if self.tier >= len(self.config.tiers):
            return None
        name = self.config.tiers[self.tier]
        if name in _WIRE_TIERS and now < self._wire_block_until:
            # Quality backoff in force: the ladder is capped below the
            # wire rungs until the drift hold window expires.
            return None
        if name == "evict":
            if not self.allow_evict or rank < 0:
                return None   # ladder capped below eviction
            # Edge-triggered, NOT a persistent tier: the straggler is
            # removed from the signal, the degradation tiers below stay
            # until the cooldown unwinds them, and a SECOND straggler
            # sustaining lateness earns its own eviction after its own
            # sustain window.
            self.evicted.add(rank)
            self._m_evictions.labels(rank=str(rank)).inc()
            self._m_transitions.labels(action="escalate", tier=name).inc()
            _log.warning(
                "adaptation_event action=evict rank=%d lateness_ms=%.1f",
                rank, lateness * 1e3)
            _flight.recorder().note("adapt", (
                "evict", self.tier, name, rank,
                round(lateness * 1e3, 3)))
            return {"action": "escalate", "tier": self.tier,
                    "name": name, "rank": rank, "lateness_s": lateness}
        self.tier += 1
        self._m_tier.set(self.tier)
        self._m_transitions.labels(action="escalate", tier=name).inc()
        ev = {"action": "escalate", "tier": self.tier, "name": name,
              "rank": rank, "lateness_s": lateness}
        self._set_wire_gauge()
        _log.warning(
            "adaptation_event action=escalate tier=%d name=%s rank=%d "
            "lateness_ms=%.1f", self.tier, name, rank, lateness * 1e3)
        _flight.recorder().note("adapt", (
            "escalate", self.tier, name, rank, round(lateness * 1e3, 3)))
        return ev

    def _deescalate(self, lateness: float, now: float) -> Optional[dict]:
        if self.tier <= 0:
            return None
        name = self.config.tiers[self.tier - 1]
        if name == "evict":
            # Eviction is not unwound by the ladder — readmission is the
            # elastic driver's probe/backoff story (docs/elastic.md).
            return None
        self.tier -= 1
        self._m_tier.set(self.tier)
        self._m_transitions.labels(action="deescalate", tier=name).inc()
        self._set_wire_gauge()
        _log.warning(
            "adaptation_event action=deescalate tier=%d dropped=%s "
            "lateness_ms=%.1f", self.tier, name, lateness * 1e3)
        _flight.recorder().note("adapt", (
            "deescalate", self.tier, name, -1, round(lateness * 1e3, 3)))
        return {"action": "deescalate", "tier": self.tier, "name": name,
                "rank": -1, "lateness_s": lateness}
