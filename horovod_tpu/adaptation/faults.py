"""Declarative fault injection — the chaos half of the self-healing loop.

A robustness claim is only as good as the faults it was proven against,
and real fleets misbehave in ways unit mocks don't: a rank that is
*slow* rather than dead, a worker whose control-plane announces stop
arriving while its heartbeat stays alive, a process that dies mid-step.
This module makes those scenarios first-class and **deterministic**: a
declarative per-rank spec (``HOROVOD_TPU_FAULT_SPEC``) is parsed once,
resolved against this process's rank and elastic generation, and hooked
into exactly three places — the engine enqueue path
(``CollectiveEngine.enqueue``), the coordinator announce path
(``CoordinatorClient``), and — through the env contract the elastic
driver already propagates — every relaunched worker generation.

Grammar (clauses separated by ``;``, fields by ``:``)::

    spec   := clause (';' clause)*
    clause := field (':' field)*
    field  := key ['=' value]

    rank=N | rank=*        which process rank the clause targets (required)
    gen=N                  only active in elastic generation N (default: all)
    from_step=N            first active tick (default 0)
    until_step=N           first tick past the window (default: unbounded)
    delay=80ms             sleep per enqueued collective (the slow rank)
    slow_h2d=2ms           extra sleep modeling a slow host→device path
    crash_at=N             SIGKILL self at tick N (the host-loss fault)
    drop_announce          suppress coordinator announces while active
                           (mute worker: fetch heartbeat stays alive, so
                           only the stall detector can name it)
    replica_crash_at=N     serving: SIGKILL self at decode tick N (the
                           hard replica-loss fault the fleet router's
                           failover is proven against)
    slow_decode=50ms       serving: sleep per batched decode step
    slow_prefill=200ms     serving: sleep per prefill forward (widens
                           the drain/prefill race window determinist-
                           ically)
    drop_health            serving: /healthz and /readyz hang up without
                           answering while active (a live-locked front
                           end only the prober can catch)
    long_prompt_burst=NxL  serving: once the serving tick enters the
                           clause window, submit N synthetic requests
                           with deterministic L-token prompts through
                           the engine's own admission gate (bare ``=L``
                           means N=1) — the adversarial long+short
                           prompt mix the chunked-prefill latency bound
                           is proven against. Fires once per clause.
    nan_at=N               poison ONE element of the gradient tensor
                           enqueued at tick N with NaN (the overnight-
                           NaN corruption the numerics plane's same-
                           step sentinel is proven against,
                           docs/numerics.md). Fires once per clause.
    bitflip_param=N        flip one mantissa bit of element 0 of a
                           param leaf at training step N — the silent-
                           data-corruption fault the cross-rank
                           fingerprint compare catches. ``leaf=NAME``
                           picks the first leaf whose path contains
                           NAME (default: the first leaf). Fires once
                           per clause; applied by the training loop's
                           numerics hook (observability/numerics.py).
    leaf=NAME              target-leaf substring for bitflip_param.

A *tick* is one enqueued collective on this rank — for the common
one-fused-allreduce-per-step training loop, tick == training step. The
serving clauses count their own tick stream: one tick per batched
decode step (``serving`` processes run no training collectives). In a
fleet (docs/serving.md#fleet), ``rank`` is the replica id
(``HOROVOD_TPU_REPLICA_ID``, exported by the supervisor) and ``gen``
the replica's restart incarnation — ``rank=1:replica_crash_at=30:gen=0``
crashes replica 1 once and lets its restart run clean.

Examples::

    HOROVOD_TPU_FAULT_SPEC="rank=2:delay=80ms:from_step=50"
    HOROVOD_TPU_FAULT_SPEC="rank=1:crash_at=30:gen=0"
    HOROVOD_TPU_FAULT_SPEC="rank=3:drop_announce:from_step=5; rank=0:slow_h2d=2ms"

Design constraints:

  - OFF BY DEFAULT, ZERO HOT-PATH COST WHEN UNSET: with no spec the
    process-global injector resolves to ``None`` once and the engine's
    enqueue path carries a single ``is None`` check.
  - DETERMINISTIC: ticks count enqueues (not wall time), windows are
    half-open integer ranges, and the spec is resolved once per process
    — two runs with the same spec and program inject identically.
  - OBSERVABLE: every injected fault increments
    ``hvdtpu_fault_injections_total{kind=}`` so traces/benches can
    correlate anomalies with injections.
"""

from __future__ import annotations

import os
import signal
import time
from typing import List, Optional, Tuple

from ..utils.logging import get_logger

_log = get_logger("adaptation.faults")

FAULT_SPEC_ENV = "HOROVOD_TPU_FAULT_SPEC"

_DURATION_UNITS = (("ms", 1e-3), ("us", 1e-6), ("s", 1.0))


def _parse_duration(value: str) -> float:
    v = value.strip().lower()
    for suffix, mult in _DURATION_UNITS:
        if v.endswith(suffix):
            return float(v[: -len(suffix)]) * mult
    return float(v)  # bare number = seconds


class FaultClause:
    """One parsed clause of the spec — a set of faults targeted at one
    rank (or ``*``) over one tick window (and optionally one elastic
    generation)."""

    __slots__ = ("rank", "gen", "from_step", "until_step", "delay_s",
                 "slow_h2d_s", "crash_at", "drop_announce",
                 "replica_crash_at", "slow_decode_s", "slow_prefill_s",
                 "drop_health", "long_prompt_burst", "nan_at",
                 "bitflip_param", "leaf")

    def __init__(self):
        self.rank: Optional[int] = None        # None == '*'
        self.gen: Optional[int] = None
        self.from_step = 0
        self.until_step: Optional[int] = None
        self.delay_s = 0.0
        self.slow_h2d_s = 0.0
        self.crash_at: Optional[int] = None
        self.drop_announce = False
        self.replica_crash_at: Optional[int] = None
        self.slow_decode_s = 0.0
        self.slow_prefill_s = 0.0
        self.drop_health = False
        self.long_prompt_burst: Optional[Tuple[int, int]] = None  # (N, L)
        self.nan_at: Optional[int] = None
        self.bitflip_param: Optional[int] = None
        self.leaf = ""                         # bitflip target substring

    def matches(self, rank: int, generation: int) -> bool:
        if self.rank is not None and self.rank != rank:
            return False
        if self.gen is not None and self.gen != generation:
            return False
        return True

    def in_window(self, tick: int) -> bool:
        if tick < self.from_step:
            return False
        return self.until_step is None or tick < self.until_step

    def __repr__(self):  # readable in logs/tests
        parts = [f"rank={'*' if self.rank is None else self.rank}"]
        if self.gen is not None:
            parts.append(f"gen={self.gen}")
        if self.delay_s:
            parts.append(f"delay={self.delay_s * 1e3:g}ms")
        if self.slow_h2d_s:
            parts.append(f"slow_h2d={self.slow_h2d_s * 1e3:g}ms")
        if self.crash_at is not None:
            parts.append(f"crash_at={self.crash_at}")
        if self.drop_announce:
            parts.append("drop_announce")
        if self.replica_crash_at is not None:
            parts.append(f"replica_crash_at={self.replica_crash_at}")
        if self.slow_decode_s:
            parts.append(f"slow_decode={self.slow_decode_s * 1e3:g}ms")
        if self.slow_prefill_s:
            parts.append(f"slow_prefill={self.slow_prefill_s * 1e3:g}ms")
        if self.drop_health:
            parts.append("drop_health")
        if self.long_prompt_burst is not None:
            n, plen = self.long_prompt_burst
            parts.append(f"long_prompt_burst={n}x{plen}")
        if self.nan_at is not None:
            parts.append(f"nan_at={self.nan_at}")
        if self.bitflip_param is not None:
            parts.append(f"bitflip_param={self.bitflip_param}")
            if self.leaf:
                parts.append(f"leaf={self.leaf}")
        if self.from_step:
            parts.append(f"from_step={self.from_step}")
        if self.until_step is not None:
            parts.append(f"until_step={self.until_step}")
        return ":".join(parts)


def parse_spec(text: str) -> List[FaultClause]:
    """Parse a full ``HOROVOD_TPU_FAULT_SPEC`` value. Malformed specs
    raise ``ValueError`` naming the offending field — a typo'd fault
    harness must fail loudly at startup, not silently inject nothing."""
    clauses: List[FaultClause] = []
    for raw in text.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        c = FaultClause()
        saw_rank = False
        for field in raw.split(":"):
            field = field.strip()
            if not field:
                continue
            key, sep, value = field.partition("=")
            key = key.strip()
            value = value.strip()
            if key == "rank":
                saw_rank = True
                c.rank = None if value == "*" else int(value)
            elif key == "gen":
                c.gen = int(value)
            elif key == "from_step":
                c.from_step = int(value)
            elif key == "until_step":
                c.until_step = int(value)
            elif key == "delay":
                c.delay_s = _parse_duration(value)
            elif key == "slow_h2d":
                c.slow_h2d_s = _parse_duration(value)
            elif key == "crash_at":
                c.crash_at = int(value)
            elif key == "drop_announce":
                if sep and value not in ("", "1", "true"):
                    raise ValueError(
                        f"drop_announce takes no value, got {value!r}")
                c.drop_announce = True
            elif key == "replica_crash_at":
                c.replica_crash_at = int(value)
            elif key == "slow_decode":
                c.slow_decode_s = _parse_duration(value)
            elif key == "slow_prefill":
                c.slow_prefill_s = _parse_duration(value)
            elif key == "drop_health":
                if sep and value not in ("", "1", "true"):
                    raise ValueError(
                        f"drop_health takes no value, got {value!r}")
                c.drop_health = True
            elif key == "long_prompt_burst":
                # "NxL" (N prompts of L tokens) or bare "L" (one).
                n_s, x, l_s = value.partition("x")
                try:
                    n, plen = (int(n_s), int(l_s)) if x \
                        else (1, int(n_s))
                except ValueError:
                    raise ValueError(
                        f"long_prompt_burst wants NxL or L (prompt "
                        f"tokens), got {value!r}") from None
                if n < 1 or plen < 1:
                    raise ValueError(
                        f"long_prompt_burst counts must be >= 1, "
                        f"got {value!r}")
                c.long_prompt_burst = (n, plen)
            elif key == "nan_at":
                c.nan_at = int(value)
            elif key == "bitflip_param":
                c.bitflip_param = int(value)
            elif key == "leaf":
                c.leaf = value
            else:
                raise ValueError(
                    f"unknown fault-spec field {key!r} in clause {raw!r} "
                    "(expected rank/gen/from_step/until_step/delay/"
                    "slow_h2d/crash_at/drop_announce/replica_crash_at/"
                    "slow_decode/slow_prefill/drop_health/"
                    "long_prompt_burst/nan_at/bitflip_param/leaf)")
        if not saw_rank:
            raise ValueError(
                f"fault-spec clause {raw!r} is missing the required "
                "rank= field (use rank=* to target every rank)")
        clauses.append(c)
    return clauses


def _poison_one_nan(tensor):
    """Copy ``tensor`` with element 0 set to NaN, preserving the
    caller's array flavor (numpy stays numpy; anything else — a jax
    array — comes back as a jax array). Integer payloads cannot carry
    a NaN and return None (the clause is a silent no-op on them)."""
    import numpy as np
    a = np.array(np.asarray(tensor), copy=True)
    if not np.issubdtype(a.dtype, np.floating):
        return None
    a.reshape(-1)[0] = np.nan
    if isinstance(tensor, np.ndarray):
        return a
    import jax.numpy as jnp
    return jnp.asarray(a)


class FaultInjector:
    """Per-process injector: the clauses of the spec that target this
    (rank, generation), plus the tick counter the windows are evaluated
    against. Hook points:

      - :meth:`on_enqueue` — the engine calls this once per enqueued
        collective (delay / slow_h2d / crash_at).
      - :meth:`drop_announce_active` — the coordinator client consults
        this before each announce leg (mute-worker fault).
      - :meth:`on_serving_decode` / :meth:`on_serving_prefill` — the
        inference engine's scheduler (slow_decode / slow_prefill /
        replica_crash_at; decode steps drive the serving tick stream).
      - :meth:`drop_health_active` — the serving HTTP front consults
        this before answering /healthz and /readyz.
    """

    def __init__(self, clauses: List[FaultClause], rank: int,
                 generation: int = 0):
        self.rank = int(rank)
        self.generation = int(generation)
        self.clauses = [c for c in clauses
                        if c.matches(self.rank, self.generation)]
        self._tick = 0
        self._serving_tick = 0
        # Metric handles resolved once (docs/metrics.md); label children
        # cached since the kinds are a tiny fixed set.
        from ..observability import registry as _obs
        fam = _obs.registry().counter(
            "hvdtpu_fault_injections_total",
            "Faults injected by the HOROVOD_TPU_FAULT_SPEC harness, "
            "by kind")
        self._m = {k: fam.labels(kind=k)
                   for k in ("delay", "slow_h2d", "crash", "drop_announce",
                             "replica_crash", "slow_decode",
                             "slow_prefill", "drop_health",
                             "long_prompt_burst", "nan", "bitflip")}
        self._bursts_fired: set = set()  # clause indices already fired
        self._nans_fired: set = set()    # nan_at clause indices fired
        self._flips_fired: set = set()   # bitflip clause indices fired
        if self.clauses:
            _log.warning("fault injection ARMED for rank %d gen %d: %s",
                         self.rank, self.generation,
                         "; ".join(map(repr, self.clauses)))

    @property
    def tick(self) -> int:
        return self._tick

    def _note_fault(self, kind: str, tick: int) -> None:
        """Flight-recorder breadcrumb, at most once per (kind, window
        entry): a per-enqueue event for an 80 ms delay fault would be
        noise; the postmortem only needs to know the fault was ACTIVE."""
        if tick % 50 == 0 or tick == 0:
            from ..observability import flight_recorder as _flight
            _flight.recorder().note("fault", (kind, tick))

    def on_enqueue(self, tensor=None):
        """One collective enqueued: advance the tick and apply any
        active delay/slow_h2d/crash/nan_at faults. When a ``nan_at``
        clause fires and the engine handed us its payload ``tensor``,
        returns a poisoned replacement (one element set to NaN) the
        engine assigns back; returns None otherwise — callers that
        pass no tensor keep the legacy no-return contract."""
        t = self._tick
        self._tick = t + 1
        poisoned = None
        for i, c in enumerate(self.clauses):
            if (c.nan_at is not None and t == c.nan_at
                    and i not in self._nans_fired
                    and tensor is not None):
                self._nans_fired.add(i)
                self._m["nan"].inc()
                _log.error("fault injection: nan_at=%d reached on "
                           "rank %d — poisoning one gradient element",
                           t, self.rank)
                from ..observability import flight_recorder as _flight
                _flight.recorder().note("fault", ("nan", t))
                poisoned = _poison_one_nan(tensor)
        for c in self.clauses:
            if c.crash_at is not None and t == c.crash_at:
                self._m["crash"].inc()
                _log.error("fault injection: crash_at=%d reached on "
                           "rank %d — SIGKILL self", t, self.rank)
                # Final gasp: a SIGKILL leaves no excepthook/signal
                # window, but the injector KNOWS it is about to die —
                # dump the flight recorder + metrics first, exactly what
                # a real deployment's host agent cannot do for a kernel
                # kill (docs/postmortem.md).
                from ..observability import flight_recorder as _flight
                _flight.recorder().note("fault", ("crash", t))
                _flight.dump_on("fault_crash")
                os.kill(os.getpid(), signal.SIGKILL)
            if not c.in_window(t):
                continue
            if c.delay_s > 0.0:
                self._m["delay"].inc()
                self._note_fault("delay", t)
                time.sleep(c.delay_s)
            if c.slow_h2d_s > 0.0:
                self._m["slow_h2d"].inc()
                self._note_fault("slow_h2d", t)
                time.sleep(c.slow_h2d_s)
        return poisoned

    def take_bitflips(self, step: int) -> List[str]:
        """Target-leaf patterns of ``bitflip_param`` clauses firing at
        this training step — each fires ONCE; the numerics plane's
        training hook (observability/numerics.py ``maybe_bitflip``)
        applies the flip, since only it holds the param tree."""
        out: List[str] = []
        for i, c in enumerate(self.clauses):
            if c.bitflip_param is None or i in self._flips_fired:
                continue
            if step != c.bitflip_param:
                continue
            self._flips_fired.add(i)
            self._m["bitflip"].inc()
            from ..observability import flight_recorder as _flight
            _flight.recorder().note("fault", ("bitflip", step))
            out.append(c.leaf)
        return out

    def drop_announce_active(self) -> bool:
        """True while a drop_announce clause's window covers the current
        tick — the coordinator client then suppresses the announce leg
        (the fetch heartbeat deliberately stays alive: only the stall
        detector can catch a mute-but-breathing worker)."""
        for c in self.clauses:
            if c.drop_announce and c.in_window(self._tick):
                self._m["drop_announce"].inc()
                return True
        return False

    # ------------------------------------------------- serving hook points

    @property
    def serving_tick(self) -> int:
        return self._serving_tick

    def _sigkill_self(self, kind: str, tick: int) -> None:
        self._m[kind].inc()
        _log.error("fault injection: %s reached at serving tick %d on "
                   "replica %d — SIGKILL self", kind, tick, self.rank)
        from ..observability import flight_recorder as _flight
        _flight.recorder().note("fault", (kind, tick))
        _flight.dump_on("fault_crash")
        os.kill(os.getpid(), signal.SIGKILL)

    def on_serving_decode(self) -> None:
        """One batched decode step: advance the serving tick and apply
        slow_decode / replica_crash_at faults. The crash is a SIGKILL
        with the same final-gasp blackbox dump as crash_at — the
        postmortem tool names the replica from it."""
        t = self._serving_tick
        self._serving_tick = t + 1
        for c in self.clauses:
            if c.replica_crash_at is not None and t == c.replica_crash_at:
                self._sigkill_self("replica_crash", t)
            if not c.in_window(t):
                continue
            if c.slow_decode_s > 0.0:
                self._m["slow_decode"].inc()
                self._note_fault("slow_decode", t)
                time.sleep(c.slow_decode_s)

    def on_serving_prefill(self) -> None:
        """One prefill forward: apply slow_prefill (windowed on the
        serving tick; the tick itself only advances on decode steps, so
        a prefill burst cannot skip a replica_crash_at point)."""
        for c in self.clauses:
            if c.slow_prefill_s > 0.0 and c.in_window(self._serving_tick):
                self._m["slow_prefill"].inc()
                self._note_fault("slow_prefill", self._serving_tick)
                time.sleep(c.slow_prefill_s)

    def take_long_prompt_bursts(self) -> List[int]:
        """Prompt lengths to inject right now: each long_prompt_burst
        clause fires ONCE, at the first scheduler step whose serving
        tick falls in its window (the engine consults this at the top
        of every step and submits the synthetic requests itself — the
        injector has no engine handle). Windowed on the decode-driven
        serving tick like every other serving fault."""
        out: List[int] = []
        for i, c in enumerate(self.clauses):
            if c.long_prompt_burst is None or i in self._bursts_fired:
                continue
            if not c.in_window(self._serving_tick):
                continue
            self._bursts_fired.add(i)
            n, plen = c.long_prompt_burst
            self._m["long_prompt_burst"].inc(n)
            self._note_fault("long_prompt_burst", self._serving_tick)
            out.extend([plen] * n)
        return out

    def drop_health_active(self) -> bool:
        """True while a drop_health clause covers the current serving
        tick — the HTTP front then hangs up on /healthz and /readyz
        without a response, so only a probing supervisor can tell this
        replica from a healthy one."""
        for c in self.clauses:
            if c.drop_health and c.in_window(self._serving_tick):
                self._m["drop_health"].inc()
                return True
        return False


# ---------------------------------------------------------------------------
# Process-global resolution — once, lazily, off by default.
# ---------------------------------------------------------------------------

_resolved = False
_injector: Optional[FaultInjector] = None


def injector() -> Optional[FaultInjector]:
    """The process's injector, or None when HOROVOD_TPU_FAULT_SPEC is
    unset / targets other ranks. Resolved once; callers cache the result
    so the disabled path is a single ``is None`` check."""
    global _resolved, _injector
    if _resolved:
        return _injector
    from ..utils import env as _env
    text = _env.fault_spec()
    if not text:
        _resolved = True
        return None
    clauses = parse_spec(text)
    replica = os.environ.get("HOROVOD_TPU_REPLICA_ID")
    if replica not in (None, ""):
        # Serving-fleet replica: the supervisor exports the replica id
        # (and the restart incarnation as the generation) — a replica
        # process is always jax process 0, so topology cannot tell
        # replicas apart.
        rank = int(replica)
    else:
        try:
            from .. import topology as _topo
            rank = _topo._get().process_index
        except Exception:
            rank = int(os.environ.get("HOROVOD_TPU_PROCESS_ID", "0") or 0)
    gen = int(os.environ.get("HOROVOD_TPU_ELASTIC_GENERATION", "0") or 0)
    inj = FaultInjector(clauses, rank=rank, generation=gen)
    _injector = inj if inj.clauses else None
    _resolved = True
    return _injector


def reset() -> None:
    """Test hook: forget the resolved injector so the next ``injector()``
    call re-reads the env (mirrors reset_engine())."""
    global _resolved, _injector
    _resolved = False
    _injector = None
