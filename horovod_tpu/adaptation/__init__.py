"""Self-healing collective plane (docs/adaptation.md).

Two halves of one loop:

  - :mod:`.faults` — deterministic, declarative fault injection
    (``HOROVOD_TPU_FAULT_SPEC``): slow ranks, mute announces, crashes —
    the scenarios the adaptation machinery is proven against.
  - :mod:`.policy` — the rank-0 control loop that escalates
    graceful-degradation tiers (shrink fused groups → bf16 → int8 →
    fp8 wire → evict the straggler) on sustained
    ``hvdtpu_straggler_lateness``, hysteresis-guarded and exported as
    ``hvdtpu_adaptation_*`` metrics.

The coordinator (ops/control_plane.py) hosts the policy; the eviction
tier hands off to the elastic driver (elastic/driver.py) through a
typed :class:`~horovod_tpu.elastic.failure.SlowRankFailure`.
"""

from .faults import (FAULT_SPEC_ENV, FaultClause, FaultInjector, injector,
                     parse_spec)
from .policy import DEFAULT_TIERS, AdaptationConfig, AdaptationPolicy

__all__ = [
    "FAULT_SPEC_ENV", "FaultClause", "FaultInjector", "injector",
    "parse_spec", "AdaptationConfig", "AdaptationPolicy", "DEFAULT_TIERS",
]
