"""DistributedOptimizer and state broadcast — the L3 training API.

Reference parity:
  - ``hvd.DistributedOptimizer`` for torch (horovod/torch/__init__.py:42-151):
    hooks that allreduce each gradient as it becomes ready, ``synchronize()``
    flushing handles before ``step()``, ``backward_passes_per_step`` gradient
    accumulation (torch/__init__.py:71-73,114-130).
  - TF ``DistributedOptimizer.compute_gradients``
    (horovod/tensorflow/__init__.py:151-249) and
    ``DistributedGradientTape`` (252-326).
  - ``broadcast_parameters`` (torch/__init__.py:200-229) and
    ``broadcast_optimizer_state`` (torch/__init__.py:232-348).

TPU-native redesign: the idiomatic JAX optimizer is an optax
``GradientTransformation``; we provide

  - :class:`DistributedGradientTransformation` — wraps any optax optimizer;
    its ``update`` allreduce-averages the gradients first. Out of jit this
    goes through the eager engine (getting tensor fusion + timeline +
    autotune); inside jit/shard_map it lowers to ``lax.psum`` on the mesh
    axis so XLA schedules the collective (the preferred TPU path —
    SURVEY.md §5.8).
  - :func:`allreduce_gradients` — the bare gradient-averaging hook
    (TF ``DistributedGradientTape`` equivalent).
  - :func:`broadcast_parameters` / :func:`broadcast_optimizer_state` /
    :func:`broadcast_object` — state sync at (re)start, rank-0 convention
    (SURVEY.md §5.4).
"""

from __future__ import annotations

import pickle
from typing import Any, NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from . import quantization as _quant
from . import topology as _topo
from .compression import Compression
from .ops import collective as _coll


def _is_tracing(tree) -> bool:
    return any(isinstance(x, jax.core.Tracer)
               for x in jax.tree_util.tree_leaves(tree))


def _leaf_names(tree):
    paths_and_leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [jax.tree_util.keystr(path) for path, _ in paths_and_leaves]


def allreduce_gradients(grads, *, average: bool = True,
                        compression=Compression.none,
                        axis_name: str = "dp", name_prefix: str = "grad"):
    """Average a pytree of gradients over all ranks.

    Inside a jitted SPMD program: ``lax.psum`` over ``axis_name`` (XLA
    fuses/combines these — the compiler-native version of tensor fusion).
    Outside jit: one fused submission through the eager engine, mirroring
    ``DistributedOptimizer._allreduce_grad_async``
    (torch/__init__.py:106-112).
    """
    n = _topo.size()
    wire = getattr(compression, "wire_spec", None)
    if _is_tracing(grads):
        spec = _quant.parse(wire) if wire is not None else None

        def red(g):
            if spec is not None and jnp.issubdtype(g.dtype, jnp.floating):
                try:
                    # Dual block-quantized allreduce over the mapped
                    # axis — the in-jit spelling of the executor's
                    # quantized fused program.
                    s = _quant.quantized_psum(g, axis_name, spec)
                except NameError:
                    # Not under shard_map: grads are already global, no
                    # wire to quantize — identity (times n for sums).
                    return g * (1.0 if average else n)
                return s / n if average else s
            c, ctx = compression.compress(g)
            try:
                s = jax.lax.psum(c, axis_name)
            except NameError:
                # Not under shard_map/pmap with this axis: grads produced by
                # jit-over-sharded-data are already global; averaging is the
                # identity there because XLA inserted the psum at the point
                # the loss was reduced.
                s = c * (1.0 if average else n)
                return compression.decompress(s, ctx)
            if average:
                s = s / n
            return compression.decompress(s, ctx)
        return jax.tree_util.tree_map(red, grads)

    leaves, treedef = jax.tree_util.tree_flatten(grads)
    names = _leaf_names(grads)
    eng = _coll.engine()
    sfx = eng._next_name(name_prefix)
    handles = []
    # Explicit burst: the whole gradient set fuses as ONE deterministic
    # group — without the scope, an enqueuer descheduled mid-loop on a
    # busy host splits the burst into a timing-dependent composition,
    # recompiling the fused XLA program every step.
    with eng.burst():
        for nm, leaf in zip(names, leaves):
            if wire is not None:
                # Blockwise: submit at the logical dtype; the engine
                # plans wire bytes and the executor quantizes inside
                # the fused program.
                h = _coll.allreduce_async(jnp.asarray(leaf),
                                          average=average,
                                          name=f"{name_prefix}{nm}.{sfx}",
                                          compression=compression)
                handles.append((h, None))
                continue
            c, ctx = compression.compress(jnp.asarray(leaf))
            h = _coll.allreduce_async(c, average=average,
                                      name=f"{name_prefix}{nm}.{sfx}")
            handles.append((h, ctx))
    out = [compression.decompress(h.wait(), ctx) for h, ctx in handles]
    return jax.tree_util.tree_unflatten(treedef, out)


class _DistOptState(NamedTuple):
    inner: Any
    acc: Any            # gradient accumulation buffers
    counter: jnp.ndarray  # passes since last sync
    residual: Any = None  # error-feedback residual (lossy wire formats)


class DistributedGradientTransformation:
    """optax-style wrapper: allreduce grads, then run the inner optimizer.

    ``backward_passes_per_step > 1`` accumulates gradients locally for N
    calls and performs the (averaged) allreduce + inner update only on the
    Nth, mirroring torch/__init__.py:71-73,114-130. Between sync steps the
    update is zero (parameters unchanged), like Horovod skipping
    ``step()``'s collective work.

    Error feedback (on by default for the blockwise wire formats): the
    quantization error of each step's transmitted gradient is kept as a
    per-parameter residual and added to the NEXT step's gradient before
    compression, so the error is deferred instead of lost — the standard
    EF-SGD construction (what makes aggressive wire compression converge
    like fp32). The residual is this rank's ``delta - roundtrip(delta)``
    where ``roundtrip`` is exactly the phase-1 wire quantization
    (compression.local_roundtrip), so the carried error matches what the
    wire actually dropped.
    """

    def __init__(self, optimizer, *, compression=Compression.none,
                 backward_passes_per_step: int = 1, average: bool = True,
                 axis_name: str = "dp", op_average: Optional[bool] = None,
                 error_feedback: Optional[bool] = None):
        self.inner = optimizer
        self.compression = compression
        self.backward_passes_per_step = int(backward_passes_per_step)
        self.average = average if op_average is None else op_average
        self.axis_name = axis_name
        self._ef_explicit = error_feedback is not None
        if error_feedback is None:
            # Blockwise formats are lossy on the wire; cast/none formats
            # keep EF off by default (fp16/bf16 roundtrip error is noise
            # and the extra state/compute buys nothing).
            error_feedback = getattr(compression, "wire_spec", None) \
                is not None
        self.error_feedback = bool(error_feedback)
        self._reset_residual = False

    def set_compression(self, compression) -> None:
        """Switch wire compression mid-run — the optimizer-level hook of
        the adaptation ladder (docs/adaptation.md).

        The error-feedback residual is RESET on the next ``update``: it
        measures ``delta - roundtrip(delta)`` against the OLD spec's
        quantizer, and carrying it across a spec switch would inject a
        correction the new wire never dropped (measured as a one-step
        numerics glitch on every escalation). Unless the caller pinned
        ``error_feedback`` explicitly, its default is re-derived for the
        new spec (blockwise on, cast/none off). Under jit the switch
        takes effect on the next trace (the compression is baked into
        the compiled update); the eager engine path switches
        immediately."""
        self.compression = compression
        if not self._ef_explicit:
            self.error_feedback = getattr(
                compression, "wire_spec", None) is not None
        self._reset_residual = True

    def _roundtrip(self, g):
        """This rank's transmitted value for gradient ``g`` — what the
        residual must be measured against."""
        rt = getattr(self.compression, "local_roundtrip", None)
        if rt is not None:
            return rt(g)
        wire, ctx = self.compression.compress(g)
        return self.compression.decompress(wire, ctx)

    def _apply_ef(self, grads, residual):
        """(delta, new_residual, reduce-input) for one sync: add the
        carried residual, compute what this step's wire drops."""
        delta = jax.tree_util.tree_map(
            lambda g, e: g + e.astype(g.dtype), grads, residual)
        new_residual = jax.tree_util.tree_map(
            lambda d: d - self._roundtrip(d), delta)
        self._note_ef_residual(new_residual)
        return delta, new_residual

    def _note_ef_residual(self, residual) -> None:
        """Quantization-drift telemetry (docs/numerics.md#drift): the
        global residual L2 norm is exactly what this step's wire
        dropped. Eager path only — under jit the tree holds tracers and
        the sample is skipped (the torch shim's per-bucket hook covers
        the compiled story there)."""
        from .observability import numerics as _numerics
        if not _numerics.enabled() or _is_tracing(residual):
            return
        try:
            total = 0.0
            for leaf in jax.tree_util.tree_leaves(residual):
                a = np.asarray(leaf, dtype=np.float64)
                total += float(np.sum(a * a))
            _numerics.note_ef_residual("jax", float(np.sqrt(total)))
        except Exception:   # telemetry must never kill the update
            pass

    # optax GradientTransformation interface -------------------------------

    def init(self, params):
        inner = self.inner.init(params)
        residual = (jax.tree_util.tree_map(jnp.zeros_like, params)
                    if self.error_feedback else None)
        if self.backward_passes_per_step <= 1:
            return _DistOptState(inner, None, jnp.zeros((), jnp.int32),
                                 residual)
        acc = jax.tree_util.tree_map(jnp.zeros_like, params)
        return _DistOptState(inner, acc, jnp.zeros((), jnp.int32), residual)

    def update(self, grads, state: _DistOptState, params=None):
        residual = getattr(state, "residual", None)
        if self._reset_residual:
            # set_compression: the carried residual belongs to the OLD
            # wire's quantizer — zero it rather than double-correct.
            self._reset_residual = False
            if residual is not None:
                residual = jax.tree_util.tree_map(jnp.zeros_like, residual)
        if self.error_feedback and residual is None:
            # State from a pre-EF checkpoint (or init with EF toggled on
            # later): start the residual at zero.
            residual = jax.tree_util.tree_map(jnp.zeros_like, grads)

        if self.backward_passes_per_step <= 1:
            if self.error_feedback:
                grads, residual = self._apply_ef(grads, residual)
            reduced = allreduce_gradients(
                grads, average=self.average, compression=self.compression,
                axis_name=self.axis_name)
            updates, inner = self.inner.update(reduced, state.inner, params)
            return updates, _DistOptState(inner, None, state.counter,
                                          residual)

        acc = jax.tree_util.tree_map(lambda a, g: a + g, state.acc, grads)
        counter = state.counter + 1
        n = self.backward_passes_per_step

        if _is_tracing(grads):
            def do_sync(operand):
                acc_, inner_, res_ = operand
                scaled = jax.tree_util.tree_map(lambda a: a / n, acc_)
                new_res = res_
                if self.error_feedback:
                    scaled, new_res = self._apply_ef(scaled, res_)
                reduced = allreduce_gradients(
                    scaled, average=self.average,
                    compression=self.compression, axis_name=self.axis_name)
                updates, new_inner = self.inner.update(
                    reduced, inner_, params)
                zeros = jax.tree_util.tree_map(jnp.zeros_like, acc_)
                return updates, zeros, new_inner, new_res

            def skip(operand):
                acc_, inner_, res_ = operand
                updates = jax.tree_util.tree_map(jnp.zeros_like, acc_)
                return updates, acc_, inner_, res_

            updates, acc, inner, residual = jax.lax.cond(
                counter % n == 0, do_sync, skip,
                (acc, state.inner, residual))
            return updates, _DistOptState(inner, acc, counter % n, residual)

        if int(counter) % n == 0:
            scaled = jax.tree_util.tree_map(lambda a: a / n, acc)
            if self.error_feedback:
                scaled, residual = self._apply_ef(scaled, residual)
            reduced = allreduce_gradients(
                scaled, average=self.average, compression=self.compression,
                axis_name=self.axis_name)
            updates, inner = self.inner.update(reduced, state.inner, params)
            acc = jax.tree_util.tree_map(jnp.zeros_like, acc)
            return updates, _DistOptState(inner, acc, counter % n, residual)
        updates = jax.tree_util.tree_map(jnp.zeros_like, grads)
        return updates, _DistOptState(state.inner, acc, counter, residual)


def DistributedOptimizer(optimizer, *, compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         average: bool = True, axis_name: str = "dp",
                         error_feedback: Optional[bool] = None):
    """Factory matching the reference's ``hvd.DistributedOptimizer(opt)``
    call shape (torch/__init__.py:152-176). Returns a
    :class:`DistributedGradientTransformation` wrapping ``optimizer``."""
    return DistributedGradientTransformation(
        optimizer, compression=compression,
        backward_passes_per_step=backward_passes_per_step,
        average=average, axis_name=axis_name,
        error_feedback=error_feedback)


def broadcast_parameters(params, root_rank: int = 0):
    """Broadcast a pytree of parameters from ``root_rank``
    (torch/__init__.py:200-229). Returns the synced tree; one fused
    submission for the whole tree."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    names = _leaf_names(params)
    eng = _coll.engine()
    sfx = eng._next_name("bcastp")
    handles = []
    with eng.burst():
        for nm, leaf in zip(names, leaves):
            handles.append(_coll.broadcast_async(
                jnp.asarray(leaf), root_rank, name=f"param{nm}.{sfx}"))
    out = [h.wait() for h in handles]
    return jax.tree_util.tree_unflatten(treedef, out)


def broadcast_optimizer_state(opt_state, root_rank: int = 0):
    """Broadcast optimizer state from ``root_rank``
    (torch/__init__.py:232-348). The reference tensorizes scalar state
    entries, broadcasts, and casts back via callbacks; here non-array leaves
    take the same round-trip through 0-d arrays."""
    leaves, treedef = jax.tree_util.tree_flatten(opt_state)
    names = _leaf_names(opt_state)
    eng = _coll.engine()
    sfx = eng._next_name("bcasts")
    handles = []
    metas = []
    with eng.burst():
        for nm, leaf in zip(names, leaves):
            if isinstance(leaf, (int, float, bool, np.number)):
                arr = jnp.asarray(leaf)
                metas.append(type(leaf))
            else:
                arr = jnp.asarray(leaf)
                metas.append(None)
            handles.append(_coll.broadcast_async(
                arr, root_rank, name=f"state{nm}.{sfx}"))
    out = []
    for h, meta in zip(handles, metas):
        val = h.wait()
        if meta is not None:
            val = meta(np.asarray(val).item())
        out.append(val)
    return jax.tree_util.tree_unflatten(treedef, out)


def broadcast_object(obj, root_rank: int = 0, name: Optional[str] = None):
    """Broadcast an arbitrary picklable object (the generalization of the
    reference's scalar-state tensorize/broadcast trick,
    torch/__init__.py:264-298): pickle → uint8 tensor → broadcast length,
    then payload."""
    topo = _topo.topology()
    nm = name or _coll.engine()._next_name("bcast_obj")
    # This process holds the payload if the root *virtual rank* is one of
    # its local devices (single-controller: one process drives local_size
    # virtual ranks).
    is_root_process = topo.rank <= root_rank < topo.rank + topo.local_size
    if is_root_process:
        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8).copy()
    else:
        payload = np.zeros((0,), dtype=np.uint8)
    n = _coll.broadcast(jnp.asarray(payload.shape[0], jnp.int32), root_rank,
                        name=nm + ".len")
    n = int(np.asarray(n))
    if not is_root_process:
        payload = np.zeros((n,), dtype=np.uint8)
    data = _coll.broadcast(jnp.asarray(payload), root_rank, name=nm + ".data")
    return pickle.loads(np.asarray(data).tobytes())
