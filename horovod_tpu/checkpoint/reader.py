"""Restore side — checksum-verified shard reads and manifest resharding.

The core restore primitive is :func:`read_block`: give it a manifest
leaf entry and any index block of that leaf, and it reads exactly the
shard files whose saved spans overlap the block, verifies each against
its manifest crc32, and assembles the requested region. That one
function is what makes restore *layout-free*: a rank restoring into a
different process count or mesh never sees the save-time layout — it
asks for its new addressable blocks and the overlap math fetches the
right spans (the elastic grow/shrink gap called out in ISSUE.md: a
rejoined worker no longer has to swallow the full broadcast pytree).

Corruption surfaces as the typed :exc:`CorruptShardError` (missing
file, byte-count mismatch, crc mismatch, undecodable payload) — the
engine catches it and falls back to the previous committed step.
"""

from __future__ import annotations

import io
import os
import re
import zlib
from typing import Any, Dict, List, Optional

import numpy as np

from . import manifest as _manifest
from .layout import (Index, full_index, intersect_spans, relative_slices)


class CorruptShardError(RuntimeError):
    """A shard file failed integrity verification against the manifest."""

    def __init__(self, path: str, reason: str):
        super().__init__(f"corrupt checkpoint shard {path}: {reason}")
        self.path = path
        self.reason = reason


def load_shard(step_dir: str, shard_entry: dict) -> np.ndarray:
    """One shard file, crc32-verified against its manifest entry."""
    path = os.path.join(step_dir, shard_entry["file"])
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        raise CorruptShardError(path, "shard file missing")
    if len(data) != int(shard_entry["nbytes"]):
        raise CorruptShardError(
            path, f"size {len(data)} != manifest {shard_entry['nbytes']}")
    crc = f"{zlib.crc32(data) & 0xFFFFFFFF:08x}"
    if crc != shard_entry["crc32"]:
        raise CorruptShardError(
            path, f"crc32 {crc} != manifest {shard_entry['crc32']}")
    try:
        return np.load(io.BytesIO(data), allow_pickle=False)
    except Exception as e:
        raise CorruptShardError(path, f"undecodable payload: {e}")


def shards_overlapping(leaf_entry: dict, block: Index) -> List[dict]:
    """Manifest shard entries whose saved spans intersect ``block`` —
    the exact file set a resharded restore of that block must read."""
    out = []
    for shard_entry in leaf_entry["shards"]:
        if intersect_spans(_manifest.parse_index(shard_entry["index"]),
                           block) is not None:
            out.append(shard_entry)
    return out


def read_block(step_dir: str, leaf_entry: dict,
               block: Optional[Index] = None) -> np.ndarray:
    """Assemble one index block of a leaf from overlapping shard files.

    ``block=None`` means the full leaf. Raises CorruptShardError on any
    bad shard, and ValueError if the saved shards do not cover the
    requested block (a manifest from an incompatible layout)."""
    shape = tuple(int(d) for d in leaf_entry["shape"])
    if block is None:
        block = full_index(shape)
    dtype = np.dtype(leaf_entry["dtype"])
    out = np.empty(tuple(b - a for a, b in block), dtype=dtype)
    covered = 0
    for shard_entry in leaf_entry["shards"]:
        src_index = _manifest.parse_index(shard_entry["index"])
        inter = intersect_spans(src_index, block) if block else src_index
        if block and inter is None:
            continue
        data = load_shard(step_dir, shard_entry)
        if tuple(data.shape) != tuple(b - a for a, b in src_index):
            raise CorruptShardError(
                os.path.join(step_dir, shard_entry["file"]),
                f"shape {data.shape} != manifest span {src_index}")
        if not block:  # 0-d leaf: single full shard
            return data.astype(dtype, copy=False).reshape(())
        out[relative_slices(block, inter)] = \
            data[relative_slices(src_index, inter)]
        n = 1
        for a, b in inter:
            n *= b - a
        covered += n
    want = int(np.prod([b - a for a, b in block], dtype=np.int64)) \
        if block else 1
    if covered < want:
        raise ValueError(
            f"checkpoint shards cover {covered} of {want} elements of "
            f"{leaf_entry['key']!r} block {block} — incomplete layout")
    return out


def read_tree(step_dir: str, man: dict,
              template: Any = None) -> Any:
    """Full-leaf restore of every leaf, rebuilt into a pytree.

    With ``template``, leaves are matched by tree-path string and the
    result has the template's structure (works for any pytree —
    NamedTuple optax states included). Without one, the structure is
    rebuilt from the manifest keys, which works for trees of
    dicts/lists/tuples and raises a clear error otherwise.
    """
    import jax

    by_key: Dict[str, np.ndarray] = {}
    for leaf_entry in man["leaves"]:
        by_key[leaf_entry["key"]] = read_block(step_dir, leaf_entry)
    if template is not None:
        flat, treedef = jax.tree_util.tree_flatten_with_path(template)
        leaves = []
        for path, _ in flat:
            key = jax.tree_util.keystr(path)
            if key not in by_key:
                raise KeyError(
                    f"checkpoint has no leaf {key!r}; manifest holds "
                    f"{sorted(by_key)[:8]}...")
            leaves.append(by_key.pop(key))
        if by_key:
            raise KeyError(
                f"checkpoint leaves {sorted(by_key)} missing from the "
                "restore template")
        return jax.tree_util.tree_unflatten(treedef, leaves)
    return rebuild_tree(by_key)


_PART_RE = re.compile(r"\['([^']*)'\]|\[(\d+)\]")


def rebuild_tree(by_key: Dict[str, np.ndarray]) -> Any:
    """Rebuild nested dicts/lists from tree-path keys (templateless
    restore). Attribute paths (``.field`` — NamedTuples, custom nodes)
    need a template: the manifest records no class to rebuild."""
    root: Dict[Any, Any] = {}
    for key, value in by_key.items():
        parts = []
        pos = 0
        for m in _PART_RE.finditer(key):
            if m.start() != pos:
                raise ValueError(
                    f"cannot rebuild pytree node for leaf {key!r} "
                    "without a template (pass template= to restore — "
                    "required for NamedTuple/custom-node states)")
            parts.append(m.group(1) if m.group(1) is not None
                         else int(m.group(2)))
            pos = m.end()
        if pos != len(key) or not parts:
            raise ValueError(
                f"cannot rebuild pytree node for leaf {key!r} without "
                "a template (pass template= to restore)")
        node = root
        for part, nxt in zip(parts[:-1], parts[1:]):
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return _listify(root)


def _listify(node: Any) -> Any:
    """Integer-keyed dicts back into lists (list/tuple tree nodes round-
    trip as lists — tuple-ness is not recorded in the manifest)."""
    if not isinstance(node, dict):
        return node
    out = {k: _listify(v) for k, v in node.items()}
    if out and all(isinstance(k, int) for k in out):
        if sorted(out) == list(range(len(out))):
            return [out[i] for i in range(len(out))]
    return out
