"""Leaf→shard layout derivation — who writes which spans of which leaf.

The reference's checkpoint convention funnels the whole model through
rank 0 (SURVEY.md §5.4); the sharded engine instead derives, per pytree
leaf, the set of index blocks and the process that owns each, straight
from the leaf's ``jax.sharding``:

  - a sharded ``jax.Array`` contributes one :class:`Shard` per distinct
    index block of ``sharding.devices_indices_map`` — replicas dedupe to
    the lowest-process owner, so every block is written exactly once;
  - a fully replicated array (or a plain host ``numpy`` array — the
    ``ElasticState`` host-snapshot case) is a single full-extent shard
    owned by process 0, reproducing the rank-0-save convention for the
    state that really is replicated.

``process_fn`` overrides the device→process attribution. Its production
value is the default (``device.process_index``); tests and the
resharding bench use it to *simulate* a multi-host layout on the 8-device
single-process CPU mesh (e.g. ``lambda d: d.id // 2`` acts like 4 hosts
of 2 chips), which is what lets the world-size-4 → 2 → 1 restore matrix
run in one process.

Index blocks are half-open per-dimension spans ``((start, stop), ...)``
— the normalized form of the slice tuples JAX hands out — and
:func:`intersect_spans` is the one piece of geometry the resharded
restore needs: a rank restoring into a new layout reads exactly the
source shards whose spans overlap its new addressable blocks.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax

Span = Tuple[int, int]
Index = Tuple[Span, ...]


@dataclasses.dataclass(frozen=True)
class Shard:
    """One index block of a leaf and the process that writes it."""

    index: Index
    process: int

    @property
    def slices(self) -> Tuple[slice, ...]:
        return tuple(slice(a, b) for a, b in self.index)

    def nelems(self) -> int:
        n = 1
        for a, b in self.index:
            n *= b - a
        return n


@dataclasses.dataclass(frozen=True)
class LeafLayout:
    """Global shape/dtype of a leaf plus its deduped shard map."""

    shape: Tuple[int, ...]
    dtype: str
    shards: Tuple[Shard, ...]
    replicated: bool

    def shards_of(self, process: int) -> Tuple[Shard, ...]:
        return tuple(s for s in self.shards if s.process == process)


def normalize_index(slices: Sequence[slice], shape: Sequence[int]) -> Index:
    """Half-open per-dim spans from a slice tuple (fills None bounds)."""
    out: List[Span] = []
    for sl, dim in zip(slices, shape):
        start, stop, step = sl.indices(int(dim))
        if step != 1:
            raise ValueError(f"non-unit-stride shard slice {sl!r}")
        out.append((start, stop))
    # 0-d leaves (optax count scalars) get an empty index — one block.
    return tuple(out)


def full_index(shape: Sequence[int]) -> Index:
    return tuple((0, int(d)) for d in shape)


def intersect_spans(a: Index, b: Index) -> Optional[Index]:
    """Per-dim intersection of two blocks; None when they are disjoint."""
    out: List[Span] = []
    for (a0, a1), (b0, b1) in zip(a, b):
        lo, hi = max(a0, b0), min(a1, b1)
        if lo >= hi:
            return None
        out.append((lo, hi))
    return tuple(out)


def relative_slices(outer: Index, inner: Index) -> Tuple[slice, ...]:
    """``inner`` re-based into the coordinates of the ``outer`` block."""
    return tuple(slice(i0 - o0, i1 - o0)
                 for (o0, _), (i0, i1) in zip(outer, inner))


def _is_sharded_jax_array(x: Any) -> bool:
    return (isinstance(x, jax.Array) and hasattr(x, "sharding")
            and not x.sharding.is_fully_replicated)


def leaf_layout(x: Any,
                process_fn: Optional[Callable[[Any], int]] = None
                ) -> LeafLayout:
    """Derive a leaf's layout from its value (see module docstring)."""
    arr_shape = tuple(int(d) for d in np.shape(x))
    dtype = str(np.asarray(x).dtype) if not isinstance(x, jax.Array) \
        else str(x.dtype)
    if _is_sharded_jax_array(x):
        idx_map = x.sharding.devices_indices_map(x.shape)
        owners: Dict[Index, int] = {}
        for dev, slices in idx_map.items():
            idx = normalize_index(slices, arr_shape)
            proc = int(process_fn(dev)) if process_fn is not None \
                else int(dev.process_index)
            prev = owners.get(idx)
            if prev is None or proc < prev:
                owners[idx] = proc
        shards = tuple(Shard(index=idx, process=proc)
                       for idx, proc in sorted(owners.items()))
        return LeafLayout(shape=arr_shape, dtype=dtype, shards=shards,
                          replicated=False)
    return LeafLayout(
        shape=arr_shape, dtype=dtype,
        shards=(Shard(index=full_index(arr_shape), process=0),),
        replicated=True)


def tree_keys(tree: Any) -> Tuple[Tuple[str, Any], ...]:
    """Stable ``(keystr, leaf)`` pairs in flatten order — the leaf
    addressing scheme shared by layouts, shard file names and the
    manifest."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return tuple((jax.tree_util.keystr(path), leaf) for path, leaf in flat)


def tree_layout(tree: Any,
                process_fn: Optional[Callable[[Any], int]] = None
                ) -> Dict[str, LeafLayout]:
    """``{leaf keystr: LeafLayout}`` for every leaf of ``tree``."""
    return {key: leaf_layout(leaf, process_fn)
            for key, leaf in tree_keys(tree)}


def process_count(layouts: Dict[str, LeafLayout]) -> int:
    """Number of distinct writing processes a layout set implies."""
    procs = {s.process for ll in layouts.values() for s in ll.shards}
    return max(procs) + 1 if procs else 1


def shard_data(x: Any, shard: Shard) -> np.ndarray:
    """Host copy of one shard's block (the device→host snapshot unit).

    For a sharded ``jax.Array`` the block is fetched from the matching
    addressable shard — local data only, no cross-host gather. Falls
    back to slicing the (addressable) global value, which also covers
    replicated leaves and plain host arrays.
    """
    # Always a real copy: on the CPU backend np.asarray of a jax buffer
    # may alias device memory, and the next (donating) jitted step would
    # overwrite the snapshot under the async writer.
    if _is_sharded_jax_array(x):
        for s in x.addressable_shards:
            if normalize_index(s.index, x.shape) == shard.index:
                return np.array(s.data, copy=True)
    arr = np.asarray(jax.device_get(x)) if isinstance(x, jax.Array) \
        else np.asarray(x)
    return np.array(arr[shard.slices], copy=True)
