"""CheckpointEngine — sharded, asynchronous, crash-atomic commits.

The train-loop contract (the whole point of the subsystem, ISSUE 4):

  ``save(tree, step)``  snapshots this process's shards device→host and
  returns; serialization, fsync, the commit barrier, the rank-0
  manifest write and the LATEST flip all happen on a background thread.
  The loop blocks only for the snapshot — plus, if the *previous* save
  is still in flight, for joining it (back-pressure instead of
  unbounded buffered checkpoints). Both components are accounted as
  ``hvdtpu_checkpoint_blocked_seconds_total`` vs. the full
  ``hvdtpu_checkpoint_save_seconds`` histogram, so the observability
  plane shows exactly what the async engine saved the loop.

Two-phase commit (crash at ANY instant leaves the previous complete
commit restorable):

  phase 1   every process writes its shard files + crc32 sidecars into
            ``<root>/step-<N>/``; a barrier confirms all of phase 1.
  phase 2   rank 0 assembles ``manifest.json`` from the shared layouts
            and the sidecar checksums, writes it atomically, then flips
            ``<root>/LATEST`` (atomic rename + dir fsync). A second
            barrier keeps any rank from racing past a commit its peers
            have not observed.

Restore walks committed steps newest-first: a :exc:`CorruptShardError`
in the requested step logs, counts, and falls back to the previous
commit (``strict=True`` raises instead). ``restore_addressable``
is the elastic-resharding path — each rank reads only the shard-file
spans overlapping its *new* layout's blocks.

Retention: ``keep_last`` committed steps survive (default
``HOROVOD_TPU_CHECKPOINT_KEEP``, 0 = unlimited); GC runs on rank 0
after each commit and never touches the step LATEST names.
"""

from __future__ import annotations

import os
import shutil
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..observability import registry as _obs
from ..utils import env as _env
from ..utils.logging import get_logger
from . import manifest as _manifest
from .layout import LeafLayout, Shard, shard_data, tree_layout
from .reader import CorruptShardError, read_block, read_tree
from .writer import AsyncWriter, atomic_write_bytes, read_sidecar, \
    write_shard

_log = get_logger("checkpoint.engine")


def _metrics():
    r = _obs.registry()
    return {
        "bytes": r.counter(
            "hvdtpu_checkpoint_bytes_written_total",
            "Checkpoint bytes written by this process (payload + "
            "sidecars + manifest)").labels(),
        "shards": r.counter(
            "hvdtpu_checkpoint_shards_written_total",
            "Shard files written by this process").labels(),
        "save": r.histogram(
            "hvdtpu_checkpoint_save_seconds",
            "End-to-end save duration: snapshot through commit",
            buckets=_obs.LATENCY_BUCKETS).labels(),
        "blocked": r.counter(
            "hvdtpu_checkpoint_blocked_seconds_total",
            "Seconds the training loop was blocked inside save() — "
            "snapshot plus joining a previous in-flight write"),
        "restore": r.histogram(
            "hvdtpu_checkpoint_restore_seconds",
            "Restore duration", buckets=_obs.LATENCY_BUCKETS).labels(),
        "gc": r.counter(
            "hvdtpu_checkpoint_gc_steps_total",
            "Committed steps deleted by keep-last-N retention"),
        "corrupt": r.counter(
            "hvdtpu_checkpoint_corrupt_shards_total",
            "Shards that failed crc32/shape verification on restore"),
        "last_step": r.gauge(
            "hvdtpu_checkpoint_last_committed_step",
            "Step of the last commit this process finished"),
    }


def verify_fingerprint(key: str, arr, man: dict, where: str = "") -> None:
    """Recompute one leaf's value fingerprint and check it against the
    manifest (docs/numerics.md#checkpoint). No-op for manifests without
    fingerprints (pre-fingerprint checkpoints stay restorable) or for
    keys the manifest does not digest. Raises
    :exc:`~horovod_tpu.checkpoint.reader.CorruptShardError` on
    mismatch — the shard bytes matched their crc32, but the VALUES are
    not what was saved (corruption upstream of serialization)."""
    fps = man.get("fingerprints") or {}
    want = fps.get(key)
    if want is None:
        return
    from ..observability import numerics as _numerics
    got = _numerics.fingerprint_leaf(key, arr)
    if (got[0] != float(want[0]) or got[1] != int(want[1])
            or got[2] != int(want[2])):
        raise CorruptShardError(
            os.path.join(where, key) if where else key,
            f"value fingerprint mismatch: got [norm={got[0]!r}, "
            f"crc={got[1]}, n={got[2]}], manifest says [norm="
            f"{float(want[0])!r}, crc={int(want[1])}, n={int(want[2])}]")


class SaveHandle:
    """Ticket for one in-flight save; resolved by engine.wait()."""

    def __init__(self, step: int, directory: str):
        self.step = step
        self.directory = directory
        self.committed = False


class CheckpointEngine:
    """Sharded async checkpoint engine over one root directory.

    ``process_index`` / ``process_count`` default to the live topology
    (1-process standalone without ``hvd.init()``); tests and the bench
    pass them explicitly together with a ``process_fn`` to simulate a
    multi-host layout inside one process. ``barrier`` defaults to a tiny
    named allreduce when the real process count is > 1 and a no-op
    otherwise.
    """

    def __init__(self, directory: str, *,
                 keep_last: Optional[int] = None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 process_fn: Optional[Callable[[Any], int]] = None,
                 barrier: Optional[Callable[[str], None]] = None,
                 mesh_axes: Optional[Dict[str, int]] = None):
        self.directory = directory
        self.keep_last = _env.checkpoint_keep() if keep_last is None \
            else int(keep_last)
        pi, pc = self._topology_defaults()
        self.process_index = pi if process_index is None \
            else int(process_index)
        self.process_count = pc if process_count is None \
            else int(process_count)
        self.process_fn = process_fn
        self.mesh_axes = dict(mesh_axes or {})
        self._barrier = barrier if barrier is not None \
            else self._default_barrier
        self._writer = AsyncWriter()
        self._inflight: Optional[SaveHandle] = None
        self._m = _metrics()

    # ------------------------------------------------------------ save

    def save(self, tree: Any, step: int, *, extra: Optional[dict] = None,
             block: bool = False,
             layouts: Optional[Dict[str, LeafLayout]] = None
             ) -> SaveHandle:
        """Snapshot this process's shards and commit asynchronously.

        Returns as soon as the device→host snapshot is done (and any
        previous save is joined). ``block=True`` waits for the commit —
        equivalent to ``save(...); wait()``.
        """
        t0 = time.perf_counter()
        self.wait()  # back-pressure: join the previous in-flight write
        if layouts is None:
            layouts = tree_layout(tree, self.process_fn)
        values = {key: leaf for key, leaf in
                  _layout_leaves(tree, layouts)}
        # Device→host snapshot of OUR shards only (the blocking part).
        mine: List[Tuple[str, np.ndarray]] = []
        for i, (key, ll) in enumerate(layouts.items()):
            for j, shard in enumerate(ll.shards):
                if shard.process != self.process_index:
                    continue
                mine.append((_manifest.shard_filename(i, j),
                             shard_data(values[key], shard)))
        # Per-leaf VALUE fingerprints for the manifest
        # (docs/numerics.md#checkpoint) — rank 0 only (it writes the
        # manifest and, per the engine contract, holds the full host
        # tree). Computed from the snapshot the shards came from, so a
        # later in-memory corruption cannot retroactively "verify".
        fps = None
        if self.process_index == 0:
            from ..observability import numerics as _numerics
            fps = {key: _numerics.fingerprint_leaf(key, values[key])
                   for key in layouts}
        step = int(step)
        sdir = _manifest.step_dir(self.directory, step)
        os.makedirs(sdir, exist_ok=True)
        handle = SaveHandle(step, sdir)
        self._inflight = handle
        pcount = self.process_count
        extra = dict(extra or {})

        def _job():
            self._write_and_commit(handle, layouts, mine, pcount, extra,
                                   t0, fps)

        self._writer.submit(_job)
        blocked = time.perf_counter() - t0
        self._m["blocked"].inc(blocked)
        if block:
            self.wait()
        return handle

    def _write_and_commit(self, handle: SaveHandle,
                          layouts: Dict[str, LeafLayout],
                          mine: List[Tuple[str, np.ndarray]],
                          pcount: int, extra: dict, t0: float,
                          fps: Optional[Dict[str, list]] = None) -> None:
        written = 0
        for filename, arr in mine:
            crc, nbytes = write_shard(handle.directory, filename, arr)
            written += nbytes
        self._m["shards"].inc(len(mine))
        # Phase boundary: every rank's shards durable before anyone
        # writes (or trusts) a manifest.
        self._barrier(f"ckpt.shards.{handle.step}")
        if self.process_index == 0:
            man_bytes = self._commit_rank0(handle, layouts, pcount,
                                           extra, fps)
            written += man_bytes
        self._barrier(f"ckpt.commit.{handle.step}")
        handle.committed = True
        self._m["bytes"].inc(written)
        self._m["last_step"].set(handle.step)
        self._m["save"].observe(time.perf_counter() - t0)
        from ..observability import flight_recorder as _flight
        _flight.recorder().note("checkpoint",
                                ("commit", handle.step, "sharded"))

    def _commit_rank0(self, handle: SaveHandle,
                      layouts: Dict[str, LeafLayout], pcount: int,
                      extra: dict,
                      fps: Optional[Dict[str, list]] = None) -> int:
        shard_meta: Dict[str, List[dict]] = {}
        for i, (key, ll) in enumerate(layouts.items()):
            metas = []
            for j in range(len(ll.shards)):
                filename = _manifest.shard_filename(i, j)
                crc, nbytes = read_sidecar(handle.directory, filename)
                metas.append({"file": filename, "crc32": crc,
                              "nbytes": nbytes})
            shard_meta[key] = metas
        man = _manifest.manifest_dict(
            handle.step, pcount, layouts, shard_meta,
            mesh_axes=self.mesh_axes, extra=extra, fingerprints=fps)
        data = _manifest.dumps(man)
        atomic_write_bytes(
            os.path.join(handle.directory, _manifest.MANIFEST), data)
        # THE commit point: LATEST now names a fully durable step.
        atomic_write_bytes(os.path.join(self.directory, _manifest.LATEST),
                           (_manifest.step_dirname(handle.step) + "\n")
                           .encode())
        self._gc(handle.step)
        return len(data)

    def wait(self) -> Optional[SaveHandle]:
        """Join the in-flight save (no-op when idle); re-raises a
        background write failure."""
        handle, self._inflight = self._inflight, None
        self._writer.wait()
        return handle

    @property
    def busy(self) -> bool:
        return self._writer.busy

    def close(self) -> None:
        self.wait()
        self._writer.close()

    # --------------------------------------------------------- restore

    def latest_step(self) -> Optional[int]:
        return _manifest.read_latest(self.directory)

    def steps(self) -> List[int]:
        return _manifest.list_steps(self.directory)

    def restore(self, step: Optional[int] = None, *,
                template: Any = None, strict: bool = False) -> Any:
        """Full-tree restore (every leaf assembled to global shape).

        Walks candidate steps newest-first starting at ``step`` (default
        LATEST): a corrupt shard counts, logs, and falls back to the
        previous commit unless ``strict``."""
        t0 = time.perf_counter()
        for cand, last in self._candidates(step, strict):
            try:
                man = _manifest.read_manifest(self.directory, cand)
                sdir = _manifest.step_dir(self.directory, cand)
                tree = read_tree(sdir, man, template=template)
                self._verify_tree_fingerprints(tree, man, sdir)
                self._m["restore"].observe(time.perf_counter() - t0)
                return tree
            except CorruptShardError as e:
                self._corrupt(e, cand, strict or last)

    def restore_manifest(self, step: Optional[int] = None) -> dict:
        step = self._resolve(step)
        return _manifest.read_manifest(self.directory, step)

    def restore_addressable(self, layouts: Dict[str, LeafLayout],
                            step: Optional[int] = None, *,
                            process_index: Optional[int] = None,
                            strict: bool = False
                            ) -> Dict[str, List[Tuple[Shard, np.ndarray]]]:
        """Resharded restore: read ONLY the saved spans overlapping this
        process's blocks under a NEW target layout (different process
        count / mesh than at save time).

        Returns ``{leaf key: [(target Shard, block array), ...]}`` for
        the shards ``layouts`` assigns to ``process_index`` (default:
        this engine's). Fully-replicated target leaves are returned to
        every process (each reads them from the shared directory)."""
        proc = self.process_index if process_index is None \
            else int(process_index)
        t0 = time.perf_counter()
        for cand, last in self._candidates(step, strict):
            try:
                man = _manifest.read_manifest(self.directory, cand)
                sdir = _manifest.step_dir(self.directory, cand)
                entries = {e["key"]: e for e in man["leaves"]}
                out: Dict[str, List[Tuple[Shard, np.ndarray]]] = {}
                for key, ll in layouts.items():
                    if key not in entries:
                        raise KeyError(
                            f"checkpoint step {cand} has no leaf {key!r}")
                    wanted = ll.shards if ll.replicated else \
                        ll.shards_of(proc)
                    blocks = []
                    saved_shape = tuple(
                        int(d) for d in entries[key]["shape"])
                    for shard in wanted:
                        block = read_block(sdir, entries[key],
                                           shard.index or None)
                        # Fingerprint verification needs the WHOLE leaf
                        # value; a resharded read only materializes it
                        # when this block covers the full saved shape
                        # (replicated leaves, single-shard leaves).
                        if (not shard.index
                                or tuple((a, b) for a, b in shard.index)
                                == tuple((0, d) for d in saved_shape)):
                            verify_fingerprint(key, block, man, sdir)
                        blocks.append((shard, block))
                    out[key] = blocks
                self._m["restore"].observe(time.perf_counter() - t0)
                return out
            except CorruptShardError as e:
                self._corrupt(e, cand, strict or last)

    def _verify_tree_fingerprints(self, tree: Any, man: dict,
                                  sdir: str) -> None:
        """Check every restored leaf's value digest against the
        manifest (docs/numerics.md#checkpoint); raises
        CorruptShardError so the restore loop falls back exactly like
        a crc failure."""
        if not man.get("fingerprints"):
            return
        import jax
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            verify_fingerprint(jax.tree_util.keystr(path), leaf, man,
                               sdir)

    def _resolve(self, step: Optional[int]) -> int:
        if step is not None:
            return int(step)
        latest = self.latest_step()
        if latest is None:
            raise FileNotFoundError(
                f"no committed checkpoint under {self.directory!r}")
        return latest

    def _candidates(self, step: Optional[int], strict: bool):
        """(step, is_last_candidate) pairs newest-first: the requested
        step, then — unless strict — every older committed step."""
        start = self._resolve(step)
        if strict:
            return [(start, True)]
        older = [s for s in self.steps() if s < start]
        chain = [start] + sorted(older, reverse=True)
        return [(s, i == len(chain) - 1) for i, s in enumerate(chain)]

    def _corrupt(self, e: CorruptShardError, step: int,
                 is_last: bool) -> None:
        self._m["corrupt"].inc()
        if is_last:
            raise e
        _log.warning("step %d unrestorable (%s); falling back to the "
                     "previous commit", step, e.reason)

    # -------------------------------------------------------------- gc

    def _gc(self, committed_step: int) -> None:
        """Keep the last ``keep_last`` committed steps (rank 0, after a
        successful commit). Never deletes the step LATEST names; also
        sweeps older aborted (manifest-less) step directories."""
        if self.keep_last <= 0:
            return
        latest = self.latest_step()
        committed = self.steps()
        keep = set(committed[-self.keep_last:])
        keep.add(committed_step)
        if latest is not None:
            keep.add(latest)
        floor = min(keep) if keep else committed_step
        for name in os.listdir(self.directory):
            m = _manifest._STEP_RE.match(name)
            if not m:
                continue
            s = int(m.group(1))
            drop = (s in committed and s not in keep) or \
                (s not in committed and s < floor)
            if drop:
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)
                self._m["gc"].inc()

    # -------------------------------------------------------- plumbing

    @staticmethod
    def _topology_defaults() -> Tuple[int, int]:
        from .. import topology as _topo
        try:
            t = _topo._get()
            return t.process_index, t.process_count
        except Exception:
            return 0, 1

    def _default_barrier(self, name: str) -> None:
        if self.process_count <= 1:
            return
        from .. import topology as _topo
        try:
            real = _topo._get().process_count
        except Exception:
            real = 1
        if real <= 1:  # simulated multi-process layout, single process
            return
        import jax.numpy as jnp

        from ..ops import collective as _coll
        _coll.allreduce(jnp.zeros((1,), jnp.float32), average=False,
                        name=name)


def _layout_leaves(tree: Any, layouts: Dict[str, LeafLayout]):
    """(key, leaf) pairs checked against the layout's key set."""
    from .layout import tree_keys
    pairs = tree_keys(tree)
    keys = {k for k, _ in pairs}
    if keys != set(layouts):
        missing = set(layouts) - keys
        extra = keys - set(layouts)
        raise ValueError(
            f"layout/tree mismatch: layout-only keys {sorted(missing)}, "
            f"tree-only keys {sorted(extra)}")
    return pairs
