"""Durable shard writing — crash-atomic files and the async writer thread.

Durability discipline (the satellite fix that also lands in
``utils/checkpoint.py``): ``os.replace`` alone orders nothing on several
filesystems — after power loss the rename can survive while the data
blocks do not, leaving a complete-looking but empty target. Every write
here therefore goes tmp → ``flush`` → ``fsync(file)`` → ``replace`` →
``fsync(parent dir)`` (the directory entry itself must be durable for
the rename to be).

Shard format: the standard ``.npy`` encoding (``allow_pickle=False`` on
both ends — shard payloads are raw arrays and restoring one must never
execute code), serialized to memory first so the crc32 covers the exact
bytes on disk; the checksum + byte count land in a ``<file>.crc32``
sidecar. Sidecars are how per-shard checksums reach rank 0's manifest
without a collective: after the commit barrier rank 0 reads them back
from the (shared) step directory.

:class:`AsyncWriter` is the single background thread behind the engine's
non-blocking save: jobs run FIFO, ``wait()`` joins and re-raises the
first failure, and a failed job poisons the writer until waited on — a
training loop cannot silently keep "committing" over a dead disk.
"""

from __future__ import annotations

import io
import os
import queue
import threading
import zlib
from typing import Callable, Optional, Tuple

import numpy as np


def fsync_dir(path: str) -> None:
    """fsync a directory so renames/creates inside it are durable.
    Best-effort: some filesystems (and platforms) refuse O_RDONLY
    directory fds — those also do not need the flush."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(target: str, data: bytes) -> None:
    """tmp + flush + fsync + rename + parent-dir fsync."""
    parent = os.path.dirname(os.path.abspath(target))
    tmp = target + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, target)
    fsync_dir(parent)


def encode_shard(arr: np.ndarray) -> Tuple[bytes, str]:
    """``.npy`` bytes + crc32 hex of exactly those bytes."""
    buf = io.BytesIO()
    # reshape: ascontiguousarray promotes 0-d to 1-d, which would break
    # the manifest span check on restore.
    np.save(buf, np.ascontiguousarray(arr).reshape(np.shape(arr)),
            allow_pickle=False)
    data = buf.getvalue()
    return data, f"{zlib.crc32(data) & 0xFFFFFFFF:08x}"


def write_shard(directory: str, filename: str,
                arr: np.ndarray) -> Tuple[str, int]:
    """Write one shard + its crc32 sidecar; returns (crc hex, nbytes)."""
    data, crc = encode_shard(arr)
    atomic_write_bytes(os.path.join(directory, filename), data)
    atomic_write_bytes(os.path.join(directory, filename + ".crc32"),
                       f"{crc} {len(data)}\n".encode())
    return crc, len(data)


def read_sidecar(directory: str, filename: str) -> Tuple[str, int]:
    """(crc hex, nbytes) recorded next to a shard file."""
    with open(os.path.join(directory, filename + ".crc32")) as f:
        crc, nbytes = f.read().split()
    return crc, int(nbytes)


class AsyncWriter:
    """One background thread running write jobs FIFO.

    ``submit`` never blocks on I/O; ``wait`` drains the queue and
    re-raises the first job failure. After a failure every subsequent
    submit/wait keeps raising until ``wait`` has surfaced it once.
    """

    def __init__(self, name: str = "hvdtpu-ckpt-writer"):
        self._queue: "queue.Queue[Optional[Callable[[], None]]]" = \
            queue.Queue()
        self._idle = threading.Event()
        self._idle.set()
        self._error: Optional[BaseException] = None
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                return
            try:
                if self._error is None:
                    job()
            except BaseException as e:  # surfaced on wait()
                with self._lock:
                    if self._error is None:
                        self._error = e
            finally:
                if self._queue.unfinished_tasks == 1:
                    self._idle.set()
                self._queue.task_done()

    def submit(self, job: Callable[[], None]) -> None:
        self._raise_pending()
        self._idle.clear()
        self._queue.put(job)

    def wait(self) -> None:
        self._queue.join()
        self._idle.set()
        self._raise_pending()

    def _raise_pending(self) -> None:
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise RuntimeError(
                "asynchronous checkpoint write failed") from err

    @property
    def busy(self) -> bool:
        return not self._idle.is_set()

    def close(self) -> None:
        self._queue.put(None)
        self._thread.join(timeout=5)
