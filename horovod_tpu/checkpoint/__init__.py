"""Sharded async checkpoint engine (ISSUE 4; docs/checkpoint.md).

The reference delegates checkpointing to the host framework and
standardizes only rank-0-save / broadcast-on-restore (SURVEY.md §5.4) —
``utils/checkpoint.py`` keeps that convention. This subsystem is the
pod-scale replacement: per-host sharded save (ZeRO-sharded state never
transits one host), async background writes, two-phase crash-atomic
commit, and manifest-driven resharded restore for elastic grow/shrink.

    engine = CheckpointEngine("/nfs/job/ckpt")
    engine.save(state, step)          # returns after the host snapshot
    ...
    state = engine.restore(template=state)
"""

from .engine import CheckpointEngine, SaveHandle
from .layout import LeafLayout, Shard, leaf_layout, tree_layout
from .manifest import list_steps, read_latest, read_manifest
from .reader import CorruptShardError, read_block, read_tree
from .writer import AsyncWriter, atomic_write_bytes, fsync_dir

__all__ = [
    "AsyncWriter", "CheckpointEngine", "CorruptShardError", "LeafLayout",
    "SaveHandle", "Shard", "atomic_write_bytes", "fsync_dir",
    "leaf_layout", "list_steps", "read_block", "read_latest",
    "read_manifest", "read_tree", "tree_layout",
]
