"""Checkpoint manifest — the commit record that makes a step restorable.

A committed step is a directory ``<root>/step-<N>/`` holding shard files
plus one ``manifest.json``, and the commit *point* is the atomic flip of
``<root>/LATEST`` to that directory's name. The manifest is written by
rank 0 only, strictly after every rank's shards (and their crc32
sidecars) are durably on disk — so the existence of a manifest certifies
a complete step, and the LATEST pointer certifies a complete *commit*.
Restore never trusts anything else: shard files without a manifest are
an aborted save; a manifest LATEST does not name is merely history.

Schema (JSON, no pickle anywhere in the metadata path)::

    {
      "format": "horovod_tpu.checkpoint/1",
      "step": 70,
      "process_count": 4,            # writers at save time
      "mesh_axes": {"dp": 8},        # informational, from the engine
      "leaves": [
        {"key": "['params']['w']",   # jax.tree_util.keystr address
         "shape": [64, 64], "dtype": "float32", "replicated": false,
         "shards": [{"file": "L00000.S000.npy",
                     "index": [[0, 16], [0, 64]],
                     "process": 0, "crc32": "9a0b...", "nbytes": 4096},
                    ...]},
        ...
      ],
      "extra": {...},                # JSON-able caller payload
      "fingerprints": {              # per-leaf VALUE digests
        "['params']['w']": [12.5, 317488301, 4096],   # [norm, crc, n]
        ...                          # (docs/numerics.md#checkpoint)
      }
    }

``key`` uses the tree-path string so restore can address leaves of any
pytree via a template; trees made of dicts/lists/tuples also rebuild
without one (reader.rebuild_tree).
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional

from .layout import Index, LeafLayout, Shard

FORMAT = "horovod_tpu.checkpoint/1"
MANIFEST = "manifest.json"
LATEST = "LATEST"
_STEP_RE = re.compile(r"^step-(\d+)$")


def step_dirname(step: int) -> str:
    return f"step-{int(step)}"


def step_dir(root: str, step: int) -> str:
    return os.path.join(root, step_dirname(step))


def shard_filename(leaf_idx: int, shard_idx: int) -> str:
    """Deterministic per-(leaf, shard) name every process computes
    identically from the shared layout — no naming coordination."""
    return f"L{leaf_idx:05d}.S{shard_idx:03d}.npy"


def list_steps(root: str) -> List[int]:
    """Committed steps (directories with a manifest), ascending."""
    if not os.path.isdir(root):
        return []
    steps = []
    for name in os.listdir(root):
        m = _STEP_RE.match(name)
        if m and os.path.exists(os.path.join(root, name, MANIFEST)):
            steps.append(int(m.group(1)))
    return sorted(steps)


def read_latest(root: str) -> Optional[int]:
    """Step the LATEST pointer names, or None before any commit."""
    path = os.path.join(root, LATEST)
    try:
        with open(path) as f:
            content = f.read().strip()
    except FileNotFoundError:
        return None
    m = _STEP_RE.match(content)
    if m:
        return int(m.group(1))
    return int(content)


def manifest_dict(step: int, process_count: int,
                  layouts: Dict[str, LeafLayout],
                  shard_meta: Dict[str, List[dict]],
                  mesh_axes: Optional[Dict[str, int]] = None,
                  extra: Optional[dict] = None,
                  fingerprints: Optional[Dict[str, list]] = None) -> dict:
    """Assemble the manifest from layouts + per-shard file metadata
    (``shard_meta[key][shard_idx]`` = {"file", "crc32", "nbytes"}).

    ``fingerprints`` maps leaf key -> ``[norm, crc, n]`` value digests
    (observability/numerics.fingerprint_leaf, docs/numerics.md#checkpoint):
    where the per-shard crc32 certifies the BYTES of each file, the
    fingerprint certifies the assembled leaf VALUES — restore recomputes
    and raises CorruptShardError on mismatch, catching corruption that
    happened before serialization (e.g. an in-memory bitflip the shard
    crc faithfully preserved)."""
    leaves = []
    for key, ll in layouts.items():
        shards = []
        for j, shard in enumerate(ll.shards):
            meta = shard_meta[key][j]
            shards.append({
                "file": meta["file"],
                "index": [[a, b] for a, b in shard.index],
                "process": shard.process,
                "crc32": meta["crc32"],
                "nbytes": meta["nbytes"],
            })
        leaves.append({"key": key, "shape": list(ll.shape),
                       "dtype": ll.dtype, "replicated": ll.replicated,
                       "shards": shards})
    man = {"format": FORMAT, "step": int(step),
           "process_count": int(process_count),
           "mesh_axes": dict(mesh_axes or {}),
           "leaves": leaves, "extra": extra if extra is not None else {}}
    if fingerprints is not None:
        man["fingerprints"] = {
            k: [float(v[0]), int(v[1]), int(v[2])]
            for k, v in fingerprints.items()}
    return man


def parse_index(entry: List[List[int]]) -> Index:
    return tuple((int(a), int(b)) for a, b in entry)


def leaf_entry_layout(entry: dict) -> LeafLayout:
    """LeafLayout back out of a manifest leaf entry (restore side)."""
    return LeafLayout(
        shape=tuple(int(d) for d in entry["shape"]),
        dtype=entry["dtype"],
        shards=tuple(Shard(index=parse_index(s["index"]),
                           process=int(s["process"]))
                     for s in entry["shards"]),
        replicated=bool(entry["replicated"]))


def read_manifest(root: str, step: int) -> dict:
    path = os.path.join(step_dir(root, step), MANIFEST)
    with open(path) as f:
        data = json.load(f)
    if data.get("format") != FORMAT:
        raise ValueError(
            f"unsupported checkpoint manifest format "
            f"{data.get('format')!r} at {path}")
    return data


def dumps(manifest: dict) -> bytes:
    return (json.dumps(manifest, indent=1, sort_keys=True) + "\n").encode()
