"""Black-box flight recorder — the post-mortem half of the telemetry
plane (docs/postmortem.md).

The live planes (metrics, tracing, adaptation) die with the process:
when a rank crashes or a job stalls at 3am there is no record of the
last collective each rank completed, what the adaptation ladder was
doing, or which rank diverged first. This module keeps an **always-on,
bounded ring buffer** of structured events per rank and dumps it to
``<HOROVOD_TPU_BLACKBOX>/blackbox-rank{rank}.jsonl`` on the abnormal
exits that matter: an uncaught exception, SIGTERM, a stall escalation,
an eviction, or an injected crash. ``python -m
horovod_tpu.tools.postmortem`` merges the per-rank dumps onto rank 0's
clock and answers *which rank died first, in which phase, and where the
fleet diverged*.

Design constraints:

  - NEAR-ZERO HOT-PATH COST: :meth:`FlightRecorder.note` is the
    PyTimeline tuple-enqueue pattern — one enabled-flag check, one
    tuple build, one ``deque.append`` (the deque bounds itself via
    ``maxlen``). All formatting happens at dump time. The ring records
    even with no dump directory configured (``bench_engine.py
    --recorder`` holds the cost under 1% of step time,
    BENCH_RECORDER.json).
  - STRUCTURED: events are (monotonic_ts, kind, payload-tuple); kind
    schemas live in ``_FIELDS`` so the dump renders self-describing
    JSONL and the postmortem tool never parses display text.
  - CRASH-SAFE OUTPUT: the dump writes the header line first and
    flushes per line — a process killed mid-dump leaves a valid JSONL
    *prefix*, which the postmortem reader tolerates (torn tail lines
    are skipped).
  - CLOCK-ALIGNED: the dump header carries the PR 5 trace clock fields
    (``offset_to_rank0_us`` etc. from the control-plane handshake), so
    the postmortem tool realigns per-rank event times exactly like
    ``tools/trace`` realigns per-rank timelines.

Event kinds (payload fields):

  ================  ========================================================
  ``init``          rank, world, generation — recorded at hvd.init()
  ``group_deliver`` seq, op, n — fused group agreed/delivered
  ``group_done``    seq, op, n, queue_ms, exec_ms — fused group executed
  ``group_error``   seq, op, n, error
  ``step``          idx — StepTimer step began
  ``step_end``      idx, step_ms, input_ms, h2d_ms, compute_ms, comm_ms
  ``wire_epoch``    epochs — adaptation wire-override list applied
  ``adapt``         action, tier, name, rank, lateness_ms — ladder moves
  ``failure``       rank, kind, detail — coordinator failure event seen
  ``fault``         kind, tick — injected fault fired
  ``checkpoint``    action, step, backend — commit/restore
  ``elastic``       event, generation, world — driver transitions
  ``coord_error``   detail — coordinator client gave up (typed error)
  ``stall``         names, age_s — engine stall escalation
  ``serving``       event, active — serving drain began/finished
  ``request``       event, trace, detail — serving request lifecycle:
                    admit/first_token/evict/finish keyed by the
                    request's trace id (docs/serving.md#request-tracing;
                    the postmortem names the in-flight requests and
                    their phase when a replica dies)
  ``serving_replica`` event, replica, detail — fleet supervisor
                    lifecycle: spawn/ready/crash/restart/drain/exit
  ``pipeline``      schedule, stages, microbatches, virtual, warmup,
                    steady, drain, bubble_share — pipeline program built
  ``data``          event, epoch, offset, detail — input-pipeline
                    lifecycle: epoch boundaries, cursor commits, resume
                    (docs/data.md; the postmortem surfaces the last
                    committed cursor per rank)
  ``alert``         alert, severity, series, who, value, baseline —
                    health-detector alert fired (docs/health.md; the
                    dump shows what the anomaly plane saw before a
                    death)
  ``numerics``      event, step, who, value, detail — numerics-plane
                    evidence (docs/numerics.md): ``nonfinite`` (who =
                    producing rank, value = element count, detail =
                    source) and ``divergence`` (who = divergent rank,
                    detail = leaf) — the postmortem names the first
                    nonfinite step/rank and the divergence chain from
                    these
  ================  ========================================================
"""

from __future__ import annotations

import collections
import json
import os
import signal
import sys
import threading
import time
from typing import Optional, Tuple

from ..utils import env as _env
from ..utils.logging import get_logger

_log = get_logger("observability.blackbox")

# Payload field names per event kind (dump-time schema; note() only ever
# builds a tuple).
_FIELDS = {
    "init": ("rank", "world", "generation"),
    "group_deliver": ("seq", "op", "n"),
    "group_done": ("seq", "op", "n", "queue_ms", "exec_ms"),
    "group_error": ("seq", "op", "n", "error"),
    "step": ("idx",),
    "step_end": ("idx", "step_ms", "input_ms", "h2d_ms", "compute_ms",
                 "comm_ms"),
    "wire_epoch": ("epochs",),
    "adapt": ("action", "tier", "name", "rank", "lateness_ms"),
    # NB: payload field names must not collide with the event's own
    # "kind"/"t_us" keys — the dump merges them into one JSON object.
    "failure": ("rank", "failure_kind", "detail"),
    "fault": ("fault", "tick"),
    "checkpoint": ("action", "step", "backend"),
    "elastic": ("event", "generation", "world"),
    "coord_error": ("detail",),
    "stall": ("names", "age_s"),
    "serving": ("event", "active"),
    "request": ("event", "trace", "detail"),
    "serving_replica": ("event", "replica", "detail"),
    "pipeline": ("schedule", "stages", "microbatches", "virtual",
                 "warmup", "steady", "drain", "bubble_share"),
    "data": ("event", "epoch", "offset", "detail"),
    "alert": ("alert", "severity", "series", "who", "value", "baseline"),
    "autotune": ("event", "knob", "value", "score", "baseline", "detail"),
    "numerics": ("event", "step", "who", "value", "detail"),
}

# Recording lever — module-global single check like registry._enabled.
# Always on by default (the point of a flight recorder); the overhead
# bench toggles it for the A/B.
_enabled = True


def enabled() -> bool:
    return _enabled


def set_enabled(value: bool) -> None:
    global _enabled
    _enabled = bool(value)


class FlightRecorder:
    """Bounded per-process ring of structured events + the dump path."""

    def __init__(self, capacity: Optional[int] = None):
        self._ring = collections.deque(
            maxlen=capacity or _env.blackbox_capacity())
        self.rank = -1
        self.world = 0
        self.generation = 0
        self.clock = {"offset_to_rank0_us": 0.0, "rtt_us": 0.0,
                      "clock_synced": False}
        self._dump_lock = threading.Lock()
        self.last_dump_path: Optional[str] = None
        self.last_dump_reason: Optional[str] = None

    # ------------------------------------------------------------ record

    def note(self, kind: str, payload: Tuple = ()) -> None:
        """Append one event. HOT PATH: enabled check + tuple + append;
        the payload must already be a tuple of json-safe scalars (the
        convenience wrappers below build them)."""
        if not _enabled:
            return
        self._ring.append((time.monotonic(), kind, payload))

    # Convenience wrappers for the engine's dispatch loops — kept thin
    # so the call sites stay one line.

    def group_deliver(self, seq, op: str, n: int) -> None:
        if not _enabled:
            return
        self._ring.append((time.monotonic(), "group_deliver",
                           (seq, op, n)))

    def group_done(self, seq, op: str, n: int, t_deliver: float,
                   t_start: float, t_end: float) -> None:
        if not _enabled:
            return
        self._ring.append((t_end, "group_done",
                           (seq, op, n,
                            round((t_start - t_deliver) * 1e3, 3),
                            round((t_end - t_start) * 1e3, 3))))

    def group_error(self, seq, op: str, n: int, error: str) -> None:
        if not _enabled:
            return
        self._ring.append((time.monotonic(), "group_error",
                           (seq, op, n, str(error)[:500])))

    # ---------------------------------------------------------- identity

    def configure(self, rank: int, world: int, generation: int = 0
                  ) -> None:
        self.rank = int(rank)
        self.world = int(world)
        self.generation = int(generation)

    def set_clock_meta(self, offset_s: float, rtt_s: float,
                       synced: bool) -> None:
        """Record the control-plane clock handshake result (the PR 5
        header fields) for the dump header — same sign convention as the
        trace sidecar: positive offset means rank 0's monotonic clock
        reads ahead of ours."""
        self.clock = {"offset_to_rank0_us": float(offset_s) * 1e6,
                      "rtt_us": float(rtt_s) * 1e6,
                      "clock_synced": bool(synced)}

    # -------------------------------------------------------------- dump

    def _snapshot(self):
        """Copy the ring without a hot-path lock: deque appends are
        thread-safe; a concurrent append during list() raises
        RuntimeError, so retry a few times (dump happens at death —
        losing the race forever would mean the process is still healthy,
        which contradicts dumping)."""
        for _ in range(5):
            try:
                return list(self._ring)
            except RuntimeError:
                time.sleep(0.001)
        return list(self._ring)  # last try, let it raise

    def dump(self, reason: str, exc: Optional[BaseException] = None,
             directory: Optional[str] = None,
             window_s: Optional[float] = None) -> Optional[str]:
        """Write the last ``window_s`` seconds of events to
        ``<dir>/blackbox-rank{rank}.jsonl``. Returns the path, or None
        when no directory is configured. Header first + per-line flush:
        a kill mid-dump leaves a valid prefix. Safe to call more than
        once (later dumps overwrite — the freshest evidence wins)."""
        directory = directory or _env.blackbox_dir()
        if not directory:
            return None
        window_s = window_s if window_s is not None \
            else _env.blackbox_window_secs()
        now_mono = time.monotonic()
        events = [e for e in self._snapshot()
                  if now_mono - e[0] <= window_s]
        rank = self.rank if self.rank >= 0 else int(
            os.environ.get("HOROVOD_TPU_PROCESS_ID", "0") or 0)
        path = os.path.join(directory, f"blackbox-rank{rank}.jsonl")
        with self._dump_lock:
            try:
                os.makedirs(directory, exist_ok=True)
                with open(path, "w") as f:
                    header = {
                        "blackbox": 1,
                        "rank": rank,
                        "world": self.world,
                        "generation": self.generation,
                        "reason": reason,
                        "error": (f"{type(exc).__name__}: {exc}"[:2000]
                                  if exc is not None else None),
                        "time_unix": time.time(),
                        "mono_us": int(now_mono * 1e6),
                        "window_s": window_s,
                        "events": len(events),
                        **self.clock,
                    }
                    f.write(json.dumps(header) + "\n")
                    f.flush()
                    for ts, kind, payload in events:
                        fields = _FIELDS.get(kind)
                        if fields is not None and len(fields) == len(payload):
                            data = dict(zip(fields, payload))
                        elif isinstance(payload, dict):
                            data = payload
                        else:
                            data = {"payload": list(payload)}
                        f.write(json.dumps(
                            {"t_us": int(ts * 1e6), "kind": kind,
                             **data}, default=str) + "\n")
                        f.flush()
                    os.fsync(f.fileno())
            except OSError as e:  # never fail the death path over telemetry
                _log.warning("blackbox dump failed: %s", e)
                return None
        self.last_dump_path = path
        self.last_dump_reason = reason
        from . import registry as _reg
        _reg.registry().counter(
            "hvdtpu_blackbox_dumps_total",
            "Flight-recorder dumps written, by trigger reason"
        ).labels(reason=reason).inc()
        if reason != "inflight":   # the periodic writer would spam
            _log.warning("flight recorder dumped %d events to %s "
                         "(reason: %s)", len(events), path, reason)
        return path


_recorder = FlightRecorder()


def recorder() -> FlightRecorder:
    """The process-global flight recorder (always recording)."""
    return _recorder


def reset() -> None:
    """Test hook: fresh ring + identity (mirrors reset_engine())."""
    global _recorder
    _recorder = FlightRecorder()


_final_flush_hooks: list = []


def register_final_flush(fn) -> None:
    """Register a best-effort flush callback to run on every final-gasp
    path (:func:`dump_on`) alongside the recorder dump and the metrics
    flush. Used by writers whose buffered tail would otherwise die with
    the process — the serving request-trace writer registers its
    close() here so an injected SIGKILL leaves a complete trace.
    Idempotent per callable."""
    if fn not in _final_flush_hooks:
        _final_flush_hooks.append(fn)


def dump_on(reason: str, exc: Optional[BaseException] = None) -> None:
    """Final gasp, shared by every abnormal-exit path (excepthook,
    SIGTERM, stall escalation, worker-harness exception, injected
    crash): dump the flight recorder AND flush the last metrics
    snapshot, so neither HOROVOD_TPU_BLACKBOX nor
    HOROVOD_TPU_METRICS_FILE is ever stale-at-death. Best-effort —
    never raises."""
    try:
        _recorder.dump(reason, exc=exc)
    except Exception as e:  # pragma: no cover - defensive
        _log.warning("blackbox dump failed: %s", e)
    try:
        from . import export as _export
        _export.final_metrics_flush()
    except Exception as e:  # pragma: no cover - defensive
        _log.warning("final metrics flush failed: %s", e)
    for fn in list(_final_flush_hooks):
        try:
            fn()
        except Exception as e:  # pragma: no cover - defensive
            _log.warning("final flush hook failed: %s", e)


# ---------------------------------------------------------------------------
# Crash hooks + the periodic (continuous) dumper
# ---------------------------------------------------------------------------

_hooks_installed = False
_periodic_thread: Optional[threading.Thread] = None


def _periodic_loop(interval_s: float) -> None:
    """Continuous persistence, the actual black-box design: some death
    paths leave NO exit window at all — the JAX coordination service
    LOG(FATAL)s surviving clients within ~100 ms of a peer's death, and
    a SIGKILL is un-hookable by definition — so the ring is rewritten
    to disk every ``interval_s`` with reason ``inflight``. A real
    death-path dump later overwrites it with the precise reason; a
    hard-killed rank leaves its last in-flight snapshot as evidence."""
    while True:
        time.sleep(interval_s)
        rec = _recorder
        if rec.last_dump_reason not in (None, "inflight"):
            return   # a terminal dump happened; stop overwriting it
        try:
            rec.dump("inflight")
        except Exception:  # pragma: no cover - defensive
            pass


def maybe_install_hooks() -> None:
    """Install the crash machinery once (called by ``hvd.init()``):
    chain ``sys.excepthook`` and the SIGTERM handler so an uncaught
    exception or a termination signal dumps the recorder and flushes
    the metrics file before the process dies, and start the periodic
    in-flight dumper (see :func:`_periodic_loop`). Only armed when a
    blackbox directory or a metrics file is configured — otherwise
    there is nothing to write and the process's signal semantics stay
    untouched."""
    global _hooks_installed, _periodic_thread
    if _hooks_installed:
        return
    if not (_env.blackbox_dir() or _env.metrics_file()
            or _env.history_dir()):
        # history_dir counts: its sampler registers a final-gasp flush
        # (the last window before a death must reach the history file).
        return
    _hooks_installed = True

    interval = _env.blackbox_interval_secs()
    if _env.blackbox_dir() and interval > 0:
        _periodic_thread = threading.Thread(
            target=_periodic_loop, args=(interval,),
            name="hvd-tpu-blackbox", daemon=True)
        _periodic_thread.start()

    prev_excepthook = sys.excepthook

    def _excepthook(exc_type, exc, tb):
        dump_on("exception", exc=exc)
        prev_excepthook(exc_type, exc, tb)

    sys.excepthook = _excepthook

    try:
        prev_sigterm = signal.getsignal(signal.SIGTERM)

        def _on_sigterm(signum, frame):
            dump_on("sigterm")
            if callable(prev_sigterm):
                prev_sigterm(signum, frame)
            else:
                # Restore default disposition and re-deliver, so the
                # exit status still says "killed by SIGTERM".
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):  # pragma: no cover - non-main thread
        pass

    # Clean-exit dump: without it, a healthy run's file would keep the
    # last "inflight" snapshot and read like a death. Skipped when a
    # terminal dump (exception/sigterm/...) already told the real story.
    import atexit

    def _atexit_dump():
        if _recorder.last_dump_reason in (None, "inflight"):
            dump_on("exit")

    atexit.register(_atexit_dump)
