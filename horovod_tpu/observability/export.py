"""Metrics export — Prometheus text exposition, JSON snapshot files, and
the rank-0 HTTP endpoint.

Three consumers, three surfaces over the ONE registry snapshot:

  - ``horovod_tpu.metrics_snapshot()`` — in-process dict (tests, user
    logging loops, the elastic driver's health line).
  - ``HOROVOD_TPU_METRICS_FILE=/path.json`` — a daemon thread rewrites
    the file (atomic tmp+rename) every ``HOROVOD_TPU_METRICS_INTERVAL``
    seconds (default 15), plus one final flush at interpreter exit. In
    multi-process jobs a ``{rank}`` placeholder in the path expands to
    the process index; without it only process 0 writes (two writers on
    one path would corrupt it).
  - ``HOROVOD_TPU_METRICS_PORT=9091`` — process 0 serves Prometheus
    text exposition (version 0.0.4) at ``/metrics`` and the raw JSON
    snapshot at ``/metrics.json`` over stdlib ``http.server``; no new
    dependencies. Port 0 binds an ephemeral port (tests).

Everything starts from :func:`maybe_start_exporters`, called by
``hvd.init()`` — idempotent, and a no-op when neither env var is set.
"""

from __future__ import annotations

import atexit
import json
import math
import os
import threading
from typing import Optional

from ..utils import env as _env
from ..utils.logging import get_logger
from . import registry as _reg

_log = get_logger("observability")

METRICS_FILE_ENV = "HOROVOD_TPU_METRICS_FILE"
METRICS_PORT_ENV = "HOROVOD_TPU_METRICS_PORT"
METRICS_INTERVAL_ENV = "HOROVOD_TPU_METRICS_INTERVAL"


# --------------------------------------------------------------------------
# Prometheus text exposition
# --------------------------------------------------------------------------

def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _exemplar_suffix(val: dict, le, prev_le) -> str:
    """OpenMetrics exemplar annotation for one ``_bucket`` line — the
    exemplar belongs on the bucket *containing* its value (spec: an
    exemplar's value must lie within the bucket's range). Empty string
    when this bucket doesn't own it."""
    ex = val.get("exemplar")
    if not ex:
        return ""
    v = ex["value"]
    hi = math.inf if isinstance(le, str) else float(le)
    lo = -math.inf if prev_le is None else (
        math.inf if isinstance(prev_le, str) else float(prev_le))
    if not (lo < v <= hi or (math.isinf(hi) and v > lo)):
        return ""
    tid = str(ex["trace_id"]).replace("\\", "\\\\").replace('"', '\\"')
    return (f' # {{trace_id="{tid}"}} {_fmt(v)}'
            f' {_fmt(round(ex.get("time_unix", 0.0), 3))}')


def prometheus_text(snap: Optional[dict] = None, *,
                    exemplars: bool = False,
                    percentiles: bool = True) -> str:
    """Render a registry snapshot as Prometheus text exposition format
    (version 0.0.4): ``# HELP`` / ``# TYPE`` headers, ``_bucket`` series
    with cumulative ``le`` labels ending at ``+Inf``, ``_sum`` and
    ``_count`` per histogram.

    ``percentiles=True`` (default) additionally emits ``{name}_p50`` /
    ``{name}_p90`` / ``{name}_p99`` gauge series per histogram — the
    same log-bucket estimate ``/metrics.json`` already serves, so
    scrape-only consumers (dashboards with no recording rules) see the
    percentile view too.

    ``exemplars=True`` appends each histogram's worst-recent exemplar
    (docs/metrics.md#exemplars) to the ``_bucket`` line containing its
    value, in OpenMetrics syntax (``# {trace_id="..."} value ts``) —
    the endpoint enables this when the scraper negotiates
    ``application/openmetrics-text`` (v0.0.4 has no exemplar syntax)."""
    snap = snap if snap is not None else _reg.snapshot()
    lines = []
    for name in sorted(snap):
        fam = snap[name]
        if fam["help"]:
            esc = fam["help"].replace("\\", "\\\\").replace("\n", "\\n")
            lines.append(f"# HELP {name} {esc}")
        lines.append(f"# TYPE {name} {fam['type']}")
        pct_lines = {q: [] for q in ("p50", "p90", "p99")}
        for label_key in sorted(fam["values"]):
            val = fam["values"][label_key]
            if fam["type"] == "histogram":
                prev_le = None
                for le, cum in val["buckets"]:
                    lab = (label_key + "," if label_key else "") \
                        + f'le="{_fmt(le)}"'
                    ex = (_exemplar_suffix(val, le, prev_le)
                          if exemplars else "")
                    lines.append(f"{name}_bucket{{{lab}}} {cum}{ex}")
                    prev_le = le
                block = f"{{{label_key}}}" if label_key else ""
                lines.append(f"{name}_sum{block} {_fmt(val['sum'])}")
                lines.append(f"{name}_count{block} {val['count']}")
                if percentiles:
                    pct = histogram_percentiles(val, (0.5, 0.9, 0.99))
                    for q, v in pct.items():
                        pct_lines[q].append(
                            f"{name}_{q}{block} {_fmt(v)}")
            else:
                block = f"{{{label_key}}}" if label_key else ""
                lines.append(f"{name}{block} {_fmt(val)}")
        for q in ("p50", "p90", "p99"):
            if pct_lines[q]:
                lines.append(
                    f"# TYPE {name}_{q} gauge")
                lines.extend(pct_lines[q])
    return "\n".join(lines) + "\n"


# --------------------------------------------------------------------------
# Percentile estimation from log-bucketed histogram snapshots
# --------------------------------------------------------------------------

def histogram_percentiles(hist: dict, qs=(0.5, 0.9, 0.99)) -> dict:
    """Estimate percentiles from a histogram snapshot
    (``{"buckets": [[le, cumulative], ...], "count", ...}`` — the
    registry's format, with ``le`` possibly the string "+Inf" when the
    snapshot came through strict JSON).

    Prometheus-style linear interpolation within the containing bucket:
    exact to within one bucket width — the resolution the log-bucketed
    layout was chosen for. The +Inf bucket has no upper bound, so a
    percentile landing there returns the largest finite bound (a known
    underestimate; the export cannot do better without raw samples).
    Returns ``{"p50": v, ...}`` keyed by percentile name, or {} for an
    empty histogram.

    Used by both the trace report (tools/trace.py routes its lateness
    samples through the same bucket layout) and the HTTP endpoint's
    ``/metrics.json`` view, so offline and live numbers come from one
    estimator."""
    count = hist.get("count", 0)
    buckets = hist.get("buckets") or []
    if not count or not buckets:
        return {}
    bounds = [math.inf if isinstance(le, str) else float(le)
              for le, _ in buckets]
    cums = [c for _, c in buckets]
    finite = [b for b in bounds if not math.isinf(b)]
    top = finite[-1] if finite else 0.0
    out = {}
    for q in qs:
        target = q * count
        v = top
        for i, cum in enumerate(cums):
            if cum >= target:
                hi = bounds[i]
                lo = bounds[i - 1] if i > 0 else 0.0
                prev = cums[i - 1] if i > 0 else 0
                if math.isinf(hi):
                    v = top
                elif cum == prev:
                    v = hi
                else:
                    v = lo + (hi - lo) * (target - prev) / (cum - prev)
                break
        name = f"p{q * 100:g}".replace(".", "_")
        out[name] = v
    return out


def with_percentiles(snap: dict, qs=(0.5, 0.9, 0.99)) -> dict:
    """Add a ``"percentiles"`` dict to every histogram value of a
    (json-safe) snapshot — the endpoint's JSON view, so dashboards get
    p50/p90/p99 without re-implementing bucket math."""
    for fam in snap.values():
        if fam["type"] != "histogram":
            continue
        for val in fam["values"].values():
            val["percentiles"] = histogram_percentiles(val, qs)
    return snap


# --------------------------------------------------------------------------
# JSON snapshot file
# --------------------------------------------------------------------------

def json_safe_snapshot(prefix=None) -> dict:
    """Registry snapshot with ``inf`` bucket bounds replaced by the
    string "+Inf" — strict JSON (``json.dumps`` would emit the invalid
    bare ``Infinity`` literal otherwise). ``prefix=`` filters families
    like :func:`registry.snapshot` (a str or a tuple of prefixes) —
    per-tick consumers (the fleet history sampler scraping
    ``/metrics.json?prefix=hvdtpu_serving_,hvdtpu_slo_``) should never
    serialize the whole registry."""
    snap = _reg.snapshot(prefix=prefix)
    for fam in snap.values():
        if fam["type"] != "histogram":
            continue
        for val in fam["values"].values():
            val["buckets"] = [["+Inf" if math.isinf(le) else le, c]
                              for le, c in val["buckets"]]
    return snap


def write_json_snapshot(path: str) -> None:
    """One atomic JSON snapshot write (tmp + rename — a scraper reading
    mid-write must never see a torn file)."""
    snap = json_safe_snapshot()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(snap, f, indent=1, sort_keys=True)
    os.replace(tmp, path)


def _resolved_file_path() -> Optional[str]:
    path = _env.metrics_file()
    if not path:
        return None
    rank = _process_index()
    if "{rank}" in path:
        return path.replace("{rank}", str(rank))
    return path if rank == 0 else None


def _process_index() -> int:
    try:
        from .. import topology as _topo
        return _topo._get().process_index
    except Exception:
        return 0


class _JsonWriter:
    """Periodic JSON snapshot writes, scheduled on the ONE shared
    telemetry timer thread (observability/ticker.py) — this class used
    to own its own daemon thread, and the history sampler would have
    spawned a second; the regression test in tests/test_history.py
    pins the single-thread consolidation."""

    def __init__(self, path: str, interval_s: float):
        self._path = path
        from . import ticker as _ticker
        self._handle = _ticker.ticker().add(
            "metrics-file", interval_s, self._write, final=self._write)

    def _write(self):
        try:
            write_json_snapshot(self._path)
        except OSError as e:  # never fail the job over telemetry
            _log.warning("metrics snapshot write failed: %s", e)

    def stop(self):
        from . import ticker as _ticker
        _ticker.ticker().remove(self._handle)  # runs the final flush


# --------------------------------------------------------------------------
# HTTP endpoint (stdlib only)
# --------------------------------------------------------------------------

class MetricsServer:
    """Prometheus + JSON endpoint over ``http.server`` (no new deps).

    ``/metrics``       → text exposition (Content-Type the Prometheus
                         scraper expects, version 0.0.4)
    ``/metrics.json``  → the raw snapshot dict
    """

    def __init__(self, port: int):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                route, _, query = self.path.partition("?")
                params = dict(
                    kv.split("=", 1) for kv in query.split("&")
                    if "=" in kv)
                prefix = params.get("prefix") or None
                if prefix and "," in prefix:
                    # Comma-separated prefixes select a union of
                    # families (the fleet history sampler scrapes
                    # ?prefix=hvdtpu_serving_,hvdtpu_slo_) — the
                    # registry accepts a tuple.
                    prefix = tuple(p for p in prefix.split(",") if p)
                if route == "/metrics":
                    # Content negotiation: a scraper that asks for
                    # OpenMetrics gets exemplars (# {trace_id=...}
                    # syntax) and the EOF marker; v0.0.4 text has no
                    # exemplar syntax, so the default stays clean.
                    accept = self.headers.get("Accept", "")
                    om = "openmetrics" in accept
                    text = prometheus_text(
                        _reg.snapshot(prefix=prefix), exemplars=om)
                    if om:
                        body = (text + "# EOF\n").encode()
                        ctype = ("application/openmetrics-text; "
                                 "version=1.0.0; charset=utf-8")
                    else:
                        body = text.encode()
                        ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif route == "/metrics.json":
                    body = json.dumps(
                        with_percentiles(json_safe_snapshot(prefix)),
                        sort_keys=True).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr
                pass

        self._httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="hvd-tpu-metrics-http",
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


# --------------------------------------------------------------------------
# Lifecycle
# --------------------------------------------------------------------------

_lock = threading.Lock()
_json_writer: Optional[_JsonWriter] = None
_server: Optional[MetricsServer] = None
_started = False


def maybe_start_exporters() -> None:
    """Start whichever exporters the env configures (idempotent; called
    by ``hvd.init()``). A plain HTTP port is rank-0 only — one scrape
    target per job, like the reference's rank-0 timeline file; the
    per-rank port forms (``{rank}`` placeholder / ``base+rank``) bind an
    endpoint on EVERY process so multi-process jobs are scrapeable per
    rank. JSON files are per-process when the path has a ``{rank}``
    placeholder."""
    global _json_writer, _server, _started
    if not _reg.enabled():
        return
    with _lock:
        if _started:
            return
        _started = True
        path = _resolved_file_path()
        if path:
            _json_writer = _JsonWriter(path, _env.metrics_interval_secs())
        rank = _process_index()
        port = _env.metrics_port(rank)
        if port is not None and (rank == 0 or _env.metrics_port_per_rank()):
            try:
                _server = MetricsServer(port)
                _log.info("metrics endpoint on :%d (/metrics, "
                          "/metrics.json)", _server.port)
            except OSError as e:
                _log.warning("metrics endpoint failed to bind: %s", e)
        if _json_writer is not None or _server is not None:
            atexit.register(stop_exporters)


def final_metrics_flush() -> None:
    """Final-gasp snapshot write (docs/postmortem.md): rewrite the
    configured HOROVOD_TPU_METRICS_FILE with the current registry state
    RIGHT NOW — called from the flight recorder's crash hooks so the
    file is never stale-at-death (the periodic writer's last pass can
    be up to one interval old, and a SIGKILLed process never reaches
    its stop() flush). Works whether or not the periodic writer was
    started; a no-op when no file is configured."""
    path = _resolved_file_path()
    if not path:
        return
    try:
        write_json_snapshot(path)
    except OSError as e:  # never fail a death path over telemetry
        _log.warning("final metrics flush failed: %s", e)


def server_port() -> Optional[int]:
    """Port of the live metrics endpoint, or None when none is bound.
    HOROVOD_TPU_METRICS_PORT=0 binds an ephemeral port — this is how a
    caller (the serving replica announcing itself to the fleet
    supervisor, docs/serving.md#fleet) learns which one."""
    with _lock:
        return _server.port if _server is not None else None


def stop_exporters() -> None:
    """Stop the exporters, flushing one final JSON snapshot."""
    global _json_writer, _server, _started
    with _lock:
        if _json_writer is not None:
            _json_writer.stop()
            _json_writer = None
        if _server is not None:
            _server.stop()
            _server = None
        _started = False
