"""Metrics export — Prometheus text exposition, JSON snapshot files, and
the rank-0 HTTP endpoint.

Three consumers, three surfaces over the ONE registry snapshot:

  - ``horovod_tpu.metrics_snapshot()`` — in-process dict (tests, user
    logging loops, the elastic driver's health line).
  - ``HOROVOD_TPU_METRICS_FILE=/path.json`` — a daemon thread rewrites
    the file (atomic tmp+rename) every ``HOROVOD_TPU_METRICS_INTERVAL``
    seconds (default 15), plus one final flush at interpreter exit. In
    multi-process jobs a ``{rank}`` placeholder in the path expands to
    the process index; without it only process 0 writes (two writers on
    one path would corrupt it).
  - ``HOROVOD_TPU_METRICS_PORT=9091`` — process 0 serves Prometheus
    text exposition (version 0.0.4) at ``/metrics`` and the raw JSON
    snapshot at ``/metrics.json`` over stdlib ``http.server``; no new
    dependencies. Port 0 binds an ephemeral port (tests).

Everything starts from :func:`maybe_start_exporters`, called by
``hvd.init()`` — idempotent, and a no-op when neither env var is set.
"""

from __future__ import annotations

import atexit
import json
import math
import os
import threading
from typing import Optional

from ..utils import env as _env
from ..utils.logging import get_logger
from . import registry as _reg

_log = get_logger("observability")

METRICS_FILE_ENV = "HOROVOD_TPU_METRICS_FILE"
METRICS_PORT_ENV = "HOROVOD_TPU_METRICS_PORT"
METRICS_INTERVAL_ENV = "HOROVOD_TPU_METRICS_INTERVAL"


# --------------------------------------------------------------------------
# Prometheus text exposition
# --------------------------------------------------------------------------

def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def prometheus_text(snap: Optional[dict] = None) -> str:
    """Render a registry snapshot as Prometheus text exposition format
    (version 0.0.4): ``# HELP`` / ``# TYPE`` headers, ``_bucket`` series
    with cumulative ``le`` labels ending at ``+Inf``, ``_sum`` and
    ``_count`` per histogram."""
    snap = snap if snap is not None else _reg.snapshot()
    lines = []
    for name in sorted(snap):
        fam = snap[name]
        if fam["help"]:
            esc = fam["help"].replace("\\", "\\\\").replace("\n", "\\n")
            lines.append(f"# HELP {name} {esc}")
        lines.append(f"# TYPE {name} {fam['type']}")
        for label_key in sorted(fam["values"]):
            val = fam["values"][label_key]
            if fam["type"] == "histogram":
                for le, cum in val["buckets"]:
                    lab = (label_key + "," if label_key else "") \
                        + f'le="{_fmt(le)}"'
                    lines.append(f"{name}_bucket{{{lab}}} {cum}")
                block = f"{{{label_key}}}" if label_key else ""
                lines.append(f"{name}_sum{block} {_fmt(val['sum'])}")
                lines.append(f"{name}_count{block} {val['count']}")
            else:
                block = f"{{{label_key}}}" if label_key else ""
                lines.append(f"{name}{block} {_fmt(val)}")
    return "\n".join(lines) + "\n"


# --------------------------------------------------------------------------
# Percentile estimation from log-bucketed histogram snapshots
# --------------------------------------------------------------------------

def histogram_percentiles(hist: dict, qs=(0.5, 0.9, 0.99)) -> dict:
    """Estimate percentiles from a histogram snapshot
    (``{"buckets": [[le, cumulative], ...], "count", ...}`` — the
    registry's format, with ``le`` possibly the string "+Inf" when the
    snapshot came through strict JSON).

    Prometheus-style linear interpolation within the containing bucket:
    exact to within one bucket width — the resolution the log-bucketed
    layout was chosen for. The +Inf bucket has no upper bound, so a
    percentile landing there returns the largest finite bound (a known
    underestimate; the export cannot do better without raw samples).
    Returns ``{"p50": v, ...}`` keyed by percentile name, or {} for an
    empty histogram.

    Used by both the trace report (tools/trace.py routes its lateness
    samples through the same bucket layout) and the HTTP endpoint's
    ``/metrics.json`` view, so offline and live numbers come from one
    estimator."""
    count = hist.get("count", 0)
    buckets = hist.get("buckets") or []
    if not count or not buckets:
        return {}
    bounds = [math.inf if isinstance(le, str) else float(le)
              for le, _ in buckets]
    cums = [c for _, c in buckets]
    finite = [b for b in bounds if not math.isinf(b)]
    top = finite[-1] if finite else 0.0
    out = {}
    for q in qs:
        target = q * count
        v = top
        for i, cum in enumerate(cums):
            if cum >= target:
                hi = bounds[i]
                lo = bounds[i - 1] if i > 0 else 0.0
                prev = cums[i - 1] if i > 0 else 0
                if math.isinf(hi):
                    v = top
                elif cum == prev:
                    v = hi
                else:
                    v = lo + (hi - lo) * (target - prev) / (cum - prev)
                break
        name = f"p{q * 100:g}".replace(".", "_")
        out[name] = v
    return out


def with_percentiles(snap: dict, qs=(0.5, 0.9, 0.99)) -> dict:
    """Add a ``"percentiles"`` dict to every histogram value of a
    (json-safe) snapshot — the endpoint's JSON view, so dashboards get
    p50/p90/p99 without re-implementing bucket math."""
    for fam in snap.values():
        if fam["type"] != "histogram":
            continue
        for val in fam["values"].values():
            val["percentiles"] = histogram_percentiles(val, qs)
    return snap


# --------------------------------------------------------------------------
# JSON snapshot file
# --------------------------------------------------------------------------

def json_safe_snapshot() -> dict:
    """Registry snapshot with ``inf`` bucket bounds replaced by the
    string "+Inf" — strict JSON (``json.dumps`` would emit the invalid
    bare ``Infinity`` literal otherwise)."""
    snap = _reg.snapshot()
    for fam in snap.values():
        if fam["type"] != "histogram":
            continue
        for val in fam["values"].values():
            val["buckets"] = [["+Inf" if math.isinf(le) else le, c]
                              for le, c in val["buckets"]]
    return snap


def write_json_snapshot(path: str) -> None:
    """One atomic JSON snapshot write (tmp + rename — a scraper reading
    mid-write must never see a torn file)."""
    snap = json_safe_snapshot()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(snap, f, indent=1, sort_keys=True)
    os.replace(tmp, path)


def _resolved_file_path() -> Optional[str]:
    path = _env.metrics_file()
    if not path:
        return None
    rank = _process_index()
    if "{rank}" in path:
        return path.replace("{rank}", str(rank))
    return path if rank == 0 else None


def _process_index() -> int:
    try:
        from .. import topology as _topo
        return _topo._get().process_index
    except Exception:
        return 0


class _JsonWriter:
    def __init__(self, path: str, interval_s: float):
        self._path = path
        self._interval = max(0.05, interval_s)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="hvd-tpu-metrics-file",
                                        daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(self._interval):
            self._write()
        self._write()  # final flush on stop

    def _write(self):
        try:
            write_json_snapshot(self._path)
        except OSError as e:  # never fail the job over telemetry
            _log.warning("metrics snapshot write failed: %s", e)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5.0)


# --------------------------------------------------------------------------
# HTTP endpoint (stdlib only)
# --------------------------------------------------------------------------

class MetricsServer:
    """Prometheus + JSON endpoint over ``http.server`` (no new deps).

    ``/metrics``       → text exposition (Content-Type the Prometheus
                         scraper expects, version 0.0.4)
    ``/metrics.json``  → the raw snapshot dict
    """

    def __init__(self, port: int):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.split("?")[0] == "/metrics":
                    body = prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.split("?")[0] == "/metrics.json":
                    body = json.dumps(with_percentiles(json_safe_snapshot()),
                                      sort_keys=True).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr
                pass

        self._httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="hvd-tpu-metrics-http",
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


# --------------------------------------------------------------------------
# Lifecycle
# --------------------------------------------------------------------------

_lock = threading.Lock()
_json_writer: Optional[_JsonWriter] = None
_server: Optional[MetricsServer] = None
_started = False


def maybe_start_exporters() -> None:
    """Start whichever exporters the env configures (idempotent; called
    by ``hvd.init()``). A plain HTTP port is rank-0 only — one scrape
    target per job, like the reference's rank-0 timeline file; the
    per-rank port forms (``{rank}`` placeholder / ``base+rank``) bind an
    endpoint on EVERY process so multi-process jobs are scrapeable per
    rank. JSON files are per-process when the path has a ``{rank}``
    placeholder."""
    global _json_writer, _server, _started
    if not _reg.enabled():
        return
    with _lock:
        if _started:
            return
        _started = True
        path = _resolved_file_path()
        if path:
            _json_writer = _JsonWriter(path, _env.metrics_interval_secs())
        rank = _process_index()
        port = _env.metrics_port(rank)
        if port is not None and (rank == 0 or _env.metrics_port_per_rank()):
            try:
                _server = MetricsServer(port)
                _log.info("metrics endpoint on :%d (/metrics, "
                          "/metrics.json)", _server.port)
            except OSError as e:
                _log.warning("metrics endpoint failed to bind: %s", e)
        if _json_writer is not None or _server is not None:
            atexit.register(stop_exporters)


def final_metrics_flush() -> None:
    """Final-gasp snapshot write (docs/postmortem.md): rewrite the
    configured HOROVOD_TPU_METRICS_FILE with the current registry state
    RIGHT NOW — called from the flight recorder's crash hooks so the
    file is never stale-at-death (the periodic writer's last pass can
    be up to one interval old, and a SIGKILLed process never reaches
    its stop() flush). Works whether or not the periodic writer was
    started; a no-op when no file is configured."""
    path = _resolved_file_path()
    if not path:
        return
    try:
        write_json_snapshot(path)
    except OSError as e:  # never fail a death path over telemetry
        _log.warning("final metrics flush failed: %s", e)


def server_port() -> Optional[int]:
    """Port of the live metrics endpoint, or None when none is bound.
    HOROVOD_TPU_METRICS_PORT=0 binds an ephemeral port — this is how a
    caller (the serving replica announcing itself to the fleet
    supervisor, docs/serving.md#fleet) learns which one."""
    with _lock:
        return _server.port if _server is not None else None


def stop_exporters() -> None:
    """Stop the exporters, flushing one final JSON snapshot."""
    global _json_writer, _server, _started
    with _lock:
        if _json_writer is not None:
            _json_writer.stop()
            _json_writer = None
        if _server is not None:
            _server.stop()
            _server = None
        _started = False
