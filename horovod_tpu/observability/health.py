"""Online anomaly detection over the telemetry history window — the
plane that notices a job getting *slower* before anyone files a pager
(docs/health.md).

The history sampler (observability/history.py) reduces each window to
flat series (counter rates, gauge values, windowed histogram
p50/p99/mean). This module watches those series live and fires typed
:class:`Alert` objects from three detector families:

  - :class:`EwmaDetector` — robust EWMA z-score for *level shifts*:
    step-time regression, MFU droop, collective-share creep. The
    deviation scale is an EWMA of absolute residuals (a streaming MAD
    stand-in) and updates are winsorized at 3σ, so one spike neither
    fires nor poisons the baseline, while a sustained shift fires for
    several windows before the baseline absorbs it.
  - :class:`TrendDetector` — Theil–Sen slope over a bounded window for
    *monotone drifts*: HBM-live leak, serving queue-depth runaway. The
    median-of-pairwise-slopes estimator is robust to outliers, and the
    signal-to-noise gate (projected growth must dominate the residual
    MAD) is the false-positive guard: a noisy-but-flat gauge has
    growth ≈ 0 relative to its residuals and never trips.
  - :class:`RateDetector` — windowed event counting for *spikes*:
    replica restarts, elastic worker failures.

Every fired alert lands in four places at once: the flight recorder
(``alert`` event — a post-mortem shows what the detectors saw before a
death), the ``hvdtpu_health_alerts_total{kind,severity}`` family, a
structured ``health_alert`` log line, and — on rank 0 / the fleet
supervisor — an optional fire-and-forget webhook POST
(``HOROVOD_TPU_ALERT_URL``, stdlib, bounded timeout, its own daemon
thread so an unreachable receiver can never stall the sampler).
Regression/leak alerts additionally feed the adaptation policy's
ladder (docs/health.md#adaptation): locally through
:func:`drain_policy_alerts`, cross-rank through the coordinator's
``AlertNoteRequest`` RPC — hysteresis-guarded exactly like measured
lateness, so an alert can *start* the sustain clock but never bypass
it.

The same :class:`HealthMonitor` runs offline (``emit=False``) inside
``python -m horovod_tpu.tools.health`` over merged history files, so
the CLI's verdicts and the live plane's alerts come from one
implementation.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import math
import statistics
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Tuple

from ..utils import env as _env
from ..utils.logging import get_logger
from . import registry as _reg

_log = get_logger("observability.health")

# Every alert kind the plane can fire. The drift test asserts each is
# documented in docs/health.md and registers its metric label.
ALERT_KINDS = (
    "step_time_regression",   # windowed step time shifted up
    "mfu_droop",              # model-FLOPs utilization shifted down
    "collective_share_creep", # collective share of step time shifted up
    "hbm_leak",               # device memory in monotone growth
    "queue_depth_runaway",    # serving queue depth in monotone growth
    "restart_spike",          # replica restarts / worker failures spiking
    "nonfinite_rate",         # NaN/Inf elements seen in local payloads
    "grad_norm_explosion",    # global gradient norm shifted up
    "loss_spike",             # loss value shifted up
    "rank_divergence",        # param fingerprints disagree across ranks
    "quantization_drift",     # EF residual norm in monotone growth
)

# Kinds the adaptation policy consumes as ladder inputs.
# quantization_drift is the QUALITY direction: instead of clamping
# lateness, the policy backs the quantized wire off to fp32
# (adaptation/policy.py, docs/numerics.md#drift).
POLICY_ALERT_KINDS = ("step_time_regression", "hbm_leak",
                      "quantization_drift")


@dataclasses.dataclass
class Alert:
    """One typed health alert — everything a responder (or the
    adaptation policy) needs without re-reading the history."""

    kind: str
    severity: str              # "warning" | "critical"
    series: str                # the series key that tripped
    rank: int = -1             # offending rank (-1: not a training rank)
    replica: int = -1          # offending serving replica (-1: n/a)
    value: float = 0.0         # the observation that tripped
    baseline: float = 0.0      # what the detector expected
    window_s: float = 0.0      # the window the detector judged over
    t_unix: float = 0.0
    evidence: dict = dataclasses.field(default_factory=dict)

    @property
    def message(self) -> str:
        who = (f"replica {self.replica}" if self.replica >= 0
               else f"rank {self.rank}" if self.rank >= 0 else "process")
        return (f"{self.kind} on {who}: {self.series} = "
                f"{self.value:.6g} vs baseline {self.baseline:.6g} "
                f"over {self.window_s:.0f}s")

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["message"] = self.message
        return d


# --------------------------------------------------------------------------
# Detectors — pure, deterministically-testable state machines
# --------------------------------------------------------------------------

class EwmaDetector:
    """Robust EWMA z-score level-shift detector.

    ``direction="up"`` fires on sustained increases (latency,
    share), ``"down"`` on decreases (MFU). A trip requires BOTH a
    z-score above ``z_threshold`` (deviation dominates the noise
    floor) and a relative/absolute change above ``min_rel`` /
    ``min_abs`` (a dead-quiet series must not alert over nanoseconds).
    """

    def __init__(self, direction: str = "up", *, alpha: float = 0.25,
                 z_threshold: float = 4.0, min_rel: float = 0.2,
                 min_abs: float = 0.0, min_baseline: float = 0.0,
                 warmup: int = 5):
        if direction not in ("up", "down"):
            raise ValueError(f"direction must be up/down, got {direction}")
        self.direction = direction
        self.alpha = alpha
        self.z_threshold = z_threshold
        self.min_rel = min_rel
        self.min_abs = min_abs
        # Below this baseline the detector holds fire: a share/MFU
        # gauge sitting at ~0 during job bring-up "shifts" infinitely
        # in relative terms the moment real work starts — that is
        # cold start, not a regression.
        self.min_baseline = min_baseline
        self.warmup = max(2, warmup)
        self._mean: Optional[float] = None
        self._dev = 0.0
        self._n = 0
        self._t0: Optional[float] = None
        self._warm: List[float] = []

    def update(self, t: float, v: float) -> Optional[dict]:
        if self._mean is None:
            # Baseline bootstrap: hold fire through the warmup window,
            # then initialize from its MEDIAN and MAD — a first-sample
            # init would let one compile-spike sample poison the
            # baseline for the rest of the run (the EWMA then glides
            # down through a later genuine shift without ever firing).
            if self._t0 is None:
                self._t0 = t
            self._warm.append(v)
            self._n += 1
            if self._n >= self.warmup:
                self._mean = statistics.median(self._warm)
                self._dev = statistics.median(
                    abs(x - self._mean) for x in self._warm)
                self._warm = []
            return None
        mean, dev = self._mean, self._dev
        delta = v - mean
        signed = delta if self.direction == "up" else -delta
        # Noise floor: the EWMA absolute residual, with a relative
        # epsilon so a near-constant series doesn't divide by ~0.
        scale = max(dev, abs(mean) * 1e-3, 1e-12)
        z = signed / scale
        rel = signed / abs(mean) if mean else float("inf")
        fired = None
        if (self._n >= self.warmup and z >= self.z_threshold
                and signed >= self.min_abs
                and abs(mean) >= self.min_baseline
                and (rel >= self.min_rel or abs(mean) == 0.0)):
            fired = {"z": round(z, 2), "baseline": mean,
                     "deviation": dev, "rel_change": round(rel, 4),
                     "window_s": t - (self._t0 or t)}
        # Winsorized update: clamp the sample at 3 scale units so one
        # outlier (or the first windows of a real shift) can't yank the
        # baseline to the new level instantly.
        clipped = mean + max(-3.0 * scale, min(3.0 * scale, delta))
        self._mean = mean + self.alpha * (clipped - mean)
        self._dev = ((1 - self.alpha) * dev
                     + self.alpha * abs(clipped - self._mean))
        self._n += 1
        return fired


class TrendDetector:
    """Theil–Sen monotone-trend detector over a bounded window.

    Fires when the median pairwise slope projects growth over the
    window that (a) exceeds ``min_rel`` of the window median (or
    ``min_abs``), and (b) dominates the residual noise by ``snr``
    — the false-positive guard a plain "is it higher than before"
    check lacks: a noisy-but-flat series has residual MAD of the same
    order as any apparent growth and stays quiet."""

    def __init__(self, *, window: int = 12, min_points: int = 8,
                 min_rel: float = 0.05, min_abs: float = 0.0,
                 snr: float = 4.0, mk_z: float = 3.0):
        self.window = window
        self.min_points = max(3, min_points)
        self.min_rel = min_rel
        self.min_abs = min_abs
        self.snr = snr
        self.mk_z = mk_z
        self._pts: Deque[Tuple[float, float]] = collections.deque(
            maxlen=window)

    def update(self, t: float, v: float) -> Optional[dict]:
        self._pts.append((t, v))
        if len(self._pts) < self.min_points:
            return None
        pts = list(self._pts)
        slopes = []
        mk_s = 0
        for i in range(len(pts)):
            ti, vi = pts[i]
            for j in range(i + 1, len(pts)):
                tj, vj = pts[j]
                if tj > ti:
                    slopes.append((vj - vi) / (tj - ti))
                mk_s += (vj > vi) - (vj < vi)
        if not slopes:
            return None
        # Mann–Kendall monotonicity gate: a genuine drift has nearly
        # every pair ordered (S → n(n-1)/2, z large); pure noise has
        # S ≈ 0. This is what keeps a long noisy-flat series quiet
        # even when one window's Theil–Sen slope happens to look big.
        n = len(pts)
        mk_var = n * (n - 1) * (2 * n + 5) / 18.0
        z = (mk_s - 1) / math.sqrt(mk_var) if mk_var > 0 else 0.0
        if z < self.mk_z:
            return None
        slope = statistics.median(slopes)
        if slope <= 0:
            return None
        span = pts[-1][0] - pts[0][0]
        growth = slope * span
        t_med = statistics.median(p[0] for p in pts)
        v_med = statistics.median(p[1] for p in pts)
        resid = [abs(v - (v_med + slope * (tt - t_med)))
                 for tt, v in pts]
        mad = statistics.median(resid)
        floor = max(self.min_rel * abs(v_med), self.min_abs,
                    self.snr * mad, 1e-12)
        if growth > floor:
            return {"slope_per_s": slope, "growth": growth,
                    "baseline": pts[0][1], "residual_mad": mad,
                    "mk_z": round(z, 2), "window_s": span}
        return None


class RateDetector:
    """Windowed event-count spike detector over a *rate* series (the
    history reduction of a counter). Fires when at least ``threshold``
    events landed within the trailing ``window_s``."""

    def __init__(self, *, threshold: float = 3.0,
                 window_s: float = 600.0):
        self.threshold = threshold
        self.window_s = window_s
        self._events: Deque[Tuple[float, float]] = collections.deque()
        self._last_t: Optional[float] = None

    def update(self, t: float, rate: float) -> Optional[dict]:
        dt = (t - self._last_t) if self._last_t is not None else 0.0
        self._last_t = t
        n = max(0.0, rate) * max(dt, 0.0)
        if n > 0:
            self._events.append((t, n))
        while self._events and t - self._events[0][0] > self.window_s:
            self._events.popleft()
        total = sum(n for _, n in self._events)
        if total >= self.threshold:
            return {"events": round(total, 3),
                    "window_s": min(self.window_s,
                                    t - self._events[0][0]
                                    if self._events else 0.0),
                    "baseline": 0.0}
        return None


# --------------------------------------------------------------------------
# Series matching
# --------------------------------------------------------------------------

def split_series_key(key: str) -> Tuple[str, str, str]:
    """``family{labels}|suffix`` → (family, label_block, suffix)."""
    base, _, suffix = key.partition("|")
    fam, _, labels = base.partition("{")
    return fam, labels.rstrip("}"), suffix


@dataclasses.dataclass(frozen=True)
class DetectorSpec:
    """One alert kind: which series it watches and how."""

    kind: str
    severity: str
    families: Tuple[str, ...]     # exact family names
    suffix: str                   # "" for gauges/counters, "mean"/...
    factory: Callable[[], object]
    labels: str = ""              # required label fragment ("" = any)

    def matches(self, key: str) -> bool:
        fam, label_block, suffix = split_series_key(key)
        if self.labels and self.labels not in label_block:
            return False
        return fam in self.families and suffix == self.suffix


def default_specs() -> List[DetectorSpec]:
    """The stock detector plane (docs/health.md#detectors)."""
    return [
        DetectorSpec(
            "step_time_regression", "warning",
            ("hvdtpu_step_seconds",), "mean",
            lambda: EwmaDetector("up", min_rel=0.15)),
        DetectorSpec(
            "mfu_droop", "warning",
            ("hvdtpu_mfu",), "",
            lambda: EwmaDetector("down", min_rel=0.1, min_abs=0.01,
                                 min_baseline=0.01)),
        DetectorSpec(
            "collective_share_creep", "warning",
            ("hvdtpu_collective_step_share",), "",
            lambda: EwmaDetector("up", min_rel=0.15, min_abs=0.05,
                                 min_baseline=0.02)),
        DetectorSpec(
            "hbm_leak", "critical",
            ("hvdtpu_hbm_bytes_in_use",), "",
            lambda: TrendDetector(min_rel=0.02)),
        DetectorSpec(
            "queue_depth_runaway", "critical",
            ("hvdtpu_serving_queue_depth",
             "hvdtpu_fleet_replica_queue_depth"), "",
            lambda: TrendDetector(min_rel=0.5, min_abs=4.0)),
        DetectorSpec(
            "restart_spike", "critical",
            ("hvdtpu_fleet_replica_restarts_total",
             "hvdtpu_elastic_worker_failures_total"), "",
            lambda: RateDetector(threshold=3.0, window_s=600.0)),
        # ---- numerics plane (docs/numerics.md#detectors) ----
        # The windowed twin of the same-step sentinel: even if the
        # immediate alert was refire-suppressed, a sustained nonfinite
        # stream shows up in the counter's rate series.
        DetectorSpec(
            "nonfinite_rate", "critical",
            ("hvdtpu_numerics_nonfinite_total",), "",
            lambda: RateDetector(threshold=1.0, window_s=120.0)),
        DetectorSpec(
            "grad_norm_explosion", "critical",
            ("hvdtpu_numerics_grad_norm",), "",
            lambda: EwmaDetector("up", min_rel=1.0, z_threshold=6.0)),
        DetectorSpec(
            "loss_spike", "warning",
            ("hvdtpu_numerics_loss",), "",
            lambda: EwmaDetector("up", min_rel=0.5, z_threshold=6.0)),
        DetectorSpec(
            "quantization_drift", "warning",
            ("hvdtpu_numerics_ef_residual_norm",), "",
            lambda: TrendDetector(min_rel=0.2)),
        # Windowed backstop for the same-step divergence alert rank 0
        # fires from record_fingerprint: any mismatch event in the
        # counter's rate series pages, even if the immediate alert was
        # refire-suppressed. The label filter keeps the routine
        # computed/compared event rates from matching.
        DetectorSpec(
            "rank_divergence", "critical",
            ("hvdtpu_numerics_fingerprints_total",), "",
            lambda: RateDetector(threshold=1.0, window_s=600.0),
            labels='event="mismatch"'),
    ]


# --------------------------------------------------------------------------
# The monitor
# --------------------------------------------------------------------------

# Alerts the adaptation policy should see, fed by every local monitor
# and drained by the coordinator's policy tick (rank 0); remote ranks
# additionally forward via the AlertNoteRequest RPC.
_policy_alerts: Deque[dict] = collections.deque(maxlen=64)
_policy_lock = threading.Lock()


def queue_policy_alert(alert: "Alert") -> None:
    with _policy_lock:
        _policy_alerts.append(
            {"kind": alert.kind, "rank": alert.rank,
             "t_unix": alert.t_unix})


def drain_policy_alerts() -> List[dict]:
    """Pending ladder-input alerts (``{"kind", "rank", "t_unix"}``),
    cleared on read — the coordinator's ``_maybe_adapt`` consumes
    these (docs/health.md#adaptation)."""
    with _policy_lock:
        out = list(_policy_alerts)
        _policy_alerts.clear()
    return out


def post_webhook(url: str, payload: dict, timeout_s: float = 2.0) -> None:
    """Fire-and-forget alert POST (stdlib only): its own daemon thread,
    bounded timeout, errors logged once — telemetry must never stall
    the sampler or the job."""
    import urllib.request

    def _post():
        try:
            req = urllib.request.Request(
                url, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=timeout_s).close()
        except Exception as e:
            _log.warning("alert webhook POST failed: %s", e)

    threading.Thread(target=_post, name="hvd-tpu-alert-webhook",
                     daemon=True).start()


class HealthMonitor:
    """Routes live series through the detector specs and fans fired
    alerts out to the recorder/metrics/log/webhook/policy surfaces.

    ``emit=False`` collects alerts in ``self.alerts`` without side
    effects — the offline mode ``tools/health`` runs over merged
    history files. ``refire_s`` suppresses repeat alerts per
    (kind, series) so a sustained regression pages once per window,
    not once per sample."""

    def __init__(self, specs: Optional[List[DetectorSpec]] = None, *,
                 emit: bool = True, rank: int = -1, replica: int = -1,
                 webhook_url: Optional[str] = None,
                 refire_s: float = 60.0,
                 alert_sink: Optional[Callable[[Alert], None]] = None):
        self.specs = specs if specs is not None else default_specs()
        self.emit = emit
        self.rank = rank
        self.replica = replica
        self.webhook_url = webhook_url
        self.refire_s = refire_s
        self.alert_sink = alert_sink
        self.alerts: List[Alert] = []
        self._detectors: Dict[Tuple[int, str], object] = {}
        self._route: Dict[str, List[int]] = {}
        self._last_fire: Dict[Tuple[str, str], float] = {}
        self._m_alerts = _reg.registry().counter(
            "hvdtpu_health_alerts_total",
            "Health alerts fired by the online detector plane, by "
            "alert kind and severity (docs/health.md)")

    def observe(self, series: Dict[str, float], t: float,
                t_unix: Optional[float] = None) -> List[Alert]:
        """Feed one history sample's series; returns alerts fired."""
        fired: List[Alert] = []
        for key, v in series.items():
            if v is None:
                continue
            route = self._route.get(key)
            if route is None:
                route = [i for i, s in enumerate(self.specs)
                         if s.matches(key)]
                self._route[key] = route
            for i in route:
                spec = self.specs[i]
                det = self._detectors.get((i, key))
                if det is None:
                    det = spec.factory()
                    self._detectors[(i, key)] = det
                ev = det.update(t, float(v))
                if not ev:
                    continue
                last = self._last_fire.get((spec.kind, key))
                if last is not None and t - last < self.refire_s:
                    continue
                self._last_fire[(spec.kind, key)] = t
                fired.append(self._fire(spec, key, float(v), ev,
                                        t_unix if t_unix is not None
                                        else time.time()))
        return fired

    def fire(self, kind: str, severity: str, series: str, value: float,
             *, baseline: float = 0.0,
             evidence: Optional[dict] = None,
             t: Optional[float] = None,
             t_unix: Optional[float] = None) -> Optional[Alert]:
        """Fire a typed alert directly, bypassing the detector plane —
        the same fan-out (metric/recorder/log/policy/webhook) with the
        same per-(kind, series) refire suppression. The numerics
        plane's same-step sentinels (nonfinite payloads, fingerprint
        divergence) use this: their evidence is exact, not statistical,
        so no windowed detector should gate them. Returns None when
        refire-suppressed."""
        t = time.monotonic() if t is None else t
        last = self._last_fire.get((kind, series))
        if last is not None and t - last < self.refire_s:
            return None
        self._last_fire[(kind, series)] = t
        ev = dict(evidence or {})
        ev.setdefault("baseline", baseline)
        spec = DetectorSpec(kind, severity, (), "", lambda: None)
        return self._fire(spec, series, float(value), ev,
                          t_unix if t_unix is not None else time.time())

    def _fire(self, spec: DetectorSpec, key: str, value: float,
              evidence: dict, t_unix: float) -> Alert:
        alert = Alert(
            kind=spec.kind, severity=spec.severity, series=key,
            rank=self.rank, replica=self.replica, value=value,
            baseline=float(evidence.get("baseline", 0.0)),
            window_s=float(evidence.get("window_s", 0.0)),
            t_unix=t_unix, evidence=evidence)
        self.alerts.append(alert)
        if len(self.alerts) > 1024:
            del self.alerts[:512]
        if not self.emit:
            return alert
        self._m_alerts.labels(kind=alert.kind,
                              severity=alert.severity).inc()
        from . import flight_recorder as _flight
        _flight.recorder().note("alert", (
            alert.kind, alert.severity, alert.series,
            alert.replica if alert.replica >= 0 else alert.rank,
            round(alert.value, 6), round(alert.baseline, 6)))
        _log.warning(
            "health_alert kind=%s severity=%s series=%s rank=%d "
            "replica=%d value=%.6g baseline=%.6g window_s=%.1f",
            alert.kind, alert.severity, alert.series, alert.rank,
            alert.replica, alert.value, alert.baseline, alert.window_s)
        if alert.kind in POLICY_ALERT_KINDS:
            queue_policy_alert(alert)
        if self.alert_sink is not None:
            try:
                self.alert_sink(alert)
            except Exception as e:  # pragma: no cover - defensive
                _log.warning("alert sink failed: %s", e)
        if self.webhook_url:
            post_webhook(self.webhook_url, alert.to_dict())
        return alert


def _coordinator_alert_sink(alert: Alert) -> None:
    """Forward a ladder-input alert to the rank-0 coordinator over the
    existing control-plane channel (best-effort; docs/health.md#
    adaptation). Only multi-process fallback engines hold a client —
    single-process jobs feed the policy through the local queue."""
    if alert.kind not in POLICY_ALERT_KINDS:
        return
    try:
        from ..ops import collective as _coll
        eng = _coll._engine
        client = getattr(eng, "_mp_client", None) if eng else None
        if client is not None and alert.rank > 0:
            client.note_alert(alert.kind, alert.rank, alert.severity,
                              alert.value)
    except Exception as e:
        _log.debug("coordinator alert forward failed: %s", e)


def default_monitor() -> HealthMonitor:
    """The live monitor ``hvd.init()`` hands the history sampler: local
    rank identity, webhook on rank 0 only (one receiver, not N copies),
    cross-rank policy forwarding armed."""
    from . import flight_recorder as _flight
    rank = max(_flight.recorder().rank, 0)
    try:
        from .. import topology as _topo
        rank = _topo._get().process_index
    except Exception:
        pass
    url = _env.alert_url() if rank == 0 else None
    return HealthMonitor(rank=rank, webhook_url=url,
                         alert_sink=_coordinator_alert_sink)
