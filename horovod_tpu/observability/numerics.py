"""Numerics observability plane — the plane that watches the *numbers*
(docs/numerics.md).

Every plane so far watches *time* (step latency, TTFT, HBM, queue
depth); nothing watches whether the values flowing through the job are
still finite, still sane, still identical across ranks. This module is
that plane:

  - **Step telemetry** — global gradient norm, per-source nonfinite
    element counts, loss value and update/param-norm ratio, computed
    *in-graph* by ``build_train_step`` as one small piggybacked
    reduction and read back with a one-step deferral (the host never
    blocks on the current step's device values), exported as the
    ``hvdtpu_numerics_*`` families the history store samples.
  - **Nonfinite sentinels** — the engine's fused-pack path and the
    torch shim's bucket fill count nonfinite elements on the LOCAL,
    pre-reduction payload (a post-allreduce NaN has already spread to
    every rank — only the local count can name the producer), and
    :func:`note_nonfinite` fires a same-step ``nonfinite_rate`` alert
    through the health plane's own fan-out (metric + flight recorder +
    log + webhook) the moment a count lands.
  - **Cross-rank divergence fingerprints** — :func:`fingerprint_tree`
    reduces a param tree to per-leaf ``(norm, crc-of-seeded-subsample)``
    digests; ranks ship them over the existing coordinator channel
    (``note_fingerprint``) and rank 0 majority-compares each step's set
    (:func:`record_fingerprint`), firing a typed ``rank_divergence``
    alert naming the first divergent leaf and rank.
  - **Quantization drift** — per-group error-feedback residual norms
    land in ``hvdtpu_numerics_ef_residual_norm`` via
    :func:`note_ef_residual`; a trend detector
    (observability/health.py) watches the series and a sustained drift
    alert lets the adaptation policy back a quantized wire off to fp32
    (docs/adaptation.md).

Design constraints (same bar as the registry / flight recorder):

  - OFF BY DEFAULT, SINGLE-FLAG NO-OP: everything here is gated on the
    module-global ``_enabled`` (armed by ``HOROVOD_TPU_NUMERICS=1`` at
    ``hvd.init()`` or ``set_enabled(True)``); a disabled plane costs
    one flag check at each hook site.
  - NO EXTRA HOST SYNC: in-graph stats ride the step's own jitted
    program as extra replicated outputs; the host materializes step
    N's stats while step N+1 runs (:class:`StepStats`).
  - ATTRIBUTABLE: nonfinite counts are measured pre-reduction, and the
    in-graph counter returns a per-rank vector (each shard deposits
    its local count at its own linear mesh index) so the alert can say
    *which rank* produced the first NaN.
"""

from __future__ import annotations

import math
import threading
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils import env as _env
from ..utils.logging import get_logger
from . import registry as _reg

_log = get_logger("observability.numerics")

# How many elements the fingerprint subsample covers per leaf. Index 0
# is always included — the deterministic corruption clause
# (``bitflip_param``, adaptation/faults.py) flips element 0, so the crc
# catches it with certainty; the remaining indices are drawn from a
# per-leaf seeded generator so two leaves never share a sample pattern.
FINGERPRINT_SAMPLE = 16

# Recording lever — module-global single check like registry._enabled,
# but OFF by default: numerics telemetry is opt-in
# (HOROVOD_TPU_NUMERICS=1), unlike the always-on metrics registry.
_enabled = False


def enabled() -> bool:
    return _enabled


def set_enabled(value: bool) -> None:
    global _enabled
    _enabled = bool(value)


def maybe_enable_from_env() -> bool:
    """Arm the plane from ``HOROVOD_TPU_NUMERICS`` (called by
    ``hvd.init()``; idempotent)."""
    if _env.numerics_enabled():
        set_enabled(True)
    return _enabled


# --------------------------------------------------------------------------
# Metric families (docs/metrics.md) — resolved lazily, cached.
# --------------------------------------------------------------------------

_fams: Optional[dict] = None
_fams_lock = threading.Lock()


def _families() -> dict:
    global _fams
    if _fams is None:
        with _fams_lock:
            if _fams is None:
                r = _reg.registry()
                _fams = {
                    "nonfinite": r.counter(
                        "hvdtpu_numerics_nonfinite_total",
                        "Nonfinite (NaN/Inf) elements observed in local "
                        "pre-reduction payloads, by source "
                        "(docs/numerics.md)"),
                    "grad_norm": r.gauge(
                        "hvdtpu_numerics_grad_norm",
                        "Global (post-reduction) gradient L2 norm of the "
                        "last completed training step"),
                    "loss": r.gauge(
                        "hvdtpu_numerics_loss",
                        "Loss value of the last completed training step"),
                    "update_ratio": r.gauge(
                        "hvdtpu_numerics_update_ratio",
                        "Update-norm / param-norm ratio of the last "
                        "completed training step (learning-rate "
                        "sanity signal)"),
                    "ef_residual": r.gauge(
                        "hvdtpu_numerics_ef_residual_norm",
                        "Error-feedback residual L2 norm per quantized "
                        "group — the live quantization-drift signal, "
                        "by group"),
                    "fingerprints": r.counter(
                        "hvdtpu_numerics_fingerprints_total",
                        "Cross-rank param fingerprint events, by event "
                        "(computed/compared/mismatch)"),
                }
    return _fams


# --------------------------------------------------------------------------
# Immediate alerts — the health plane's fan-out, without a detector
# --------------------------------------------------------------------------

_monitor = None
_monitor_lock = threading.Lock()


def _alert_monitor():
    """A spec-less HealthMonitor used purely for its alert fan-out
    (metric + recorder + log + policy + webhook) — one implementation
    of "fire a typed alert" shared with the windowed detector plane.
    Prefers the sampler's live monitor (so e2e surfaces like
    ``monitor.alerts`` see immediate alerts too) and falls back to a
    private one when no sampler is running."""
    global _monitor
    from . import history as _history
    s = _history.sampler()
    if s is not None and s.monitor is not None:
        return s.monitor
    if _monitor is None:
        with _monitor_lock:
            if _monitor is None:
                from . import health as _health
                _monitor = _health.HealthMonitor(
                    specs=[], rank=_process_index(),
                    alert_sink=_health._coordinator_alert_sink)
    return _monitor


def fire_alert(kind: str, severity: str, series: str, value: float, *,
               baseline: float = 0.0, evidence: Optional[dict] = None):
    """Fire a typed health alert NOW (same-step path — no detector
    window). Refire-suppressed per (kind, series) like the detector
    plane, so a NaN that persists for 500 steps pages once per window,
    not 500 times. Returns the Alert or None (suppressed)."""
    try:
        return _alert_monitor().fire(kind, severity, series, value,
                                     baseline=baseline,
                                     evidence=evidence)
    except Exception as e:  # telemetry must never kill the step
        _log.warning("numerics alert failed: %s", e)
        return None


# --------------------------------------------------------------------------
# Nonfinite sentinels
# --------------------------------------------------------------------------

def count_nonfinite(buf) -> int:
    """Nonfinite element count of a host buffer (numpy view; no copy).
    Integer dtypes are finite by construction and return 0.

    Clean-path cost matters: this runs on the engine cycle thread while
    the training thread spins in ``wait()``, so every extra Python-level
    numpy call ping-pongs the GIL (measured ~10x the isolated cost).
    The fast path is ONE dot product — BLAS releases the GIL, and a
    finite sum of squares proves every element finite (squares are
    non-negative, so infinities cannot cancel; any NaN/Inf element
    forces a NaN/Inf dot). A finite-but-overflowing buffer merely falls
    through to the exact count, which answers 0."""
    a = np.asarray(buf)
    if a.dtype.kind != "f":
        return 0
    if a.ndim == 1 and math.isfinite(float(np.dot(a, a))):
        return 0
    return int(a.size - np.count_nonzero(np.isfinite(a)))


def note_nonfinite(count: int, *, source: str, step: int = -1,
                   rank: Optional[int] = None, detail: str = "") -> None:
    """Record nonfinite elements observed in a local payload: counter +
    flight-recorder ``numerics`` event + same-step ``nonfinite_rate``
    alert. No-ops on count<=0 — call sites pass raw counts and this
    stays the single branch on the clean path."""
    if count <= 0 or not _enabled:
        return
    who = rank if rank is not None else _process_index()
    _families()["nonfinite"].labels(source=source).inc(count)
    from . import flight_recorder as _flight
    _flight.recorder().note("numerics", (
        "nonfinite", step, who, count, (detail or source)[:120]))
    fire_alert(
        "nonfinite_rate", "critical",
        f'hvdtpu_numerics_nonfinite_total{{source="{source}"}}',
        float(count),
        evidence={"step": step, "rank": who, "source": source,
                  "detail": detail})


# One sentinel tick per scanned fusion buffer — for the common
# one-fused-allreduce-per-step loop this counts training steps, the
# same convention the fault injector's tick stream uses.
_scan_tick = 0


def scan_payload(buf, *, source: str = "collective") -> int:
    """Nonfinite sentinel for the engine's fused-pack path: count
    nonfinite elements in an already-packed LOCAL buffer (one
    ``np.isfinite`` pass over contiguous host memory, piggybacked on
    the pack the engine just paid for) and raise the same-step alert
    if any. Returns the count. Gated on :func:`enabled` — the caller
    only pays one flag check when the plane is off."""
    global _scan_tick
    if not _enabled:
        return 0
    t = _scan_tick
    _scan_tick = t + 1
    c = count_nonfinite(buf)
    if c:
        note_nonfinite(c, source=source, step=t)
    return c


def note_loss(step: int, loss: float) -> None:
    """Record a completed step's loss; a nonfinite loss is itself a
    sentinel (the classic overnight-NaN page)."""
    if not _enabled:
        return
    if math.isfinite(loss):
        _families()["loss"].set(loss)
    else:
        note_nonfinite(1, source="loss", step=step, detail="loss")


def note_ef_residual(group: str, norm: float) -> None:
    """Per-group error-feedback residual norm — the quantization-drift
    series the trend detector watches (docs/numerics.md#drift)."""
    if not _enabled or not math.isfinite(norm):
        return
    _families()["ef_residual"].labels(group=str(group)[:60]).set(norm)


# --------------------------------------------------------------------------
# Deferred in-graph step stats (build_train_step aux channel)
# --------------------------------------------------------------------------

class StepStats:
    """Host-side sink for the train step's in-graph numerics aux.

    ``note(step, loss, aux)`` stores the CURRENT step's device values
    and materializes the PREVIOUS step's (whose program has long since
    finished) — the host never blocks on in-flight device work, so the
    plane adds no synchronization to the step loop. ``flush()`` drains
    the last pending step (end of training / final gasp)."""

    def __init__(self):
        self._pending: Optional[Tuple[int, object, dict]] = None
        self._lock = threading.Lock()

    def note(self, step: int, loss, aux: dict) -> None:
        if not _enabled:
            return
        with self._lock:
            prev, self._pending = self._pending, (step, loss, aux)
        if prev is not None:
            self._materialize(*prev)

    def flush(self) -> None:
        with self._lock:
            prev, self._pending = self._pending, None
        if prev is not None:
            self._materialize(*prev)

    def _materialize(self, step: int, loss, aux: dict) -> None:
        try:
            fams = _families()
            loss_v = float(np.asarray(loss))
            note_loss(step, loss_v)
            gn = aux.get("grad_norm")
            if gn is not None:
                gn = float(np.asarray(gn))
                if math.isfinite(gn):
                    fams["grad_norm"].set(gn)
            ur = aux.get("update_ratio")
            if ur is not None:
                ur = float(np.asarray(ur))
                if math.isfinite(ur):
                    fams["update_ratio"].set(ur)
            nf = aux.get("nonfinite_by_rank")
            if nf is not None:
                nf = np.asarray(nf)
                for r in np.nonzero(nf)[0]:
                    note_nonfinite(int(nf[r]), source="grad", step=step,
                                   rank=int(r), detail="train_step")
        except Exception as e:  # pragma: no cover - defensive
            _log.warning("numerics step stats failed: %s", e)


_step_stats = StepStats()


def step_stats() -> StepStats:
    """The process-global step-stats sink ``build_train_step`` feeds."""
    return _step_stats


# --------------------------------------------------------------------------
# Param-tree fingerprints (divergence + checkpoint integrity)
# --------------------------------------------------------------------------

def _leaf_paths(tree) -> List[Tuple[str, object]]:
    """Stable ``(path, leaf)`` pairs — jax keypath rendering, sorted by
    path so every rank enumerates identically."""
    import jax
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]
    out.sort(key=lambda kv: kv[0])
    return out


def _sample_indices(name: str, n: int, k: int, seed: int) -> np.ndarray:
    rng = np.random.default_rng(
        (zlib.crc32(name.encode()) ^ (seed & 0xFFFFFFFF)) & 0xFFFFFFFF)
    if n <= k:
        return np.arange(n)
    idx = rng.integers(1, n, size=k - 1)
    return np.concatenate(([0], idx))  # element 0 always sampled


def fingerprint_leaf(name: str, arr, *, k: int = FINGERPRINT_SAMPLE,
                     seed: int = 0) -> List:
    """``[norm, crc, n]`` digest of one leaf: float64 L2 norm (host
    accumulation — deterministic given identical values) + crc32 of the
    raw bytes of a seeded deterministic ``k``-element subsample. Two
    replicas holding bitwise-identical leaves produce identical
    digests; a single flipped mantissa bit changes the norm and — for
    element 0 or any sampled element — the crc."""
    a = np.asarray(arr).reshape(-1)
    if a.size == 0:
        return [0.0, 0, 0]
    norm = float(np.sqrt(np.sum(np.square(a.astype(np.float64)))))
    idx = _sample_indices(name, a.size, k, seed)
    crc = zlib.crc32(np.ascontiguousarray(a[idx]).tobytes())
    return [norm, int(crc), int(a.size)]


def fingerprint_tree(tree, *, seed: int = 0) -> Dict[str, List]:
    """Per-leaf digests of a whole param tree, keyed by jax keypath.
    Pulls each leaf to host — cheap at fingerprint cadence (default
    every ``HOROVOD_TPU_NUMERICS_FP_INTERVAL`` steps), not a hot-path
    call."""
    out = {}
    for name, leaf in _leaf_paths(tree):
        out[name] = fingerprint_leaf(name, leaf, seed=seed)
    if _enabled:
        _families()["fingerprints"].labels(event="computed").inc()
    return out


def compare_fingerprints(by_rank: Dict[int, Dict[str, List]]
                         ) -> List[Tuple[str, int]]:
    """Majority-compare one step's per-rank digests. Returns
    ``(leaf, rank)`` mismatches — every rank whose digest for a leaf
    disagrees with the majority value, first divergent leaf first
    (path-sorted, matching :func:`_leaf_paths` order)."""
    if len(by_rank) < 2:
        return []
    leaves = sorted({leaf for d in by_rank.values() for leaf in d})
    out: List[Tuple[str, int]] = []
    for leaf in leaves:
        votes: Dict[tuple, List[int]] = {}
        for rank, digests in by_rank.items():
            key = tuple(digests.get(leaf, []))
            votes.setdefault(key, []).append(rank)
        if len(votes) <= 1:
            continue
        majority = max(votes.values(), key=len)
        for key, ranks in votes.items():
            if ranks is majority:
                continue
            out.extend((leaf, r) for r in sorted(ranks))
    return out


# ---- rank-0 collection point (the coordinator service feeds this) -------

_fp_lock = threading.Lock()
_fp_pending: Dict[int, Dict[int, Dict[str, List]]] = {}  # step -> rank -> d


def record_fingerprint(rank: int, step: int, digests: Dict[str, List],
                       world: int) -> List[Tuple[str, int]]:
    """Rank-0 side of the divergence check: stash one rank's digests
    for a step and, once all ``world`` ranks reported (or a newer step
    starts arriving), majority-compare and fire one typed
    ``rank_divergence`` alert per divergent (leaf, rank). Returns the
    mismatches (tests / the coordinator's log line)."""
    ready: Optional[Dict[int, Dict[str, List]]] = None
    ready_step = step
    with _fp_lock:
        _fp_pending.setdefault(step, {})[rank] = digests
        if len(_fp_pending[step]) >= max(world, 2):
            ready = _fp_pending.pop(step)
        elif len(_fp_pending) > 4:
            # The oldest pending step can no longer complete (a rank
            # died or skipped its probe) — compare what did arrive so
            # a divergence is still caught, and stop accumulating.
            ready_step = min(_fp_pending)
            ready = _fp_pending.pop(ready_step)
    if ready is None:
        return []
    mismatches = compare_fingerprints(ready)
    fams = _families()
    fams["fingerprints"].labels(event="compared").inc()
    if not mismatches:
        return []
    fams["fingerprints"].labels(event="mismatch").inc(len(mismatches))
    from . import flight_recorder as _flight
    for leaf, bad_rank in mismatches:
        _flight.recorder().note("numerics", (
            "divergence", ready_step, bad_rank, 1, leaf[:120]))
        fire_alert(
            "rank_divergence", "critical",
            f"hvdtpu_numerics_fingerprint:{leaf}", 1.0,
            evidence={"step": ready_step, "rank": bad_rank,
                      "leaf": leaf,
                      "ranks_reporting": sorted(ready)})
    first_leaf, first_rank = mismatches[0]
    _log.error("rank_divergence at step %d: leaf %s on rank %d "
               "disagrees with the majority fingerprint "
               "(%d mismatch(es) total)", ready_step, first_leaf,
               first_rank, len(mismatches))
    return mismatches


def reset_fingerprints() -> None:
    """Test hook: forget pending per-step digests."""
    with _fp_lock:
        _fp_pending.clear()


def maybe_send_fingerprint(tree, step: int) -> Optional[Dict[str, List]]:
    """Periodic divergence probe for a training loop: at the configured
    cadence, digest the param tree and ship it to rank 0 over the
    existing coordinator channel (best-effort, single attempt — exactly
    like ``note_alert``). Single-process jobs (no coordinator client)
    feed :func:`record_fingerprint` directly, which is a no-op below
    two ranks. Returns the digests when a probe ran (tests)."""
    if not _enabled:
        return None
    interval = _env.numerics_fp_interval()
    if interval <= 0 or step % interval != 0:
        return None
    digests = fingerprint_tree(tree)
    rank, world = _process_rank_world()
    client = _coordinator_client()
    if client is not None and rank > 0:
        client.note_fingerprint(step, digests)
    else:
        record_fingerprint(rank, step, digests, world)
    return digests


def _coordinator_client():
    try:
        from ..ops import collective as _coll
        eng = _coll._engine
        return getattr(eng, "_mp_client", None) if eng else None
    except Exception:
        return None


def _process_index() -> int:
    import os
    try:
        from .. import topology as _topo
        return _topo._get().process_index
    except Exception:
        return int(os.environ.get("HOROVOD_TPU_PROCESS_ID", "0") or 0)


def _process_rank_world() -> Tuple[int, int]:
    import os
    try:
        from .. import topology as _topo
        t = _topo._get()
        return t.process_index, t.process_count
    except Exception:
        return (int(os.environ.get("HOROVOD_TPU_PROCESS_ID", "0") or 0),
                int(os.environ.get("HOROVOD_TPU_NPROCS", "1") or 1))


# --------------------------------------------------------------------------
# Deterministic corruption (the bitflip_param fault clause)
# --------------------------------------------------------------------------

def flip_mantissa_bit(arr, index: int = 0, bit: int = 0):
    """Return a copy of ``arr`` with one mantissa bit of element
    ``index`` flipped — the minimal silent-data-corruption primitive
    the fingerprint compare is proven against. Works on any float
    dtype via its same-width unsigned view."""
    a = np.array(np.asarray(arr), copy=True)
    flat = a.reshape(-1)
    u = flat.view(f"u{a.dtype.itemsize}")
    u[index] ^= np.array(1 << bit, dtype=u.dtype)
    return a


def maybe_bitflip(tree, step: int):
    """Apply any armed ``bitflip_param`` fault clause to the tree at
    its chosen step (adaptation/faults.py). Zero cost when no injector
    is armed (one ``is None`` check); returns the (possibly corrupted)
    tree. The flip targets element 0 of the first leaf whose path
    contains the clause's ``leaf=`` substring (first leaf overall when
    unnamed) — element 0 is always in the fingerprint subsample, so
    the compare at rank 0 names the leaf with certainty."""
    from ..adaptation import faults as _faults_mod
    inj = _faults_mod.injector()
    if inj is None:
        return tree
    patterns = inj.take_bitflips(step)
    if not patterns:
        return tree
    import jax
    for pattern in patterns:
        flat = _leaf_paths(tree)
        target = None
        for name, leaf in flat:
            if not pattern or pattern in name:
                target = name
                break
        if target is None:
            _log.warning("bitflip_param: no leaf matches %r", pattern)
            continue

        def _rewrite(path, leaf, _target=target):
            name = jax.tree_util.keystr(path)
            if name == _target:
                return flip_mantissa_bit(leaf)
            return leaf

        tree = jax.tree_util.tree_map_with_path(_rewrite, tree)
        _log.error("fault injection: bitflip_param at step %d flipped "
                   "one mantissa bit in leaf %s", step, target)
    return tree
