"""One shared timer thread for every periodic telemetry task.

Before the history plane landed, each periodic exporter spawned its own
daemon thread (the JSON metrics writer, and would-be samplers after it)
— N wakeup loops for N exporters, each with its own stop event and
join path. This module is the consolidation: a single process-global
scheduler thread (``hvd-tpu-telemetry``) owning every periodic
telemetry callback, each with its own interval. The JSON snapshot
writer (export.py) and the telemetry history sampler (history.py) both
register here; a regression test asserts exactly one telemetry timer
thread exists no matter how many exporters are armed.

Semantics:

  - Callbacks run ON the shared thread — they must be quick (a snapshot
    + file write, not a training step) and never raise; exceptions are
    caught and logged so one broken exporter cannot starve the rest.
  - Per-task intervals: the thread sleeps until the earliest next
    deadline. A task that overruns simply delays its next tick (and the
    other tasks' — the price of one thread, acceptable for
    second-scale telemetry cadences).
  - ``remove()`` runs the task's optional ``final`` callback (the
    exporters' flush-on-stop contract) and is idempotent.
  - The thread is created lazily on first ``add`` and parks when the
    task list empties — importing this module costs nothing.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from ..utils.logging import get_logger

_log = get_logger("observability.ticker")

THREAD_NAME = "hvd-tpu-telemetry"


class _Task:
    __slots__ = ("name", "interval_s", "fn", "final", "next_at")

    def __init__(self, name: str, interval_s: float, fn: Callable[[], None],
                 final: Optional[Callable[[], None]]):
        self.name = name
        self.interval_s = max(0.05, float(interval_s))
        self.fn = fn
        self.final = final
        self.next_at = time.monotonic() + self.interval_s


class Ticker:
    """The shared periodic-task scheduler (one per process via
    :func:`ticker`; instantiable directly for tests)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._tasks: Dict[int, _Task] = {}
        self._next_id = 0
        self._thread: Optional[threading.Thread] = None
        self._stopping = False

    def add(self, name: str, interval_s: float, fn: Callable[[], None],
            final: Optional[Callable[[], None]] = None) -> int:
        """Register ``fn`` to run every ``interval_s`` seconds on the
        shared thread; returns a handle for :meth:`remove`. ``final``
        (optional) runs once at removal — the flush-on-stop hook."""
        with self._lock:
            self._next_id += 1
            handle = self._next_id
            self._tasks[handle] = _Task(name, interval_s, fn, final)
            if self._thread is None or not self._thread.is_alive():
                self._stopping = False
                self._thread = threading.Thread(
                    target=self._loop, name=THREAD_NAME, daemon=True)
                self._thread.start()
        self._wake.set()
        return handle

    def remove(self, handle: int) -> None:
        """Unregister; runs the task's ``final`` callback (on the
        caller's thread — remove-at-exit must flush even when the
        scheduler thread is already torn down). Idempotent."""
        with self._lock:
            task = self._tasks.pop(handle, None)
        self._wake.set()
        if task is not None and task.final is not None:
            try:
                task.final()
            except Exception as e:  # never fail teardown over telemetry
                _log.warning("final flush of %s failed: %s", task.name, e)

    def tasks(self) -> Dict[int, str]:
        """Live task names by handle (tests / diagnostics)."""
        with self._lock:
            return {h: t.name for h, t in self._tasks.items()}

    def stop(self) -> None:
        """Tear down: run every final callback and stop the thread."""
        with self._lock:
            handles = list(self._tasks)
        for h in handles:
            self.remove(h)
        with self._lock:
            self._stopping = True
            thread = self._thread
            self._thread = None
        self._wake.set()
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=5.0)

    # --------------------------------------------------------------- loop

    def _loop(self) -> None:
        while True:
            with self._lock:
                if self._stopping:
                    return
                now = time.monotonic()
                due = [t for t in self._tasks.values() if t.next_at <= now]
                for t in due:
                    # Fixed cadence from now — an overrunning task skips
                    # ticks instead of bursting to catch up.
                    t.next_at = now + t.interval_s
                nxt = min((t.next_at for t in self._tasks.values()),
                          default=None)
            for t in due:
                try:
                    t.fn()
                except Exception as e:  # one bad exporter != all dead
                    _log.warning("telemetry task %s failed: %s", t.name, e)
            if nxt is None:
                # No tasks: park until add() wakes us (lazy thread that
                # never spins on an empty schedule).
                self._wake.wait()
            else:
                self._wake.wait(timeout=max(0.0, nxt - time.monotonic()))
            self._wake.clear()


_ticker = Ticker()


def ticker() -> Ticker:
    """The process-global telemetry scheduler — ONE timer thread shared
    by every periodic exporter (JSON writer, history sampler)."""
    return _ticker
