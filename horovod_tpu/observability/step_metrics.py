"""Per-step training telemetry shared by the framework shims.

The Horovod paper's headline diagnostic is *collective share of step
time* — the number that tells you whether you are compute-bound or
communication-bound, and whether tensor fusion / compression is paying
off (PAPERS.md, arxiv 1802.05799 §5). :class:`StepTimer` computes it
from the registry itself: the engine accounts every fused collective's
execution seconds into ``hvdtpu_op_execute_seconds_total`` (across ALL
ops — allreduce, allgather, broadcast) and its control-plane wait into
the ``negotiate`` phase of ``hvdtpu_op_phase_seconds``, so the
breakdown needs no framework-specific hooks into the collective path.

Per-step attribution (docs/metrics.md, docs/postmortem.md): each step
is decomposed into

  - ``input``      the gap between the previous step's ``end()`` and
                   this step's ``begin()`` — time spent waiting on the
                   data pipeline,
  - ``h2d``        host→device transfer, measured when the loop calls
                   :meth:`mark_h2d_done` after staging the batch,
  - ``collective`` fused-program execute seconds plus negotiate-phase
                   wait (the engine's own counters, delta over the
                   step),
  - ``compute``    the step remainder.

exported as ``hvdtpu_step_phase_seconds{phase=}`` histograms and
``hvdtpu_step_phase_share{phase=}`` gauges, plus an MFU gauge (FLOPs
from ``lowered.cost_analysis()`` via :func:`flops_of_lowered` or a
user-supplied ``flops_per_step``) and HBM live/peak gauges from
``device.memory_stats()``. When the engine's Python timeline is active,
the same breakdown is emitted as ``STEP_*`` spans so ``python -m
horovod_tpu.tools.trace report`` can render a per-rank input-bound vs
compute-bound vs comm-bound verdict (docs/tracing.md).

One class serves all three shims:

  - Keras: :class:`horovod_tpu.keras.callbacks.MetricsCallback` wraps it
    in the callback API.
  - torch / TF: exported as ``horovod_tpu.torch.StepMetrics`` /
    ``horovod_tpu.tensorflow.StepMetrics`` — use as a context manager
    around each step::

        metrics = hvd.torch.StepMetrics(batch_size=64)
        for batch in loader:
            with metrics:
                train_step(batch)

Recorded metrics (all labeled ``framework=...`` unless noted):
  - ``hvdtpu_step_seconds`` (histogram)
  - ``hvdtpu_step_phase_seconds`` / ``hvdtpu_step_phase_share``
    (histogram / gauge, also labeled ``phase=``)
  - ``hvdtpu_samples_total`` (counter)
  - ``hvdtpu_samples_per_second`` (gauge, last step)
  - ``hvdtpu_collective_step_share`` (gauge in [0, 1], last step;
    ``hvdtpu_allreduce_step_share`` remains as a deprecated alias)
  - ``hvdtpu_mfu`` / ``hvdtpu_model_flops_per_second`` (gauges, only
    when a FLOPs-per-step figure is known; MFU additionally needs a
    peak — HOROVOD_TPU_PEAK_FLOPS or the TPU device-kind table)
  - ``hvdtpu_hbm_bytes_in_use`` / ``hvdtpu_hbm_peak_bytes`` (gauges,
    labeled ``device=``; falls back to host RSS when the backend has no
    ``memory_stats``, labeled ``device="host"``)
"""

from __future__ import annotations

import time
from typing import Optional

from . import registry as _reg
from ..utils import env as _env

STEP_PHASES = ("input", "h2d", "compute", "collective")

# Peak dense FLOP/s per chip by device kind (bf16; the MFU denominator
# when HOROVOD_TPU_PEAK_FLOPS is unset). Matching is substring-based on
# jax's Device.device_kind. CPU backends have no entry — MFU is simply
# not exported there unless the env var supplies a peak.
_PEAK_FLOPS_BY_KIND = (
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def flops_of_lowered(lowered) -> Optional[float]:
    """FLOPs of one invocation of a lowered/compiled jax computation,
    from XLA's ``cost_analysis()`` — pass the result as
    ``StepTimer(..., flops_per_step=...)``::

        lowered = jax.jit(train_step).lower(params, batch)
        timer = StepTimer("torch", flops_per_step=flops_of_lowered(
            lowered.compile()))

    Accepts a ``jax.stages.Lowered`` or ``Compiled``; returns None when
    the backend exposes no cost analysis (the caller then supplies its
    own analytic figure)."""
    try:
        ca = lowered.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not ca:
        return None
    flops = ca.get("flops", 0.0)
    return float(flops) if flops else None


def _local_peak_flops() -> Optional[float]:
    """Peak FLOP/s across this process's devices (env override first,
    then the device-kind table); None when unknown."""
    env_peak = _env.peak_flops()
    if env_peak is not None:
        return env_peak
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return None
    total = 0.0
    for d in devices:
        kind = str(getattr(d, "device_kind", "")).lower()
        for marker, peak in _PEAK_FLOPS_BY_KIND:
            if marker in kind:
                total += peak
                break
    return total or None


def _collective_execute_seconds() -> float:
    """Execute-seconds across ALL collective ops (allreduce, allgather,
    broadcast — fused groups of any kind count; the old implementation
    read only ``op="allreduce"`` and under-reported mixed workloads)."""
    fam = _reg.registry().counter(
        "hvdtpu_op_execute_seconds_total",
        "Cumulative wall seconds executing fused collective groups")
    return sum(child.value for _, child in fam.items())


def _negotiate_wait_seconds() -> float:
    """Cumulative negotiate-phase seconds across all ops — the
    control-plane wait (enqueue → group delivered), which is where time
    waiting on a late peer lands."""
    fam = _reg.registry().histogram(
        "hvdtpu_op_phase_seconds",
        "Per-collective latency by lifecycle phase (negotiate = "
        "enqueue until the group is agreed/delivered; queue = "
        "delivery until XLA dispatch; execute = fused program wall "
        "time)", buckets=_reg.LATENCY_BUCKETS)
    return sum(child.sum for key, child in fam.items()
               if 'phase="negotiate"' in key)


class StepTimer:
    """Brackets one training step; records step time, samples/sec, the
    collective share of step time, and the input/h2d/compute/collective
    attribution. Cheap enough to leave on: a few ``time.perf_counter``
    calls and registry writes per step.

    ``flops_per_step`` (model FLOPs executed per step, e.g. from
    :func:`flops_of_lowered`) enables the ``hvdtpu_mfu`` /
    ``hvdtpu_model_flops_per_second`` gauges."""

    def __init__(self, framework: str, batch_size: Optional[int] = None,
                 flops_per_step: Optional[float] = None):
        self.framework = framework
        self.batch_size = batch_size
        self.flops_per_step = flops_per_step
        r = _reg.registry()
        labels = {"framework": framework}
        self._h_step = r.histogram(
            "hvdtpu_step_seconds", "Training step wall time",
            buckets=_reg.LATENCY_BUCKETS).labels(**labels)
        phase_h = r.histogram(
            "hvdtpu_step_phase_seconds",
            "Per-step attribution: input (data-pipeline wait before the "
            "step), h2d (host-to-device staging, via mark_h2d_done), "
            "collective (fused execute + negotiate wait), compute (the "
            "remainder)", buckets=_reg.LATENCY_BUCKETS)
        phase_g = r.gauge(
            "hvdtpu_step_phase_share",
            "Fraction of the last step cycle (input wait + step wall "
            "time) spent in each phase")
        self._h_phase = {p: phase_h.labels(framework=framework, phase=p)
                         for p in STEP_PHASES}
        self._g_phase = {p: phase_g.labels(framework=framework, phase=p)
                         for p in STEP_PHASES}
        self._c_samples = r.counter(
            "hvdtpu_samples_total", "Training samples processed"
        ).labels(**labels)
        self._g_rate = r.gauge(
            "hvdtpu_samples_per_second",
            "Samples/sec of the most recent step").labels(**labels)
        self._g_share = r.gauge(
            "hvdtpu_collective_step_share",
            "Fraction of the last step's wall time spent executing "
            "fused collective groups (all ops)").labels(**labels)
        # DEPRECATION ALIAS: the canonical series is
        # hvdtpu_collective_step_share (it counts every collective op,
        # not just allreduce); this name stays for existing dashboards
        # and now carries the same all-ops value.
        self._g_share_legacy = r.gauge(
            "hvdtpu_allreduce_step_share",
            "DEPRECATED alias of hvdtpu_collective_step_share").labels(
            **labels)
        # MFU/FLOPs children are resolved lazily on first set: an
        # eagerly-created child would export a misleading 0.0 for
        # timers that never supply a flops figure or have no known
        # peak.
        self._fam_mfu = r.gauge(
            "hvdtpu_mfu",
            "Model FLOPs utilization of the last step: flops_per_step / "
            "step seconds / local peak FLOP/s (needs flops_per_step and "
            "a known peak)")
        self._fam_flops = r.gauge(
            "hvdtpu_model_flops_per_second",
            "Model FLOP/s of the last step (needs flops_per_step)")
        self._g_mfu = None
        self._g_flops = None
        self._g_hbm = r.gauge(
            "hvdtpu_hbm_bytes_in_use",
            "Device memory currently allocated, per local device "
            "(device='host': process RSS fallback when the backend has "
            "no memory_stats)")
        self._g_hbm_peak = r.gauge(
            "hvdtpu_hbm_peak_bytes",
            "Peak device memory allocated, per local device (host "
            "fallback: peak RSS)")
        self._peak_flops = _local_peak_flops() if flops_per_step else None
        self._t0: Optional[float] = None
        self._t_prev_end: Optional[float] = None
        self._h2d_mark: Optional[float] = None
        self._h2d_credit = 0.0
        self._ar0 = 0.0
        self._neg0 = 0.0
        self._step_idx = 0
        self.last_step_s = 0.0
        self.last_samples_per_s = 0.0
        self.last_collective_share = 0.0
        self.last_phases = {p: 0.0 for p in STEP_PHASES}

    # Back-compat: pre-attribution callers read last_allreduce_share.
    @property
    def last_allreduce_share(self) -> float:
        return self.last_collective_share

    def begin(self) -> None:
        self._ar0 = _collective_execute_seconds()
        self._neg0 = _negotiate_wait_seconds()
        self._h2d_mark = None
        from . import flight_recorder as _fr
        _fr.recorder().note("step", (self._step_idx,))
        self._t0 = time.perf_counter()

    def mark_h2d_done(self) -> None:
        """Optional: call once the batch is staged on device — the time
        from ``begin()`` to this mark is attributed to ``h2d`` instead
        of ``compute``."""
        if self._t0 is not None:
            self._h2d_mark = time.perf_counter()

    def credit_h2d(self, seconds: float) -> None:
        """Attribute ``seconds`` of the NEXT step's pre-step gap to
        ``h2d`` instead of ``input``. The device prefetcher
        (docs/data.md#prefetch) calls this when the consumer blocked on
        a batch whose host→device copy was not fully overlapped: the
        wait happened before ``begin()``, where only the input phase
        could otherwise see it. Capped at the actual gap in ``end()``
        — crediting more than was waited cannot mint h2d time."""
        if seconds > 0:
            self._h2d_credit += seconds

    def _timeline(self):
        """The engine's Python timeline writer, if one is live (never
        creates an engine). Imported lazily: observability must stay
        importable before ops."""
        from ..ops import collective as _coll
        eng = _coll._engine
        return eng.timeline if eng is not None else None

    def _sample_memory(self) -> None:
        """HBM live/peak per local device; host-RSS fallback keeps the
        gauges present on backends without memory_stats (CPU tests)."""
        sampled = False
        try:
            import jax
            for d in jax.local_devices():
                stats_fn = getattr(d, "memory_stats", None)
                stats = stats_fn() if stats_fn is not None else None
                if not stats:
                    continue
                label = f"{d.platform}:{d.id}"
                in_use = stats.get("bytes_in_use")
                peak = stats.get("peak_bytes_in_use")
                if in_use is not None:
                    self._g_hbm.labels(device=label).set(float(in_use))
                    sampled = True
                if peak is not None:
                    self._g_hbm_peak.labels(device=label).set(float(peak))
        except Exception:
            pass
        if not sampled:
            try:
                import resource
                rss_page = 0
                try:
                    with open("/proc/self/statm") as f:
                        rss_page = int(f.read().split()[1])
                except OSError:
                    pass
                page = resource.getpagesize()
                peak_kb = resource.getrusage(
                    resource.RUSAGE_SELF).ru_maxrss
                if rss_page:
                    self._g_hbm.labels(device="host").set(
                        float(rss_page * page))
                self._g_hbm_peak.labels(device="host").set(
                    float(peak_kb) * 1024.0)
            except Exception:
                pass

    def end(self, samples: Optional[int] = None) -> None:
        if self._t0 is None:
            return
        t_end = time.perf_counter()
        t0 = self._t0
        dt = max(t_end - t0, 1e-9)
        self._t0 = None
        n = samples if samples is not None else self.batch_size
        self.last_step_s = dt
        self._h_step.observe(dt)
        if n:
            self.last_samples_per_s = n / dt
            self._c_samples.inc(n)
            self._g_rate.set(self.last_samples_per_s)

        # Attribution: input is the pre-step gap; collective is the
        # engine's own execute + negotiate-wait accounting over the
        # step; compute is what remains of the in-step wall time.
        input_s = (max(0.0, t0 - self._t_prev_end)
                   if self._t_prev_end is not None else 0.0)
        self._t_prev_end = t_end
        h2d_s = (max(0.0, self._h2d_mark - t0)
                 if self._h2d_mark is not None else 0.0)
        # Prefetcher-credited staging time: part of the pre-step gap was
        # an unoverlapped device copy, not the data source.
        credit = min(self._h2d_credit, input_s)
        self._h2d_credit = 0.0
        input_s -= credit
        h2d_s += credit
        exec_s = _collective_execute_seconds() - self._ar0
        neg_s = _negotiate_wait_seconds() - self._neg0
        collective_s = min(max(exec_s + neg_s, 0.0), dt)
        compute_s = max(0.0, dt - collective_s - h2d_s)
        phases = {"input": input_s, "h2d": h2d_s,
                  "compute": compute_s, "collective": collective_s}
        cycle = input_s + dt
        for p, v in phases.items():
            self._h_phase[p].observe(v)
            self._g_phase[p].set(v / cycle if cycle > 0 else 0.0)
        self.last_phases = phases

        share = min(max(exec_s, 0.0) / dt, 1.0)
        self.last_collective_share = max(share, 0.0)
        self._g_share.set(self.last_collective_share)
        self._g_share_legacy.set(self.last_collective_share)

        if self.flops_per_step:
            rate = self.flops_per_step / dt
            if self._g_flops is None:
                self._g_flops = self._fam_flops.labels(
                    framework=self.framework)
            self._g_flops.set(rate)
            if self._peak_flops:
                if self._g_mfu is None:
                    self._g_mfu = self._fam_mfu.labels(
                        framework=self.framework)
                self._g_mfu.set(rate / self._peak_flops)
        self._sample_memory()

        # Step spans into the live trace (Python writer only) so the
        # cross-rank report can attribute input/compute per rank; and a
        # step event into the flight recorder so the postmortem knows
        # the phase a dead rank was in (docs/postmortem.md).
        idx = self._step_idx
        self._step_idx += 1
        try:
            tl = self._timeline()
        except Exception:
            tl = None
        if tl is not None:
            # perf_counter and monotonic share the clock on CPython/
            # Linux; anchor the spans on monotonic to match the writer.
            now_m = time.monotonic()
            m_end = now_m - (time.perf_counter() - t_end)
            m_t0 = m_end - dt
            if input_s > 0:
                tl.execute_span("_step", "STEP_INPUT",
                                m_t0 - input_s, m_t0)
            if h2d_s > 0:
                tl.execute_span("_step", "STEP_H2D", m_t0, m_t0 + h2d_s)
            tl.execute_span("_step", "STEP_COMPUTE", m_t0 + h2d_s,
                            m_t0 + h2d_s + compute_s)
        from . import flight_recorder as _fr
        _fr.recorder().note("step_end", (
            idx, round(dt * 1e3, 3), round(input_s * 1e3, 3),
            round(h2d_s * 1e3, 3), round(compute_s * 1e3, 3),
            round(collective_s * 1e3, 3)))

    # Context-manager sugar for the torch/TF step loop.

    def __enter__(self) -> "StepTimer":
        self.begin()
        return self

    def __exit__(self, *exc) -> None:
        self.end()
