"""Per-step training telemetry shared by the framework shims.

The Horovod paper's headline diagnostic is *allreduce share of step
time* — the number that tells you whether you are compute-bound or
communication-bound, and whether tensor fusion / compression is paying
off (PAPERS.md, arxiv 1802.05799 §5). :class:`StepTimer` computes it
from the registry itself: the engine accounts every fused collective's
execution seconds into ``hvdtpu_op_execute_seconds_total``, so the
share is (counter delta across the step) / (step wall time) — no
framework-specific hooks into the collective path needed.

One class serves all three shims:

  - Keras: :class:`horovod_tpu.keras.callbacks.MetricsCallback` wraps it
    in the callback API.
  - torch / TF: exported as ``horovod_tpu.torch.StepMetrics`` /
    ``horovod_tpu.tensorflow.StepMetrics`` — use as a context manager
    around each step::

        metrics = hvd.torch.StepMetrics(batch_size=64)
        for batch in loader:
            with metrics:
                train_step(batch)

Recorded metrics (all labeled ``framework=...``):
  - ``hvdtpu_step_seconds`` (histogram)
  - ``hvdtpu_samples_total`` (counter)
  - ``hvdtpu_samples_per_second`` (gauge, last step)
  - ``hvdtpu_allreduce_step_share`` (gauge in [0, 1], last step)
"""

from __future__ import annotations

import time
from typing import Optional

from . import registry as _reg


def _allreduce_execute_seconds() -> float:
    fam = _reg.registry().counter(
        "hvdtpu_op_execute_seconds_total",
        "Cumulative wall seconds executing fused collective groups")
    return fam.labels(op="allreduce").value


class StepTimer:
    """Brackets one training step; records step time, samples/sec and
    the allreduce share of step time. Cheap enough to leave on: two
    ``time.perf_counter`` calls and four registry writes per step."""

    def __init__(self, framework: str, batch_size: Optional[int] = None):
        self.framework = framework
        self.batch_size = batch_size
        r = _reg.registry()
        labels = {"framework": framework}
        self._h_step = r.histogram(
            "hvdtpu_step_seconds", "Training step wall time",
            buckets=_reg.LATENCY_BUCKETS).labels(**labels)
        self._c_samples = r.counter(
            "hvdtpu_samples_total", "Training samples processed"
        ).labels(**labels)
        self._g_rate = r.gauge(
            "hvdtpu_samples_per_second",
            "Samples/sec of the most recent step").labels(**labels)
        self._g_share = r.gauge(
            "hvdtpu_allreduce_step_share",
            "Fraction of the last step's wall time spent executing "
            "allreduce groups").labels(**labels)
        self._t0: Optional[float] = None
        self._ar0 = 0.0
        self.last_step_s = 0.0
        self.last_samples_per_s = 0.0
        self.last_allreduce_share = 0.0

    def begin(self) -> None:
        self._ar0 = _allreduce_execute_seconds()
        self._t0 = time.perf_counter()

    def end(self, samples: Optional[int] = None) -> None:
        if self._t0 is None:
            return
        dt = max(time.perf_counter() - self._t0, 1e-9)
        self._t0 = None
        n = samples if samples is not None else self.batch_size
        self.last_step_s = dt
        self._h_step.observe(dt)
        if n:
            self.last_samples_per_s = n / dt
            self._c_samples.inc(n)
            self._g_rate.set(self.last_samples_per_s)
        share = min((_allreduce_execute_seconds() - self._ar0) / dt, 1.0)
        self.last_allreduce_share = max(share, 0.0)
        self._g_share.set(self.last_allreduce_share)

    # Context-manager sugar for the torch/TF step loop.

    def __enter__(self) -> "StepTimer":
        self.begin()
        return self

    def __exit__(self, *exc) -> None:
        self.end()
