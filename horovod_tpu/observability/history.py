"""Telemetry history ring — the durable, queryable signal store behind
the health plane (docs/health.md).

Every plane so far answers "what is happening now" (registry
snapshots, live gauges) or "what happened at death" (flight recorder).
Nothing could answer "has this job been getting *slower* for the last
20 minutes?" — the question that catches the slow degradations
(regressions, leaks, queue runaways) that cost real pod-hours without
ever crashing anything. This module keeps that history:

  - A background **sampler** (one task on the shared telemetry timer
    thread, observability/ticker.py — never a thread of its own)
    snapshots the registry every ``HOROVOD_TPU_HISTORY_INTERVAL``
    (default 5 s) and reduces consecutive snapshots to per-window
    *series*: counter **rates**, gauge **values**, and histogram
    bucket deltas rendered as windowed **p50/p99** (the existing
    log-bucket estimator), **mean** (exact, from sum/count deltas —
    the log buckets are only bucket-width-exact, which would hide a
    20% shift inside one power-of-two bucket) and **rate**.
  - Each sample appends ONE JSON line to a bounded, crash-safe
    **per-rank file** (``<HOROVOD_TPU_HISTORY>/history-rank{rank}
    .jsonl``): header line first, flush per line (a SIGKILL leaves a
    valid JSONL prefix — the PyTimeline valid-prefix contract),
    size-capped with segment rotation (``.1`` .. ``.N``, oldest
    deleted), and a final-gasp sample+flush registered with
    ``flight_recorder.register_final_flush`` so the last window before
    a death reaches disk. The header carries the PR 5 clock fields
    (``offset_to_rank0_us``), so ``python -m horovod_tpu.tools.health``
    merges per-rank files onto rank 0's clock exactly like the trace
    and postmortem tools.
  - The same samples feed the **online detector plane**
    (observability/health.py) in-process — the sampler hands every
    tick's series to the configured :class:`~.health.HealthMonitor`,
    which is what turns "the file says it got slower" into a typed
    alert while the job is still alive.

Series keys: ``{family}{{label_block}}`` for counters (value = rate/s)
and gauges (value = last write); histogram-derived series append a
``|p50`` / ``|p99`` / ``|mean`` / ``|rate`` suffix. One flat dict per
sample keeps the file grep-able and the detectors trivially keyed.

The sampler can read any snapshot-shaped ``source`` — the local
registry (training ranks) or a scraped replica ``/metrics.json``
(the fleet supervisor samples each replica's metrics into its own
``history-replica{i}.jsonl`` so serving trends survive replica death,
serving/fleet.py).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..utils import env as _env
from ..utils.logging import get_logger
from . import registry as _reg
from .export import histogram_percentiles

_log = get_logger("observability.history")

SCHEMA_VERSION = 1

# Recording lever for the overhead A/B (bench_engine.py --health) —
# module-global single check like registry._enabled; a disabled sampler
# skips its tick entirely (the task stays scheduled so the A/B toggles
# in-process).
_enabled = True


def enabled() -> bool:
    return _enabled


def set_enabled(value: bool) -> None:
    global _enabled
    _enabled = bool(value)


# --------------------------------------------------------------------------
# Snapshot deltas → flat series
# --------------------------------------------------------------------------

def _series_key(name: str, label_key: str) -> str:
    return f"{name}{{{label_key}}}" if label_key else name


def _delta_hist(prev: Optional[dict], cur: dict) -> Optional[dict]:
    """Windowed histogram: bucket/sum/count deltas between two
    cumulative snapshots (prev None = everything is new). Returns a
    snapshot-shaped dict for the percentile estimator, or None when
    nothing landed in the window. Tolerates "+Inf" string bounds
    (snapshots that crossed strict JSON)."""
    pc = {b[0] if isinstance(b[0], str) else float(b[0]): b[1]
          for b in (prev or {}).get("buckets", [])}
    dcount = cur.get("count", 0) - (prev or {}).get("count", 0)
    if dcount <= 0:
        return None
    buckets = []
    for le, cum in cur.get("buckets", []):
        key = le if isinstance(le, str) else float(le)
        buckets.append([le, cum - pc.get(key, 0)])
    return {"buckets": buckets, "count": dcount,
            "sum": cur.get("sum", 0.0) - (prev or {}).get("sum", 0.0)}


def series_from_snapshots(prev: Optional[dict], cur: dict,
                          dt_s: float) -> Dict[str, float]:
    """Reduce two consecutive registry snapshots to this window's flat
    series dict (see module docstring for the key scheme)."""
    dt_s = max(dt_s, 1e-9)
    out: Dict[str, float] = {}
    for name, fam in cur.items():
        kind = fam.get("type")
        pvals = ((prev or {}).get(name) or {}).get("values", {})
        for label_key, val in fam.get("values", {}).items():
            key = _series_key(name, label_key)
            if kind == "gauge":
                out[key] = float(val)
            elif kind == "counter":
                d = float(val) - float(pvals.get(label_key, 0.0))
                if d < 0:
                    # Counter reset (a scraped replica restarted):
                    # Prometheus rate semantics — the new value IS the
                    # delta since the reset.
                    d = float(val)
                out[key] = d / dt_s
            elif kind == "histogram":
                prev_hist = pvals.get(label_key)
                if (prev_hist and val.get("count", 0)
                        < prev_hist.get("count", 0)):
                    prev_hist = None  # reset: everything is new
                d = _delta_hist(prev_hist, val)
                if d is None:
                    continue
                pct = histogram_percentiles(d, (0.5, 0.99))
                out[f"{key}|p50"] = pct.get("p50", 0.0)
                out[f"{key}|p99"] = pct.get("p99", 0.0)
                out[f"{key}|mean"] = d["sum"] / d["count"]
                out[f"{key}|rate"] = d["count"] / dt_s
    return out


# --------------------------------------------------------------------------
# Crash-safe rotating writer
# --------------------------------------------------------------------------

class HistoryWriter:
    """Append-only JSONL with header line + per-line flush and bounded
    segment rotation — ``history-{label}.jsonl`` is the live segment,
    ``.jsonl.1`` the most recent rotated one, ``.jsonl.{N}`` the
    oldest. Total on-disk bound: ``(segments + 1) * max_bytes``."""

    def __init__(self, directory: str, label: str, *,
                 max_bytes: Optional[int] = None,
                 segments: Optional[int] = None,
                 meta: Optional[Callable[[], dict]] = None):
        self.directory = directory
        self.label = label
        self.path = os.path.join(directory, f"history-{label}.jsonl")
        self._max_bytes = (max_bytes if max_bytes is not None
                           else _env.history_max_bytes())
        self._segments = (segments if segments is not None
                          else _env.history_segments())
        self._meta = meta
        self._lock = threading.Lock()
        self._f = None
        self._size = 0

    def _header(self) -> dict:
        h = {"history": SCHEMA_VERSION, "label": self.label,
             "time_unix": time.time(),
             "mono_us": int(time.monotonic() * 1e6)}
        if self._meta is not None:
            try:
                h.update(self._meta())
            except Exception:  # pragma: no cover - defensive
                pass
        return h

    def _open(self) -> None:
        os.makedirs(self.directory, exist_ok=True)
        self._f = open(self.path, "w")
        line = json.dumps(self._header()) + "\n"
        self._f.write(line)
        self._f.flush()
        self._size = len(line)

    def _rotate(self) -> None:
        """Shift the segment chain up by one and start a fresh live
        file (with a fresh header — the clock offset may have synced
        since the last segment opened)."""
        self._f.close()
        self._f = None
        oldest = f"{self.path}.{self._segments}"
        if os.path.exists(oldest):
            os.unlink(oldest)
        for i in range(self._segments - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        if self._segments > 0:
            os.replace(self.path, f"{self.path}.1")
        self._open()

    def append(self, sample: dict) -> None:
        """One sample line; flushed immediately (crash-safe prefix)."""
        line = json.dumps(sample) + "\n"
        with self._lock:
            if self._f is None:
                self._open()
            elif self._size + len(line) > self._max_bytes:
                self._rotate()
            self._f.write(line)
            self._f.flush()
            self._size += len(line)

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


# --------------------------------------------------------------------------
# The sampler
# --------------------------------------------------------------------------

def _default_meta() -> dict:
    """Header fields for a training rank: identity + the PR 5 clock
    handshake result, read from the flight recorder at segment-open
    time (the handshake may complete after init — rotation refreshes
    the fields)."""
    from . import flight_recorder as _flight
    rec = _flight.recorder()
    meta = {"rank": max(rec.rank, 0), "world": rec.world,
            "generation": rec.generation}
    meta.update(rec.clock)
    return meta


class HistorySampler:
    """Periodic snapshot→delta→append pipeline, one ticker task.

    ``source`` returns a registry-shaped snapshot dict (default: the
    local registry, optionally prefix-filtered). ``monitor`` (a
    :class:`~.health.HealthMonitor`) receives every tick's series —
    the live detector plane."""

    def __init__(self, directory: str, label: str, *,
                 interval_s: Optional[float] = None,
                 source: Optional[Callable[[], dict]] = None,
                 monitor=None,
                 prefix: Optional[str] = None,
                 writer: Optional[HistoryWriter] = None,
                 meta: Optional[Callable[[], dict]] = None):
        self.interval_s = (interval_s if interval_s is not None
                           else _env.history_interval_secs())
        self._source = source or (
            lambda: _reg.registry().snapshot(prefix=prefix))
        self.monitor = monitor
        self.writer = writer or HistoryWriter(
            directory, label, meta=meta or _default_meta)
        self._prev: Optional[dict] = None
        self._prev_t = 0.0
        r = _reg.registry()
        self._m_samples = r.counter(
            "hvdtpu_history_samples_total",
            "Telemetry history samples appended, by history label"
        ).labels(label=label)
        self._m_errors = r.counter(
            "hvdtpu_history_sample_errors_total",
            "History sampler ticks that failed (source unreachable / "
            "write error) — the file simply has a gap").labels()
        self._handle: Optional[int] = None

    # ------------------------------------------------------------- tick

    def tick(self) -> Optional[dict]:
        """One sample: snapshot, delta, append, feed the detectors.
        Returns the sample (tests), None when disabled or first tick
        (nothing to delta against)."""
        if not _enabled:
            return None
        now = time.monotonic()
        try:
            snap = self._source()
        except Exception as e:
            self._m_errors.inc()
            _log.warning("history source failed: %s", e)
            return None
        prev, self._prev = self._prev, snap
        prev_t, self._prev_t = self._prev_t, now
        if prev is None:
            return None
        series = series_from_snapshots(prev, snap, now - prev_t)
        sample = {"t_us": int(now * 1e6),
                  "u": round(time.time(), 3),
                  "dt_s": round(now - prev_t, 3),
                  "s": {k: _json_safe(v) for k, v in series.items()}}
        try:
            self.writer.append(sample)
            self._m_samples.inc()
        except OSError as e:
            self._m_errors.inc()
            _log.warning("history append failed: %s", e)
        if self.monitor is not None:
            try:
                self.monitor.observe(series, t=now, t_unix=time.time())
            except Exception as e:  # detectors must never kill sampling
                _log.warning("health detectors failed: %s", e)
        return sample

    # -------------------------------------------------------- lifecycle

    def start(self) -> "HistorySampler":
        from . import ticker as _ticker
        if self._handle is None:
            self._handle = _ticker.ticker().add(
                f"history-{self.writer.label}", self.interval_s,
                self.tick, final=self.final_flush)
        return self

    def stop(self) -> None:
        from . import ticker as _ticker
        if self._handle is not None:
            handle, self._handle = self._handle, None
            _ticker.ticker().remove(handle)  # runs final_flush
        self.writer.close()

    def final_flush(self) -> None:
        """Final-gasp: capture the current window RIGHT NOW — also
        registered with the flight recorder's death paths, so the last
        seconds before a crash reach the history file."""
        try:
            self.tick()
        except Exception:  # pragma: no cover - defensive
            pass


_sampler: Optional[HistorySampler] = None
_lock = threading.Lock()


def sampler() -> Optional[HistorySampler]:
    """The process's env-configured history sampler, if one started."""
    return _sampler


def maybe_start_sampler() -> Optional[HistorySampler]:
    """Start the env-configured history sampler + detector plane
    (called by ``hvd.init()``; idempotent, no-op without
    ``HOROVOD_TPU_HISTORY``)."""
    global _sampler
    directory = _env.history_dir()
    if not directory or not _reg.enabled():
        return None
    if _env.replica_id() is not None:
        # Serving-fleet replicas are sampled BY the supervisor (scraped
        # into history-replica{i}.jsonl, serving/fleet.py) so their
        # trends survive replica death; a process-local sampler here
        # would add a second, rank-named file that dies with the
        # replica and collides across replicas.
        return None
    with _lock:
        if _sampler is not None:
            return _sampler
        monitor = None
        if _env.health_detectors_enabled():
            from . import health as _health
            monitor = _health.default_monitor()
        rank = _process_index()
        _sampler = HistorySampler(directory, f"rank{rank}",
                                  monitor=monitor).start()
        from . import flight_recorder as _flight
        _flight.register_final_flush(_sampler.final_flush)
        _log.info("telemetry history to %s every %.1fs (detectors %s)",
                  _sampler.writer.path, _sampler.interval_s,
                  "on" if monitor else "off")
    return _sampler


def stop_sampler() -> None:
    global _sampler
    with _lock:
        if _sampler is not None:
            _sampler.stop()
            _sampler = None


def _process_index() -> int:
    try:
        from .. import topology as _topo
        return _topo._get().process_index
    except Exception:
        return int(os.environ.get("HOROVOD_TPU_PROCESS_ID", "0") or 0)


def _json_safe(v: float):
    if isinstance(v, float):
        if math.isnan(v) or math.isinf(v):
            return None
        return round(v, 9)
    return v


# --------------------------------------------------------------------------
# Loading + merging (the tools/health side)
# --------------------------------------------------------------------------

class HistoryFile:
    """One label's merged history: header meta + samples ordered by
    aligned (rank-0-clock) time."""

    def __init__(self, label: str, meta: dict, samples: List[dict]):
        self.label = label
        self.meta = meta
        self.samples = samples

    @property
    def rank(self) -> Optional[int]:
        return self.meta.get("rank")

    def series(self) -> Dict[str, List[Tuple[float, float]]]:
        """``{series_key: [(t_seconds_aligned, value), ...]}`` with
        None values dropped."""
        out: Dict[str, List[Tuple[float, float]]] = {}
        for s in self.samples:
            t = s.get("t_aligned_us", s.get("t_us", 0)) / 1e6
            for k, v in (s.get("s") or {}).items():
                if v is None:
                    continue
                out.setdefault(k, []).append((t, float(v)))
        return out


def read_segment(path: str) -> Tuple[dict, List[dict]]:
    """One segment: (header, samples). Tolerates a torn tail — a
    SIGKILL mid-append leaves a valid prefix plus at most one partial
    line, which is skipped (and any undecodable line after it)."""
    header: dict = {}
    samples: List[dict] = []
    try:
        with open(path) as f:
            for i, line in enumerate(f):
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue  # torn tail (or mid-file corruption): skip
                if i == 0 and "history" in obj:
                    header = obj
                else:
                    samples.append(obj)
    except OSError:
        pass
    return header, samples


def _segment_paths(live_path: str) -> List[str]:
    """Oldest → newest: ``.{N}`` .. ``.1`` then the live file."""
    out = []
    i = 1
    while os.path.exists(f"{live_path}.{i}"):
        out.append(f"{live_path}.{i}")
        i += 1
    out.reverse()
    if os.path.exists(live_path):
        out.append(live_path)
    return out


def load_label(live_path: str) -> Optional[HistoryFile]:
    """All segments of one label, concatenated oldest-first, sample
    times aligned onto rank 0's clock via each segment's own header
    offset (segments may have re-synced between rotations)."""
    label = os.path.basename(live_path)
    if label.startswith("history-"):
        label = label[len("history-"):]
    if label.endswith(".jsonl"):
        label = label[: -len(".jsonl")]
    meta: dict = {}
    samples: List[dict] = []
    for seg in _segment_paths(live_path):
        header, segment_samples = read_segment(seg)
        offset = float(header.get("offset_to_rank0_us", 0.0))
        for s in segment_samples:
            if "t_us" in s:
                s["t_aligned_us"] = s["t_us"] + offset
        if header:
            meta = header  # newest header wins (freshest clock sync)
        samples.extend(segment_samples)
    if not meta and not samples:
        return None
    samples.sort(key=lambda s: s.get("t_aligned_us", s.get("t_us", 0)))
    return HistoryFile(label, meta, samples)


def load_history(inputs: List[str]) -> List[HistoryFile]:
    """Load every history label under the given files/directories —
    a directory expands to its ``history-*.jsonl`` live files (rotated
    segments are folded into their label automatically)."""
    live_paths: List[str] = []
    for p in inputs:
        if os.path.isdir(p):
            import glob as _glob
            live_paths.extend(sorted(
                f for f in _glob.glob(os.path.join(p, "history-*.jsonl"))))
        else:
            live_paths.append(p)
    out = []
    for lp in live_paths:
        hf = load_label(lp)
        if hf is not None:
            out.append(hf)
    if not out:
        raise FileNotFoundError(
            f"no history files found under {inputs} (expected "
            "history-<label>.jsonl, see docs/health.md)")
    return out
