"""Typed metrics registry — the quantitative telemetry plane.

The reference ships observability in its core (the timeline writer,
timeline.h:66-68, and the stall detector, operations.cc:1625-1672) but
exposes nothing *numeric*: knowing where time goes (negotiate vs. fuse
vs. execute) is what made tensor fusion and autotuning tunable in the
first place (PAPERS.md, arxiv 1802.05799), and a production deployment
needs that as scrapeable counters, not log lines. This module is the
single registry every layer reports into:

  - :class:`Counter`   — monotone float totals (wire bytes, cache hits).
  - :class:`Gauge`     — last-write-wins values (world size, stalls).
  - :class:`Histogram` — log-bucketed distributions (op phase latency,
    compile seconds, fused-group size). Log buckets because collective
    latencies span six orders of magnitude (µs cache hits to multi-second
    compiles); linear buckets would waste resolution at one end.

Design constraints (docs/metrics.md):

  - THREAD-SAFE: the engine's background cycle, the executor (called
    from that cycle), the coordinator's socketserver handler threads and
    user threads all write concurrently. Each child metric carries its
    own small lock; families share the registry lock only at creation.
  - NEAR-ZERO COST WHEN DISABLED: every mutator starts with one module
    global check (``_enabled``) and returns — no lock, no dict lookup.
    ``HOROVOD_TPU_METRICS=0`` disables; default on (a counter add under
    the GIL is nanoseconds, guarded by the BENCH_METRICS overhead test).
  - LABELS: a family (``counter("hvdtpu_wire_bytes_total", ...)``)
    hands out children per label set (``family.labels(spec="int8x256")``)
    the Prometheus way. Hot paths cache the child handle once — the
    label-dict lookup never sits in a per-op loop.

Snapshot format (:func:`snapshot`): a plain dict keyed by metric name,
each entry ``{"type", "help", "values": {label_str: value}}`` where a
histogram value is ``{"buckets": [[le, cumulative_count], ...], "sum",
"count"}`` with monotone cumulative sums ending at the +Inf bucket ==
count — the exact invariant the Prometheus text exposition needs
(observability/export.py renders from this same snapshot).
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils import env as _env

# Resolved once at import (read-once env-knob semantics like every other
# engine knob); set_enabled() flips it for the A/B overhead bench.
_enabled = _env.metrics_enabled()
# Exemplar replacement window (HOROVOD_TPU_EXEMPLAR_TTL), also
# read-once — the per-histogram default for Histogram.exemplar.
_exemplar_ttl = _env.exemplar_ttl_secs()


def enabled() -> bool:
    return _enabled


def set_enabled(value: bool) -> None:
    """Flip metric recording at runtime (the overhead bench's A/B lever;
    exporters keep serving whatever was recorded)."""
    global _enabled
    _enabled = bool(value)


def log2_buckets(lo: float, hi: float) -> List[float]:
    """Power-of-two bucket bounds covering [lo, hi] — the default
    log-bucketing for latency histograms."""
    bounds = []
    b = lo
    while b <= hi * (1 + 1e-12):
        bounds.append(b)
        b *= 2.0
    return bounds


# Default latency bounds: 1 µs .. ~134 s in 27 power-of-two buckets.
LATENCY_BUCKETS = log2_buckets(1e-6, 128.0)
# Fused-group sizes: 1 .. 4096 tensors.
SIZE_BUCKETS = log2_buckets(1.0, 4096.0)
# Byte sizes: 64 B .. 4 GiB.
BYTE_BUCKETS = log2_buckets(64.0, float(4 << 30))


def _label_key(labels: Dict[str, str]) -> str:
    """Canonical label string — doubles as the snapshot dict key and the
    Prometheus exposition label block (sans braces)."""
    if not labels:
        return ""
    esc = {k: str(v).replace("\\", "\\\\").replace('"', '\\"')
           .replace("\n", "\\n") for k, v in labels.items()}
    return ",".join(f'{k}="{esc[k]}"' for k in sorted(esc))


class Counter:
    """Monotone total. ``inc`` only accepts non-negative deltas."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if not _enabled:
            return
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-write-wins value."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        if not _enabled:
            return
        self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Log-bucketed histogram with Prometheus cumulative semantics.

    Optionally carries one **exemplar** — the trace id of the *worst
    recent* observation (docs/metrics.md#exemplars): an ``observe``
    that passes ``exemplar=`` replaces the stored one when its value is
    at least as large, or when the incumbent is older than
    ``exemplar_ttl_s`` (default HOROVOD_TPU_EXEMPLAR_TTL, 60 s — a
    stale champion must not pin the link forever: "worst recent", not
    "worst ever"). This is what lets an aggregate p99 (TTFT, failover)
    link to one concrete, inspectable request in the serving trace
    plane (docs/serving.md#request-tracing)."""

    __slots__ = ("_lock", "_bounds", "_counts", "_sum", "_count",
                 "_ex_ttl", "_ex_value", "_ex_trace", "_ex_time")

    def __init__(self, buckets: Sequence[float],
                 exemplar_ttl_s: Optional[float] = None):
        self._lock = threading.Lock()
        self._bounds = sorted(float(b) for b in buckets)
        if not self._bounds:
            raise ValueError("histogram needs at least one bucket bound")
        # One count per finite bound plus the +Inf overflow slot.
        self._counts = [0] * (len(self._bounds) + 1)
        self._sum = 0.0
        self._count = 0
        self._ex_ttl = (_exemplar_ttl if exemplar_ttl_s is None
                        else float(exemplar_ttl_s))
        self._ex_value = 0.0
        self._ex_trace: Optional[str] = None
        self._ex_time = 0.0

    def observe(self, value: float, exemplar: Optional[str] = None,
                now: Optional[float] = None) -> None:
        if not _enabled:
            return
        v = float(value)
        i = bisect.bisect_left(self._bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if exemplar is not None:
                t = time.time() if now is None else float(now)
                if (self._ex_trace is None or v >= self._ex_value
                        or t - self._ex_time > self._ex_ttl):
                    self._ex_value = v
                    self._ex_trace = str(exemplar)
                    self._ex_time = t

    @property
    def exemplar(self) -> Optional[dict]:
        """``{"value", "trace_id", "time_unix"}`` of the worst recent
        exemplar-carrying observation, or None."""
        with self._lock:
            if self._ex_trace is None:
                return None
            return {"value": self._ex_value, "trace_id": self._ex_trace,
                    "time_unix": self._ex_time}

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def snapshot(self) -> dict:
        """``{"buckets": [[le, cumulative], ...], "sum", "count"}`` with
        the +Inf bucket last and equal to ``count``; plus ``"exemplar"``
        when one was recorded."""
        with self._lock:
            counts = list(self._counts)
            s, n = self._sum, self._count
            ex = (None if self._ex_trace is None else
                  {"value": self._ex_value, "trace_id": self._ex_trace,
                   "time_unix": self._ex_time})
        out = []
        cum = 0
        for le, c in zip(self._bounds, counts[:-1]):
            cum += c
            out.append([le, cum])
        out.append([math.inf, cum + counts[-1]])
        snap = {"buckets": out, "sum": s, "count": n}
        if ex is not None:
            snap["exemplar"] = ex
        return snap


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """One named metric family handing out per-label-set children."""

    __slots__ = ("name", "kind", "help", "_buckets", "_lock", "_children")

    def __init__(self, name: str, kind: str, help_text: str,
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.kind = kind
        self.help = help_text
        self._buckets = buckets
        self._lock = threading.Lock()
        self._children: Dict[str, object] = {}

    def labels(self, **labels: str):
        key = _label_key(labels)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    if self.kind == "histogram":
                        child = Histogram(self._buckets or LATENCY_BUCKETS)
                    else:
                        child = _KINDS[self.kind]()
                    self._children[key] = child
        return child

    # Unlabeled convenience surface: family acts as its own "" child.

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def set(self, value: float) -> None:
        self.labels().set(value)

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        self.labels().observe(value, exemplar=exemplar)

    @property
    def value(self) -> float:
        return self.labels().value

    def clear(self) -> None:
        """Drop every child — for gauge families whose label sets are
        transient (per-stalled-tensor gauges must disappear when the
        stall resolves, or the export lies forever)."""
        with self._lock:
            self._children.clear()

    def items(self) -> List[Tuple[str, object]]:
        with self._lock:
            return list(self._children.items())


class MetricsRegistry:
    """Process-global named registry of metric families."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _family(self, name: str, kind: str, help_text: str,
                buckets: Optional[Sequence[float]] = None) -> _Family:
        fam = self._families.get(name)
        if fam is None:
            with self._lock:
                fam = self._families.get(name)
                if fam is None:
                    fam = _Family(name, kind, help_text, buckets)
                    self._families[name] = fam
        elif fam.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind}, "
                f"requested {kind}")
        return fam

    def counter(self, name: str, help_text: str = "") -> _Family:
        return self._family(name, "counter", help_text)

    def gauge(self, name: str, help_text: str = "") -> _Family:
        return self._family(name, "gauge", help_text)

    def histogram(self, name: str, help_text: str = "",
                  buckets: Optional[Sequence[float]] = None) -> _Family:
        return self._family(name, "histogram", help_text, buckets)

    def snapshot(self, prefix: Optional[str] = None) -> dict:
        """Plain-dict snapshot of every family (see module docstring).

        ``prefix`` (a string, or a tuple of strings) restricts the
        snapshot to families whose name starts with it — the cheap
        form for per-tick consumers (the fleet sampler, shim callbacks)
        that only ever read one corner of the registry and were
        deep-copying all of it every tick."""
        out: Dict[str, dict] = {}
        with self._lock:
            fams = list(self._families.values())
        if prefix is not None:
            fams = [f for f in fams if f.name.startswith(prefix)]
        for fam in fams:
            values = {}
            for key, child in fam.items():
                if isinstance(child, Histogram):
                    values[key] = child.snapshot()
                else:
                    values[key] = child.value
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "values": values}
        return out


_registry = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-global registry every horovod_tpu layer reports into."""
    return _registry


def snapshot(prefix: Optional[str] = None) -> dict:
    """``horovod_tpu.metrics_snapshot()`` — one coherent dict of every
    metric (counters/gauges as floats, histograms with monotone
    cumulative bucket sums). Safe to call from any thread at any time.
    ``prefix=`` (string or tuple) restricts to matching family names —
    use it in per-tick consumers instead of snapshotting everything.

    There is deliberately NO reset: registry totals survive engine and
    executor resets (the reason the ad-hoc per-instance counters moved
    here), and hot paths cache child handles that a swap would orphan.
    Consumers wanting per-window numbers diff two snapshots."""
    return _registry.snapshot(prefix=prefix)
