"""Unified metrics & health telemetry (docs/metrics.md).

Public surface:

  - :func:`get_registry` / :func:`metrics_snapshot` — the typed,
    thread-safe metrics registry and its plain-dict snapshot.
  - :func:`prometheus_text` — Prometheus text exposition of a snapshot.
  - :func:`maybe_start_exporters` — env-driven JSON-file writer and
    rank-0 HTTP endpoint (called by ``hvd.init()``).
  - :class:`StepTimer` — per-step samples/sec + allreduce-share hook the
    framework shims build on.

NOTE: the name ``registry`` is deliberately NOT re-exported here — it
must keep resolving to the :mod:`.registry` submodule (the engine,
executor, control plane and elastic driver all do
``from ..observability import registry as _obs``); the function is
exported as :func:`get_registry`.
"""

from .registry import (Counter, Gauge, Histogram, MetricsRegistry, enabled,
                       set_enabled)
from .registry import registry as get_registry
from .registry import snapshot as metrics_snapshot
from .export import (MetricsServer, final_metrics_flush,
                     histogram_percentiles, maybe_start_exporters,
                     prometheus_text, stop_exporters, with_percentiles,
                     write_json_snapshot)
from .step_metrics import StepTimer, flops_of_lowered
# NOTE: like ``registry`` above, the name ``flight_recorder`` must keep
# resolving to the submodule (engine/tools do ``from ..observability
# import flight_recorder as _fr``); the accessor is exported as
# :func:`get_flight_recorder`.
from .flight_recorder import FlightRecorder
from .flight_recorder import recorder as get_flight_recorder
# NOTE: ``history``/``health``/``ticker`` likewise stay submodule names
# (the fleet, tools and tests import them as modules); the telemetry
# history + anomaly-detection plane's classes are exported directly.
from .health import Alert, HealthMonitor
from .history import HistorySampler, HistoryWriter, load_history

__all__ = [
    "Alert", "Counter", "FlightRecorder", "Gauge", "HealthMonitor",
    "Histogram", "HistorySampler", "HistoryWriter", "MetricsRegistry",
    "MetricsServer", "StepTimer", "enabled", "final_metrics_flush",
    "flight_recorder", "flops_of_lowered", "get_flight_recorder",
    "get_registry", "health", "histogram_percentiles", "history",
    "load_history", "maybe_start_exporters", "metrics_snapshot",
    "prometheus_text", "registry", "set_enabled", "stop_exporters",
    "ticker", "with_percentiles", "write_json_snapshot",
]
