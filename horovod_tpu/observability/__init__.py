"""Unified metrics & health telemetry (docs/metrics.md).

Public surface:

  - :func:`get_registry` / :func:`metrics_snapshot` — the typed,
    thread-safe metrics registry and its plain-dict snapshot.
  - :func:`prometheus_text` — Prometheus text exposition of a snapshot.
  - :func:`maybe_start_exporters` — env-driven JSON-file writer and
    rank-0 HTTP endpoint (called by ``hvd.init()``).
  - :class:`StepTimer` — per-step samples/sec + allreduce-share hook the
    framework shims build on.

NOTE: the name ``registry`` is deliberately NOT re-exported here — it
must keep resolving to the :mod:`.registry` submodule (the engine,
executor, control plane and elastic driver all do
``from ..observability import registry as _obs``); the function is
exported as :func:`get_registry`.
"""

from .registry import (Counter, Gauge, Histogram, MetricsRegistry, enabled,
                       set_enabled)
from .registry import registry as get_registry
from .registry import snapshot as metrics_snapshot
from .export import (MetricsServer, histogram_percentiles,
                     maybe_start_exporters, prometheus_text, stop_exporters,
                     with_percentiles, write_json_snapshot)
from .step_metrics import StepTimer

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "MetricsServer",
    "StepTimer", "enabled", "get_registry", "histogram_percentiles",
    "maybe_start_exporters", "metrics_snapshot", "prometheus_text",
    "registry", "set_enabled", "stop_exporters", "with_percentiles",
    "write_json_snapshot",
]
