"""horovod_tpu.keras — the Keras framework shim.

Parity target: horovod/keras/__init__.py (148) + horovod/tensorflow/keras/
__init__.py (155) + the shared impl horovod/_keras/__init__.py (109): a
``DistributedOptimizer`` built as a dynamic subclass of the wrapped
optimizer's class (so saved models restore without the framework,
_keras/__init__.py:63-70), eager ``allreduce/allgather/broadcast`` on
host values, ``broadcast_variables`` and ``load_model`` that re-wraps
every stock optimizer class (_keras/__init__.py:93-109).

The reference targets Keras 2 over TF sessions and hooks
``get_gradients`` (graph mode). Keras 3 is multi-backend and routes every
gradient application through ``Optimizer.apply`` — that is the hook here.
The collectives run on the TPU-native XLA engine; gradients cross from
whatever backend Keras is using:

- ``torch`` backend: tensors move through the torch shim's transport.
- ``tensorflow`` backend: eager tensors via numpy; inside a traced
  ``tf.function`` the allreduce is bridged with ``tf.py_function`` (the
  host-callback analogue of the reference's AsyncOpKernel,
  tensorflow/mpi_ops.cc:281-303).
- ``jax`` backend: concrete arrays go straight to the engine. Inside a
  jitted step (``model.fit``), collectives must be part of the SPMD
  program — use ``lax.psum`` over a mesh axis ('dp' is tried
  automatically under ``shard_map``) or Keras's own
  ``keras.distribution`` sharding; an un-shardable tracer raises with
  that guidance rather than silently skipping the reduction.
- ``numpy`` backend: direct.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import keras

from .. import ops as _ops
from .. import topology as _topo
from ..compression import Compression
from ..topology import (init, shutdown, is_initialized, rank, local_rank,
                        size, local_size, mpi_threads_supported)
from . import callbacks

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "local_rank", "size",
    "local_size", "mpi_threads_supported", "Compression",
    "DistributedOptimizer", "broadcast_global_variables",
    "broadcast_variables", "allreduce", "allgather", "broadcast",
    "load_model", "callbacks",
]


# ---------------------------------------------------------------------------
# Backend bridging
# ---------------------------------------------------------------------------

def _backend() -> str:
    return keras.backend.backend()


def _is_jax_tracer(x) -> bool:
    import jax
    return isinstance(x, jax.core.Tracer)


def _jax_inline_allreduce(g):
    """Inside a jitted Keras-JAX train step the reduction must be part of
    the SPMD program. Under shard_map with a 'dp' axis, psum does it.

    Without an axis in scope, Keras 3's own jitted train step is an SPMD
    program over sharded arrays: if a Keras distribution (DataParallel)
    is active in this single-controller process, XLA already inserts the
    gradient reduction from the shardings and the wrapper must pass
    through (reducing twice would double-average). Only when neither an
    axis nor a distribution can do the reduction do we fail loudly
    instead of silently training divergent replicas (the multi-process
    no-sharding case)."""
    import jax
    from jax import lax
    try:
        return lax.psum(g, "dp") / lax.psum(
            jax.numpy.ones((), g.dtype), "dp")
    except NameError as e:
        # Other named axes in scope mean we are inside shard_map but the
        # data axis has a different name — pass-through would silently
        # train divergent shards, so fail with the rename guidance.
        try:
            from jax._src import core as _src_core
            axes = dict(_src_core.get_axis_env().axis_sizes)
        except Exception:  # API drift: fall back to no-axes assumption
            axes = {}
        if axes:
            raise RuntimeError(
                "horovod_tpu.keras.DistributedOptimizer reduces over the "
                f"mesh axis named 'dp', but the axes in scope are "
                f"{sorted(axes)}. Name your data-parallel shard_map axis "
                "'dp' (or psum the gradients yourself).") from e
        if jax.process_count() == 1:
            # Plain jitted Keras step, no shard_map: either the arrays
            # are replicated (identical gradients everywhere — averaging
            # is the identity) or a keras.distribution shards them and
            # XLA inserts the reduction from the shardings. Both cases
            # pass through.
            return g
        raise RuntimeError(
            "horovod_tpu.keras.DistributedOptimizer was traced into a "
            "jitted train step with no 'dp' mesh axis in scope in a "
            "multi-process job. With the Keras JAX backend, either run "
            "the optimizer inside shard_map over a mesh with a 'dp' "
            "axis, or use SPMD data parallelism "
            "(keras.distribution.DataParallel / horovod_tpu.parallel) "
            "where XLA inserts the gradient reduction itself.") from e


def _allreduce_grad(g, name: Optional[str], compression) -> object:
    """Average one backend gradient tensor across ranks, preserving its
    backend type. Single-tensor convenience over the batch helpers (one
    copy of every backend branch lives in the *_batch functions)."""
    kb = _backend()
    if kb == "torch":
        from . import _torch_bridge
        return _torch_bridge.allreduce_average(g, name, compression)
    if kb == "tensorflow":
        import tensorflow as tf
        if not tf.executing_eagerly():
            return _tf_graph_allreduce_batch([g], [name], compression)[0]
        out = _engine_allreduce_batch([g.numpy()], [name], compression)[0]
        return tf.constant(out, dtype=g.dtype)
    if kb == "jax":
        if _is_jax_tracer(g):
            return _jax_inline_allreduce(g)
        import jax.numpy as jnp
        return jnp.asarray(_engine_allreduce_batch(
            [np.asarray(g)], [name], compression)[0])
    # numpy / anything array-like
    arr = keras.ops.convert_to_numpy(g)
    return keras.ops.convert_to_tensor(
        _engine_allreduce_batch([arr], [name], compression)[0])


def _engine_allreduce_batch(arrs, names, compression):
    """ONE engine burst for a list of host arrays: submit every gradient
    async (the engine fuses the burst into as few XLA collectives as the
    threshold allows), then wait all handles — the Keras-side counterpart
    of the TF shim's grouped bridge. Sequential blocking submits would
    pay one negotiation round-trip per gradient."""
    comp = compression if compression is not None else Compression.none
    blockwise = comp if getattr(comp, "wire_spec", None) is not None \
        else None
    handles = []
    with _ops.engine().burst():
        for arr, nm in zip(arrs, names):
            wire, ctx = comp.compress(arr)
            handles.append((_ops.allreduce_async(wire, average=True,
                                                 name=nm,
                                                 compression=blockwise),
                            ctx, arr.dtype))
    # Batched readback: one device_get for the whole group instead of a
    # per-gradient round trip (utils/interop.to_host_many — the
    # bridge-batching fix the BENCH_SHIMS measurement exposed).
    from ..utils.interop import to_host_many
    waited = to_host_many([h.wait() for h, _, _ in handles])
    outs = []
    for (h, ctx, dt), out in zip(handles, waited):
        out = comp.decompress(out, ctx)
        outs.append(np.asarray(out, dtype=dt))
    return outs


def _tf_graph_allreduce_batch(gs, names, compression):
    """One py_function crossing for the whole gradient group inside a
    traced tf.function (mirrors tensorflow._grouped_bridge)."""
    import tensorflow as tf
    blockwise = compression \
        if getattr(compression, "wire_spec", None) is not None else None
    wire = (None if blockwise is not None
            else getattr(compression, "wire_dtype", None))
    wire_np = np.dtype(wire) if wire is not None else None

    def host(*xs):
        handles = []
        dts = []
        with _ops.engine().burst():
            for x, nm in zip(xs, names):
                arr = x.numpy()
                dts.append(arr.dtype)
                if wire_np is not None and np.issubdtype(arr.dtype,
                                                         np.floating):
                    arr = arr.astype(wire_np)
                handles.append(_ops.allreduce_async(
                    arr, average=True, name=nm, compression=blockwise))
        # Batched readback (interop.to_host_many): one device_get for
        # the group, not one round trip per gradient.
        from ..utils.interop import to_host_many
        waited = to_host_many([h.wait() for h in handles])
        return [np.asarray(out, dtype=dt)
                for out, dt in zip(waited, dts)]

    outs = tf.py_function(host, list(gs), Tout=[g.dtype for g in gs])
    if len(gs) == 1 and not isinstance(outs, (list, tuple)):
        outs = [outs]
    for g, o in zip(gs, outs):
        o.set_shape(g.shape)
    return list(outs)


# ---------------------------------------------------------------------------
# DistributedOptimizer
# ---------------------------------------------------------------------------

class _DistributedOptimizer:
    """Mixin copied onto a dynamic subclass of the wrapped optimizer's
    class (_keras/__init__.py:63-70) so ``isinstance`` checks, LR
    schedules and model saving keep working."""

    _hvd_wrapped = True
    # Class-level defaults: instances deserialized by load_model() never
    # pass through DistributedOptimizer(), which sets instance attrs.
    _hvd_name = None
    _hvd_compression = Compression.none

    def apply(self, grads, trainable_variables=None):
        if not _topo.is_initialized():
            init()
        if _topo.size() > 1:
            prefix = self._hvd_name or f"Distributed{type(self).__name__}"
            grads = self._hvd_reduce(list(grads), prefix)
        return super(self.__class__, self).apply(grads, trainable_variables)

    def _hvd_reduce(self, grads, prefix):
        """Average the gradient list across ranks in ONE batched
        submission where the backend allows it (eager TF / concrete jax
        / numpy via an engine burst; traced tf.function via a single
        py_function group); jax tracers stay per-leaf (inline psum —
        XLA fuses those itself), torch delegates to its bridge."""
        comp = self._hvd_compression
        names = [f"{prefix}.grad.{i}" for i in range(len(grads))]
        idx = [i for i, g in enumerate(grads) if g is not None]
        if not idx:
            return grads
        kb = _backend()
        out = list(grads)
        if kb == "tensorflow":
            import tensorflow as tf
            if not tf.executing_eagerly():
                red = _tf_graph_allreduce_batch(
                    [grads[i] for i in idx], [names[i] for i in idx],
                    comp)
                for i, r in zip(idx, red):
                    out[i] = r
                return out
            arrs = [grads[i].numpy() for i in idx]
            red = _engine_allreduce_batch(arrs,
                                          [names[i] for i in idx], comp)
            for i, r in zip(idx, red):
                out[i] = tf.constant(r, dtype=grads[i].dtype)
            return out
        if kb == "jax" and not any(_is_jax_tracer(grads[i]) for i in idx):
            arrs = [np.asarray(grads[i]) for i in idx]
            red = _engine_allreduce_batch(arrs,
                                          [names[i] for i in idx], comp)
            import jax.numpy as jnp
            for i, r in zip(idx, red):
                out[i] = jnp.asarray(r)
            return out
        if kb == "numpy":
            arrs = [keras.ops.convert_to_numpy(grads[i]) for i in idx]
            red = _engine_allreduce_batch(arrs,
                                          [names[i] for i in idx], comp)
            for i, r in zip(idx, red):
                out[i] = keras.ops.convert_to_tensor(r)
            return out
        # torch backend / jax tracers: per-leaf path.
        return [g if g is None else _allreduce_grad(g, nm, comp)
                for g, nm in zip(grads, names)]


def _make_wrapped_class(cls):
    ns = {k: v for k, v in _DistributedOptimizer.__dict__.items()
          if k not in ("__dict__", "__weakref__")}
    return type(cls.__name__, (cls,), ns)


def DistributedOptimizer(optimizer, name: Optional[str] = None,
                         compression=Compression.none):
    """Wrap a ``keras.optimizers.Optimizer`` so every gradient is
    allreduce-averaged across ranks before the update rule runs
    (_keras/__init__.py:20-70). The returned object is an instance of a
    dynamic subclass with the SAME class name, so a model saved with it
    loads without horovod_tpu installed."""
    cls = _make_wrapped_class(optimizer.__class__)
    new = cls.from_config(optimizer.get_config())
    new._hvd_name = name or f"Distributed{optimizer.__class__.__name__}"
    new._hvd_compression = compression
    return new


# ---------------------------------------------------------------------------
# Eager host-value collectives (_keras/__init__.py:78-90)
# ---------------------------------------------------------------------------

def _host_array(value) -> np.ndarray:
    """Python scalars/lists default to 32-bit, as ``tf.constant`` does in
    the reference's host-value helpers (_keras/__init__.py:78-90);
    explicit numpy 64-bit arrays still hit the engine's narrowing guard."""
    if isinstance(value, np.ndarray):
        return value
    arr = np.asarray(value)
    if arr.dtype == np.float64:
        return arr.astype(np.float32)
    if arr.dtype == np.int64:
        return arr.astype(np.int32)
    return arr


def allreduce(value, name: Optional[str] = None, average: bool = True):
    """Allreduce a host value (scalar / array); returns numpy."""
    out = _ops.allreduce(_host_array(value), average=average, name=name)
    return np.asarray(out)


def allgather(value, name: Optional[str] = None):
    out = _ops.allgather(np.atleast_1d(_host_array(value)), name=name)
    return np.asarray(out)


def broadcast(value, root_rank: int = 0, name: Optional[str] = None):
    out = _ops.broadcast(_host_array(value), root_rank, name=name)
    return np.asarray(out)


# ---------------------------------------------------------------------------
# Variable broadcast + model loading
# ---------------------------------------------------------------------------

def broadcast_variables(variables, root_rank: int = 0) -> None:
    """Broadcast ``keras.Variable``s from ``root_rank`` in place — the
    rank-0 state sync used at (re)start (tensorflow/__init__.py:95-114)."""
    from ..utils.wire import movement_payload, movement_restore
    handles = []
    for i, v in enumerate(variables):
        arr = np.asarray(keras.ops.convert_to_numpy(v))  # not ascontiguousarray: it promotes 0-dim to (1,)
        wire, from_bits = movement_payload(arr)
        h = _ops.broadcast_async(
            wire, root_rank, name=f"keras.bcast.{i}.{getattr(v, 'path', i)}")
        handles.append((v, arr.dtype, arr.shape, from_bits, h))
    for v, dtype, shape, from_bits, h in handles:
        v.assign(movement_restore(h.wait(), dtype, shape, from_bits))


def broadcast_global_variables(root_rank: int = 0, model=None) -> None:
    """Broadcast all of a model's variables (weights + optimizer slots).
    Keras 3 has no global-variables collection; pass the model (the
    callback does this automatically)."""
    if model is None:
        raise ValueError(
            "Keras 3 has no global variable collection; pass model= or "
            "use callbacks.BroadcastGlobalVariablesCallback")
    broadcast_variables(model.variables, root_rank)
    if getattr(model, "optimizer", None) is not None:
        broadcast_variables(model.optimizer.variables, root_rank)


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compile=True):
    """Load a model, re-wrapping every stock optimizer class in
    ``DistributedOptimizer`` so restored training resumes distributed
    (_keras/__init__.py:93-109)."""
    import inspect

    horovod_objects = {}
    for attr in dir(keras.optimizers):
        obj = getattr(keras.optimizers, attr)
        if (inspect.isclass(obj)
                and issubclass(obj, keras.optimizers.Optimizer)
                and obj is not keras.optimizers.Optimizer):
            wrapped = _make_wrapped_class(obj)
            horovod_objects[obj.__name__] = wrapped
            horovod_objects[obj.__name__.lower()] = wrapped
    if custom_optimizers is not None:
        horovod_objects.update(
            {cls.__name__: _make_wrapped_class(cls)
             for cls in custom_optimizers})
    if custom_objects is not None:
        horovod_objects.update(custom_objects)
    return keras.models.load_model(filepath, custom_objects=horovod_objects,
                                   compile=compile)
