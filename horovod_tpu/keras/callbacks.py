"""Keras callbacks — parity with horovod/_keras/callbacks.py (168 LoC) and
its two façades (horovod/keras/callbacks.py, horovod/tensorflow/keras/
callbacks.py), rebuilt for Keras 3's multi-backend callback API.

- ``BroadcastGlobalVariablesCallback`` — rank-0 state sync at train start
  (_keras/callbacks.py:20-30).
- ``MetricAverageCallback`` — epoch-end metric allreduce
  (_keras/callbacks.py:33-67).
- ``LearningRateScheduleCallback`` — epoch/batch LR schedule with momentum
  correction (_keras/callbacks.py:70-147).
- ``LearningRateWarmupCallback`` — gradual 1/N → 1 warmup over the first
  epochs (_keras/callbacks.py:149-168).
- ``MetricsCallback`` — per-step samples/sec and allreduce share of step
  time into the horovod_tpu metrics registry (docs/metrics.md; no
  reference equivalent — the reference's only quantitative surface is
  the timeline file).
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import numpy as np

import keras

from .. import ops as _ops
from .. import topology as _topo
from ..observability import StepTimer


def _get_lr(optimizer) -> float:
    return float(keras.ops.convert_to_numpy(optimizer.learning_rate))


def _set_lr(optimizer, value: float) -> None:
    optimizer.learning_rate = value


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcast all model variables from ``root_rank`` when training
    begins, and optimizer slot variables as soon as they exist (after the
    first batch builds them) — ensures consistent initialization of all
    workers when training starts or resumes from a checkpoint."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self._model_done = False
        self._opt_done = False

    def on_train_begin(self, logs=None):
        from . import broadcast_variables
        if not self._model_done:
            broadcast_variables(self.model.variables, self.root_rank)
            self._model_done = True

    def on_train_batch_end(self, batch, logs=None):
        # Optimizer slots (momentum, Adam moments, iteration counter) are
        # built lazily by the first apply; sync them once available so a
        # restored rank-0 optimizer state propagates.
        from . import broadcast_variables
        if not self._opt_done and getattr(
                self.model, "optimizer", None) is not None:
            vs = self.model.optimizer.variables
            if vs:
                broadcast_variables(vs, self.root_rank)
                self._opt_done = True


class MetricAverageCallback(keras.callbacks.Callback):
    """Average epoch metrics across ranks before other callbacks (e.g.
    checkpointing or LR plateau schedules) consume them. Order matters:
    place this before them in the callback list, as the reference docs
    instruct (_keras/callbacks.py:33-67)."""

    def _average_metrics_in_place(self, logs):
        logs = logs or {}
        reduced = {}
        for metric, value in sorted(logs.items()):
            if isinstance(value, (int, float, np.floating, np.integer)):
                out = _ops.allreduce(
                    np.asarray(float(value), dtype=np.float32),
                    average=True, name=f"metric.{metric}")
                reduced[metric] = float(np.asarray(out))
        logs.update(reduced)

    def on_epoch_end(self, epoch, logs=None):
        self._average_metrics_in_place(logs)


class MetricsCallback(keras.callbacks.Callback):
    """Report per-step training telemetry into the metrics registry
    (``hvdtpu_step_seconds``, ``hvdtpu_samples_per_second``,
    ``hvdtpu_allreduce_step_share`` — all labeled ``framework=keras``)
    and optionally into the Keras logs dict.

    ``batch_size`` enables the samples/sec series (Keras 3 batch logs
    do not carry the batch size); without it only step time and
    allreduce share are recorded. ``log_metrics=True`` additionally
    writes ``samples_per_sec`` / ``allreduce_share`` into each batch's
    ``logs`` so they surface in progress bars and History."""

    def __init__(self, batch_size: Optional[int] = None,
                 log_metrics: bool = False,
                 flops_per_step: Optional[float] = None):
        super().__init__()
        # flops_per_step (e.g. observability.flops_of_lowered) arms the
        # hvdtpu_mfu / hvdtpu_model_flops_per_second gauges.
        self._timer = StepTimer("keras", batch_size=batch_size,
                                flops_per_step=flops_per_step)
        self._log_metrics = log_metrics

    def on_train_batch_begin(self, batch, logs=None):
        self._timer.begin()

    def on_train_batch_end(self, batch, logs=None):
        self._timer.end()
        if self._log_metrics and logs is not None:
            if self._timer.batch_size:
                logs["samples_per_sec"] = self._timer.last_samples_per_s
            logs["collective_share"] = self._timer.last_collective_share
            # Deprecated alias (same all-ops value; see docs/metrics.md).
            logs["allreduce_share"] = self._timer.last_collective_share


class LearningRateScheduleCallback(keras.callbacks.Callback):
    """Multiply the initial LR by ``multiplier`` (a constant or a
    function of epoch) between ``start_epoch`` and ``end_epoch``.

    ``staircase=True`` adjusts once per epoch; ``staircase=False``
    interpolates per batch using ``steps_per_epoch`` (auto-detected from
    ``self.params['steps']`` when possible). When the wrapped optimizer
    has momentum and ``momentum_correction`` is on, momentum is scaled by
    ``new_lr/old_lr`` for the batches where LR changed and restored after
    (the momentum-correction trick from the large-batch SGD literature,
    _keras/callbacks.py:103-117).

    Note: with a compiled/jitted train step, only ``learning_rate``
    (a Keras variable) is guaranteed to take effect mid-training;
    momentum on some optimizers is a Python constant captured at trace
    time, in which case momentum correction only applies on eagerly
    executing backends.
    """

    def __init__(self, multiplier: Union[float, Callable[[float], float]],
                 start_epoch: int = 0, end_epoch: Optional[int] = None,
                 staircase: bool = True, momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None):
        super().__init__()
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.initial_lr = None
        self.restore_momentum = None
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = None
        if not callable(multiplier):
            self.staircase = True
            self.multiplier = lambda epoch: multiplier
        else:
            self.multiplier = multiplier

    def _autodetect_steps_per_epoch(self) -> int:
        if self.params and self.params.get("steps"):
            return self.params["steps"]
        raise ValueError(
            f"Could not autodetect steps per epoch; pass steps_per_epoch "
            f"to {self.__class__.__name__}()")

    def _adjust_learning_rate(self, epoch: float) -> None:
        old_lr = _get_lr(self.model.optimizer)
        new_lr = self.initial_lr * self.multiplier(epoch)
        _set_lr(self.model.optimizer, new_lr)
        if (self.momentum_correction
                and hasattr(self.model.optimizer, "momentum")
                and old_lr > 0):
            self.restore_momentum = self.model.optimizer.momentum
            self.model.optimizer.momentum = (
                self.restore_momentum * new_lr / old_lr)

    def _restore_momentum_if_needed(self) -> None:
        if self.restore_momentum:
            self.model.optimizer.momentum = self.restore_momentum
            self.restore_momentum = None

    def on_train_begin(self, logs=None):
        self.initial_lr = _get_lr(self.model.optimizer)
        if not self.staircase and not self.steps_per_epoch:
            self.steps_per_epoch = self._autodetect_steps_per_epoch()

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch

    def on_train_batch_begin(self, batch, logs=None):
        if (self.current_epoch is None
                or self.current_epoch < self.start_epoch
                or (self.end_epoch is not None
                    and self.current_epoch >= self.end_epoch)):
            return
        if self.staircase and batch == 0:
            self._adjust_learning_rate(self.current_epoch)
        elif not self.staircase:
            epoch = self.current_epoch + float(batch) / self.steps_per_epoch
            self._adjust_learning_rate(epoch)

    def on_train_batch_end(self, batch, logs=None):
        self._restore_momentum_if_needed()

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = _get_lr(self.model.optimizer)


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradually scale the LR from ``initial_lr/size`` up to ``initial_lr``
    over the first ``warmup_epochs`` — 'Accurate, Large Minibatch SGD'
    warmup (_keras/callbacks.py:149-168)."""

    def __init__(self, warmup_epochs: int = 5,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None, verbose: int = 0):
        def multiplier(epoch):
            epoch += 1.0 / self.steps_per_epoch
            size = _topo.size()
            return 1.0 / size * (epoch * (size - 1) / warmup_epochs + 1)

        super().__init__(multiplier, start_epoch=0, end_epoch=warmup_epochs,
                         staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch)
        self.verbose = verbose

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.end_epoch - 1 and self.verbose > 0:
            print(f"\nEpoch {epoch + 1}: finished gradual learning rate "
                  f"warmup to {_get_lr(self.model.optimizer):g}.")


class CheckpointCallback(keras.callbacks.Callback):
    """Async checkpoint save hook on the sharded engine
    (docs/checkpoint.md).

    Every ``every_epochs`` epoch end, the model's weights (a list of
    host arrays — replicated state, so rank 0 writes under the engine's
    layout rules) are handed to a
    :class:`horovod_tpu.checkpoint.CheckpointEngine`; serialization and
    the atomic commit run on the engine's background thread, so
    ``model.fit`` is blocked only for the snapshot. The in-flight write
    is joined at train end (and by the next save). ``step`` in the
    checkpoint is the epoch number; restore with
    ``weights = engine.restore(template=model.get_weights())`` followed
    by ``model.set_weights(weights)``.
    """

    def __init__(self, directory=None, *, engine=None,
                 every_epochs: int = 1):
        super().__init__()
        if (directory is None) == (engine is None):
            raise ValueError(
                "pass exactly one of directory= or engine=")
        if engine is None:
            from ..checkpoint import CheckpointEngine
            engine = CheckpointEngine(directory)
        self.engine = engine
        self.every_epochs = max(1, int(every_epochs))

    def on_epoch_end(self, epoch, logs=None):
        if (epoch + 1) % self.every_epochs == 0:
            self.engine.save(list(self.model.get_weights()),
                             step=epoch + 1)

    def on_train_end(self, logs=None):
        self.engine.wait()
