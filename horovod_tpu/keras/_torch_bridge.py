"""Torch-backend gradient bridge for the Keras shim.

Isolated in its own module so ``horovod_tpu.keras`` does not import torch
unless Keras is actually running on the torch backend.
"""

from __future__ import annotations

from typing import Optional

from ..compression import Compression as _JaxCompression


def allreduce_average(g, name: Optional[str], compression):
    import torch

    from .. import torch as _hvd_torch

    comp = _hvd_torch.Compression.none
    wire_spec = getattr(compression, "wire_spec", None)
    if wire_spec is not None:
        # Blockwise wire formats cross by spec, not by cast: the torch
        # tensor enters the engine at its logical dtype and the fused
        # XLA program quantizes on the wire.
        comp = (_hvd_torch.Compression.int8_blockwise
                if wire_spec.startswith("int8")
                else _hvd_torch.Compression.fp8_blockwise)
        out = _hvd_torch.mpi_ops.synchronize(
            _hvd_torch.mpi_ops.allreduce_async(
                g, average=True, name=name, compression=comp))
        return out
    if compression is _JaxCompression.fp16:
        comp = _hvd_torch.Compression.fp16
    elif compression is _JaxCompression.bf16:
        # bf16 crosses the torch<->engine boundary natively (the torch
        # shim transports bf16 as uint16 bit patterns).
        orig = g.dtype
        out = _hvd_torch.mpi_ops.synchronize(
            _hvd_torch.mpi_ops.allreduce_async(
                g.to(torch.bfloat16), average=True, name=name))
        return out.to(orig)
    elif compression is not _JaxCompression.none and compression is not None:
        # fp8 (and future wire formats) have no torch-side transport yet;
        # degrade to fp16 LOUDLY rather than silently dropping compression.
        import warnings
        warnings.warn(
            f"{getattr(compression, '__name__', compression)} has no "
            "torch-backend transport; using fp16 wire compression instead")
        comp = _hvd_torch.Compression.fp16
    wire, ctx = comp.compress(g)
    out = _hvd_torch.mpi_ops.synchronize(
        _hvd_torch.mpi_ops.allreduce_async(wire, average=True, name=name))
    return comp.decompress(out, ctx)
