"""Torch-backend gradient bridge for the Keras shim.

Isolated in its own module so ``horovod_tpu.keras`` does not import torch
unless Keras is actually running on the torch backend.
"""

from __future__ import annotations

from typing import Optional

from ..compression import Compression as _JaxCompression


def allreduce_average(g, name: Optional[str], compression):
    from .. import torch as _hvd_torch
    comp = (_hvd_torch.Compression.fp16
            if compression is _JaxCompression.fp16
            else _hvd_torch.Compression.none)
    wire, ctx = comp.compress(g)
    out = _hvd_torch.mpi_ops.synchronize(
        _hvd_torch.mpi_ops.allreduce_async(wire, average=True, name=name))
    return comp.decompress(out, ctx)
