"""Fleet router — queue-depth-aware request routing with
zero-dropped-request failover across serving replicas.

The router is the only thing a client talks to. It keeps a scraped
view of every replica (``/readyz`` for admission, the
``hvdtpu_serving_*`` queue gauges from each replica's metrics endpoint
for load), and for each ``POST /generate``:

  1. **admits** onto the least-loaded ready replica — score is
     ``(active + queue_depth) / batch_slots``, i.e. outstanding work
     per slot, so a draining or backed-up replica naturally repels
     traffic before it starts rejecting it — minus a **cache-warmth
     bonus**: the router hashes the prompt's block-aligned prefixes
     with the SAME chained digest the engine's shared prefix cache
     uses (kv_cache.prefix_hashes) and remembers which hashes it sent
     where, so a request sharing a system prompt prefers the replica
     whose prefix cache is already warm (its prefill touches only the
     suffix) over a cold one with marginally less load;
  2. **streams** tokens from the replica (the replica-side NDJSON
     protocol, server.py) and relays them to the client;
  3. **fails over**: a replica that dies before the first token is
     transparently retried on a healthy replica (the request is simply
     re-prefilled); one that dies mid-stream is *resumed* — the router
     re-submits ``prompt + tokens-emitted-so-far`` with the remaining
     token budget, so the client's stream continues seamlessly and, for
     greedy decode, token-for-token identically to an uncontended run
     (the KV cache the dead replica lost is rebuilt by one prefill on
     the survivor — prefill is the recovery primitive, exactly like
     re-rendezvous is for training, docs/elastic.md).

Deadlines propagate: the client's ``deadline_ms`` budget is decremented
per hop and shipped to the replica, an expired request answers **504
and is never retried** (a retry nobody waits for is pure waste), and
queue-full (**429**) carries a ``Retry-After`` derived from the
fleet-wide drain rate.

The router deliberately holds NO generation state beyond the in-flight
request's emitted tokens — replicas own KV; the router owns retry. That
is what makes a replica process disposable (fleet.py can SIGKILL one at
any time) without the serving tier as a whole dropping a request.

Request identity and tracing (docs/serving.md#request-tracing): the
router mints ONE trace id per client request (or accepts the client's
via ``X-Request-Id`` / body ``request_id``) and ships it on every
dispatch, retry, and failover re-dispatch — the same id names the
request in every replica it touches, in the flight recorder, in metric
exemplars, and in the per-process request-trace files
(serving/reqtrace.py) where the router contributes the ``REQUEST``
wall span, per-attempt ``DISPATCH`` spans, and the
detection→resume ``FAILOVER`` span.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence

from ..observability import registry as _obs
from ..utils import env as _env
from ..utils.logging import get_logger
from . import qos as _qos
from . import reqtrace as _rt
from . import slo as _slo
from .engine import DEADLINE_ERROR
from .fleet import ReplicaEndpoint
from .kv_cache import prefix_hashes

_log = get_logger("serving.router")

# Server-side cap on one routed generation (mirrors server.py).
ROUTER_TIMEOUT_S = 600.0
# How long a replica stays excluded from a request's retry loop after
# failing it (it usually also drops from the scrape view, but the
# scrape cadence must not gate failover).
_EXCLUDE_S = 2.0
# Per-read socket timeout on a replica token stream: generous (a decode
# step under load is milliseconds; even a slow_decode fault is tens of
# ms) but finite, so a fully hung replica cannot wedge a client that
# set no deadline.
_STREAM_READ_S = 120.0
# Prefix hashes remembered per replica for cache-warmth scoring (LRU;
# roughly mirrors the replica-side prefix cache, which also evicts LRU
# under pool pressure — an optimistic shadow, never load-bearing).
_WARMTH_ENTRIES = 8192
# Score bonus pinning a leased session to its replica
# (docs/serving.md#session-affinity): worth two slots of outstanding
# work — decisively above the prefix-warmth bonus (≤ 1.0), so a leased
# session sticks through ordinary load imbalance, but a replica that
# stops being READY (draining, dead) still repels it and failover falls
# back to normal dispatch.
_SESSION_PIN_BONUS = 2.0


def _metrics():
    r = _obs.registry()
    return {
        "requests": r.counter(
            "hvdtpu_fleet_requests_total",
            "Routed requests by outcome: completed, expired (deadline "
            "→ 504), rejected (fleet-wide queue-full → 429), failed, "
            "bad_request"),
        "retries": r.counter(
            "hvdtpu_fleet_retries_total",
            "Dispatch attempts beyond the first, by reason: connect, "
            "crash (stream broke), queue_full, draining, failed"),
        "failovers": r.counter(
            "hvdtpu_fleet_failovers_total",
            "Requests moved to another replica after their replica "
            "died, by phase: prefill (before first token) or "
            "midstream (resumed with re-prefill)"),
        "failover_s": r.histogram(
            "hvdtpu_fleet_failover_seconds",
            "Failure detection → first token from the replacement "
            "replica (exemplar: trace id of the worst recent "
            "failover)", buckets=_obs.LATENCY_BUCKETS).labels(),
        "request_s": r.histogram(
            "hvdtpu_fleet_request_seconds",
            "End-to-end routed request wall (relay start → terminal "
            "outcome) — the denominator of the per-request latency "
            "budget (exemplar: trace id of the worst recent request)",
            buckets=_obs.LATENCY_BUCKETS).labels(),
        "dispatch": r.counter(
            "hvdtpu_fleet_dispatch_total",
            "Dispatches by replica index (the admission policy, "
            "observable)"),
        "ready": r.gauge(
            "hvdtpu_fleet_replicas_ready",
            "Replicas currently admitting (readyz 200 at last "
            "scrape)").labels(),
        "queue": r.gauge(
            "hvdtpu_fleet_replica_queue_depth",
            "Scraped hvdtpu_serving_queue_depth per replica index — "
            "the router's own view of the signal it balances on"),
        "warmth": r.counter(
            "hvdtpu_fleet_dispatch_warmth_total",
            "Dispatches by prefix-cache warmth of the chosen replica: "
            "warm (some prompt prefix previously routed there), cold "
            "(none), or unhashed (prompt shorter than one block)"),
        # Same family the replica engine registers — in a real fleet
        # the router is its own process, and its front-door quota
        # sheds must be visible under the same name
        # (docs/serving.md#qos).
        "shed": r.counter(
            "hvdtpu_serving_shed_total",
            "Requests shed by the QoS plane before prefill, by reason "
            "(quota: over the tenant token-rate quota; deadline_pred: "
            "remaining deadline cannot cover predicted prefill + one "
            "decode step) (docs/serving.md#qos)"),
    }


@dataclasses.dataclass
class ReplicaView:
    """The router's scraped view of one replica."""

    endpoint: ReplicaEndpoint
    ready: bool = False
    ok: bool = False              # at least one successful scrape
    queue_depth: float = 0.0
    active: float = 0.0
    slots: float = 1.0
    t_scraped: float = 0.0
    block_size: Optional[int] = None   # scraped from /healthz; the
    #                                    prefix-hash granularity
    # Prefix hashes this router has routed here (bounded LRU) — the
    # warmth estimate behind prefix-aware admission.
    warm: "OrderedDict" = dataclasses.field(default_factory=OrderedDict)
    # Session ids holding a KV lease here, from /healthz (plus the
    # router's own shadow adds between scrapes) — the pin targets.
    sessions: set = dataclasses.field(default_factory=set)
    # Per-QoS-class queued/active counts from /healthz
    # (docs/serving.md#qos) — empty until the replica advertises them.
    qos_classes: Dict[str, dict] = dataclasses.field(
        default_factory=dict)
    reserved_slots: float = 0.0

    @property
    def score(self) -> float:
        """Outstanding work per decode slot — lower admits first."""
        return (self.active + self.queue_depth) / max(1.0, self.slots)

    def class_score(self, qos_class: Optional[str]) -> float:
        """Class-aware load score (docs/serving.md#qos): top-priority
        (interactive) requests are scored by the replica's
        *interactive-only* backlog — under a fleet-wide bulk backlog
        every replica's global score saturates equally and placement
        degenerates to random, which collides interactive requests on
        one replica's reserved slot; counting only same-class work
        spreads them instead. The global score stays as a small
        tiebreak, and other classes keep the global policy."""
        if qos_class != _qos.TOP_CLASS:
            return self.score
        cc = self.qos_classes.get(qos_class)
        if cc is None:
            return self.score
        own = float(cc.get("active", 0)) + float(cc.get("queued", 0))
        return own / max(1.0, self.slots) + 1e-3 * self.score

    def warmth(self, hashes: Sequence[bytes]) -> float:
        """Fraction of the prompt's prefix blocks previously routed to
        this replica (longest-prefix, like the replica-side cache)."""
        if not hashes:
            return 0.0
        n = 0
        for h in hashes:
            if h not in self.warm:
                break
            n += 1
        return n / len(hashes)

    def note_dispatch(self, hashes: Sequence[bytes]) -> None:
        for h in hashes:
            if h in self.warm:
                self.warm.move_to_end(h)
            else:
                self.warm[h] = True
        while len(self.warm) > _WARMTH_ENTRIES:
            self.warm.popitem(last=False)


class StaticBackends:
    """Fixed endpoint list (external replicas / stub-replica tests) —
    the same ``endpoints()`` surface as :class:`fleet.Fleet`."""

    def __init__(self, endpoints: Sequence[ReplicaEndpoint]):
        self._endpoints = list(endpoints)

    def endpoints(self) -> List[ReplicaEndpoint]:
        return list(self._endpoints)


def pick_replica(views: Sequence[ReplicaView],
                 exclude: Optional[set] = None,
                 rr: int = 0,
                 warmth: Optional[Dict[int, float]] = None,
                 qos_class: Optional[str] = None
                 ) -> Optional[ReplicaView]:
    """The routing policy, isolated for unit testing: among ready,
    scrape-confirmed, non-excluded replicas, the lowest *effective*
    score — load score minus the replica's prefix-cache warmth for THIS
    prompt (``warmth``: fraction of prefix blocks already routed there,
    worth up to one slot's outstanding work) — ties broken round-robin
    by ``rr``. None when nobody can admit. With no warmth map this is
    exactly the pre-prefix-cache policy. ``qos_class`` makes the load
    term class-aware (docs/serving.md#qos): an interactive request is
    scored by each replica's interactive-only backlog, so a fleet-wide
    bulk backlog cannot starve (or randomize) interactive dispatch."""
    exclude = exclude or set()
    warmth = warmth or {}
    ok = [v for v in views
          if v.ready and v.ok and v.endpoint.index not in exclude]
    if not ok:
        return None

    def eff(v: ReplicaView) -> float:
        return v.class_score(qos_class) \
            - warmth.get(v.endpoint.index, 0.0)

    best = min(eff(v) for v in ok)
    tied = [v for v in ok if eff(v) == best]
    return tied[rr % len(tied)]


class Router:
    """HTTP front end balancing ``/generate`` across a replica fleet.

    ``backends`` is anything with ``endpoints() ->
    List[ReplicaEndpoint]`` — a :class:`fleet.Fleet` (endpoints move as
    replicas restart) or a :class:`StaticBackends`.
    """

    def __init__(self, backends, port: int = 0, host: str = "0.0.0.0",
                 scrape_interval_s: Optional[float] = None,
                 max_attempts: Optional[int] = None):
        self.backends = backends
        self._scrape_interval = (scrape_interval_s
                                 if scrape_interval_s is not None
                                 else _env.fleet_probe_interval_secs())
        self._max_attempts = max_attempts
        self._views: Dict[int, ReplicaView] = {}
        self._views_lock = threading.Lock()
        self._rr = 0
        self._m = _metrics()
        self._stop = threading.Event()
        self._scrape_thread: Optional[threading.Thread] = None
        self._next_id = 0
        self._id_lock = threading.Lock()
        # QoS plane (docs/serving.md#qos): front-door token-rate
        # quotas (the replica-side check still covers single-replica
        # deployments) and a 429/queue-full pressure window the
        # autoscaler reads via qos_signals().
        self._quota = _qos.QuotaLedger(_qos.policy())
        self._pressure: deque = deque()
        self._pressure_lock = threading.Lock()
        self._build_http(host, port)

    def _note_pressure(self) -> None:
        with self._pressure_lock:
            self._pressure.append(time.monotonic())

    def qos_signals(self) -> dict:
        """The autoscaler's signal sample (docs/serving.md#qos):
        fleet-wide outstanding work per slot across ready replicas,
        the ready count, and recent 429/queue-full pressure per
        second (10 s window)."""
        with self._views_lock:
            views = [v for v in self._views.values()
                     if v.ready and v.ok]
        slots = sum(v.slots for v in views)
        work = sum(v.active + v.queue_depth for v in views)
        now = time.monotonic()
        with self._pressure_lock:
            while self._pressure and self._pressure[0] < now - 10.0:
                self._pressure.popleft()
            pressure = len(self._pressure) / 10.0
        return {"load_per_slot": work / max(1.0, slots),
                "n_replicas": len(views),
                "retry_pressure": pressure}

    # ------------------------------------------------------ scraping

    def _scrape_one(self, view: ReplicaView) -> None:
        ep = view.endpoint
        try:
            conn = http.client.HTTPConnection(
                ep.host, ep.port, timeout=max(
                    1.0, self._scrape_interval * 4))
            try:
                conn.request("GET", "/readyz")
                view.ready = conn.getresponse().status == 200
            finally:
                conn.close()
        except OSError:
            view.ready = False
            view.ok = False
            return
        # healthz first, every cycle: besides being the load fallback,
        # it carries block_size (the prefix-hash granularity) and the
        # live session-lease ids the pinning policy routes on — both
        # are healthz-only.
        got = self._scrape_healthz(view)
        if ep.metrics_port:
            # The registry gauges stay the primary load signal when a
            # metrics endpoint exists.
            got = self._scrape_metrics(view) or got
        view.ok = got
        view.t_scraped = time.monotonic()

    def _scrape_metrics(self, view: ReplicaView) -> bool:
        """The primary load signal: the replica's own
        ``hvdtpu_serving_*`` gauges from its registry endpoint."""
        ep = view.endpoint
        try:
            conn = http.client.HTTPConnection(
                ep.host, ep.metrics_port, timeout=max(
                    1.0, self._scrape_interval * 4))
            try:
                # prefix= keeps the per-scrape payload to the serving
                # families — the replica never serializes (and the
                # router never parses) the whole registry per tick.
                conn.request("GET", "/metrics.json?prefix=hvdtpu_serving_")
                resp = conn.getresponse()
                if resp.status != 200:
                    return False
                snap = json.loads(resp.read())
            finally:
                conn.close()
        except (OSError, ValueError):
            return False

        def gauge(name, default=None):
            try:
                return float(snap[name]["values"][""])
            except (KeyError, TypeError, ValueError):
                return default

        q = gauge("hvdtpu_serving_queue_depth")
        a = gauge("hvdtpu_serving_active_requests")
        s = gauge("hvdtpu_serving_batch_slots")
        if q is None or a is None:
            return False
        view.queue_depth, view.active = q, a
        if s:
            view.slots = s
        return True

    def _scrape_healthz(self, view: ReplicaView) -> bool:
        """Fallback when the replica runs with metrics disabled
        (HOROVOD_TPU_METRICS=0): /healthz carries the same numbers."""
        ep = view.endpoint
        try:
            conn = http.client.HTTPConnection(
                ep.host, ep.port, timeout=max(
                    1.0, self._scrape_interval * 4))
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                if resp.status != 200:
                    return False
                h = json.loads(resp.read())
            finally:
                conn.close()
        except (OSError, ValueError):
            return False
        view.queue_depth = float(h.get("queue_depth", 0))
        view.active = float(h.get("active_requests", 0))
        view.slots = float(h.get("batch_slots", 1) or 1)
        if h.get("block_size"):
            view.block_size = int(h["block_size"])
        if "sessions" in h:
            view.sessions = set(h.get("sessions") or [])
        if isinstance(h.get("qos_classes"), dict):
            view.qos_classes = h["qos_classes"]
        view.reserved_slots = float(h.get("reserved_slots", 0) or 0)
        return True

    def _scrape_cycle(self) -> None:
        eps = {ep.index: ep for ep in self.backends.endpoints()}
        with self._views_lock:
            # Drop vanished replicas; reset views whose port moved
            # (a restarted replica is a NEW backend).
            for idx in list(self._views):
                if idx not in eps:
                    del self._views[idx]
                elif self._views[idx].endpoint != eps[idx]:
                    self._views[idx] = ReplicaView(endpoint=eps[idx])
            for idx, ep in eps.items():
                if idx not in self._views:
                    self._views[idx] = ReplicaView(endpoint=ep)
            views = list(self._views.values())
        for v in views:
            self._scrape_one(v)
            self._m["queue"].labels(
                replica=str(v.endpoint.index)).set(v.queue_depth)
        self._m["ready"].set(sum(1 for v in views if v.ready))

    def _scrape_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._scrape_cycle()
            except Exception as e:  # never die over telemetry
                _log.warning("scrape cycle failed: %s", e)
            self._stop.wait(self._scrape_interval)

    def _pick(self, exclude: Dict[int, float],
              prompt: Optional[List[int]] = None,
              session_id: Optional[str] = None,
              qos_class: Optional[str] = None
              ) -> Optional[ReplicaView]:
        now = time.monotonic()
        live = {i for i, until in exclude.items() if until > now}
        with self._views_lock:
            views = list(self._views.values())
        warmth: Dict[int, float] = {}
        if prompt:
            for v in views:
                hashes = prefix_hashes(prompt, v.block_size or 16)
                warmth[v.endpoint.index] = v.warmth(hashes)
        if session_id:
            # Session pinning rides the warmth channel: the replica
            # advertising this session's lease gets a bonus big enough
            # to win any warmth tie, while exclusion (failover) and
            # readiness still override it unconditionally.
            for v in views:
                if session_id in v.sessions:
                    warmth[v.endpoint.index] = warmth.get(
                        v.endpoint.index, 0.0) + _SESSION_PIN_BONUS
        self._rr += 1
        view = pick_replica(views, exclude=live, rr=self._rr,
                            warmth=warmth, qos_class=qos_class)
        if view is not None and prompt:
            hashes = prefix_hashes(prompt, view.block_size or 16)
            state = ("unhashed" if not hashes else
                     "warm" if warmth.get(view.endpoint.index) else
                     "cold")
            self._m["warmth"].labels(state=state).inc()
            view.note_dispatch(hashes)
        return view

    # ------------------------------------------------------ dispatch

    def _relay(self, rid: str, prompt: List[int], max_new: int,
               temperature: Optional[float],
               deadline: Optional[float], emit,
               session_id: Optional[str] = None,
               tenant: Optional[str] = None, slo=None) -> dict:
        """Drive one client request across the fleet until it
        completes (see :meth:`_relay_attempts`), timing the wall: the
        ``REQUEST`` trace span and the ``hvdtpu_fleet_request_seconds``
        histogram (exemplar: this trace id) cover relay start →
        terminal outcome — the denominator every per-request budget
        share divides by."""
        t0m = time.monotonic()
        meta = self._relay_attempts(rid, prompt, max_new, temperature,
                                    deadline, emit,
                                    session_id=session_id,
                                    tenant=tenant, slo=slo)
        t1m = time.monotonic()
        self._m["request_s"].observe(t1m - t0m, exemplar=rid)
        span_args = {"status": meta["status"],
                     "retries": meta["retries"],
                     "tokens": len(meta["tokens"])}
        if tenant or slo is not None:
            label = _slo.resolve_tenant(tenant)
            span_args["tenant"] = meta.get("tenant", label)
            if isinstance(meta.get("slo"), dict):
                span_args["slo_met"] = meta["slo"].get("slo_met")
            self._account_slo(label, meta)
            if label and meta["status"] == "completed":
                # Tenant drain rate: what quota Retry-After quotes
                # (docs/serving.md#qos).
                self._quota.note_completion(
                    label, len(prompt) + len(meta["tokens"]))
        _rt.span(rid, "REQUEST", t0m, t1m, span_args)
        return meta

    def _account_slo(self, tenant_label: str, meta: dict) -> None:
        """Fleet-side goodput recount from the replica's verdict: the
        router re-counts hvdtpu_slo_* in ITS registry (real fleets
        keep one registry per process), and is the only place that
        sees requests no replica ever answered — those land as shed
        or deadline here (docs/serving.md#slo)."""
        status = meta.get("status")
        if status == "completed":
            verdict = meta.get("slo")
            if not isinstance(verdict, dict):
                return
            m = _slo.metrics()
            if verdict.get("slo_met"):
                m["goodput"].labels(tenant=tenant_label).inc()
                return
            for dim in ("ttft", "tpot"):
                if verdict.get(f"{dim}_violation"):
                    m["violations"].labels(tenant=tenant_label,
                                           reason=dim).inc()
        elif status == "expired":
            _slo.record_shed(tenant_label, "deadline")
        elif status == "failed":
            _slo.record_shed(tenant_label, "shed")

    def _relay_attempts(self, rid: str, prompt: List[int],
                        max_new: int, temperature: Optional[float],
                        deadline: Optional[float], emit,
                        session_id: Optional[str] = None,
                        tenant: Optional[str] = None,
                        slo=None) -> dict:
        """Pick → stream → (on death) fail over, until terminal.
        ``emit(tok)`` is called once per generated token in order;
        returns the terminal meta dict {"status": ..., "retries": N,
        ...}. The SAME ``rid`` rides every dispatch — a failover
        re-dispatch reuses the identity, never re-mints it."""
        emitted: List[int] = []
        exclude: Dict[int, float] = {}
        qos_class = _qos.policy().class_of(
            _slo.resolve_tenant(tenant)) if tenant else None
        attempts = 0
        retries = 0
        t_fail: Optional[float] = None     # failover stopwatch
        fail_phase: Optional[str] = None   # phase/origin at FIRST
        fail_from: Optional[int] = None    # detection (span args)
        cur_idx: Optional[int] = None      # replica of the live attempt
        n_backends = max(1, len(self.backends.endpoints()))
        max_attempts = self._max_attempts or max(6, 3 * n_backends)

        def expired() -> bool:
            return deadline is not None and time.monotonic() > deadline

        def retry(reason: str) -> None:
            nonlocal retries
            retries += 1
            self._m["retries"].labels(reason=reason).inc()
            if reason == "queue_full":
                # Retry-After pressure: a scale-up signal for the
                # QoS autoscaler (docs/serving.md#qos).
                self._note_pressure()

        def emit_observed(tok: int) -> None:
            # First token after a failover closes the detection→resume
            # stopwatch (kept across back-to-back failed attempts: the
            # client's gap is measured from the FIRST detection).
            nonlocal t_fail
            if t_fail is not None:
                now = time.monotonic()
                self._m["failover_s"].observe(now - t_fail,
                                              exemplar=rid)
                _rt.span(rid, "FAILOVER", t_fail, now,
                         {"phase": fail_phase, "from": fail_from,
                          "to": cur_idx})
                t_fail = None
            emit(tok)

        while True:
            if expired():
                return {"status": "expired", "error": DEADLINE_ERROR,
                        "retries": retries, "tokens": emitted}
            if attempts >= max_attempts:
                return {"status": "failed",
                        "error": f"no replica completed the request "
                                 f"after {attempts} attempts",
                        "retries": retries, "tokens": emitted}
            view = self._pick(exclude, prompt, session_id=session_id,
                              qos_class=qos_class)
            if view is None:
                # Nobody ready right now (mass restart, all draining):
                # wait out a scrape cycle rather than failing a
                # promised request — bounded by deadline/attempts.
                attempts += 1
                wait = self._scrape_interval
                if deadline is not None:
                    wait = min(wait, max(0.0,
                                         deadline - time.monotonic()))
                time.sleep(wait)
                continue
            attempts += 1
            idx = view.endpoint.index
            cur_idx = idx
            self._m["dispatch"].labels(replica=str(idx)).inc()
            t_att = time.monotonic()
            outcome = self._stream_from(
                rid, view.endpoint, prompt + emitted,
                max_new - len(emitted), temperature, deadline,
                emitted, emit_observed, session_id=session_id,
                tenant=tenant, slo=slo)
            _rt.span(rid, "DISPATCH", t_att, time.monotonic(),
                     {"replica": idx, "outcome": outcome["kind"]})
            if outcome["kind"] == "done":
                if session_id:
                    # Shadow the lease the replica just formed so the
                    # session's next turn pins here even if it lands
                    # before the next healthz scrape.
                    view.sessions.add(session_id)
                return {"status": "completed", "retries": retries,
                        "tokens": emitted, "replica": idx,
                        **outcome.get("meta", {})}
            if outcome["kind"] == "deadline":
                return {"status": "expired", "error": DEADLINE_ERROR,
                        "retries": retries, "tokens": emitted}
            if outcome["kind"] == "bad_request":
                return {"status": "bad_request",
                        "error": outcome["error"],
                        "retries": retries, "tokens": emitted}
            # Retryable: crash/connect/queue_full/draining/failed.
            exclude[idx] = time.monotonic() + _EXCLUDE_S
            retry(outcome["kind"])
            if outcome["kind"] in ("crash", "connect"):
                phase = "midstream" if emitted else "prefill"
                self._m["failovers"].labels(phase=phase).inc()
                if t_fail is None:
                    t_fail = time.monotonic()
                    fail_phase, fail_from = phase, idx
                _log.warning(
                    "replica %d died %s request %s (%d tokens emitted)"
                    " — failing over", idx,
                    "mid-stream of" if emitted else "before first "
                    "token of", rid, len(emitted))

    def _stream_from(self, rid: str, ep: ReplicaEndpoint,
                     prompt: List[int], max_new: int,
                     temperature: Optional[float],
                     deadline: Optional[float], emitted: List[int],
                     emit, session_id: Optional[str] = None,
                     tenant: Optional[str] = None, slo=None) -> dict:
        """One dispatch attempt against one replica, streaming. Appends
        to ``emitted`` / calls ``emit`` as tokens land. Returns a
        tagged outcome: done / deadline / bad_request, or a retryable
        kind (connect, crash, queue_full, draining, failed)."""
        body = {"tokens": prompt, "max_new_tokens": max_new,
                "stream": True}
        if temperature is not None:
            body["temperature"] = temperature
        if session_id:
            body["session_id"] = session_id
        if tenant:
            body["tenant"] = tenant
        if slo is not None:
            body["slo"] = slo
        if deadline is not None:
            remaining_ms = (deadline - time.monotonic()) * 1e3
            if remaining_ms <= 0:
                return {"kind": "deadline"}
            body["deadline_ms"] = round(remaining_ms, 1)
        read_timeout = _STREAM_READ_S
        if deadline is not None:
            read_timeout = min(read_timeout, max(
                0.1, deadline - time.monotonic() + 1.0))
        try:
            conn = http.client.HTTPConnection(ep.host, ep.port,
                                              timeout=read_timeout)
            try:
                conn.request(
                    "POST", "/generate", json.dumps(body),
                    {"Content-Type": "application/json",
                     "X-Request-Id": rid})
                resp = conn.getresponse()
                if resp.status == 429:
                    resp.read()
                    return {"kind": "queue_full"}
                if resp.status == 503:
                    resp.read()
                    return {"kind": "draining"}
                if resp.status == 400:
                    err = resp.read().decode(errors="replace")
                    return {"kind": "bad_request", "error": err}
                if resp.status == 504:
                    resp.read()
                    return {"kind": "deadline"}
                if resp.status != 200:
                    resp.read()
                    return {"kind": "failed"}
                saw_done = False
                while True:
                    line = resp.readline()
                    if not line:
                        break
                    line = line.strip()
                    if not line:
                        continue
                    obj = json.loads(line)
                    if "t" in obj:
                        emitted.append(int(obj["t"]))
                        emit(int(obj["t"]))
                    elif obj.get("done"):
                        saw_done = True
                        if obj.get("status") == "completed":
                            return {"kind": "done", "meta": {
                                k: obj[k] for k in ("ttft_ms",
                                                    "latency_ms",
                                                    "tenant", "slo")
                                if k in obj}}
                        if DEADLINE_ERROR in str(obj.get("error")):
                            return {"kind": "deadline"}
                        return {"kind": "failed"}
                if not saw_done:
                    # Stream broke without a terminal line: the
                    # replica died under this request.
                    return {"kind": "crash"}
                return {"kind": "failed"}
            finally:
                conn.close()
        except (http.client.HTTPException, TimeoutError, OSError,
                ValueError):
            # Connection refused/reset, torn JSON line (killed
            # mid-write), read timeout: all read as replica loss. If
            # the status line never arrived, the request may not have
            # been admitted at all — still safe to retry, generation
            # is idempotent (greedy) or re-sampled (temperature).
            return {"kind": "crash" if emitted else "connect"}

    # ---------------------------------------------------------- HTTP

    def _build_http(self, host: str, port: int) -> None:
        from http.server import BaseHTTPRequestHandler, \
            ThreadingHTTPServer

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def _reply(self, code: int, payload: dict,
                       headers: Optional[dict] = None) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (http.server API)
                path = self.path.split("?")[0]
                if path == "/healthz":
                    with outer._views_lock:
                        views = list(outer._views.values())
                    self._reply(200, {
                        "status": "routing",
                        "replicas": [{
                            "index": v.endpoint.index,
                            "port": v.endpoint.port,
                            "ready": v.ready,
                            "queue_depth": v.queue_depth,
                            "active": v.active,
                            "slots": v.slots,
                            "score": round(v.score, 4),
                        } for v in views],
                        "ready_replicas": sum(
                            1 for v in views if v.ready),
                    })
                    return
                if path == "/readyz":
                    with outer._views_lock:
                        n = sum(1 for v in outer._views.values()
                                if v.ready)
                    if n > 0:
                        self._reply(200, {"status": "ready",
                                          "ready_replicas": n})
                    else:
                        self._reply(503, {"status": "no ready "
                                                    "replicas"})
                    return
                self._reply(404, {"error": "not found"})

            def do_POST(self):  # noqa: N802 (http.server API)
                if self.path.split("?")[0] != "/generate":
                    self._reply(404, {"error": "not found"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    tokens = body["tokens"]
                    if not isinstance(tokens, list) or not tokens:
                        raise ValueError(
                            "'tokens' must be a non-empty list")
                    tokens = [int(t) for t in tokens]
                    max_new = int(body.get("max_new_tokens", 64))
                    temperature = body.get("temperature")
                    stream = bool(body.get("stream", False))
                    deadline_ms = body.get(
                        "deadline_ms",
                        self.headers.get("X-Request-Deadline-Ms"))
                    # Tenant + SLO attribution (docs/serving.md#slo):
                    # validated here so a malformed "slo" is a 400 at
                    # the front door, not a retry storm.
                    tenant = self.headers.get("X-Tenant") \
                        or body.get("tenant")
                    slo_req = body.get("slo")
                    _slo.parse_slo(slo_req)
                except (KeyError, ValueError, TypeError,
                        json.JSONDecodeError) as e:
                    outer._m["requests"].labels(
                        outcome="bad_request").inc()
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                deadline = None
                if deadline_ms not in (None, ""):
                    deadline = time.monotonic() \
                        + float(deadline_ms) / 1e3
                else:
                    deadline = time.monotonic() + ROUTER_TIMEOUT_S
                # The request's ONE trace id: the client's, if it
                # brought one, else freshly minted — reused verbatim on
                # every retry and failover hop from here on.
                rid = str(self.headers.get("X-Request-Id")
                          or body.get("request_id")
                          or outer._request_id())
                if tenant:
                    # Front-door token-rate quota (docs/serving.md#
                    # qos): enforced here so a fleet of N replicas
                    # cannot multiply a tenant's quota by N via
                    # retries; Retry-After from the tenant's own
                    # measured drain rate.
                    label = _slo.resolve_tenant(tenant)
                    retry = outer._quota.admit(
                        label, len(tokens) + max_new)
                    if retry is not None:
                        outer._m["requests"].labels(
                            outcome="rejected").inc()
                        outer._m["shed"].labels(reason="quota").inc()
                        _slo.record_shed(label, "shed")
                        self._reply(
                            429,
                            {"error": "tenant over token-rate quota",
                             "trace_id": rid},
                            headers={"Retry-After": retry})
                        return
                sid = self.headers.get("X-Session-Id") \
                    or body.get("session_id")
                sid = str(sid) if sid else None
                if stream:
                    self._do_stream(rid, tokens, max_new, temperature,
                                    deadline, sid, tenant, slo_req)
                else:
                    self._do_unary(rid, tokens, max_new, temperature,
                                   deadline, sid, tenant, slo_req)

            def _do_unary(self, rid, tokens, max_new, temperature,
                          deadline, session_id=None, tenant=None,
                          slo=None) -> None:
                t0 = time.perf_counter()
                meta = outer._relay(rid, tokens, max_new, temperature,
                                    deadline, emit=lambda t: None,
                                    session_id=session_id,
                                    tenant=tenant, slo=slo)
                outer._count(meta["status"])
                if meta["status"] == "completed":
                    t_egress = time.monotonic()
                    reply = {
                        "id": rid, "trace_id": rid,
                        "tokens": meta["tokens"],
                        "retries": meta["retries"],
                        "replica": meta.get("replica"),
                        "latency_ms": round(
                            (time.perf_counter() - t0) * 1e3, 3)}
                    egress_args = {"tokens": len(meta["tokens"])}
                    if "ttft_ms" in meta:
                        reply["ttft_ms"] = meta["ttft_ms"]
                    if "tenant" in meta:
                        reply["tenant"] = meta["tenant"]
                        egress_args["tenant"] = meta["tenant"]
                    if "slo" in meta:
                        reply["slo"] = meta["slo"]
                        if isinstance(meta["slo"], dict):
                            egress_args["slo_met"] = \
                                meta["slo"].get("slo_met")
                    self._reply(200, reply)
                    _rt.span(rid, "EGRESS", t_egress,
                             time.monotonic(), egress_args)
                elif meta["status"] == "expired":
                    self._reply(504, {"error": DEADLINE_ERROR,
                                      "trace_id": rid,
                                      "retries": meta["retries"]})
                elif meta["status"] == "bad_request":
                    self._reply(400, {"error": meta["error"],
                                      "trace_id": rid})
                else:
                    self._reply(503, {"error": meta["error"],
                                      "trace_id": rid,
                                      "retries": meta["retries"]},
                                headers={"Retry-After": 1})

            def _do_stream(self, rid, tokens, max_new, temperature,
                           deadline, session_id=None, tenant=None,
                           slo=None) -> None:
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/x-ndjson")
                self.send_header("Cache-Control", "no-store")
                self.close_connection = True
                self.end_headers()

                def line(obj) -> None:
                    self.wfile.write(
                        json.dumps(obj).encode() + b"\n")
                    self.wfile.flush()

                try:
                    line({"id": rid, "trace_id": rid})
                    meta = outer._relay(
                        rid, tokens, max_new, temperature, deadline,
                        emit=lambda t: line({"t": t}),
                        session_id=session_id,
                        tenant=tenant, slo=slo)
                    outer._count(meta["status"])
                    done = {"done": True,
                            "status": ("completed"
                                       if meta["status"] == "completed"
                                       else "failed"),
                            "n": len(meta["tokens"]),
                            "trace_id": rid,
                            "retries": meta["retries"]}
                    for k in ("ttft_ms", "latency_ms", "tenant",
                              "slo"):
                        if k in meta:
                            done[k] = meta[k]
                    if meta["status"] != "completed":
                        done["error"] = meta.get("error")
                    line(done)
                except (BrokenPipeError, ConnectionResetError,
                        OSError):
                    pass   # client hung up; nothing to unwind

            def log_message(self, *args):  # silence per-request stderr
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="hvd-tpu-fleet-router", daemon=True)

    def _request_id(self) -> str:
        """Mint a trace id: globally unique (uuid) with a short local
        sequence suffix for log readability."""
        with self._id_lock:
            n = self._next_id
            self._next_id += 1
        return f"{uuid.uuid4().hex[:12]}-{n}"

    def _count(self, status: str) -> None:
        outcome = {"completed": "completed", "expired": "expired",
                   "bad_request": "bad_request"}.get(status, "failed")
        self._m["requests"].labels(outcome=outcome).inc()

    # ------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._scrape_cycle()   # one synchronous pass: never route blind
        self._scrape_thread = threading.Thread(
            target=self._scrape_loop, name="hvd-tpu-fleet-scrape",
            daemon=True)
        self._scrape_thread.start()
        self._http_thread.start()
        _log.info("fleet router on :%d (%d replica(s) scraped)",
                  self.port, len(self._views))

    def shutdown(self) -> None:
        self._stop.set()
        if self._scrape_thread is not None:
            self._scrape_thread.join(timeout=5.0)
        self._httpd.shutdown()
        self._httpd.server_close()
        self._http_thread.join(timeout=5.0)
