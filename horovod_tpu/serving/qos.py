"""Multi-tenant QoS plane (docs/serving.md#qos): priority classes,
deficit-weighted-round-robin admission, deadline-aware shedding,
token-rate quotas, and SLO-driven fleet autoscaling.

PR 18's SLO plane measured the problem — a bulk burst inflates the
interactive tenant's TTFT p99 by ~54x under strict-FIFO admission on a
static fleet (BENCH_SLO.json ``two_tenant``).  This module is the
control plane that closes it:

* :class:`QosPolicy` maps tenants to priority classes via the same
  ``HOROVOD_TPU_SLO_CONFIG`` file the SLO plane reads — tenant rows
  grow optional ``priority`` / ``weight`` / ``quota_tokens_per_s``
  fields (:data:`QOS_CONFIG_FIELDS`, stripped before SLO parsing so
  old configs stay valid).
* :class:`ClassQueues` replaces the engine's single FIFO admission
  queue with per-class queues drained under deficit-weighted round
  robin (DWRR): every backlogged class earns deficit each round in
  proportion to its weight, so interactive gets most admissions while
  bulk can never be starved outright.
* :func:`shed_decision` / :func:`predict_prefill_s` decide, *before*
  prefill, whether a deadline can still be met given the measured
  per-bucket prefill EWMA plus a minimum decode budget — requests that
  would 504 anyway are shed at the queue head instead of burning a
  batch slot.
* :class:`QuotaLedger` enforces per-tenant token-rate quotas with a
  token bucket and computes Retry-After from the tenant's *own
  measured drain rate* (tokens actually completed per second), not the
  global queue estimate.
* :class:`AutoscalerState` is the pure hysteresis state machine
  (sustain / cooldown clocks, PR 6 ladder pattern) that turns load
  pressure + health alerts into scale-up/down decisions;
  :class:`FleetAutoscaler` is the supervisor-side thread that feeds it
  and applies decisions via ``Fleet.scale_to``.

Everything here is host-side stdlib Python — no JAX imports — so the
fast test tier exercises it without an accelerator.
"""

from __future__ import annotations

import collections
import json
import logging
import math
import threading
import time
from typing import (Callable, Deque, Dict, Iterator, List, Mapping,
                    Optional, Sequence, Tuple)

from ..utils import env as _env

_log = logging.getLogger("horovod_tpu.serving.qos")

# Priority classes in descending priority order.  The first class is
# the "top" class batch-slot reservations protect (docs/serving.md#qos).
PRIORITY_CLASSES = ("interactive", "default", "bulk")
DEFAULT_CLASS = "default"
TOP_CLASS = PRIORITY_CLASSES[0]

# Default DWRR weights per class when the config row names a priority
# but no explicit weight.
DEFAULT_WEIGHTS = {"interactive": 4.0, "default": 2.0, "bulk": 1.0}

# Tenant-row fields owned by the QoS plane.  slo.SloPolicy strips
# these before parse_slo() so extending a config with QoS fields never
# invalidates the SLO half of the file.
QOS_CONFIG_FIELDS = ("priority", "weight", "quota_tokens_per_s")

# Per-class floor on quota Retry-After seconds: bulk clients are told
# to back off longer so interactive retries drain first.
RETRY_AFTER_FLOOR_S = {"interactive": 1, "default": 1, "bulk": 4}
RETRY_AFTER_CAP_S = 60


def class_rank(name: str) -> int:
    """Position in :data:`PRIORITY_CLASSES` (lower = higher priority);
    unknown names rank with ``default``."""
    try:
        return PRIORITY_CLASSES.index(name)
    except ValueError:
        return PRIORITY_CLASSES.index(DEFAULT_CLASS)


class TenantQos:
    """Resolved QoS spec for one tenant: priority class, DWRR weight,
    optional token-rate quota."""

    __slots__ = ("priority", "weight", "quota_tokens_per_s")

    def __init__(self, priority: str = DEFAULT_CLASS,
                 weight: Optional[float] = None,
                 quota_tokens_per_s: Optional[float] = None):
        if priority not in PRIORITY_CLASSES:
            raise ValueError(
                "unknown priority class %r (expected one of %s)"
                % (priority, ", ".join(PRIORITY_CLASSES)))
        if weight is not None and not weight > 0:
            raise ValueError("weight must be > 0, got %r" % (weight,))
        if quota_tokens_per_s is not None and not quota_tokens_per_s > 0:
            raise ValueError("quota_tokens_per_s must be > 0, got %r"
                             % (quota_tokens_per_s,))
        self.priority = priority
        self.weight = (float(weight) if weight is not None
                       else DEFAULT_WEIGHTS[priority])
        self.quota_tokens_per_s = (
            float(quota_tokens_per_s)
            if quota_tokens_per_s is not None else None)

    def to_dict(self) -> dict:
        d = {"priority": self.priority, "weight": self.weight}
        if self.quota_tokens_per_s is not None:
            d["quota_tokens_per_s"] = self.quota_tokens_per_s
        return d


def _parse_row(row: object) -> Optional[TenantQos]:
    """Extract the QoS half of one tenant config row; None when the
    row carries no QoS fields (tenant rides the default spec)."""
    if not isinstance(row, dict):
        return None
    if not any(k in row for k in QOS_CONFIG_FIELDS):
        return None
    return TenantQos(
        priority=str(row.get("priority", DEFAULT_CLASS)),
        weight=row.get("weight"),
        quota_tokens_per_s=row.get("quota_tokens_per_s"))


class QosPolicy:
    """Tenant → QoS class/weight/quota mapping, loaded from the same
    ``HOROVOD_TPU_SLO_CONFIG`` file as :class:`..slo.SloPolicy`.  A
    malformed file degrades to everything-default with a warning — the
    QoS plane must never take the serving path down."""

    def __init__(self, config_path: Optional[str] = None):
        self.tenants: Dict[str, TenantQos] = {}
        self.default = TenantQos()
        path = config_path if config_path is not None else _env.slo_config()
        if not path:
            return
        try:
            with open(path) as f:
                doc = json.load(f)
            for name, row in (doc.get("tenants") or {}).items():
                spec = _parse_row(row)
                if spec is not None:
                    self.tenants[str(name)] = spec
            d = _parse_row(doc.get("default"))
            if d is not None:
                self.default = d
        except (OSError, ValueError) as e:
            _log.warning("ignoring QoS config %s: %s", path, e)
            self.tenants = {}
            self.default = TenantQos()

    def spec_of(self, tenant: Optional[str]) -> TenantQos:
        if tenant is not None and tenant in self.tenants:
            return self.tenants[tenant]
        return self.default

    def class_of(self, tenant: Optional[str]) -> str:
        return self.spec_of(tenant).priority

    def quota_of(self, tenant: Optional[str]) -> Optional[float]:
        return self.spec_of(tenant).quota_tokens_per_s

    def class_weights(self) -> Dict[str, float]:
        """Effective DWRR weight per priority class: the max weight of
        any tenant mapped there (plus the default spec), so a class's
        share follows the most-privileged tenant the operator put in
        it."""
        w = {c: 0.0 for c in PRIORITY_CLASSES}
        for spec in list(self.tenants.values()) + [self.default]:
            w[spec.priority] = max(w[spec.priority], spec.weight)
        for c in PRIORITY_CLASSES:
            if w[c] <= 0:
                w[c] = DEFAULT_WEIGHTS[c]
        return w


_policy: Optional[QosPolicy] = None
_policy_lock = threading.Lock()


def policy() -> QosPolicy:
    """Process-wide QoS policy singleton (mirrors ``slo.policy()``)."""
    global _policy
    with _policy_lock:
        if _policy is None:
            _policy = QosPolicy()
        return _policy


def _reset_policy() -> None:
    global _policy
    with _policy_lock:
        _policy = None


# --------------------------------------------------------------------------
# Deficit-weighted round-robin admission queues
# --------------------------------------------------------------------------

class ClassQueues:
    """Per-priority-class FIFO queues drained under DWRR.

    Drop-in replacement surface for the engine's single ``deque``:
    ``append`` / ``__len__`` / ``__bool__`` / ``__iter__`` (class
    order, FIFO within class).  Selection happens through
    :meth:`select`, which pops the next request per DWRR among classes
    an ``allowed`` predicate admits; :meth:`pushback` returns a popped
    request to its queue head (and refunds its deficit) when admission
    fails downstream, e.g. on KV-pool exhaustion.

    DWRR mechanics: each class carries a deficit counter.  When no
    eligible backlogged class has deficit >= 1 (one request costs 1),
    every eligible backlogged class is replenished by its weight —
    so over a saturated period admissions per class converge to the
    weight ratio, and any backlogged class with weight > 0 is served
    within one round (no starvation).  Deficit resets when a class
    empties (standard DWRR) so idle classes cannot bank credit.
    """

    def __init__(self, weights: Optional[Mapping[str, float]] = None):
        w = dict(DEFAULT_WEIGHTS)
        if weights:
            for k, v in weights.items():
                if k in w and v > 0:
                    w[k] = float(v)
        self._weights = w
        self._q: Dict[str, Deque[object]] = {
            c: collections.deque() for c in PRIORITY_CLASSES}
        self._deficit: Dict[str, float] = {
            c: 0.0 for c in PRIORITY_CLASSES}
        self._cursor = 0

    def append(self, req: object,
               qos_class: Optional[str] = None) -> None:
        cls = qos_class or getattr(req, "qos_class", None) or DEFAULT_CLASS
        if cls not in self._q:
            cls = DEFAULT_CLASS
        self._q[cls].append(req)

    def __len__(self) -> int:
        return sum(len(q) for q in self._q.values())

    def __bool__(self) -> bool:
        return any(self._q.values())

    def __iter__(self) -> Iterator[object]:
        for c in PRIORITY_CLASSES:
            yield from self._q[c]

    def depths(self) -> Dict[str, int]:
        return {c: len(q) for c, q in self._q.items()}

    def heads(self) -> List[object]:
        """Current head request of each non-empty class (priority
        order) — the shed/expiry scan looks here."""
        return [q[0] for q in self._q.values() if q]

    def remove(self, req: object) -> bool:
        """Remove a specific queued request (expiry/shed at any class
        head); True when found."""
        for c, q in self._q.items():
            try:
                q.remove(req)
            except ValueError:
                continue
            if not q:
                self._deficit[c] = 0.0
            return True
        return False

    def select(self, allowed: Optional[Callable[[str], bool]] = None
               ) -> Optional[object]:
        """Pop the next request under DWRR among non-empty classes
        passing ``allowed`` (None = all).  Returns None when nothing
        is eligible."""
        eligible = [c for c in PRIORITY_CLASSES
                    if self._q[c] and (allowed is None or allowed(c))]
        if not eligible:
            return None
        n = len(PRIORITY_CLASSES)
        for _round in range(2):
            for off in range(n):
                c = PRIORITY_CLASSES[(self._cursor + off) % n]
                if c not in eligible:
                    continue
                if self._deficit[c] >= 1.0:
                    self._deficit[c] -= 1.0
                    req = self._q[c].popleft()
                    if not self._q[c]:
                        self._deficit[c] = 0.0
                    self._cursor = (self._cursor + off) % n
                    setattr(req, "qos_class", c)
                    return req
            # No eligible class had deficit — replenish proportionally
            # to weight, scaled so the heaviest eligible class reaches
            # a full quantum in one round (fractional weights stay
            # proportional but cannot stall the loop).
            need = 1.0 - max(self._deficit[c] for c in eligible)
            fastest = max(self._weights[c] for c in eligible)
            k = max(1, int(math.ceil(need / fastest)))
            for c in eligible:
                self._deficit[c] += k * self._weights[c]
        return None  # pragma: no cover - unreachable with weights > 0

    def pushback(self, req: object) -> None:
        """Return a just-selected request to its queue head and refund
        the deficit it consumed (admission failed downstream)."""
        cls = getattr(req, "qos_class", None) or DEFAULT_CLASS
        if cls not in self._q:
            cls = DEFAULT_CLASS
        self._q[cls].appendleft(req)
        self._deficit[cls] += 1.0


# --------------------------------------------------------------------------
# Deadline-aware shedding
# --------------------------------------------------------------------------

def predict_prefill_s(n_tokens: int,
                      ewma_by_bucket: Mapping[int, float],
                      bucket_of: Callable[[int], int],
                      chunk_tokens: int = 0) -> float:
    """Predicted prefill seconds for a prompt of ``n_tokens`` from a
    per-bucket cost EWMA.

    Monolithic path (``chunk_tokens == 0``): cost of the prompt's
    padding bucket.  Chunked path: per-chunk cost of the chunk bucket
    times the number of chunks.  Unmeasured buckets fall back to the
    largest measured bucket's cost scaled by the bucket ratio (an
    optimistic-but-monotone estimate); with no measurements at all the
    prediction is 0.0 — shedding stays off until the EWMA warms up,
    because shedding on a guess converts servable requests into 504s.
    """
    if n_tokens <= 0:
        return 0.0
    if chunk_tokens and chunk_tokens > 0:
        n_chunks = (n_tokens + chunk_tokens - 1) // chunk_tokens
        per = _bucket_cost(bucket_of(chunk_tokens), ewma_by_bucket)
        return n_chunks * per
    return _bucket_cost(bucket_of(n_tokens), ewma_by_bucket)


def _bucket_cost(bucket: int,
                 ewma_by_bucket: Mapping[int, float]) -> float:
    if not ewma_by_bucket:
        return 0.0
    v = ewma_by_bucket.get(bucket)
    if v is not None:
        return v
    # Scale the largest measured bucket linearly — prefill cost grows
    # at least linearly in padded length, so this under-estimates
    # (sheds conservatively) rather than over-sheds.
    largest = max(ewma_by_bucket)
    return ewma_by_bucket[largest] * (bucket / float(largest))


def shed_decision(remaining_s: float, predicted_prefill_s: float,
                  min_decode_s: float) -> bool:
    """True when a deadline-carrying request should be shed before
    prefill: the remaining budget cannot cover predicted prefill plus
    one minimum decode step, so it would 504 after burning a slot.
    With no measurements yet (both predictions 0) never shed."""
    need = predicted_prefill_s + min_decode_s
    if need <= 0.0:
        return False
    return remaining_s < need


# --------------------------------------------------------------------------
# Per-tenant token-rate quotas + measured drain rate
# --------------------------------------------------------------------------

class _TokenBucket:
    __slots__ = ("rate", "burst", "tokens", "t_last")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.t_last = now

    def _refill(self, now: float) -> None:
        dt = max(0.0, now - self.t_last)
        self.t_last = now
        self.tokens = min(self.burst, self.tokens + dt * self.rate)

    def take(self, n: float, now: float) -> float:
        """Deduct ``n`` tokens if available; returns 0.0 on success,
        else the deficit (tokens short) with no deduction."""
        self._refill(now)
        if self.tokens >= n:
            self.tokens -= n
            return 0.0
        return n - self.tokens


# Drain-rate observation window: long enough to smooth decode-tick
# granularity, short enough to track a throttled tenant's real rate.
_DRAIN_WINDOW_S = 30.0


class QuotaLedger:
    """Token-rate quota enforcement plus per-tenant measured drain
    rates (docs/serving.md#qos).

    ``admit`` charges ``prompt + max_new_tokens`` against the tenant's
    bucket (burst = 2s of rate, so short bursts ride through).  On
    rejection the Retry-After is ``deficit / drain_rate`` where
    ``drain_rate`` is the tenant's *own measured* completion rate over
    the last 30s — a tenant the fleet is actually serving quickly gets
    a short backoff; one whose work is crawling gets an honest long
    one.  Tenants with no completions yet fall back to the quota rate
    itself.  The result is clamped to a per-class floor
    (:data:`RETRY_AFTER_FLOOR_S`) and :data:`RETRY_AFTER_CAP_S`."""

    def __init__(self, qos_policy: Optional[QosPolicy] = None):
        self._policy = qos_policy
        self._buckets: Dict[str, _TokenBucket] = {}
        self._done: Dict[str, Deque[Tuple[float, float]]] = {}
        self._lock = threading.Lock()

    def _spec(self, tenant: Optional[str]) -> TenantQos:
        pol = self._policy if self._policy is not None else policy()
        return pol.spec_of(tenant)

    def admit(self, tenant: Optional[str], tokens: float,
              now: Optional[float] = None) -> Optional[int]:
        """Charge ``tokens`` against the tenant's quota.  None = admitted
        (or no quota configured); otherwise the Retry-After seconds to
        return with the 429."""
        spec = self._spec(tenant)
        rate = spec.quota_tokens_per_s
        if rate is None or tenant is None:
            return None
        now = time.monotonic() if now is None else now
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None or b.rate != rate:
                b = _TokenBucket(rate, burst=2.0 * rate, now=now)
                self._buckets[tenant] = b
            deficit = b.take(float(tokens), now)
        if deficit <= 0.0:
            return None
        return self.retry_after_s(tenant, deficit, now=now)

    def note_completion(self, tenant: Optional[str], tokens: float,
                        now: Optional[float] = None) -> None:
        """Record ``tokens`` drained (prompt + generated) for the
        tenant's measured-rate window."""
        if tenant is None or tokens <= 0:
            return
        now = time.monotonic() if now is None else now
        with self._lock:
            dq = self._done.setdefault(tenant, collections.deque())
            dq.append((now, float(tokens)))
            while dq and dq[0][0] < now - _DRAIN_WINDOW_S:
                dq.popleft()

    def drain_rate(self, tenant: Optional[str],
                   now: Optional[float] = None) -> Optional[float]:
        """Tenant's measured completion rate (tokens/s) over the last
        30s; None with no completions in window."""
        if tenant is None:
            return None
        now = time.monotonic() if now is None else now
        with self._lock:
            dq = self._done.get(tenant)
            if not dq:
                return None
            while dq and dq[0][0] < now - _DRAIN_WINDOW_S:
                dq.popleft()
            if not dq:
                return None
            total = sum(n for _, n in dq)
            span = max(1.0, now - dq[0][0])
        return total / span

    def retry_after_s(self, tenant: Optional[str], deficit: float,
                      now: Optional[float] = None) -> int:
        """Seconds until ``deficit`` tokens plausibly drain for this
        tenant: measured drain rate first, quota rate as fallback,
        clamped to the class floor and the global cap."""
        spec = self._spec(tenant)
        rate = self.drain_rate(tenant, now=now)
        if rate is None or rate <= 0:
            rate = spec.quota_tokens_per_s or 1.0
        floor = RETRY_AFTER_FLOOR_S.get(spec.priority, 1)
        return max(floor, min(RETRY_AFTER_CAP_S,
                              int(math.ceil(deficit / rate))))


class QuotaExceededError(Exception):
    """Request rejected by per-tenant token-rate quota; carries the
    Retry-After seconds computed from the tenant's drain rate."""

    def __init__(self, retry_after_s: int, tenant: Optional[str] = None):
        super().__init__("tenant %s over token-rate quota" % (tenant,))
        self.retry_after_s = int(retry_after_s)
        self.tenant = tenant


# --------------------------------------------------------------------------
# SLO-driven fleet autoscaling
# --------------------------------------------------------------------------

class AutoscalerConfig:
    """Hysteresis knobs for the fleet autoscaler (PR 6 ladder pattern:
    sustain window to escalate, cooldown window to de-escalate,
    clocks reset on every action)."""

    def __init__(self, min_replicas: int, max_replicas: int, *,
                 high_load: float = 1.5, low_load: float = 0.25,
                 sustain_s: float = 3.0, cooldown_s: float = 15.0,
                 alert_hold_s: float = 10.0,
                 ttft_target_ms: Optional[float] = None):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.high_load = high_load    # outstanding work per slot
        self.low_load = low_load
        self.sustain_s = sustain_s
        self.cooldown_s = cooldown_s
        self.alert_hold_s = alert_hold_s
        self.ttft_target_ms = ttft_target_ms


class AutoscalerState:
    """Pure scale-decision state machine — no threads, no I/O, fed by
    :meth:`observe` with the current signals and a monotonic clock so
    tests can drive it deterministically.

    Scale up when pressure (per-slot load above ``high_load``, a held
    ``queue_depth_runaway`` alert, Retry-After/429 pressure, or TTFT
    p99 over target) is sustained for ``sustain_s``.  Scale down when
    load stays under ``low_load`` with no pressure for ``cooldown_s``.
    Both clocks reset after every decision, and a decision names the
    dominant signal as ``why`` for the flight recorder / metrics
    label."""

    SCALE_UP_WHYS = ("queue_runaway", "ttft_trend", "retry_pressure",
                     "queue_depth")

    def __init__(self, config: AutoscalerConfig):
        self.config = config
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None
        self._alert_until = 0.0
        self._alert_kind: Optional[str] = None

    def note_alert(self, kind: str, now: float) -> None:
        """Hold a health-plane alert (e.g. ``queue_depth_runaway``) as
        scale-up pressure for ``alert_hold_s``."""
        self._alert_until = now + self.config.alert_hold_s
        self._alert_kind = kind

    def observe(self, now: float, n_replicas: int,
                load_per_slot: float,
                retry_pressure: float = 0.0,
                ttft_p99_ms: Optional[float] = None) -> Optional[dict]:
        """Feed one signal sample; returns a decision dict
        ``{"direction": "up"|"down", "why": ..., "n": target}`` or
        None.  ``load_per_slot`` is outstanding work (active+queued)
        per batch slot across ready replicas; ``retry_pressure`` is
        recent 429/queue-full events per second observed at the
        router."""
        c = self.config
        alert_held = now < self._alert_until
        ttft_high = (c.ttft_target_ms is not None
                     and ttft_p99_ms is not None
                     and ttft_p99_ms > c.ttft_target_ms)
        why = None
        if alert_held:
            why = "queue_runaway"
        elif ttft_high:
            why = "ttft_trend"
        elif retry_pressure > 0.0:
            why = "retry_pressure"
        elif load_per_slot > c.high_load:
            why = "queue_depth"
        pressure = why is not None

        if pressure:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
            if (now - self._above_since >= c.sustain_s
                    and n_replicas < c.max_replicas):
                self._above_since = None
                return {"direction": "up", "why": why,
                        "n": n_replicas + 1}
            return None

        self._above_since = None
        if load_per_slot < c.low_load:
            if self._below_since is None:
                self._below_since = now
            if (now - self._below_since >= c.cooldown_s
                    and n_replicas > c.min_replicas):
                self._below_since = None
                return {"direction": "down", "why": "recovered",
                        "n": n_replicas - 1}
        else:
            self._below_since = None
        return None


class FleetAutoscaler:
    """Supervisor-side autoscaling thread: polls a signal source
    (normally ``Router.qos_signals``), feeds :class:`AutoscalerState`,
    and applies decisions through ``fleet.scale_to`` — recording each
    as a flight-recorder ``qos`` event plus
    ``hvdtpu_fleet_scale_events_total{direction,why}``
    (docs/serving.md#qos)."""

    def __init__(self, fleet, config: AutoscalerConfig, *,
                 signals: Optional[Callable[[], dict]] = None,
                 interval_s: float = 1.0):
        self.fleet = fleet
        self.state = AutoscalerState(config)
        self.interval_s = interval_s
        self._signals = signals
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.decisions: List[dict] = []
        from ..observability import registry as _reg
        self._m_events = _reg.registry().counter(
            "hvdtpu_fleet_scale_events_total",
            "Autoscaler scale decisions applied, by direction "
            "(up/down) and dominant signal (docs/serving.md#qos)")
        self._m_target = _reg.registry().gauge(
            "hvdtpu_fleet_target_replicas",
            "Replica count the QoS autoscaler is currently steering "
            "the fleet toward (docs/serving.md#qos)")

    def note_alert(self, kind: str) -> None:
        """Health-plane alert sink hookup (``Fleet`` forwards
        ``queue_depth_runaway`` here)."""
        self.state.note_alert(kind, time.monotonic())

    def _default_signals(self) -> dict:
        views = self.fleet.load_views()
        slots = sum(v.get("slots", 0) for v in views) or 1
        work = sum(v.get("active", 0) + v.get("queue_depth", 0)
                   for v in views)
        return {"load_per_slot": work / float(slots),
                "n_replicas": max(1, len(views))}

    def tick(self, now: Optional[float] = None) -> Optional[dict]:
        """One observe/act cycle (also called directly by tests)."""
        now = time.monotonic() if now is None else now
        try:
            sig = (self._signals or self._default_signals)()
        except Exception as e:  # pragma: no cover - defensive
            _log.debug("autoscaler signal source failed: %s", e)
            return None
        n = int(sig.get("n_replicas") or self.fleet.live_count())
        decision = self.state.observe(
            now, n,
            float(sig.get("load_per_slot", 0.0)),
            retry_pressure=float(sig.get("retry_pressure", 0.0)),
            ttft_p99_ms=sig.get("ttft_p99_ms"))
        if decision is None:
            return None
        try:
            self.fleet.scale_to(decision["n"])
        except Exception as e:
            _log.warning("autoscaler scale_to(%d) failed: %s",
                         decision["n"], e)
            return None
        self.decisions.append(decision)
        self._m_events.labels(direction=decision["direction"],
                              why=decision["why"]).inc()
        self._m_target.set(decision["n"])
        from ..observability import flight_recorder as _flight
        _flight.recorder().note("qos", (
            "scale", decision["direction"], decision["why"],
            decision["n"]))
        _log.info("qos autoscale %s -> %d replicas (%s)",
                  decision["direction"], decision["n"],
                  decision["why"])
        return decision

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.tick()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._m_target.set(self.fleet.live_count())
        self._thread = threading.Thread(
            target=self._run, name="hvd-tpu-qos-autoscaler",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
