"""Per-request SLO attribution and tenant accounting
(docs/serving.md#slo).

Three small pieces the whole serving path shares:

- **Target resolution**: a request may carry explicit TTFT/TPOT
  targets (``"slo": {"ttft_ms": .., "tpot_ms": ..}``); missing fields
  fall back to the tenant's entry in the fleet SLO config file
  (``HOROVOD_TPU_SLO_CONFIG``), then to the config's ``"default"``
  entry, then to the env-level targets (``HOROVOD_TPU_SLO_TTFT_MS`` /
  ``_TPOT_MS``). A request that resolves to no target at all carries
  no SLO — it is served and counted per-tenant, but never judged.

- **Bounded tenant cardinality**: tenant names become metric label
  values, so the first ``HOROVOD_TPU_MAX_TENANTS`` distinct names keep
  their own label and every later one collapses into the ``"other"``
  overflow bucket — a client fabricating tenant names cannot grow the
  registry without bound. Requests with no tenant land under
  ``"default"``.

- **Goodput accounting**: ``hvdtpu_slo_goodput_total{tenant}`` counts
  completed requests that met every attached target;
  ``hvdtpu_slo_violations_total{tenant, reason}`` counts the misses
  (``ttft``/``tpot``) and the requests that never got an answer at all
  (``shed`` — the 429 queue-full path; ``deadline`` — the 504 path),
  so shed load stays visible in goodput math instead of vanishing.
  ``hvdtpu_slo_violation_seconds{tenant}`` carries the exemplar
  linking the worst recent violation to its trace id.

Everything here is process-local registry state: the replica engine
judges with its own clocks, the fleet router re-counts the same
verdicts fleet-side, and the per-replica history sampler trends both
(docs/serving.md#fleet).
"""

from __future__ import annotations

import dataclasses
import json
import threading
from typing import Dict, Optional

from ..observability import registry as _obs
from ..utils import env as _env
from ..utils.logging import get_logger

_log = get_logger("serving.slo")

# Overflow label once the tenant table hits HOROVOD_TPU_MAX_TENANTS,
# and the label untenanted requests land under.
OVERFLOW_TENANT = "other"
DEFAULT_TENANT = "default"

VIOLATION_REASONS = ("ttft", "tpot", "shed", "deadline")


def _metrics():
    r = _obs.registry()
    return {
        "goodput": r.counter(
            "hvdtpu_slo_goodput_total",
            "Completed requests that met every attached SLO target, "
            "by tenant — the numerator of goodput "
            "(docs/serving.md#slo)"),
        "violations": r.counter(
            "hvdtpu_slo_violations_total",
            "SLO misses by tenant and reason: ttft / tpot (completed "
            "but late), shed (429 queue-full), deadline (504) — shed "
            "load stays visible in goodput math"),
        "request_s": r.histogram(
            "hvdtpu_slo_request_seconds",
            "End-to-end latency of SLO-attached completed requests, "
            "by tenant (submit → done on the judging process)",
            buckets=_obs.LATENCY_BUCKETS),
        "tokens": r.counter(
            "hvdtpu_slo_tokens_total",
            "Generated tokens attributed per tenant (SLO-attached "
            "requests)"),
        "violation_s": r.histogram(
            "hvdtpu_slo_violation_seconds",
            "Observed latency of the violated target (TTFT seconds "
            "for a ttft miss, per-token seconds for a tpot miss; "
            "exemplar: trace id of the worst recent violation)",
            buckets=_obs.LATENCY_BUCKETS),
    }


_m = None
_m_lock = threading.Lock()


def metrics() -> dict:
    global _m
    if _m is None:
        with _m_lock:
            if _m is None:
                _m = _metrics()
    return _m


# --------------------------------------------------------------- targets

@dataclasses.dataclass(frozen=True)
class SloTargets:
    """Resolved per-request targets, milliseconds. A None field means
    that dimension is not judged."""

    ttft_ms: Optional[float] = None
    tpot_ms: Optional[float] = None

    def __bool__(self) -> bool:
        return self.ttft_ms is not None or self.tpot_ms is not None

    def to_dict(self) -> dict:
        d = {}
        if self.ttft_ms is not None:
            d["ttft_ms"] = self.ttft_ms
        if self.tpot_ms is not None:
            d["tpot_ms"] = self.tpot_ms
        return d


def parse_slo(obj) -> Optional[SloTargets]:
    """Validate a request's ``slo`` field. None passes through; a dict
    with optional numeric ``ttft_ms``/``tpot_ms`` becomes
    :class:`SloTargets`; anything else raises ``ValueError`` (the HTTP
    400 path)."""
    if obj is None:
        return None
    if isinstance(obj, SloTargets):
        return obj
    if not isinstance(obj, dict):
        raise ValueError("'slo' must be an object with ttft_ms/tpot_ms")
    out = {}
    for key in ("ttft_ms", "tpot_ms"):
        v = obj.get(key)
        if v is None:
            continue
        if isinstance(v, bool) or not isinstance(v, (int, float)) \
                or v <= 0:
            raise ValueError(f"'slo.{key}' must be a positive number")
        out[key] = float(v)
    unknown = set(obj) - {"ttft_ms", "tpot_ms"}
    if unknown:
        raise ValueError(f"unknown 'slo' field(s): {sorted(unknown)}")
    return SloTargets(**out)


def _strip_qos(row):
    """Drop the QoS plane's tenant-row fields (priority/weight/quota,
    docs/serving.md#qos) before SLO validation — the two planes share
    one config file and parse_slo rejects unknown keys."""
    if not isinstance(row, dict):
        return row
    from . import qos as _qos
    return {k: v for k, v in row.items()
            if k not in _qos.QOS_CONFIG_FIELDS}


class SloPolicy:
    """Target resolution: request field > tenant config entry >
    config ``default`` entry > env defaults. The config file
    (``HOROVOD_TPU_SLO_CONFIG``) is read once per policy instance —
    the fleet ships one env to every replica, so the file is
    deployment-static."""

    def __init__(self, config_path: Optional[str] = None):
        path = config_path if config_path is not None \
            else _env.slo_config()
        self._tenants: Dict[str, SloTargets] = {}
        self._default: Optional[SloTargets] = None
        if path:
            try:
                with open(path) as f:
                    cfg = json.load(f)
                for name, row in (cfg.get("tenants") or {}).items():
                    self._tenants[str(name)] = \
                        parse_slo(_strip_qos(row)) or SloTargets()
                if cfg.get("default") is not None:
                    self._default = parse_slo(
                        _strip_qos(cfg["default"]))
            except (OSError, ValueError) as e:
                _log.warning("SLO config %s unreadable: %s", path, e)
        env_ttft = _env.slo_ttft_ms()
        env_tpot = _env.slo_tpot_ms()
        if env_ttft is not None or env_tpot is not None:
            base = self._default or SloTargets()
            self._default = SloTargets(
                ttft_ms=base.ttft_ms if base.ttft_ms is not None
                else env_ttft,
                tpot_ms=base.tpot_ms if base.tpot_ms is not None
                else env_tpot)

    def resolve(self, tenant: Optional[str],
                request_slo=None) -> Optional[SloTargets]:
        """Field-wise overlay: each target dimension takes the most
        specific source that names it. Returns None when nothing
        attaches an SLO (the request is never judged)."""
        req = parse_slo(request_slo)
        tenant_t = self._tenants.get(tenant) if tenant else None
        ttft = tpot = None
        for src in (req, tenant_t, self._default):
            if src is None:
                continue
            if ttft is None and src.ttft_ms is not None:
                ttft = src.ttft_ms
            if tpot is None and src.tpot_ms is not None:
                tpot = src.tpot_ms
        if ttft is None and tpot is None:
            return None
        return SloTargets(ttft_ms=ttft, tpot_ms=tpot)


_policy: Optional[SloPolicy] = None
_policy_lock = threading.Lock()


def policy() -> SloPolicy:
    """The process-global policy (config read once, first use)."""
    global _policy
    if _policy is None:
        with _policy_lock:
            if _policy is None:
                _policy = SloPolicy()
    return _policy


def _reset_policy() -> None:
    """Test hook: drop the cached policy so env/config changes apply."""
    global _policy
    _policy = None


# ---------------------------------------------------------- tenant label

_tenant_table: Dict[str, str] = {}
_tenant_lock = threading.Lock()


def resolve_tenant(name: Optional[str]) -> str:
    """Bounded-cardinality label for a tenant name: the first
    ``HOROVOD_TPU_MAX_TENANTS`` distinct names map to themselves,
    later ones to ``"other"``; no/empty name maps to ``"default"``.
    The mapping is sticky for the process lifetime, so a tenant that
    made the table keeps its label."""
    if not name:
        return DEFAULT_TENANT
    name = str(name)[:64]
    with _tenant_lock:
        label = _tenant_table.get(name)
        if label is None:
            if len(_tenant_table) < _env.max_tenants():
                label = name
            else:
                label = OVERFLOW_TENANT
            _tenant_table[name] = label
        return label


def _reset_tenants() -> None:
    """Test hook: empty the tenant table (mirrors _reset_policy)."""
    with _tenant_lock:
        _tenant_table.clear()


# ------------------------------------------------------------- verdicts

def judge(targets: SloTargets, ttft_s: Optional[float],
          tpot_s: Optional[float]) -> dict:
    """The verdict a completed request is stamped with: measured
    TTFT/TPOT against the attached targets. ``tpot_s`` is the mean
    time per output token after the first (None for single-token
    generations — that dimension then trivially passes)."""
    ttft_bad = (targets.ttft_ms is not None and ttft_s is not None
                and ttft_s * 1e3 > targets.ttft_ms)
    tpot_bad = (targets.tpot_ms is not None and tpot_s is not None
                and tpot_s * 1e3 > targets.tpot_ms)
    verdict = {
        "slo_met": not (ttft_bad or tpot_bad),
        "ttft_violation": ttft_bad,
        "tpot_violation": tpot_bad,
    }
    if ttft_s is not None:
        verdict["ttft_ms"] = round(ttft_s * 1e3, 3)
    if tpot_s is not None:
        verdict["tpot_ms"] = round(tpot_s * 1e3, 3)
    verdict.update({f"target_{k}": v
                    for k, v in targets.to_dict().items()})
    return verdict


def record_completion(tenant: str, verdict: dict,
                      latency_s: float, ttft_s: Optional[float],
                      tpot_s: Optional[float], n_tokens: int,
                      trace_id: Optional[str] = None) -> None:
    """Count one judged completion into the ``hvdtpu_slo_*`` families.
    ``tenant`` must already be a resolved label
    (:func:`resolve_tenant`)."""
    m = metrics()
    m["request_s"].labels(tenant=tenant).observe(latency_s,
                                                 exemplar=trace_id)
    m["tokens"].labels(tenant=tenant).inc(n_tokens)
    if verdict["slo_met"]:
        m["goodput"].labels(tenant=tenant).inc()
        return
    if verdict.get("ttft_violation"):
        m["violations"].labels(tenant=tenant, reason="ttft").inc()
        if ttft_s is not None:
            m["violation_s"].labels(tenant=tenant).observe(
                ttft_s, exemplar=trace_id)
    if verdict.get("tpot_violation"):
        m["violations"].labels(tenant=tenant, reason="tpot").inc()
        if tpot_s is not None:
            m["violation_s"].labels(tenant=tenant).observe(
                tpot_s, exemplar=trace_id)


def record_shed(tenant: str, reason: str) -> None:
    """Count a request that never completed: ``shed`` (queue-full 429)
    or ``deadline`` (504)."""
    metrics()["violations"].labels(tenant=tenant, reason=reason).inc()


def verdict_summary(verdict: Optional[dict]) -> str:
    """Compact verdict string for trace tables and flight-recorder
    notes: ``met``, or the comma-joined violated dimensions."""
    if not verdict:
        return "-"
    if verdict.get("slo_met"):
        return "met"
    bad = [k[:4] for k in ("ttft_violation", "tpot_violation")
           if verdict.get(k)]
    return ",".join(bad) or "miss"
