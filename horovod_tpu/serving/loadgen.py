"""Open-loop load generation for the serving fleet
(docs/serving.md#slo, docs/benchmarks.md#bench_slojson).

Every earlier serving bench closed the loop: the next request waited
for the last, so the arrival rate silently adapted to whatever the
fleet could absorb and queueing collapse never showed — throughput
looked flat while real clients would have been timing out. This module
is the MLPerf-style fix (arXiv 1909.09756): a **seeded arrival
process** fires requests on schedule regardless of completions, so
offered load is an independent variable and goodput-vs-offered-load
has a measurable knee.

Three pieces:

- :func:`build_schedule` — deterministic Poisson (``expovariate``) or
  constant-rate arrivals from ``random.Random(seed)``, each assigned a
  tenant from a weighted mix (:class:`TenantSpec`: prompt-length
  range, generation budget, optional SLO targets). Same seed → byte-
  identical schedule; :func:`schedule_checksum` pins that in bench
  contracts, and save/load round-trips the schedule as sorted-key
  JSONL for replay.

- :func:`run_schedule` — fires each arrival at its scheduled offset on
  its own thread, against the router's ``/generate`` (or an injected
  ``sender`` for tests). A bounded in-flight cap keeps a saturated
  fleet from OOMing the client: arrivals over the cap are **dropped
  and counted**, never silently skipped — offered == sent + dropped is
  an invariant the fast tier checks.

- :func:`summarize` — per-tenant percentile/goodput rollup of the
  result rows (pure stdlib; the shape ``tools/slo`` and
  ``bench_serving.py --slo`` consume).
"""

from __future__ import annotations

import dataclasses
import json
import random
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence

from ..utils.logging import get_logger

_log = get_logger("serving.loadgen")

DROP_REASON_INFLIGHT = "inflight_cap"


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's traffic shape in a mix: relative arrival weight,
    prompt-length range (tokens drawn uniformly), generation budget,
    and the SLO dict each request carries (None → tenant/env defaults
    resolve server-side)."""

    name: str
    weight: float = 1.0
    prompt_len: Sequence[int] = (8, 16)     # inclusive [lo, hi]
    max_new_tokens: int = 16
    slo: Optional[dict] = None
    vocab: int = 256
    # QoS class the tenant maps to server-side (docs/serving.md#qos) —
    # client-side attribution only; the engine resolves the real class
    # from the SLO config file. Omitted from the canonical rows when
    # None so pre-QoS schedule checksums stay byte-identical.
    priority: Optional[str] = None

    def to_dict(self) -> dict:
        d = {"name": self.name, "weight": self.weight,
             "prompt_len": list(self.prompt_len),
             "max_new_tokens": self.max_new_tokens,
             "vocab": self.vocab}
        if self.slo is not None:
            d["slo"] = dict(self.slo)
        if self.priority is not None:
            d["priority"] = self.priority
        return d


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One scheduled request: fire at ``t_s`` after start, regardless
    of what happened to every earlier arrival."""

    t_s: float
    tenant: str
    tokens: tuple
    max_new_tokens: int
    slo: Optional[dict] = None
    priority: Optional[str] = None

    def to_dict(self) -> dict:
        d = {"t_s": self.t_s, "tenant": self.tenant,
             "tokens": list(self.tokens),
             "max_new_tokens": self.max_new_tokens}
        if self.slo is not None:
            d["slo"] = dict(self.slo)
        if self.priority is not None:
            d["priority"] = self.priority
        return d


def build_schedule(rate_rps: float, duration_s: float, seed: int,
                   tenants: Sequence[TenantSpec],
                   process: str = "poisson") -> List[Arrival]:
    """Deterministic arrival schedule: ``poisson`` draws exponential
    gaps at ``rate_rps`` (the open-loop default — bursts happen, like
    real traffic), ``constant`` spaces arrivals exactly ``1/rate``
    apart. All randomness flows from ``random.Random(seed)``, so a
    fixed seed is a fixed schedule — arrival times, tenant assignment,
    prompt contents, everything."""
    if rate_rps <= 0:
        raise ValueError("rate_rps must be positive")
    if duration_s <= 0:
        raise ValueError("duration_s must be positive")
    if not tenants:
        raise ValueError("at least one TenantSpec required")
    if process not in ("poisson", "constant"):
        raise ValueError(f"unknown arrival process: {process!r}")
    rng = random.Random(seed)
    weights = [max(0.0, t.weight) for t in tenants]
    if sum(weights) <= 0:
        raise ValueError("tenant weights must sum > 0")
    out: List[Arrival] = []
    t = 0.0
    while True:
        gap = (rng.expovariate(rate_rps) if process == "poisson"
               else 1.0 / rate_rps)
        t += gap
        if t >= duration_s:
            break
        spec = rng.choices(tenants, weights=weights)[0]
        lo, hi = spec.prompt_len[0], spec.prompt_len[-1]
        n = rng.randint(int(lo), int(hi))
        tokens = tuple(rng.randrange(1, spec.vocab) for _ in range(n))
        out.append(Arrival(
            t_s=round(t, 6), tenant=spec.name, tokens=tokens,
            max_new_tokens=spec.max_new_tokens, slo=spec.slo,
            priority=spec.priority))
    return out


def schedule_checksum(arrivals: Sequence[Arrival]) -> str:
    """crc32 over the canonical JSON rows — the byte-identity pin the
    bench contract compares across regenerations."""
    payload = "\n".join(
        json.dumps(a.to_dict(), sort_keys=True) for a in arrivals)
    return f"{zlib.crc32(payload.encode()):08x}"


def save_schedule(arrivals: Sequence[Arrival], path: str) -> None:
    """Replayable trace format: one sorted-key JSON row per arrival."""
    with open(path, "w") as f:
        for a in arrivals:
            f.write(json.dumps(a.to_dict(), sort_keys=True) + "\n")


def load_schedule(path: str) -> List[Arrival]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            out.append(Arrival(
                t_s=d["t_s"], tenant=d["tenant"],
                tokens=tuple(d["tokens"]),
                max_new_tokens=d["max_new_tokens"],
                slo=d.get("slo"), priority=d.get("priority")))
    return out


def _http_sender(host: str, port: int, timeout_s: float) -> Callable:
    """The real sender: one unary POST /generate against the router,
    returning the decoded reply dict (an ``_error`` row on transport
    failure — the open loop never raises mid-run)."""
    import http.client

    def send(arrival: Arrival) -> dict:
        body = {"tokens": list(arrival.tokens),
                "max_new_tokens": arrival.max_new_tokens,
                "tenant": arrival.tenant}
        if arrival.slo is not None:
            body["slo"] = arrival.slo
        try:
            conn = http.client.HTTPConnection(host, port,
                                              timeout=timeout_s)
            try:
                conn.request("POST", "/generate", json.dumps(body),
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                payload = json.loads(
                    resp.read().decode(errors="replace") or "{}")
                payload["_http_status"] = resp.status
                return payload
            finally:
                conn.close()
        except (OSError, ValueError) as e:
            return {"_error": str(e), "_http_status": 0}

    return send


def run_schedule(arrivals: Sequence[Arrival], host: str = "127.0.0.1",
                 port: int = 8471, *, max_inflight: int = 64,
                 timeout_s: float = 60.0,
                 sender: Optional[Callable] = None) -> dict:
    """Fire the schedule open-loop: each arrival launches at its
    ``t_s`` offset whether or not earlier requests finished. At most
    ``max_inflight`` requests are outstanding; an arrival landing over
    the cap is dropped on the spot and accounted (reason
    ``inflight_cap``) — backpressure must show up in the numbers, not
    stall the clock. Returns ``{"offered", "sent", "dropped",
    "drop_reasons", "results": [row...], "wall_s"}`` with
    offered == sent + dropped guaranteed."""
    send = sender if sender is not None \
        else _http_sender(host, port, timeout_s)
    results: List[dict] = []
    lock = threading.Lock()
    inflight = threading.Semaphore(max_inflight)
    threads: List[threading.Thread] = []
    dropped: Dict[str, int] = {}
    t0 = time.perf_counter()

    def fire(arrival: Arrival) -> None:
        t_sent = time.perf_counter() - t0
        try:
            reply = send(arrival)
        finally:
            inflight.release()
        row = {"tenant": arrival.tenant, "t_s": arrival.t_s,
               "t_sent_s": round(t_sent, 6),
               "latency_s": round(time.perf_counter() - t0 - t_sent,
                                  6)}
        if arrival.priority is not None:
            row["priority"] = arrival.priority
        if isinstance(reply, dict):
            status = reply.get("_http_status", 200)
            row["http_status"] = status
            if "_error" in reply:
                row["status"] = "error"
                row["error"] = reply["_error"]
            elif status == 200:
                row["status"] = "completed"
                for k in ("ttft_ms", "latency_ms", "trace_id",
                          "slo"):
                    if k in reply:
                        row[k] = reply[k]
                if reply.get("tenant"):
                    row["tenant_label"] = reply["tenant"]
            elif status == 429:
                row["status"] = "rejected"
            elif status == 504:
                row["status"] = "deadline"
            else:
                row["status"] = "failed"
                row["error"] = str(reply.get("error"))[:200]
        else:
            row["status"] = "completed"
            row.update(reply or {})
        with lock:
            results.append(row)

    for arrival in arrivals:
        delay = arrival.t_s - (time.perf_counter() - t0)
        if delay > 0:
            time.sleep(delay)
        # Non-blocking cap check AT the scheduled instant: a full
        # window means this arrival is shed client-side, the clock
        # does not wait for capacity (that would close the loop).
        if not inflight.acquire(blocking=False):
            with lock:
                dropped[DROP_REASON_INFLIGHT] = \
                    dropped.get(DROP_REASON_INFLIGHT, 0) + 1
                drop_row = {
                    "tenant": arrival.tenant, "t_s": arrival.t_s,
                    "status": "dropped",
                    "drop_reason": DROP_REASON_INFLIGHT}
                if arrival.priority is not None:
                    drop_row["priority"] = arrival.priority
                results.append(drop_row)
            continue
        th = threading.Thread(target=fire, args=(arrival,),
                              daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=timeout_s + 5.0)
    n_dropped = sum(dropped.values())
    out = {
        "offered": len(arrivals),
        "sent": len(arrivals) - n_dropped,
        "dropped": n_dropped,
        "drop_reasons": dict(dropped),
        "results": results,
        "wall_s": round(time.perf_counter() - t0, 3),
    }
    assert out["offered"] == out["sent"] + out["dropped"]
    return out


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over a pre-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _new_rollup() -> dict:
    return {"offered": 0, "completed": 0, "dropped": 0, "rejected": 0,
            "deadline": 0, "failed": 0, "slo_met": 0,
            "slo_violations": 0, "_ttft": [], "_lat": []}


def _count_row(t: dict, row: dict) -> None:
    t["offered"] += 1
    status = row["status"]
    if status == "completed":
        t["completed"] += 1
        if "ttft_ms" in row:
            t["_ttft"].append(float(row["ttft_ms"]))
        if "latency_ms" in row:
            t["_lat"].append(float(row["latency_ms"]))
        verdict = row.get("slo")
        if isinstance(verdict, dict):
            if verdict.get("slo_met"):
                t["slo_met"] += 1
            else:
                t["slo_violations"] += 1
    elif status in ("dropped", "rejected", "deadline", "failed",
                    "error"):
        t[status if status in ("dropped", "rejected", "deadline")
          else "failed"] += 1


def _finish_rollup(t: dict) -> dict:
    ttft = sorted(t.pop("_ttft"))
    lat = sorted(t.pop("_lat"))
    judged = t["slo_met"] + t["slo_violations"]
    # Goodput denominator is OFFERED load: every dropped/rejected
    # request is a miss the client felt.
    shed = t["offered"] - t["completed"]
    t["goodput"] = t["slo_met"] if judged else t["completed"]
    t["goodput_frac"] = round(t["goodput"] / t["offered"], 4) \
        if t["offered"] else 0.0
    t["shed"] = shed
    t["ttft_p50_ms"] = round(_percentile(ttft, 0.50), 3)
    t["ttft_p99_ms"] = round(_percentile(ttft, 0.99), 3)
    t["latency_p50_ms"] = round(_percentile(lat, 0.50), 3)
    t["latency_p99_ms"] = round(_percentile(lat, 0.99), 3)
    return t


def summarize(run: dict,
              classes: Optional[Dict[str, str]] = None) -> dict:
    """Per-tenant rollup of a :func:`run_schedule` result: counts by
    status, TTFT p50/p99, goodput (completed AND slo_met — a dropped
    or shed request counts against goodput, exactly like the server-
    side `shed` reason keeps it visible in the counters).

    When any row carries a ``priority`` (a :class:`TenantSpec` with one
    set, docs/serving.md#qos) — or an explicit ``classes`` tenant→class
    mapping is given — the summary grows a ``by_class`` section with
    the same rollup shape per priority class."""
    tenants: Dict[str, dict] = {}
    by_class: Dict[str, dict] = {}
    for row in run["results"]:
        t = tenants.setdefault(row["tenant"], _new_rollup())
        _count_row(t, row)
        cls = (classes or {}).get(row["tenant"]) or row.get("priority")
        if cls is not None:
            _count_row(by_class.setdefault(str(cls), _new_rollup()),
                       row)
    out = {name: _finish_rollup(t) for name, t in tenants.items()}
    totals = {
        "offered": run["offered"], "sent": run["sent"],
        "dropped": run["dropped"],
        "goodput": sum(t["goodput"] for t in out.values()),
        "completed": sum(t["completed"] for t in out.values()),
    }
    totals["goodput_frac"] = round(
        totals["goodput"] / totals["offered"], 4) \
        if totals["offered"] else 0.0
    summary = {"tenants": out, "totals": totals}
    if by_class:
        summary["by_class"] = {cls: _finish_rollup(t)
                               for cls, t in by_class.items()}
    return summary
