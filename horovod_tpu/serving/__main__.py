"""CLI: ``python -m horovod_tpu.serving --checkpoint-dir /ckpts``.

Loads the flagship Transformer straight from a sharded-checkpoint
manifest (the architecture rides in the manifest's ``extra`` — see
``loader.transformer_extra``), reshards it onto a tensor-parallel
inference mesh, and serves ``/generate`` + ``/healthz`` until SIGTERM
drains it (docs/serving.md, docs/running.md)."""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    from ..utils import env as _env

    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.serving",
        description="Serve a sharded checkpoint: tensor-parallel "
                    "decode with continuous batching.")
    parser.add_argument("--checkpoint-dir", required=True,
                        help="sharded checkpoint root (the directory "
                             "holding step-N/ + LATEST)")
    parser.add_argument("--step", type=int, default=None,
                        help="step to serve (default: LATEST)")
    parser.add_argument("--port", type=int, default=None,
                        help="HTTP port (default: "
                             "$HOROVOD_TPU_SERVING_PORT or 8400; 0 = "
                             "ephemeral)")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--tp", type=int, default=None,
                        help="tensor-parallel width (default: all "
                             "local devices)")
    parser.add_argument("--block-size", type=int, default=16,
                        help="KV-cache block size in tokens")
    parser.add_argument("--kv-blocks", type=int, default=128,
                        help="KV pool size in blocks (scratch included)")
    parser.add_argument("--slots", type=int, default=8,
                        help="decode batch width (concurrent "
                             "generations)")
    parser.add_argument("--max-queue", type=int, default=None,
                        help="bounded admission queue (default: "
                             "$HOROVOD_TPU_SERVING_QUEUE or 32; "
                             "past it /generate returns 429)")
    parser.add_argument("--max-new-tokens", type=int, default=64,
                        help="per-request default generation budget")
    parser.add_argument("--eos-id", type=int, default=None,
                        help="stop token id (default: max-tokens only)")
    parser.add_argument("--temperature", type=float, default=0.0,
                        help="0 = greedy; > 0 = seeded sampling")
    parser.add_argument("--seed", type=int, default=0,
                        help="sampling PRNG seed")
    args = parser.parse_args(argv)

    import jax

    import horovod_tpu as hvd
    from ..parallel.mesh import create_mesh
    from .engine import InferenceEngine, ServingConfig
    from .loader import config_from_manifest, load_params, serving_config
    from .server import ServingServer

    hvd.init()   # metrics exporters + flight-recorder hooks

    devices = jax.local_devices()
    tp = args.tp if args.tp is not None else len(devices)
    if tp < 1 or tp > len(devices):
        parser.error(f"--tp {tp} out of range (1..{len(devices)} local "
                     "devices)")
    mesh = create_mesh(devices=devices[:tp], tp=tp)

    from ..checkpoint import CheckpointEngine
    eng = CheckpointEngine(args.checkpoint_dir)
    man = eng.restore_manifest(args.step)
    cfg = serving_config(config_from_manifest(man), mesh)
    params = load_params(args.checkpoint_dir, cfg, mesh,
                         step=args.step, engine=eng)
    print(f"[serving] step {man['step']}: d_model={cfg.d_model} "
          f"layers={cfg.n_layers} heads={cfg.n_heads} "
          f"vocab={cfg.vocab} tp={tp}", file=sys.stderr)

    config = ServingConfig(
        block_size=args.block_size, kv_blocks=args.kv_blocks,
        max_batch_slots=args.slots,
        max_queue=args.max_queue if args.max_queue is not None
        else _env.serving_queue(),
        max_new_tokens=args.max_new_tokens, eos_id=args.eos_id,
        temperature=args.temperature, seed=args.seed)
    engine = InferenceEngine(params, cfg, mesh, config)
    server = ServingServer(engine, port=args.port, host=args.host)
    server.install_signal_handlers()
    server.start()
    print(f"[serving] ready on :{server.port} (/generate, /healthz)",
          file=sys.stderr, flush=True)
    server.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
