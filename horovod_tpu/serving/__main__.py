"""CLI: ``python -m horovod_tpu.serving --checkpoint-dir /ckpts``.

Loads the flagship Transformer straight from a sharded-checkpoint
manifest (the architecture rides in the manifest's ``extra`` — see
``loader.transformer_extra``), reshards it onto a tensor-parallel
inference mesh, and serves ``/generate`` + ``/healthz`` + ``/readyz``
until SIGTERM drains it (docs/serving.md, docs/running.md).

``--fleet N`` turns this process into a SUPERVISOR instead: it spawns
N independent replica processes of itself (fleet.py), fronts them with
the failover router (router.py) on ``--port``, and keeps the fleet at
strength — crashed replicas restart from the same checkpoint and
re-enter rotation. ``--framework torch`` serves a checkpoint committed
by ``horovod_tpu.torch.checkpoint_hook`` (the model subtree of its
manifest; state-dict keys must mirror the flagship tree —
docs/serving.md#torch).
"""

from __future__ import annotations

import argparse
import os
import sys


def _replica_argv(args) -> list:
    """Rebuild the argv tail a fleet replica needs — every model/engine
    knob, minus --fleet/--port/--replica-id (the supervisor owns
    those)."""
    argv = ["--checkpoint-dir", args.checkpoint_dir,
            "--block-size", str(args.block_size),
            "--kv-blocks", str(args.kv_blocks),
            "--slots", str(args.slots),
            "--max-new-tokens", str(args.max_new_tokens),
            "--temperature", str(args.temperature),
            "--seed", str(args.seed),
            "--framework", args.framework,
            "--host", "127.0.0.1"]
    if args.step is not None:
        argv += ["--step", str(args.step)]
    if args.tp is not None:
        argv += ["--tp", str(args.tp)]
    if args.max_queue is not None:
        argv += ["--max-queue", str(args.max_queue)]
    if args.eos_id is not None:
        argv += ["--eos-id", str(args.eos_id)]
    if args.kv_quant is not None:
        argv += ["--kv-quant", args.kv_quant]
    if args.prefix_cache:
        argv += ["--prefix-cache"]
    if args.prefill_chunk is not None:
        argv += ["--prefill-chunk", str(args.prefill_chunk)]
    if args.session_leases is not None:
        argv += ["--session-leases", str(args.session_leases)]
    if args.reserved_slots is not None:
        argv += ["--reserved-slots", str(args.reserved_slots)]
    if args.draft_checkpoint_dir is not None:
        argv += ["--draft-checkpoint-dir", args.draft_checkpoint_dir]
        argv += ["--spec-tokens", str(args.spec_tokens)]
    return argv


def _run_fleet(args, parser) -> int:
    """Supervisor mode: no JAX in this process — the replicas own the
    devices; we own processes, probes and routing."""
    from ..observability import flight_recorder as _flight
    from ..observability.export import maybe_start_exporters
    from ..utils import env as _env
    from . import reqtrace as _reqtrace
    from .fleet import Fleet
    from .router import Router

    maybe_start_exporters()      # the router's own hvdtpu_fleet_* families
    _flight.maybe_install_hooks()
    # Supervisor blackbox identity: rank n (replicas are 0..n-1), so
    # its dump never collides with replica 0's in a shared dir.
    _flight.recorder().configure(rank=args.fleet, world=args.fleet + 1)
    # Request tracing (docs/serving.md#request-tracing): the router
    # writes its REQUEST/DISPATCH/FAILOVER spans here; replicas start
    # their own writers from the inherited HOROVOD_TPU_REQTRACE.
    _reqtrace.maybe_start(role="router")

    fleet = Fleet(args.fleet, _replica_argv(args))
    router = Router(fleet, port=(args.port if args.port is not None
                                 else _env.serving_port()),
                    host=args.host)
    autoscaler = None
    if args.autoscale_max is not None:
        from .qos import AutoscalerConfig, FleetAutoscaler
        amin = args.autoscale_min if args.autoscale_min is not None \
            else args.fleet
        cfg = AutoscalerConfig(
            amin, args.autoscale_max,
            high_load=_env.qos_scale_high(),
            low_load=_env.qos_scale_low(),
            sustain_s=_env.qos_scale_sustain_s(),
            cooldown_s=_env.qos_scale_cooldown_s())
        autoscaler = FleetAutoscaler(
            fleet, cfg, signals=router.qos_signals,
            interval_s=_env.qos_scale_interval_s())
        fleet.on_alert = autoscaler.note_alert
    print(f"[fleet] spawning {args.fleet} replica(s) from "
          f"{args.checkpoint_dir}", file=sys.stderr, flush=True)
    fleet.start()
    try:
        fleet.wait_ready(600.0)
    except TimeoutError as e:
        fleet.stop()
        parser.error(str(e))
    router.start()
    if autoscaler is not None:
        autoscaler.start()
        print(f"[fleet] autoscaler on: {autoscaler.config.min_replicas}"
              f"..{autoscaler.config.max_replicas} replicas "
              "(docs/serving.md#qos)", file=sys.stderr, flush=True)
    print(f"[fleet] routing on :{router.port} across {args.fleet} "
          "replica(s) (/generate, /healthz, /readyz)",
          file=sys.stderr, flush=True)

    import signal
    import threading
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    while not stop.wait(0.2):
        pass
    print("[fleet] stopping: draining replicas", file=sys.stderr,
          flush=True)
    if autoscaler is not None:
        autoscaler.stop()
    router.shutdown()
    fleet.stop()
    return 0


def main(argv=None) -> int:
    from ..utils import env as _env

    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.serving",
        description="Serve a sharded checkpoint: tensor-parallel "
                    "decode with continuous batching — one replica, or "
                    "a supervised fleet behind the failover router "
                    "(--fleet N).")
    parser.add_argument("--checkpoint-dir", required=True,
                        help="sharded checkpoint root (the directory "
                             "holding step-N/ + LATEST)")
    parser.add_argument("--step", type=int, default=None,
                        help="step to serve (default: LATEST)")
    parser.add_argument("--port", type=int, default=None,
                        help="HTTP port (default: "
                             "$HOROVOD_TPU_SERVING_PORT or 8400; 0 = "
                             "ephemeral); with --fleet, the ROUTER's "
                             "port (replicas bind ephemeral ports)")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--fleet", type=int, default=None,
                        help="supervise N replica processes behind the "
                             "failover router (docs/serving.md#fleet): "
                             "crash detection, restart, queue-depth-"
                             "aware routing, zero-dropped-request "
                             "failover")
    parser.add_argument("--replica-id", type=int, default=None,
                        help="(internal, set by the fleet supervisor) "
                             "this replica's index — names its "
                             "blackbox dump and fault-spec rank")
    parser.add_argument("--framework", choices=("jax", "torch"),
                        default="jax",
                        help="checkpoint flavor: 'jax' (params tree at "
                             "the manifest root) or 'torch' (a "
                             "torch.checkpoint_hook commit; the model "
                             "subtree is served)")
    parser.add_argument("--tp", type=int, default=None,
                        help="tensor-parallel width (default: all "
                             "local devices)")
    parser.add_argument("--block-size", type=int, default=16,
                        help="KV-cache block size in tokens")
    parser.add_argument("--kv-blocks", type=int, default=128,
                        help="KV pool size in blocks (scratch included)")
    parser.add_argument("--slots", type=int, default=8,
                        help="decode batch width (concurrent "
                             "generations)")
    parser.add_argument("--max-queue", type=int, default=None,
                        help="bounded admission queue (default: "
                             "$HOROVOD_TPU_SERVING_QUEUE or 32; "
                             "past it /generate returns 429)")
    parser.add_argument("--max-new-tokens", type=int, default=64,
                        help="per-request default generation budget")
    parser.add_argument("--eos-id", type=int, default=None,
                        help="stop token id (default: max-tokens only)")
    parser.add_argument("--temperature", type=float, default=0.0,
                        help="0 = greedy; > 0 = seeded sampling")
    parser.add_argument("--seed", type=int, default=0,
                        help="sampling PRNG seed")
    parser.add_argument("--kv-quant", choices=("int8", "fp8"),
                        default=None,
                        help="quantize the KV pool (wire-format absmax "
                             "blocks, ~4x resident sequences per HBM "
                             "byte; docs/serving.md#speed-levers)")
    parser.add_argument("--prefix-cache", action="store_true",
                        help="share read-only KV blocks between "
                             "requests with a common prompt prefix "
                             "(system prompts prefill once per replica)")
    parser.add_argument("--prefill-chunk", type=int, default=None,
                        help="chunked prefill: consume long prompts as "
                             "chunks of at most this many tokens, at "
                             "most one chunk between decode ticks — "
                             "bounds decode-tick tail latency under "
                             "long-prompt bursts (docs/serving.md#"
                             "chunked-prefill; budget via "
                             "$HOROVOD_TPU_SERVING_TICK_BUDGET_MS)")
    parser.add_argument("--reserved-slots", type=int, default=None,
                        help="decode-batch slots reserved for the "
                             "'interactive' priority class "
                             "(docs/serving.md#qos): bulk/default "
                             "admission stops once occupancy would "
                             "leave fewer than this many free slots "
                             "(default: $HOROVOD_TPU_SERVING_RESERVED_"
                             "SLOTS or 0)")
    parser.add_argument("--autoscale-max", type=int, default=None,
                        help="with --fleet: enable SLO-driven "
                             "autoscaling up to this many replicas "
                             "(docs/serving.md#qos); scale-ups need "
                             "sustained pressure, scale-downs drain "
                             "via /readyz")
    parser.add_argument("--autoscale-min", type=int, default=None,
                        help="autoscaler floor (default: the --fleet "
                             "value)")
    parser.add_argument("--session-leases", type=int, default=None,
                        help="max session KV leases held per replica "
                             "(session affinity, docs/serving.md#"
                             "session-affinity; 0 disables; "
                             "default 8)")
    parser.add_argument("--draft-checkpoint-dir", default=None,
                        help="drafter checkpoint for speculative "
                             "decoding (a shrunk transformer sharing "
                             "the vocab; same manifest convention)")
    parser.add_argument("--draft-step", type=int, default=None,
                        help="drafter step to serve (default: LATEST)")
    parser.add_argument("--spec-tokens", type=int, default=4,
                        help="speculative verify width k: the drafter "
                             "proposes k-1 tokens per step, the "
                             "flagship verifies them in one [slots, k] "
                             "program (needs --draft-checkpoint-dir)")
    args = parser.parse_args(argv)

    if args.fleet is not None:
        if args.fleet < 1:
            parser.error(f"--fleet {args.fleet} must be >= 1")
        if args.replica_id is not None:
            parser.error("--fleet and --replica-id are mutually "
                         "exclusive (the supervisor assigns ids)")
        if args.autoscale_max is not None:
            amin = args.autoscale_min if args.autoscale_min is not None \
                else args.fleet
            if not (1 <= amin <= args.fleet <= args.autoscale_max):
                parser.error(
                    f"--autoscale-min {amin} <= --fleet {args.fleet} "
                    f"<= --autoscale-max {args.autoscale_max} required")
        return _run_fleet(args, parser)
    if args.autoscale_max is not None or args.autoscale_min is not None:
        parser.error("--autoscale-min/--autoscale-max need --fleet")

    replica_id = args.replica_id if args.replica_id is not None \
        else _env.replica_id()
    if replica_id is not None:
        # Before anything resolves faults/metrics: the fault injector
        # and blackbox dumps key on the replica id (docs/serving.md#fleet).
        os.environ["HOROVOD_TPU_REPLICA_ID"] = str(replica_id)

    import jax

    import horovod_tpu as hvd
    from ..parallel.mesh import create_mesh
    from .engine import InferenceEngine, ServingConfig
    from .loader import (TORCH_MODEL_PREFIX, config_from_manifest,
                         load_params, serving_config)
    from .server import ServingServer

    hvd.init()   # metrics exporters + flight-recorder hooks

    if replica_id is not None:
        from ..observability import flight_recorder as _flight
        gen = int(os.environ.get("HOROVOD_TPU_ELASTIC_GENERATION",
                                 "0") or 0)
        _flight.recorder().configure(rank=replica_id, world=0,
                                     generation=gen)

    # Per-request tracing (docs/serving.md#request-tracing): one
    # catapult file per replica incarnation under HOROVOD_TPU_REQTRACE.
    from . import reqtrace as _reqtrace
    _reqtrace.maybe_start()

    devices = jax.local_devices()
    tp = args.tp if args.tp is not None else len(devices)
    if tp < 1 or tp > len(devices):
        parser.error(f"--tp {tp} out of range (1..{len(devices)} local "
                     "devices)")
    mesh = create_mesh(devices=devices[:tp], tp=tp)

    from ..checkpoint import CheckpointEngine
    eng = CheckpointEngine(args.checkpoint_dir)
    man = eng.restore_manifest(args.step)
    cfg = serving_config(config_from_manifest(man), mesh)
    key_prefix = TORCH_MODEL_PREFIX if args.framework == "torch" else ""
    params = load_params(args.checkpoint_dir, cfg, mesh,
                         step=args.step, engine=eng,
                         key_prefix=key_prefix)
    print(f"[serving] step {man['step']}: d_model={cfg.d_model} "
          f"layers={cfg.n_layers} heads={cfg.n_heads} "
          f"vocab={cfg.vocab} tp={tp} framework={args.framework}",
          file=sys.stderr)

    draft_params = draft_cfg = None
    if args.draft_checkpoint_dir is not None:
        deng = CheckpointEngine(args.draft_checkpoint_dir)
        dman = deng.restore_manifest(args.draft_step)
        draft_cfg = serving_config(config_from_manifest(dman), mesh)
        draft_params = load_params(args.draft_checkpoint_dir, draft_cfg,
                                   mesh, step=args.draft_step,
                                   engine=deng)
        print(f"[serving] drafter step {dman['step']}: "
              f"d_model={draft_cfg.d_model} layers={draft_cfg.n_layers} "
              f"(spec_tokens={args.spec_tokens})", file=sys.stderr)

    config = ServingConfig(
        block_size=args.block_size, kv_blocks=args.kv_blocks,
        max_batch_slots=args.slots,
        max_queue=args.max_queue if args.max_queue is not None
        else _env.serving_queue(),
        max_new_tokens=args.max_new_tokens, eos_id=args.eos_id,
        temperature=args.temperature, seed=args.seed,
        kv_quant=args.kv_quant,
        spec_tokens=(args.spec_tokens if draft_params is not None
                     else 0),
        prefix_cache=args.prefix_cache,
        prefill_chunk=args.prefill_chunk,
        session_leases=(args.session_leases
                        if args.session_leases is not None else 8),
        reserved_slots=(args.reserved_slots
                        if args.reserved_slots is not None
                        else _env.serving_reserved_slots()))
    engine = InferenceEngine(params, cfg, mesh, config,
                             draft_params=draft_params,
                             draft_cfg=draft_cfg)
    server = ServingServer(engine, port=args.port, host=args.host)
    server.install_signal_handlers()
    server.start()
    from ..observability.export import server_port as _metrics_port
    mport = _metrics_port()
    tail = f" metrics=:{mport}" if mport is not None else ""
    if replica_id is not None:
        tail += f" replica={replica_id}"
    # "ready on :PORT" is parsed by the fleet supervisor and the e2e
    # tests — keep the phrase stable. Printed to stdout: the supervisor
    # owns that pipe.
    print(f"[serving] ready on :{server.port} (/generate, /healthz, "
          f"/readyz){tail}", flush=True)
    server.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
