"""Per-request distributed tracing for the serving tier
(docs/serving.md#request-tracing).

The training side has a full cross-rank trace plane (docs/tracing.md):
per-rank catapult files written through the PyTimeline tuple-enqueue
pattern, merged onto one clock by ``python -m horovod_tpu.tools.trace``.
The serving fleet had nothing — a slow or failed request could not be
followed router→replica→engine. This module is the serving twin of
that plane, Dapper-style: ONE trace id per client request, minted by
the router (or accepted via ``X-Request-Id``) and propagated on every
dispatch, retry and mid-stream failover hop, with each process writing
the request's spans into its own catapult file:

  =============  ==========================================================
  ``REQUEST``    router: relay start → terminal outcome (the wall the
                 latency budget is attributed against)
  ``DISPATCH``   router: one attempt against one replica, tagged with
                 the outcome (done/crash/queue_full/...)
  ``FAILOVER``   router: failure detection → first token from the
                 replacement replica (phase, from, to)
  ``QUEUE_WAIT`` engine: submit → admission (the queue share)
  ``ADMIT``      engine: block reservation + prefix-cache probe
                 (blocks, prefix-hit tokens)
  ``PREFILL``    engine: prefill forward + first sample (bucket,
                 suffix tokens, compile-if-any)
  ``DECODE``     engine: one batched decode / speculative-verify chunk
                 as experienced by this request (tokens emitted,
                 proposed vs accepted for spec)
  ``EGRESS``     server/router: writing the result back to the client
  =============  ==========================================================

Each request renders as its own named row (row name == trace id), so
the merged Perfetto view shows one request's life crossing process
lanes, and the ``serving`` report (tools/trace.py) computes per-request
latency-budget tables, slowest-request rankings and failover chains
from the same files. Tenant + SLO verdict ride the span args — the
router's ``REQUEST`` and each egress' ``EGRESS`` carry ``tenant`` and
``slo_met`` (docs/serving.md#slo), so budget tables attribute per
tenant and flag the misses.

Clock domain: serving fleets spawned by ``fleet.py`` are same-host
processes (the supervisor owns local pipes), and ``time.monotonic`` is
CLOCK_MONOTONIC — one clock for every process on the host — so each
writer records offset 0/synced and the merge realigns purely through
``start_mono_us``. A multi-host serving tier would need the PR 5
NTP-style handshake ported onto the router's scrape channel; the file
format already carries the fields.

Hot-path budget: span emission is the PyTimeline pattern — one module
attribute check when disabled, one tuple append when enabled; all
formatting happens on the writer's drain thread. ``bench_serving.py
--reqtrace`` A/Bs tracing on/off under the BENCH_SERVING load and the
slow-tier guard holds the overhead under 3% (BENCH_REQTRACE.json).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from ..observability import flight_recorder as _flight
from ..ops.timeline_py import PyTimeline
from ..utils import env as _env
from ..utils.logging import get_logger

_log = get_logger("serving.reqtrace")

# The router's writer identity. Replica writers get
# ``1 + 100 * replica_id + generation`` so every (replica, incarnation)
# pair is a distinct trace "rank" (a restarted replica must not clobber
# or alias its dead predecessor's file — the predecessor's spans are the
# failover evidence) while the router anchors the merge at rank 0.
ROUTER_RANK = 0

_writer: Optional[PyTimeline] = None
_lock = threading.Lock()


def writer() -> Optional[PyTimeline]:
    """The process's request-trace writer, or None when tracing is off.
    Hot loops fetch this once per scheduler step and guard the whole
    emission block on ``is not None``."""
    return _writer


def span(trace_id: str, name: str, t0: float, t1: float,
         args: Optional[dict] = None) -> None:
    """Emit one complete span on the request's row — a no-op (one
    attribute check) when tracing is off."""
    w = _writer
    if w is not None:
        w.request_span(str(trace_id), name, t0, t1, args)


def _final_flush() -> None:
    w = _writer
    if w is not None:
        w.close()


def start(path: str, rank: int = 0, proc: Optional[str] = None,
          world: int = 0) -> PyTimeline:
    """Open the process's request-trace writer at ``path`` (replacing
    any previous one). Same-host clock domain: the writer records
    offset-to-rank-0 as 0/synced (see module docstring)."""
    global _writer
    with _lock:
        if _writer is not None:
            _writer.close()
        tl = PyTimeline(path, rank=rank, world=world, proc=proc)
        tl.set_clock_meta(0.0, 0.0)
        _writer = tl
    _flight.register_final_flush(_final_flush)
    return tl


def stop() -> None:
    """Close and detach the writer (flushes the buffered tail)."""
    global _writer
    with _lock:
        if _writer is not None:
            _writer.close()
            _writer = None


def maybe_start(role: Optional[str] = None) -> Optional[PyTimeline]:
    """Start the writer for this serving process when
    ``HOROVOD_TPU_REQTRACE`` names a directory (idempotent; a no-op
    otherwise). ``role="router"`` names the fleet router's file; every
    other process is a replica, identified by ``HOROVOD_TPU_REPLICA_ID``
    (0 standalone) and its restart incarnation
    (``HOROVOD_TPU_ELASTIC_GENERATION``) — the incarnation rides the
    file name so a restarted replica can never truncate its dead
    predecessor's trace."""
    if _writer is not None:
        return _writer
    directory = _env.reqtrace_dir()
    if not directory:
        return None
    os.makedirs(directory, exist_ok=True)
    if role == "router":
        rank, proc = ROUTER_RANK, "router"
        fname = "reqtrace-router.trace.json"
    else:
        idx = _env.replica_id() or 0
        gen = int(os.environ.get("HOROVOD_TPU_ELASTIC_GENERATION",
                                 "0") or 0)
        rank = 1 + 100 * idx + gen
        proc = f"replica{idx}" + (f"/gen{gen}" if gen else "")
        fname = f"reqtrace-replica{idx}-gen{gen}.trace.json"
    tl = start(os.path.join(directory, fname), rank=rank, proc=proc)
    _log.info("request tracing to %s (proc %s)", tl._path, proc)
    return tl
