"""Stdlib HTTP front end for the inference engine.

Same no-new-deps pattern as the metrics exporter (observability/
export.py): ``http.server.ThreadingHTTPServer``, one handler thread per
connection, all of them funneling into the engine's thread-safe
``submit``.

Routes:

  ``POST /generate``   {"tokens": [...], "max_new_tokens"?,
                        "temperature"?, "stream"?, "deadline_ms"?} →
                        200 {"tokens", "id", "ttft_ms", "latency_ms"}
                        (or an NDJSON token stream with "stream": true);
                        429 + ``Retry-After`` when the bounded queue is
                        full; 503 + ``Connection: close`` while
                        draining; 504 past the deadline; 400 on a bad
                        body.
  ``GET /healthz``     LIVENESS: 200 while the process can answer —
                        including during a drain (status flips to
                        "draining" but the code stays 200, so a
                        supervisor doesn't shoot a replica that is
                        cleanly finishing its work).
  ``GET /readyz``      READINESS: 200 {"status": "ready"} while
                        admitting; 503 {"status": "draining"} once a
                        drain began — the fleet router stops routing
                        here the moment this flips
                        (docs/serving.md#fleet).

Token streaming (``"stream": true``): the reply is
``application/x-ndjson`` with no Content-Length — one ``{"id": ...}``
header line, one ``{"t": <token>}`` line per generated token flushed as
it is sampled, and a final ``{"done": true, ...}`` line, then the
connection closes. A connection that closes WITHOUT a ``done`` line
means the replica died mid-generation — that is exactly the signal the
fleet router's mid-stream failover keys on.

Metrics deliberately do NOT get a route here: the registry endpoint
(``HOROVOD_TPU_METRICS_PORT``, started by ``hvd.init()``) already
serves every ``hvdtpu_serving_*`` family — one scrape target per
process, no second port.

Shutdown: ``install_signal_handlers`` makes SIGTERM/SIGINT request a
graceful drain — admission stops (``/readyz`` flips 503), every
ACCEPTED request completes (queued ones included — acceptance is a
promise, see ``InferenceEngine.drain``), then the process exits 0.
The flight recorder's atexit hook then writes its ``exit`` dump, so a
drained shutdown is post-mortem-distinguishable from a crash
(docs/postmortem.md).
"""

from __future__ import annotations

import json
import signal
import threading
import time
from typing import Optional

from ..observability import registry as _obs
from ..utils import env as _env
from ..utils.logging import get_logger
from . import reqtrace as _rt
from . import slo as _slo
from .engine import (DEADLINE_ERROR, DrainingError, InferenceEngine,
                     QueueFullError)
from .qos import QuotaExceededError

_log = get_logger("serving.server")

# A generation can legitimately take a while under load; handlers wait
# this long on the ticket before giving up with a 504.
REQUEST_TIMEOUT_S = 600.0


def _http_metrics():
    return _obs.registry().counter(
        "hvdtpu_serving_http_requests_total",
        "HTTP requests served, by route and status code")


class ServingServer:
    """HTTP front + scheduler loop around one :class:`InferenceEngine`.

    ``port=0`` binds an ephemeral port (tests); default comes from
    ``HOROVOD_TPU_SERVING_PORT``.
    """

    def __init__(self, engine: InferenceEngine,
                 port: Optional[int] = None, host: str = "0.0.0.0"):
        from http.server import BaseHTTPRequestHandler, \
            ThreadingHTTPServer

        self.engine = engine
        self._stop = threading.Event()
        self._loop_thread: Optional[threading.Thread] = None
        self._http = _http_metrics()
        # Live /generate handlers: shutdown() must not close the process
        # under a handler still flushing a drained generation to its
        # client — that would turn a zero-drop drain into a dropped
        # response at the socket layer.
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def _reply(self, code: int, payload: dict, route: str,
                       headers: Optional[dict] = None) -> None:
                # Count BEFORE writing: the client may observe the
                # response (and assert on the metric) the instant the
                # body lands.
                outer._http.labels(route=route, code=str(code)).inc()
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                    if k.lower() == "connection" \
                            and str(v).lower() == "close":
                        self.close_connection = True
                self.end_headers()
                self.wfile.write(body)

            def _drop_health(self) -> bool:
                """drop_health fault (docs/adaptation.md): hang up on
                the probe without any status line — the supervisor's
                probe timeout, not the HTTP code, must catch it."""
                inj = outer.engine._inj
                if inj is not None and inj.drop_health_active():
                    self.close_connection = True
                    return True
                return False

            def do_GET(self):  # noqa: N802 (http.server API)
                path = self.path.split("?")[0]
                eng = outer.engine
                if path == "/healthz":
                    if self._drop_health():
                        return
                    # Liveness: 200 even while draining — the process
                    # is alive and finishing promised work.
                    self._reply(200, {
                        "status": ("draining" if outer._stop.is_set()
                                   else "serving"),
                        "active_requests": eng.active_count,
                        "queue_depth": eng.queue_depth,
                        "batch_slots": eng.config.max_batch_slots,
                        "kv_blocks_free": eng._alloc.free,
                        "kv_blocks_total": eng._alloc.total,
                        # the fleet router hashes prompt prefixes at
                        # this granularity to score cache warmth
                        "block_size": eng.config.block_size,
                        "prefix_cache": eng._prefix is not None,
                        # live session leases — the router pins these
                        # sessions here (docs/serving.md#session-affinity)
                        "sessions": eng.session_ids(),
                        "session_leases": eng.config.session_leases,
                        # per-QoS-class queued/active counts + the
                        # interactive slot reservation — the router's
                        # class-aware scoring reads these
                        # (docs/serving.md#qos)
                        "qos_classes": eng.class_counts(),
                        "reserved_slots": eng.config.reserved_slots,
                    }, "healthz")
                    return
                if path == "/readyz":
                    if self._drop_health():
                        return
                    if outer._stop.is_set():
                        self._reply(503, {"status": "draining"},
                                    "readyz",
                                    headers={"Connection": "close"})
                    else:
                        self._reply(200, {"status": "ready"}, "readyz")
                    return
                self._reply(404, {"error": "not found"}, "other")

            def do_POST(self):  # noqa: N802 (http.server API)
                if self.path.split("?")[0] != "/generate":
                    self._reply(404, {"error": "not found"}, "other")
                    return
                with outer._inflight_lock:
                    outer._inflight += 1
                try:
                    self._generate()
                finally:
                    with outer._inflight_lock:
                        outer._inflight -= 1

            def _generate(self) -> None:
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    tokens = body["tokens"]
                    if not isinstance(tokens, list):
                        raise ValueError("'tokens' must be a list")
                    stream = bool(body.get("stream", False))
                    deadline_ms = body.get(
                        "deadline_ms",
                        self.headers.get("X-Request-Deadline-Ms"))
                    deadline_s = None if deadline_ms in (None, "") \
                        else float(deadline_ms) / 1e3
                    # Tenant + SLO attribution: router forwards the
                    # tenant in X-Tenant (body "tenant" for plain
                    # clients); "slo" is always body-borne
                    # (docs/serving.md#slo).
                    tenant = self.headers.get("X-Tenant") \
                        or body.get("tenant")
                    _slo.parse_slo(body.get("slo"))
                except (KeyError, ValueError, TypeError,
                        json.JSONDecodeError) as e:
                    self._reply(400, {"error": f"bad request: {e}"},
                                "generate")
                    return
                if deadline_s is not None and deadline_s <= 0:
                    if tenant or body.get("slo") is not None:
                        _slo.record_shed(_slo.resolve_tenant(tenant),
                                         "deadline")
                    self._reply(504, {"error": DEADLINE_ERROR},
                                "generate")
                    return
                # One request identity end-to-end: the router ships its
                # trace id in X-Request-Id (body "request_id" for plain
                # clients); absent, the engine mints one
                # (docs/serving.md#request-tracing).
                trace_id = self.headers.get("X-Request-Id") \
                    or body.get("request_id")
                # Conversation identity for session affinity: the
                # router forwards it in X-Session-Id (body
                # "session_id" for plain clients).
                session_id = self.headers.get("X-Session-Id") \
                    or body.get("session_id")
                try:
                    req = outer.engine.submit(
                        tokens,
                        max_new_tokens=body.get("max_new_tokens"),
                        temperature=body.get("temperature"),
                        deadline_s=deadline_s,
                        trace_id=trace_id,
                        session_id=session_id,
                        tenant=tenant,
                        slo=body.get("slo"))
                except QuotaExceededError as e:
                    # Quota 429: Retry-After from the tenant's own
                    # measured drain rate (docs/serving.md#qos), not
                    # the global queue estimate.
                    self._reply(429, {"error": str(e)}, "generate",
                                headers={"Retry-After":
                                         e.retry_after_s})
                    return
                except QueueFullError as e:
                    self._reply(429, {"error": str(e)}, "generate",
                                headers={"Retry-After":
                                         outer.engine.retry_after_s()})
                    return
                except DrainingError as e:
                    # Draining: this replica will never take the
                    # request — close the connection so clients (and
                    # the router) re-resolve instead of reusing a
                    # socket into a dying server.
                    self._reply(503, {"error": str(e)}, "generate",
                                headers={"Connection": "close"})
                    return
                except ValueError as e:
                    self._reply(400, {"error": str(e)}, "generate")
                    return
                wait_s = REQUEST_TIMEOUT_S if deadline_s is None \
                    else min(REQUEST_TIMEOUT_S, deadline_s + 5.0)
                if stream:
                    self._stream(req, wait_s)
                    return
                try:
                    out = req.result(timeout=wait_s)
                except TimeoutError as e:
                    self._reply(504, {"error": str(e)}, "generate")
                    return
                except RuntimeError as e:
                    code = 504 if DEADLINE_ERROR in str(e) else 503
                    self._reply(code, {"error": str(e)}, "generate")
                    return
                t_egress = time.monotonic()
                reply = {
                    "id": req.id,
                    "trace_id": req.trace_id,
                    "tokens": out,
                    "ttft_ms": round(req.ttft_s * 1e3, 3),
                    "latency_ms": round(
                        (req.t_done - req.t_submit) * 1e3, 3),
                }
                egress_args = {"tokens": len(out)}
                if req.tenant:
                    reply["tenant"] = req.tenant
                    egress_args["tenant"] = req.tenant
                if req.slo_verdict is not None:
                    reply["slo"] = req.slo_verdict
                    egress_args["slo_met"] = \
                        req.slo_verdict["slo_met"]
                self._reply(200, reply, "generate")
                _rt.span(req.trace_id, "EGRESS", t_egress,
                         time.monotonic(), egress_args)

            def _stream(self, req, wait_s: float) -> None:
                """NDJSON token stream: header line, one line per
                token as it lands, terminal ``done`` line. No
                Content-Length — the close is the framing."""
                outer._http.labels(route="generate", code="200").inc()
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Cache-Control", "no-store")
                self.close_connection = True
                self.end_headers()

                def line(obj) -> None:
                    self.wfile.write(json.dumps(obj).encode() + b"\n")
                    self.wfile.flush()

                t_end = time.monotonic() + wait_s
                try:
                    line({"id": req.id, "trace_id": req.trace_id})
                    idx = 0
                    while True:
                        fresh = req.next_tokens(
                            idx, timeout=max(0.0,
                                             t_end - time.monotonic()))
                        for t in fresh:
                            line({"t": int(t)})
                        idx += len(fresh)
                        if req.done and not fresh:
                            break
                    meta = {"done": True, "status": req.status,
                            "n": idx, "trace_id": req.trace_id}
                    if req.status == "completed":
                        meta["ttft_ms"] = round(req.ttft_s * 1e3, 3)
                        meta["latency_ms"] = round(
                            (req.t_done - req.t_submit) * 1e3, 3)
                    else:
                        meta["error"] = req.error
                    egress_args = {"tokens": idx}
                    if req.tenant:
                        meta["tenant"] = req.tenant
                        egress_args["tenant"] = req.tenant
                    if req.slo_verdict is not None:
                        meta["slo"] = req.slo_verdict
                        egress_args["slo_met"] = \
                            req.slo_verdict["slo_met"]
                    t_egress = time.monotonic()
                    line(meta)
                    _rt.span(req.trace_id, "EGRESS", t_egress,
                             time.monotonic(), egress_args)
                except TimeoutError:
                    line({"done": True, "status": "failed",
                          "error": "stream timed out", "n": idx})
                except (BrokenPipeError, ConnectionResetError,
                        OSError):
                    # Client hung up mid-stream; the generation keeps
                    # decoding (its slot finishes normally) — nothing
                    # to clean up here.
                    pass

            def log_message(self, *args):  # silence per-request stderr
                pass

        port = _env.serving_port() if port is None else int(port)
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvd-tpu-serving-http",
            daemon=True)

    # -------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Start the HTTP listener and the scheduler loop thread."""
        self._http_thread.start()
        self._loop_thread = threading.Thread(
            target=self._loop, name="hvd-tpu-serving-sched", daemon=True)
        self._loop_thread.start()
        _log.info("serving on :%d (/generate, /healthz, /readyz); "
                  "metrics on the registry endpoint", self.port)

    def _loop(self) -> None:
        eng = self.engine
        while not self._stop.is_set():
            if not eng.step():
                eng.wait_for_work(0.05)

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT → graceful drain. Installed on top of the
        flight recorder's handler chain: ours runs the drain and lets
        the process exit cleanly, so the recorder's atexit dump records
        ``exit`` — not ``sigterm`` — for a drained shutdown."""
        def _on_signal(signum, frame):
            self.request_stop()

        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)

    def request_stop(self) -> None:
        self._stop.set()
        with self.engine._work:
            self.engine._work.notify_all()

    def serve_forever(self) -> None:
        """Block until a stop is requested, then drain and shut down."""
        if self._loop_thread is None:
            self.start()
        while not self._stop.wait(0.1):
            pass
        self.shutdown()

    def shutdown(self) -> None:
        """Drain (finish every accepted request) and stop."""
        self._stop.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=30.0)
        self.engine.drain()
        # Let handler threads flush the drained results to their
        # clients before tearing the listener (and the process) down.
        t_end = time.monotonic() + 10.0
        while time.monotonic() < t_end:
            with self._inflight_lock:
                if self._inflight == 0:
                    break
            time.sleep(0.02)
        self._httpd.shutdown()
        self._httpd.server_close()
        self._http_thread.join(timeout=5.0)
        _log.info("serving drained and stopped")
