"""Stdlib HTTP front end for the inference engine.

Same no-new-deps pattern as the metrics exporter (observability/
export.py): ``http.server.ThreadingHTTPServer``, one handler thread per
connection, all of them funneling into the engine's thread-safe
``submit``.

Routes:

  ``POST /generate``   {"tokens": [...], "max_new_tokens"?,
                        "temperature"?} → 200 {"tokens", "id",
                        "ttft_ms", "latency_ms"}; 429 when the bounded
                        queue is full; 503 while draining; 400 on a bad
                        body.
  ``GET /healthz``     200 {"status": "serving", ...} with live queue /
                        slot / KV-pool numbers; 503 once draining.

Metrics deliberately do NOT get a route here: the registry endpoint
(``HOROVOD_TPU_METRICS_PORT``, started by ``hvd.init()``) already
serves every ``hvdtpu_serving_*`` family — one scrape target per
process, no second port.

Shutdown: ``install_signal_handlers`` makes SIGTERM/SIGINT request a
graceful drain — admission stops (healthz flips 503), queued requests
fail fast, live slots decode to completion, then the process exits 0.
The flight recorder's atexit hook then writes its ``exit`` dump, so a
drained shutdown is post-mortem-distinguishable from a crash
(docs/postmortem.md).
"""

from __future__ import annotations

import json
import signal
import threading
from typing import Optional

from ..observability import registry as _obs
from ..utils import env as _env
from ..utils.logging import get_logger
from .engine import DrainingError, InferenceEngine, QueueFullError

_log = get_logger("serving.server")

# A generation can legitimately take a while under load; handlers wait
# this long on the ticket before giving up with a 504.
REQUEST_TIMEOUT_S = 600.0


def _http_metrics():
    return _obs.registry().counter(
        "hvdtpu_serving_http_requests_total",
        "HTTP requests served, by route and status code")


class ServingServer:
    """HTTP front + scheduler loop around one :class:`InferenceEngine`.

    ``port=0`` binds an ephemeral port (tests); default comes from
    ``HOROVOD_TPU_SERVING_PORT``.
    """

    def __init__(self, engine: InferenceEngine,
                 port: Optional[int] = None, host: str = "0.0.0.0"):
        from http.server import BaseHTTPRequestHandler, \
            ThreadingHTTPServer

        self.engine = engine
        self._stop = threading.Event()
        self._loop_thread: Optional[threading.Thread] = None
        self._http = _http_metrics()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def _reply(self, code: int, payload: dict,
                       route: str) -> None:
                # Count BEFORE writing: the client may observe the
                # response (and assert on the metric) the instant the
                # body lands.
                outer._http.labels(route=route, code=str(code)).inc()
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.split("?")[0] != "/healthz":
                    self._reply(404, {"error": "not found"}, "other")
                    return
                eng = outer.engine
                if outer._stop.is_set():
                    self._reply(503, {"status": "draining"}, "healthz")
                    return
                self._reply(200, {
                    "status": "serving",
                    "active_requests": eng.active_count,
                    "queue_depth": eng.queue_depth,
                    "batch_slots": eng.config.max_batch_slots,
                    "kv_blocks_free": eng._alloc.free,
                    "kv_blocks_total": eng._alloc.total,
                }, "healthz")

            def do_POST(self):  # noqa: N802 (http.server API)
                if self.path.split("?")[0] != "/generate":
                    self._reply(404, {"error": "not found"}, "other")
                    return
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    tokens = body["tokens"]
                    if not isinstance(tokens, list):
                        raise ValueError("'tokens' must be a list")
                except (KeyError, ValueError, json.JSONDecodeError) as e:
                    self._reply(400, {"error": f"bad request: {e}"},
                                "generate")
                    return
                try:
                    req = outer.engine.submit(
                        tokens,
                        max_new_tokens=body.get("max_new_tokens"),
                        temperature=body.get("temperature"))
                except QueueFullError as e:
                    self._reply(429, {"error": str(e)}, "generate")
                    return
                except DrainingError as e:
                    self._reply(503, {"error": str(e)}, "generate")
                    return
                except ValueError as e:
                    self._reply(400, {"error": str(e)}, "generate")
                    return
                try:
                    out = req.result(timeout=REQUEST_TIMEOUT_S)
                except TimeoutError as e:
                    self._reply(504, {"error": str(e)}, "generate")
                    return
                except RuntimeError as e:
                    self._reply(503, {"error": str(e)}, "generate")
                    return
                self._reply(200, {
                    "id": req.id,
                    "tokens": out,
                    "ttft_ms": round(req.ttft_s * 1e3, 3),
                    "latency_ms": round(
                        (req.t_done - req.t_submit) * 1e3, 3),
                }, "generate")

            def log_message(self, *args):  # silence per-request stderr
                pass

        port = _env.serving_port() if port is None else int(port)
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvd-tpu-serving-http",
            daemon=True)

    # -------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Start the HTTP listener and the scheduler loop thread."""
        self._http_thread.start()
        self._loop_thread = threading.Thread(
            target=self._loop, name="hvd-tpu-serving-sched", daemon=True)
        self._loop_thread.start()
        _log.info("serving on :%d (/generate, /healthz); metrics on the "
                  "registry endpoint", self.port)

    def _loop(self) -> None:
        eng = self.engine
        while not self._stop.is_set():
            if not eng.step():
                eng.wait_for_work(0.05)

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT → graceful drain. Installed on top of the
        flight recorder's handler chain: ours runs the drain and lets
        the process exit cleanly, so the recorder's atexit dump records
        ``exit`` — not ``sigterm`` — for a drained shutdown."""
        def _on_signal(signum, frame):
            self.request_stop()

        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)

    def request_stop(self) -> None:
        self._stop.set()
        with self.engine._work:
            self.engine._work.notify_all()

    def serve_forever(self) -> None:
        """Block until a stop is requested, then drain and shut down."""
        if self._loop_thread is None:
            self.start()
        while not self._stop.wait(0.1):
            pass
        self.shutdown()

    def shutdown(self) -> None:
        """Drain (finish live generations, fail queued) and stop."""
        self._stop.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=30.0)
        self.engine.drain()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._http_thread.join(timeout=5.0)
        _log.info("serving drained and stopped")
