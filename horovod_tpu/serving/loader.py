"""Checkpoint → inference-mesh loader — the "save" half of
train→save→serve.

The training side commits through the sharded checkpoint engine
(docs/checkpoint.md); nothing serving-specific is written — the
manifest's ``extra`` payload just needs the model architecture
(:func:`transformer_extra`, a plain JSON dict) so the server can
rebuild the :class:`~horovod_tpu.models.transformer.TransformerConfig`
without a side-channel config file.

The load is the resharding restore from PR 4 pointed at a *different*
mesh: :func:`load_params` derives each parameter's target layout from
``param_specs`` on the **inference** mesh (no arrays needed — the
layout comes straight from ``NamedSharding.devices_indices_map``),
hands it to ``CheckpointEngine.restore_addressable``, and each process
reads only the saved shard-file spans overlapping its new blocks. A
world-size-4 tensor-parallel training checkpoint therefore serves on a
ws-1 or ws-2 mesh with no gather step and no full-tree host copy —
every device's block is assembled from exactly the ``.npy`` spans that
cover it.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..checkpoint import CheckpointEngine
from ..checkpoint.layout import LeafLayout, Shard, full_index, \
    normalize_index
from ..models import transformer as tfm

# Manifest ``extra`` key under which trainers record the architecture.
CONFIG_EXTRA_KEY = "transformer_config"

# Where the torch save hook (torch.checkpoint_hook) roots the model
# tree inside its checkpoint: manifest leaf keys come out as
# ``['model']['embed']...`` — this prefix selects them (and skips the
# optimizer subtree) for ``--framework torch`` serving.
TORCH_MODEL_PREFIX = "['model']"

_DTYPE_NAMES = {"float32", "bfloat16", "float16", "float64"}


def transformer_extra(cfg: tfm.TransformerConfig) -> dict:
    """JSON-able ``extra`` payload for ``CheckpointEngine.save`` that
    lets the serving tier rebuild the config. ``n_heads`` is recorded
    explicitly (the config's own CHANGELOG note: the derived default
    changed across rounds, and attention depends on it)."""
    d = dataclasses.asdict(cfg)
    d["dtype"] = np.dtype(cfg.dtype).name
    return {CONFIG_EXTRA_KEY: d}


def config_from_manifest(man: dict, **overrides: Any
                         ) -> tfm.TransformerConfig:
    """Rebuild the training ``TransformerConfig`` from a manifest whose
    save passed :func:`transformer_extra`. ``overrides`` replace fields
    (the serving path uses them for the axis names)."""
    extra = man.get("extra") or {}
    if CONFIG_EXTRA_KEY not in extra:
        raise KeyError(
            f"manifest extra has no {CONFIG_EXTRA_KEY!r} entry — save "
            "with extra=transformer_extra(cfg) (docs/serving.md) or "
            "pass the config explicitly")
    d = dict(extra[CONFIG_EXTRA_KEY])
    name = d.get("dtype", "float32")
    if name not in _DTYPE_NAMES:
        raise ValueError(f"unsupported checkpoint dtype {name!r}")
    import jax.numpy as jnp
    d["dtype"] = getattr(jnp, name)
    d.update(overrides)
    return tfm.TransformerConfig(**d)


def serving_config(cfg: tfm.TransformerConfig,
                   mesh: jax.sharding.Mesh) -> tfm.TransformerConfig:
    """The inference variant of a training config: tensor parallelism
    follows the serving mesh's 'tp' axis, sequence/expert axes are
    dropped (decode shards heads, not sequence), remat is off (no
    backward pass to trade HBM against)."""
    tp = "tp" if "tp" in mesh.axis_names and mesh.shape["tp"] > 1 \
        else None
    return dataclasses.replace(cfg, tp_axis=tp, sp_axis=None,
                               ep_axis=None, num_experts=0, remat=False)


def _spec_by_key(cfg: tfm.TransformerConfig) -> Tuple[Any, Dict[str, P]]:
    """(specs treedef, {leaf keystr: PartitionSpec}) — the spec tree has
    the params tree's structure, so its tree-path strings match the
    manifest's leaf keys."""
    specs = tfm.param_specs(cfg)
    flat, treedef = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, P))
    return treedef, {jax.tree_util.keystr(path): spec
                     for path, spec in flat}


def target_layouts(cfg: tfm.TransformerConfig, man: dict,
                   mesh: jax.sharding.Mesh, *,
                   key_prefix: str = ""
                   ) -> Tuple[Dict[str, LeafLayout],
                              Dict[str, NamedSharding]]:
    """Per-leaf target :class:`LeafLayout` + ``NamedSharding`` on the
    inference mesh, derived from ``param_specs`` and the manifest's
    shapes — no arrays materialized (the point: the layout must exist
    *before* the data so the restore can read only what it needs).

    ``key_prefix`` selects a subtree of the checkpoint: a torch save
    hook commits ``{"model": ..., "optimizer": ...}``, so serving reads
    only the leaves under :data:`TORCH_MODEL_PREFIX` and ignores the
    rest (an unprefixed load still rejects unknown leaves loudly —
    silently skipping them would mask a wrong checkpoint)."""
    _, by_key = _spec_by_key(cfg)
    layouts: Dict[str, LeafLayout] = {}
    shardings: Dict[str, NamedSharding] = {}
    for entry in man["leaves"]:
        key = entry["key"]      # manifest key — stays the dict key so
        #                         restore_addressable finds the shards
        spec_key = key
        if key_prefix:
            if not key.startswith(key_prefix):
                continue   # outside the selected subtree (optimizer…)
            spec_key = key[len(key_prefix):]
            if spec_key not in by_key:
                continue
        elif key not in by_key:
            raise KeyError(
                f"checkpoint leaf {key!r} has no param_specs entry — "
                "is this checkpoint the flagship transformer's params "
                f"tree? (specs hold {sorted(by_key)[:4]}...)")
        shape = tuple(int(d) for d in entry["shape"])
        sharding = NamedSharding(mesh, by_key[spec_key])
        shardings[key] = sharding
        if sharding.is_fully_replicated:
            layouts[key] = LeafLayout(
                shape=shape, dtype=entry["dtype"],
                shards=(Shard(index=full_index(shape), process=0),),
                replicated=True)
            continue
        owners: Dict[tuple, int] = {}
        for dev, slices in sharding.devices_indices_map(shape).items():
            idx = normalize_index(slices, shape)
            proc = int(dev.process_index)
            prev = owners.get(idx)
            if prev is None or proc < prev:
                owners[idx] = proc
        layouts[key] = LeafLayout(
            shape=shape, dtype=entry["dtype"],
            shards=tuple(Shard(index=idx, process=proc)
                         for idx, proc in sorted(owners.items())),
            replicated=False)
    return layouts, shardings


def load_params(directory: str, cfg: tfm.TransformerConfig,
                mesh: jax.sharding.Mesh, *,
                step: Optional[int] = None,
                engine: Optional[CheckpointEngine] = None,
                key_prefix: str = "") -> Any:
    """Assemble the transformer's parameter tree on the inference mesh
    from a committed sharded checkpoint — span-overlap reads only
    (``restore_addressable``), so the save-time world size / mesh never
    has to match the serving one.

    ``engine`` lets callers keep corruption-fallback/process settings;
    by default one is built over ``directory``. ``key_prefix`` roots
    the read in a checkpoint subtree — :data:`TORCH_MODEL_PREFIX` for
    checkpoints committed by ``torch.checkpoint_hook`` (the
    ``--framework torch`` serving path). Returns the params pytree with
    every leaf a sharded ``jax.Array`` on ``mesh``.
    """
    eng = engine if engine is not None else CheckpointEngine(directory)
    man = eng.restore_manifest(step)
    treedef, by_key = _spec_by_key(cfg)
    layouts, shardings = target_layouts(cfg, man, mesh,
                                        key_prefix=key_prefix)
    missing = sorted(key_prefix + k for k in by_key
                     if key_prefix + k not in layouts)
    if missing:
        raise KeyError(
            f"checkpoint step {man['step']} is missing param leaves "
            f"{missing[:4]}{'...' if len(missing) > 4 else ''}")
    blocks = eng.restore_addressable(layouts, step)
    leaves = []
    for spec_key in by_key:   # spec flatten order == tree order
        key = key_prefix + spec_key
        shape = layouts[key].shape
        sharding = shardings[key]
        by_index = {shard.index: arr for shard, arr in blocks[key]}
        bufs = []
        for dev, slices in \
                sharding.addressable_devices_indices_map(shape).items():
            idx = normalize_index(slices, shape)
            if layouts[key].replicated:
                idx = full_index(shape)
            bufs.append(jax.device_put(by_index[idx], dev))
        leaves.append(jax.make_array_from_single_device_arrays(
            shape, sharding, bufs))
    return jax.tree_util.tree_unflatten(treedef, leaves)
