"""Block-granular KV-cache accounting — the free-list behind continuous
batching.

The device-side pool (``models.transformer.init_cache``) is a flat
array of fixed-size token blocks; this module owns the *host-side*
bookkeeping: which blocks are free, which sequence holds which, and how
many a request needs end-to-end. Slicing the cache into blocks is what
lets concurrency be bounded by total tokens instead of by
``max_batch × max_seq`` — a finished request returns whole blocks to
the pool and the next admit reuses them, with no fragmentation between
differently-sized sequences (vLLM's PagedAttention argument, SOSP '23).

Allocation is all-or-nothing and up-front: :class:`BlockAllocator`
hands a request every block its worst case needs (prompt + max new
tokens) at admission, or none at all. That conservative reservation is
the engine's no-preemption guarantee — pool exhaustion can only ever
*defer admission*; it can never strand a live sequence mid-decode or
force evicting one to disk (docs/serving.md).

Block 0 is reserved as scratch and never handed out: padded prefill
positions and inactive batch slots point their block tables at it, so
their garbage K/V writes land where no live sequence reads.

Blocks are *refcounted*: a plain allocation holds one reference, and
the shared prefix cache (:class:`PrefixCache`) adds references so two
sequences with the same system prompt can address the same read-only
prefix blocks. A block returns to the free list only when its last
holder lets go — ``release`` is a decref, not an unconditional free.
Divergence past a shared prefix is copy-on-write at block granularity:
writes only ever land in a sequence's privately-allocated blocks (a
shareable block is by construction a FULL block of prompt tokens, and
every later position falls in a later, private block), so the
"divergent copy" is realized by writing fresh K/V into fresh blocks —
shared blocks are never mutated.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import List, Optional, Sequence

SCRATCH_BLOCK = 0


def blocks_needed(prompt_len: int, max_new_tokens: int,
                  block_size: int) -> int:
    """Worst-case block count for a request: K/V is written for the
    prompt and for every generated token that is fed back (the last
    generated token is output-only), i.e. positions
    ``[0, prompt_len + max_new_tokens - 1)``."""
    if prompt_len < 1:
        raise ValueError(f"prompt_len must be >= 1, got {prompt_len}")
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}")
    tokens = prompt_len + max_new_tokens - 1
    return -(-tokens // int(block_size))


class BlockAllocator:
    """Refcounted free-list over pool blocks ``1..n_blocks-1`` (0 is
    scratch). Every operation is O(1) per block touched: ``alloc`` pops
    off the free stack (no scan of the free set), ``release``/``decref``
    push back the moment the count hits zero.

    Not thread-safe by itself — the engine serializes all scheduler
    state under its own lock.
    """

    def __init__(self, n_blocks: int):
        if n_blocks < 2:
            raise ValueError(
                f"the pool needs the scratch block plus at least one "
                f"allocatable block; got n_blocks={n_blocks}")
        self.n_blocks = int(n_blocks)
        # LIFO free stack, low ids first out — deterministic layouts
        # for the seeded bench.
        self._free: List[int] = list(range(self.n_blocks - 1, 0, -1))
        self._refs = [0] * self.n_blocks

    @property
    def total(self) -> int:
        """Allocatable blocks (excludes scratch)."""
        return self.n_blocks - 1

    @property
    def free(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.total - self.free

    def refcount(self, block: int) -> int:
        return self._refs[block]

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` blocks at one reference each, all-or-nothing; None when
        the pool cannot cover the request (the admission gate's signal
        to leave it queued)."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refs[b] = 1
        return out

    def incref(self, block: int) -> None:
        """Add a holder to a live block — how the prefix cache (and
        through it a second sequence) shares a block already in use."""
        if block == SCRATCH_BLOCK:
            raise ValueError("block 0 is the scratch block; it is "
                             "never allocated and never shared")
        if self._refs[block] <= 0:
            raise ValueError(
                f"cannot incref free KV block {block} — only a held "
                "block can gain holders")
        self._refs[block] += 1

    def decref(self, block: int) -> bool:
        """Drop one holder; frees (returns True) when the last one
        lets go. Over-release and scratch-release are hard errors —
        both would hand one block to two live sequences and silently
        corrupt their caches."""
        if block == SCRATCH_BLOCK:
            raise ValueError("block 0 is the scratch block; it is "
                             "never allocated and never released")
        if self._refs[block] <= 0:
            raise ValueError(f"double free of KV block {block}")
        self._refs[block] -= 1
        if self._refs[block] == 0:
            self._free.append(block)
            return True
        return False

    def release(self, blocks: List[int]) -> None:
        """Drop one reference on each of a finished sequence's blocks
        (shared prefix blocks stay resident under the cache's ref)."""
        for b in blocks:
            self.decref(b)


def prefix_hashes(tokens: Sequence[int], block_size: int) -> List[bytes]:
    """Chained digest per FULL prompt block: entry ``j`` identifies
    ``tokens[0:(j+1)*block_size]`` — a prefix, not a window, so two
    prompts share entry ``j`` iff they agree on every token up to
    there. Only blocks wholly inside ``tokens[:-1]`` are hashed: the
    final prompt token is never shareable because admission always
    needs at least one token to prefill (its forward produces the
    first-token logits).

    Deterministic across processes (hashlib, not Python's salted
    ``hash``) — the fleet router hashes the same prompts with the same
    function to score replica cache warmth."""
    bs = int(block_size)
    n_full = max(0, (len(tokens) - 1) // bs)
    out: List[bytes] = []
    h = b""
    for j in range(n_full):
        blk = ",".join(str(int(t)) for t in tokens[j * bs:(j + 1) * bs])
        h = hashlib.blake2b(h + blk.encode(), digest_size=16).digest()
        out.append(h)
    return out


class PrefixCache:
    """LRU map from chained prompt-prefix hashes to resident pool
    blocks — the host-side index behind shared-prefix prefill.

    The cache holds ONE reference on every block it indexes (via
    :meth:`BlockAllocator.incref`), so an indexed block outlives the
    sequence that wrote it. ``lookup`` increfs each matched block for
    the caller (the admitting sequence's own hold); ``evict_one`` pops
    the least-recently-used entry and drops the cache's reference —
    blocks still shared by live sequences are freed only when those
    finish. Not thread-safe — engine-lock discipline, like the
    allocator."""

    def __init__(self, alloc: BlockAllocator,
                 max_entries: Optional[int] = None):
        self._alloc = alloc
        self._map: "OrderedDict[bytes, int]" = OrderedDict()
        self.max_entries = max_entries

    def __len__(self) -> int:
        return len(self._map)

    def lookup(self, hashes: Sequence[bytes]) -> List[int]:
        """Longest cached prefix of ``hashes``; increfs every returned
        block (the caller now holds them) and freshens their LRU
        position."""
        out: List[int] = []
        for h in hashes:
            b = self._map.get(h)
            if b is None:
                break
            self._map.move_to_end(h)
            self._alloc.incref(b)
            out.append(b)
        return out

    def insert(self, h: bytes, block: int) -> bool:
        """Index ``block`` (held by the caller) under ``h``; the cache
        takes its own reference. No-op when the hash is already
        indexed (first writer wins — both blocks hold identical K/V,
        keeping one mapping makes sharing converge)."""
        if h in self._map:
            return False
        self._alloc.incref(block)
        self._map[h] = block
        if self.max_entries is not None \
                and len(self._map) > self.max_entries:
            self.evict_one()
        return True

    def evict_one(self) -> bool:
        """Drop the LRU entry's cache reference; True when an entry was
        evicted. The engine calls this under pool pressure until the
        pending admission fits (or the cache is empty)."""
        if not self._map:
            return False
        _, block = self._map.popitem(last=False)
        self._alloc.decref(block)
        return True


class SessionLease:
    """One conversation's resident KV claim between turns: the token
    context the blocks encode (``prompt + generated[:-1]`` of the last
    turn — exactly the positions whose K/V was written) and the leading
    pool blocks that hold it. The lease owns one reference on each
    block."""

    __slots__ = ("tokens", "blocks")

    def __init__(self, tokens: List[int], blocks: List[int]):
        self.tokens = tokens
        self.blocks = blocks

    @property
    def n_tokens(self) -> int:
        return len(self.tokens)


class SessionLeaseTable:
    """LRU map ``session_id -> SessionLease`` — KV-block survival
    between conversation turns (docs/serving.md#session-affinity).

    Where the prefix cache shares FULL prompt blocks across unrelated
    requests, a lease keeps a single conversation's *entire* context
    resident — including generated tokens, which the prefix cache never
    indexes — so the next turn of that conversation resumes decoding
    from its stored position instead of re-prefilling the transcript.

    Leases are the first thing sacrificed under pool pressure: eviction
    *demotes* a lease to the refcounted prefix cache (its full prompt-
    prefix blocks get indexed there, a degraded-but-still-warm tier)
    before dropping the lease's references. Not thread-safe —
    engine-lock discipline, like the allocator."""

    def __init__(self, alloc: BlockAllocator,
                 max_entries: Optional[int] = None):
        self._alloc = alloc
        self._map: "OrderedDict[str, SessionLease]" = OrderedDict()
        self.max_entries = max_entries

    def __len__(self) -> int:
        return len(self._map)

    def ids(self) -> List[str]:
        """Live session ids, LRU-oldest first — advertised by the
        replica's ``/healthz`` for router pinning."""
        return list(self._map)

    def get(self, session_id: str) -> Optional[SessionLease]:
        """Peek a lease (freshens LRU position; ownership stays with
        the table). The engine inspects ``tokens`` to decide between
        resuming from the lease and releasing it as divergent."""
        lease = self._map.get(session_id)
        if lease is not None:
            self._map.move_to_end(session_id)
        return lease

    def pop(self, session_id: str) -> Optional[SessionLease]:
        """Remove a lease, transferring its block references to the
        caller (who must release or re-``put`` them)."""
        return self._map.pop(session_id, None)

    def put(self, session_id: str, tokens: List[int],
            blocks: List[int]) -> None:
        """Store a lease; the table takes over the caller's reference
        on each block. A superseded lease for the same id is released
        first."""
        old = self._map.pop(session_id, None)
        if old is not None:
            self.release(old)
        self._map[session_id] = SessionLease(list(tokens), list(blocks))

    def release(self, lease: SessionLease) -> None:
        """Drop the lease's reference on every block (blocks shared
        with the prefix cache or a live sequence stay resident)."""
        self._alloc.release(lease.blocks)
        lease.blocks = []

    def evict_one(self, prefix: Optional["PrefixCache"] = None,
                  block_size: int = 0) -> bool:
        """Sacrifice the LRU lease under pool pressure; True when one
        was evicted. With a prefix cache, the lease's FULL prompt-
        prefix blocks are demoted into it first (the cache increfs what
        it indexes), so a follow-up turn still skips those chunks via
        the ordinary shared-prefix path."""
        if not self._map:
            return False
        _, lease = self._map.popitem(last=False)
        if prefix is not None and block_size > 0:
            hashes = prefix_hashes(lease.tokens, block_size)
            for j, h in enumerate(hashes[:len(lease.blocks)]):
                prefix.insert(h, lease.blocks[j])
        self.release(lease)
        return True
