"""Block-granular KV-cache accounting — the free-list behind continuous
batching.

The device-side pool (``models.transformer.init_cache``) is a flat
array of fixed-size token blocks; this module owns the *host-side*
bookkeeping: which blocks are free, which sequence holds which, and how
many a request needs end-to-end. Slicing the cache into blocks is what
lets concurrency be bounded by total tokens instead of by
``max_batch × max_seq`` — a finished request returns whole blocks to
the pool and the next admit reuses them, with no fragmentation between
differently-sized sequences (vLLM's PagedAttention argument, SOSP '23).

Allocation is all-or-nothing and up-front: :class:`BlockAllocator`
hands a request every block its worst case needs (prompt + max new
tokens) at admission, or none at all. That conservative reservation is
the engine's no-preemption guarantee — pool exhaustion can only ever
*defer admission*; it can never strand a live sequence mid-decode or
force evicting one to disk (docs/serving.md).

Block 0 is reserved as scratch and never handed out: padded prefill
positions and inactive batch slots point their block tables at it, so
their garbage K/V writes land where no live sequence reads.
"""

from __future__ import annotations

from typing import List, Optional

SCRATCH_BLOCK = 0


def blocks_needed(prompt_len: int, max_new_tokens: int,
                  block_size: int) -> int:
    """Worst-case block count for a request: K/V is written for the
    prompt and for every generated token that is fed back (the last
    generated token is output-only), i.e. positions
    ``[0, prompt_len + max_new_tokens - 1)``."""
    if prompt_len < 1:
        raise ValueError(f"prompt_len must be >= 1, got {prompt_len}")
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}")
    tokens = prompt_len + max_new_tokens - 1
    return -(-tokens // int(block_size))


class BlockAllocator:
    """Free-list over pool blocks ``1..n_blocks-1`` (0 is scratch).

    Not thread-safe by itself — the engine serializes all scheduler
    state under its own lock.
    """

    def __init__(self, n_blocks: int):
        if n_blocks < 2:
            raise ValueError(
                f"the pool needs the scratch block plus at least one "
                f"allocatable block; got n_blocks={n_blocks}")
        self.n_blocks = int(n_blocks)
        # LIFO free-list, low ids first out — deterministic layouts for
        # the seeded bench.
        self._free: List[int] = list(range(self.n_blocks - 1, 0, -1))
        self._held = [False] * self.n_blocks

    @property
    def total(self) -> int:
        """Allocatable blocks (excludes scratch)."""
        return self.n_blocks - 1

    @property
    def free(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.total - self.free

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` blocks, all-or-nothing; None when the pool cannot cover
        the request (the admission gate's signal to leave it queued)."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._held[b] = True
        return out

    def release(self, blocks: List[int]) -> None:
        """Return a finished sequence's blocks. Double-free and
        scratch-release are hard errors — both would hand one block to
        two live sequences and silently corrupt their caches."""
        for b in blocks:
            if b == SCRATCH_BLOCK:
                raise ValueError("block 0 is the scratch block; it is "
                                 "never allocated and never released")
            if not self._held[b]:
                raise ValueError(f"double free of KV block {b}")
            self._held[b] = False
            self._free.append(b)
