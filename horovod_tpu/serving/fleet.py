"""Fleet supervisor — spawn and babysit N independent serving replicas.

The single-replica serving tier (docs/serving.md) dies with its
process: one crash takes down every in-flight request. The fleet layer
applies the adaptation plane's observe→detect→act discipline
(docs/adaptation.md) to the tier where failures are user-visible:

  - **observe**: every replica is a separate ``python -m
    horovod_tpu.serving`` process announcing its HTTP + metrics ports
    on stdout; the supervisor owns the pipe.
  - **detect**: crash via process exit (``poll()``), hang via a
    periodic ``/healthz`` probe — a replica that stops answering for
    ``HOROVOD_TPU_FLEET_PROBE_FAILURES`` consecutive probes is declared
    dead and killed (the ``drop_health`` fault clause exists to prove
    this path deterministically).
  - **act**: restart from the same checkpoint directory with the
    replica's *incarnation* bumped (exported as
    ``HOROVOD_TPU_ELASTIC_GENERATION``, so a ``gen=0``-scoped
    ``replica_crash_at`` fault crashes the first incarnation once and
    lets the restart run clean), and record every transition as a
    flight-recorder ``serving_replica`` event + ``hvdtpu_fleet_*``
    metric.

The supervisor never routes: :class:`~horovod_tpu.serving.router.Router`
reads :meth:`Fleet.endpoints` each scrape cycle, so a restarted replica
(new ephemeral port) re-enters rotation the moment its ready line
appears. Replica *identity* is the index; ports are cattle.

Isolation is deliberate — replicas share nothing but the checkpoint
directory. A replica process wedged in XLA cannot poison its siblings,
and SIGKILL is always a safe supervisor action because the KV cache and
batch state are process-local (requests are recovered by the router's
failover, not by the replica).
"""

from __future__ import annotations

import dataclasses
import os
import re
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from ..observability import flight_recorder as _flight
from ..observability import registry as _obs
from ..utils import env as _env
from ..utils.logging import get_logger

_log = get_logger("serving.fleet")

# Families the replica history sampler scrapes — the serving signal
# plane plus the SLO/goodput families, not the whole registry
# (docs/health.md#fleet, docs/serving.md#slo). Comma-separated: the
# replica's /metrics.json splits it into a prefix union.
_REPLICA_HISTORY_PREFIX = "hvdtpu_serving_,hvdtpu_slo_"

# The replica's announce line (serving/__main__.py). The leading
# ``ready on :PORT`` phrase is load-bearing API — tests and the
# pre-fleet tooling grep for it.
_READY_RE = re.compile(r"ready on :(\d+)")
_METRICS_RE = re.compile(r"metrics=:(\d+)")


def _metrics():
    r = _obs.registry()
    return {
        "live": r.gauge(
            "hvdtpu_fleet_replicas_live",
            "Replica processes currently alive with a bound serving "
            "port").labels(),
        "restarts": r.counter(
            "hvdtpu_fleet_replica_restarts_total",
            "Replica restarts by the supervisor, by replica index and "
            "why the previous incarnation ended"),
        "probe_failures": r.counter(
            "hvdtpu_fleet_probe_failures_total",
            "Failed replica health probes (timeouts / refused / "
            "dropped), by replica index"),
    }


@dataclasses.dataclass(frozen=True)
class ReplicaEndpoint:
    """What the router needs to know about one live replica."""

    index: int
    host: str
    port: int
    metrics_port: Optional[int] = None


class Replica:
    """One supervised replica process (identity = index; the process,
    port and incarnation all change across restarts)."""

    def __init__(self, index: int):
        self.index = index
        self.proc: Optional[subprocess.Popen] = None
        self.port: Optional[int] = None
        self.metrics_port: Optional[int] = None
        self.generation = 0          # incarnation (restart count)
        self.restarts = 0
        self.probe_failures = 0
        self.t_spawn = 0.0
        self.ready = threading.Event()   # ready line seen (this proc)
        self.retiring = False        # scale-down drain in progress:
        #                              exit means RETIRE, not restart
        #                              (docs/serving.md#qos)
        self._reader: Optional[threading.Thread] = None

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    @property
    def up(self) -> bool:
        return self.alive and self.port is not None


class Fleet:
    """Supervisor for ``n`` serving replicas launched from one
    checkpoint.

    ``replica_argv`` is the argv tail handed to every ``python -m
    horovod_tpu.serving`` child (``--checkpoint-dir ...`` etc.);
    the supervisor adds ``--replica-id``/``--port 0`` itself and forces
    an ephemeral per-replica metrics endpoint
    (``HOROVOD_TPU_METRICS_PORT=0``) so the router has a queue-gauge
    scrape target per replica.
    """

    def __init__(self, n: int, replica_argv: List[str], *,
                 host: str = "127.0.0.1",
                 env: Optional[Dict[str, str]] = None,
                 probe_interval_s: Optional[float] = None,
                 probe_failures: Optional[int] = None,
                 max_restarts: Optional[int] = None,
                 restart_backoff_s: float = 0.5):
        if n < 1:
            raise ValueError(f"fleet needs >= 1 replica, got {n}")
        self.n = int(n)
        self.host = host
        self.replica_argv = list(replica_argv)
        self._env = dict(env) if env is not None else None
        self._probe_interval = (probe_interval_s
                                if probe_interval_s is not None
                                else _env.fleet_probe_interval_secs())
        self._probe_failures = (probe_failures
                                if probe_failures is not None
                                else _env.fleet_probe_failures())
        self.max_restarts = max_restarts
        self._backoff = float(restart_backoff_s)
        self.replicas = [Replica(i) for i in range(self.n)]
        self._m = _metrics()
        self._lock = threading.Lock()
        self._stopping = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # QoS autoscaler hookups (docs/serving.md#qos): health-plane
        # alerts forwarded via on_alert; _history_armed remembers
        # whether scale-up replicas need their own history sampler.
        self.on_alert = None
        self._history_armed = False
        # Telemetry history (docs/health.md#fleet): the SUPERVISOR
        # samples each replica's scraped serving metrics into its own
        # history-replica{i}.jsonl — replica trends survive replica
        # death (the replica's own process-local history would die
        # with it), and restarts appear as counter resets, which the
        # delta reduction handles. Plus one history-fleet.jsonl over
        # the supervisor's own registry (restart/probe counters) so
        # the restart-spike detector has a durable signal.
        self._history: list = []

    # ----------------------------------------------------------- spawn

    def _note(self, event: str, replica: int, detail: str = "") -> None:
        _flight.recorder().note("serving_replica",
                                (event, replica, detail))

    def _spawn(self, rep: Replica) -> None:
        env = dict(os.environ if self._env is None else self._env)
        env["HOROVOD_TPU_REPLICA_ID"] = str(rep.index)
        # The incarnation rides the elastic-generation contract: fault
        # clauses scope to one incarnation with gen=N exactly like they
        # scope to one elastic generation in training.
        env["HOROVOD_TPU_ELASTIC_GENERATION"] = str(rep.generation)
        # One scrape target per replica: ephemeral port, announced on
        # the ready line. A parent-level plain port would collide
        # across replicas.
        env["HOROVOD_TPU_METRICS_PORT"] = "0"
        # Blackbox dumps go to a per-INCARNATION subdir: a restarted
        # replica's periodic inflight snapshots would otherwise
        # overwrite its dead predecessor's final-gasp dump — the one
        # file the postmortem needs to name the crash
        # (docs/postmortem.md).
        bb = env.get("HOROVOD_TPU_BLACKBOX")
        if bb:
            env["HOROVOD_TPU_BLACKBOX"] = os.path.join(
                bb, f"gen{rep.generation}")
        cmd = [sys.executable, "-m", "horovod_tpu.serving",
               "--replica-id", str(rep.index), "--port", "0"] \
            + self.replica_argv
        rep.port = None
        rep.metrics_port = None
        rep.ready = threading.Event()
        rep.probe_failures = 0
        rep.t_spawn = time.monotonic()
        rep.proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL if env.get(
                "HOROVOD_TPU_FLEET_QUIET") else None,
            text=True, bufsize=1)
        self._note("spawn", rep.index, f"gen={rep.generation}")
        _log.info("replica %d spawned (pid %d, gen %d)", rep.index,
                  rep.proc.pid, rep.generation)
        rep._reader = threading.Thread(
            target=self._read_stdout, args=(rep, rep.proc),
            name=f"hvd-tpu-fleet-r{rep.index}", daemon=True)
        rep._reader.start()

    def _read_stdout(self, rep: Replica, proc: subprocess.Popen) -> None:
        """Own the replica's stdout pipe: parse the announce line, tag
        and forward everything else (a supervisor that doesn't drain
        the pipe deadlocks its child on a full buffer)."""
        try:
            for line in proc.stdout:
                m = _READY_RE.search(line)
                if m and rep.proc is proc:
                    rep.port = int(m.group(1))
                    mm = _METRICS_RE.search(line)
                    rep.metrics_port = int(mm.group(1)) if mm else None
                    rep.ready.set()
                    self._note("ready", rep.index,
                               f"port={rep.port}")
                    _log.info("replica %d ready on :%d (metrics %s)",
                              rep.index, rep.port, rep.metrics_port)
                else:
                    sys.stderr.write(f"[replica {rep.index}] {line}")
        except (ValueError, OSError):  # pipe closed mid-read
            pass

    # ------------------------------------------------------- lifecycle

    def start(self, ready_timeout_s: Optional[float] = None) -> None:
        """Spawn every replica and start the supervision loop;
        optionally block until all announce ready."""
        for rep in self.replicas:
            self._spawn(rep)
        self._thread = threading.Thread(
            target=self._supervise, name="hvd-tpu-fleet", daemon=True)
        self._thread.start()
        self._maybe_start_history()
        if ready_timeout_s is not None:
            self.wait_ready(ready_timeout_s)

    def _scrape_snapshot(self, rep: Replica) -> dict:
        """One replica's serving-metric snapshot — the prefix-filtered
        ``/metrics.json`` view (never the full registry; the prefix=
        query keeps the per-tick payload to the serving families)."""
        import urllib.request
        port = rep.metrics_port
        if not rep.up or port is None:
            raise ConnectionError(f"replica {rep.index} has no metrics "
                                  "endpoint (down or not ready)")
        with urllib.request.urlopen(
                f"http://{self.host}:{port}/metrics.json"
                f"?prefix={_REPLICA_HISTORY_PREFIX}",
                timeout=max(1.0, self._probe_interval * 4)) as resp:
            import json as _json
            return _json.loads(resp.read())

    def _maybe_start_history(self) -> None:
        """Arm the fleet history plane when HOROVOD_TPU_HISTORY is set:
        one sampler per replica (scraped, so trends survive replica
        death) plus one over the supervisor's own fleet registry, all
        sharing the telemetry timer thread. The supervisor owns the
        alert webhook for serving alerts — replicas never POST."""
        directory = _env.history_dir()
        if not directory or not _obs.enabled():
            return
        from ..observability import health as _health
        from ..observability import history as _history
        self._history_armed = True
        detectors = _env.health_detectors_enabled()
        url = _env.alert_url()
        for rep in self.replicas:
            self._start_replica_history(rep)
        fleet_monitor = _health.HealthMonitor(
            webhook_url=url,
            alert_sink=self._alert_sink) if detectors else None
        self._history.append(_history.HistorySampler(
            directory, "fleet",
            prefix=("hvdtpu_fleet_", "hvdtpu_slo_"),
            monitor=fleet_monitor,
            meta=lambda: {"role": "fleet_supervisor"},
        ).start())

    def _start_replica_history(self, rep: Replica) -> None:
        """One replica's history sampler + monitor — factored out so
        scale-up replicas (docs/serving.md#qos) get the same
        telemetry as the initial fleet."""
        if not self._history_armed:
            return
        from ..observability import health as _health
        from ..observability import history as _history
        directory = _env.history_dir()
        monitor = _health.HealthMonitor(
            replica=rep.index, webhook_url=_env.alert_url(),
            alert_sink=self._alert_sink) \
            if _env.health_detectors_enabled() else None
        self._history.append(_history.HistorySampler(
            directory, f"replica{rep.index}",
            source=(lambda r=rep: self._scrape_snapshot(r)),
            monitor=monitor,
            meta=lambda r=rep: {"replica": r.index,
                                "generation": r.generation,
                                "role": "serving_replica"},
        ).start())

    def _alert_sink(self, alert) -> None:
        """Forward scale-relevant health alerts (queue_depth_runaway)
        to the QoS autoscaler when one is attached
        (docs/serving.md#qos)."""
        cb = self.on_alert
        if cb is None or alert.kind != "queue_depth_runaway":
            return
        try:
            cb(alert.kind)
        except Exception as e:  # pragma: no cover - defensive
            _log.warning("fleet alert forward failed: %s", e)

    def wait_ready(self, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        for rep in self.replicas:
            if not rep.ready.wait(max(0.0,
                                      deadline - time.monotonic())):
                raise TimeoutError(
                    f"replica {rep.index} not ready within "
                    f"{timeout_s}s")

    def endpoints(self) -> List[ReplicaEndpoint]:
        """Live, port-announced replicas — the router's backend list,
        re-read every scrape cycle so restarts re-enter rotation."""
        out = []
        for rep in list(self.replicas):
            if rep.up:
                out.append(ReplicaEndpoint(
                    index=rep.index, host=self.host, port=rep.port,
                    metrics_port=rep.metrics_port))
        return out

    def live_count(self) -> int:
        """Replicas currently serving (up, not mid-retirement) — the
        autoscaler's notion of fleet size."""
        return sum(1 for r in list(self.replicas)
                   if r.up and not r.retiring)

    def load_views(self) -> List[dict]:
        """Supervisor-side load sample: each serving replica's
        active/queued/slots from /healthz — the autoscaler's fallback
        signal source when no router is wired in
        (docs/serving.md#qos)."""
        import http.client
        import json as _json
        out = []
        for rep in list(self.replicas):
            if not rep.up or rep.retiring:
                continue
            try:
                conn = http.client.HTTPConnection(
                    self.host, rep.port, timeout=max(
                        1.0, self._probe_interval * 4))
                try:
                    conn.request("GET", "/healthz")
                    resp = conn.getresponse()
                    if resp.status != 200:
                        continue
                    h = _json.loads(resp.read())
                finally:
                    conn.close()
            except (OSError, ValueError):
                continue
            out.append({
                "active": float(h.get("active_requests", 0)),
                "queue_depth": float(h.get("queue_depth", 0)),
                "slots": float(h.get("batch_slots", 1) or 1)})
        return out

    def scale_to(self, n: int) -> None:
        """QoS autoscaler action (docs/serving.md#qos): grow by
        spawning fresh replicas at new indices; shrink by marking the
        highest-index serving replicas ``retiring`` and SIGTERMing
        them into the existing drain path (readyz flips 503, the
        router stops admitting, every accepted request completes,
        exit 0) — the supervisor then REMOVES them instead of
        restarting, so zero requests drop through a scale-down."""
        if n < 1:
            raise ValueError(f"fleet needs >= 1 replica, got {n}")
        with self._lock:
            serving = [r for r in self.replicas
                       if r.proc is not None and not r.retiring]
            cur = len(serving)
            if n > cur:
                next_idx = 1 + max(
                    (r.index for r in self.replicas), default=-1)
                for i in range(n - cur):
                    rep = Replica(next_idx + i)
                    self.replicas.append(rep)
                    self._spawn(rep)
                    self._note("scale_up", rep.index, f"fleet={n}")
                    self._start_replica_history(rep)
            elif n < cur:
                doomed = sorted(serving, key=lambda r: -r.index)
                for rep in doomed[:cur - n]:
                    rep.retiring = True
                    self._note("scale_down", rep.index, "drain")
                    if rep.alive:
                        rep.proc.send_signal(signal.SIGTERM)
            self.n = n

    def _probe(self, rep: Replica) -> bool:
        """One /healthz liveness probe (readiness is the router's
        business — a draining replica must NOT be shot)."""
        import http.client
        try:
            conn = http.client.HTTPConnection(
                self.host, rep.port, timeout=max(
                    1.0, self._probe_interval * 4))
            try:
                conn.request("GET", "/healthz")
                return conn.getresponse().status == 200
            finally:
                conn.close()
        except OSError:
            return False

    def _supervise(self) -> None:
        while not self._stopping.is_set():
            for rep in list(self.replicas):
                if self._stopping.is_set():
                    break
                if rep.proc is None:
                    continue
                rc = rep.proc.poll()
                if rc is not None:
                    self._on_exit(rep, rc)
                    continue
                if rep.port is not None:
                    if self._probe(rep):
                        rep.probe_failures = 0
                    else:
                        rep.probe_failures += 1
                        self._m["probe_failures"].labels(
                            replica=str(rep.index)).inc()
                        if rep.probe_failures >= self._probe_failures:
                            self._note("health_timeout", rep.index,
                                       f"{rep.probe_failures} probes")
                            _log.error(
                                "replica %d unresponsive for %d "
                                "probes — killing for restart",
                                rep.index, rep.probe_failures)
                            try:
                                rep.proc.send_signal(signal.SIGKILL)
                            except OSError:
                                pass
            self._m["live"].set(
                sum(1 for r in list(self.replicas) if r.up))
            self._stopping.wait(self._probe_interval)

    def _on_exit(self, rep: Replica, rc: int) -> None:
        why = "exit" if rc == 0 else "crash"
        self._note(why, rep.index, f"rc={rc} gen={rep.generation}")
        _log.log(30 if rc else 20,
                 "replica %d (gen %d) %s with rc=%s", rep.index,
                 rep.generation, "exited" if rc == 0 else "CRASHED", rc)
        if self._stopping.is_set():
            rep.proc = None
            return
        if rep.retiring:
            # Scale-down drain completed: retire instead of restart
            # (docs/serving.md#qos).
            self._note("retired", rep.index, f"rc={rc}")
            _log.info("replica %d retired (scale-down drain done)",
                      rep.index)
            rep.proc = None
            with self._lock:
                try:
                    self.replicas.remove(rep)
                except ValueError:  # pragma: no cover - already gone
                    pass
            return
        if self.max_restarts is not None \
                and rep.restarts >= self.max_restarts:
            self._note("gave_up", rep.index,
                       f"restarts={rep.restarts}")
            _log.error("replica %d exceeded max_restarts=%d — leaving "
                       "it down", rep.index, self.max_restarts)
            rep.proc = None
            return
        # Fast-crash backoff: a replica dying within 2 s of spawn
        # (bad checkpoint, port clash) must not spin the supervisor.
        if time.monotonic() - rep.t_spawn < 2.0:
            self._stopping.wait(self._backoff)
        rep.restarts += 1
        rep.generation += 1
        self._m["restarts"].labels(replica=str(rep.index),
                                   why=why).inc()
        self._note("restart", rep.index, f"gen={rep.generation}")
        self._spawn(rep)

    def drain_replica(self, index: int) -> None:
        """Operator action: SIGTERM one replica so it drains cleanly
        (readyz flips 503, the router stops admitting, accepted work
        completes, exit 0 — and the supervisor restarts it)."""
        rep = self.replicas[index]
        if rep.alive:
            self._note("drain", index, "sigterm")
            rep.proc.send_signal(signal.SIGTERM)

    def kill_replica(self, index: int) -> None:
        """Chaos action: SIGKILL one replica mid-flight — no drain, no
        goodbye. In-flight requests on it are lost at the replica and
        recovered by the router's failover resume; the supervisor
        restarts the process like any other crash."""
        rep = self.replicas[index]
        if rep.alive:
            self._note("kill", index, "sigkill")
            rep.proc.kill()

    def stop(self, timeout_s: float = 30.0) -> None:
        """Tear the fleet down: stop restarting, SIGTERM every replica
        (graceful drain), escalate to SIGKILL past the timeout."""
        self._stopping.set()
        for sampler in self._history:
            sampler.stop()   # final flush — the last window survives
        self._history = []
        if self._thread is not None:
            self._thread.join(timeout=self._probe_interval * 4 + 1)
        for rep in self.replicas:
            if rep.alive:
                rep.proc.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + timeout_s
        for rep in self.replicas:
            if rep.proc is None:
                continue
            try:
                rep.proc.wait(timeout=max(
                    0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                _log.warning("replica %d did not drain in %.0fs — "
                             "SIGKILL", rep.index, timeout_s)
                rep.proc.kill()
                rep.proc.wait(timeout=10.0)
            self._note("stopped", rep.index,
                       f"rc={rep.proc.returncode}")
        self._m["live"].set(0)
