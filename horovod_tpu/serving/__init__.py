"""Serving tier — tensor-parallel inference with continuous batching
(docs/serving.md).

The train→save→serve path:

  - training commits params through the sharded checkpoint engine with
    ``extra=loader.transformer_extra(cfg)`` so the manifest records the
    architecture;
  - :func:`loader.load_params` reshards the ``.npy`` manifest onto the
    inference mesh via span-overlap reads (a ws-4 training checkpoint
    serves on a ws-1/2 mesh);
  - :class:`InferenceEngine` schedules requests with per-decode-step
    admission/eviction over a block-sliced KV cache;
  - :class:`server.ServingServer` fronts it with stdlib HTTP
    (``/generate`` + ``/healthz``), metrics on the existing
    ``HOROVOD_TPU_METRICS_PORT`` registry endpoint.

``python -m horovod_tpu.serving --checkpoint-dir ...`` wires it all up
from the command line (docs/running.md).
"""

from .engine import (DEADLINE_ERROR, DrainingError, InferenceEngine,
                     QueueFullError, Request, ServingConfig)
from .kv_cache import (BlockAllocator, PrefixCache, SessionLeaseTable,
                       blocks_needed, prefix_hashes)
from .loader import (TORCH_MODEL_PREFIX, config_from_manifest,
                     load_params, serving_config, transformer_extra)
from .fleet import Fleet, ReplicaEndpoint
from .qos import (AutoscalerConfig, AutoscalerState, ClassQueues,
                  FleetAutoscaler, QosPolicy, QuotaExceededError,
                  QuotaLedger, TenantQos)
from .router import Router, StaticBackends

__all__ = [
    "AutoscalerConfig", "AutoscalerState", "BlockAllocator",
    "ClassQueues", "DEADLINE_ERROR", "DrainingError", "Fleet",
    "FleetAutoscaler", "InferenceEngine", "PrefixCache",
    "QosPolicy", "QueueFullError", "QuotaExceededError", "QuotaLedger",
    "ReplicaEndpoint", "Request", "Router", "ServingConfig",
    "SessionLeaseTable", "StaticBackends", "TORCH_MODEL_PREFIX",
    "TenantQos", "blocks_needed",
    "config_from_manifest", "load_params", "prefix_hashes",
    "serving_config", "transformer_extra",
]
