"""Continuous-batching inference engine — iteration-level scheduling
over a block-sliced KV cache.

The scheduling unit is one *decode step*, not one batch (Orca's
iteration-level scheduling, OSDI '22): every step the engine

  1. **admits** requests from the bounded queue into free batch slots —
     as many as the KV pool can cover (all-or-nothing block
     reservation, kv_cache.py) — running each one's prefill and
     sampling its first token (TTFT ends here);
  2. runs **one batched decode step** for every live slot through the
     tensor-parallel ``apply_decode`` (models/transformer.py), samples
     one token per slot;
  3. **evicts** finished slots (EOS or max-tokens) immediately, freeing
     their blocks for the next admit.

A request therefore joins and leaves the batch mid-flight of everyone
else's generation — no batch-boundary barrier, which is where the
batched ≥ 2× sequential throughput in BENCH_SERVING.json comes from.

Three raw-speed levers ride on top, each independently switchable
(docs/serving.md#speed-levers, BENCH_SPEED.json):

  - **Quantized KV blocks** (``kv_quant="int8"|"fp8"``): the pool holds
    wire-dtype payload + fp32 channel-block scales (quantization.py's
    absmax format at rest), ~4x the resident sequences per HBM byte;
    dequant happens on read inside the attention program, and prefill
    attends this chunk at full precision so a from-empty prefill is
    bit-identical to the fp32 pool.
  - **Speculative decoding** (``spec_tokens=k`` + a drafter model): a
    small drafter proposes ``k-1`` greedy tokens per step; the flagship
    verifies them in ONE batched ``[slots, k]`` decode program and
    emits the accepted prefix plus its own correction — up to ``k``
    tokens per flagship call, token-identical to non-speculative greedy
    decode. Rollback of a rejected suffix is free: lengths rewind on
    the host and the garbage K/V is overwritten before it is ever
    visible (the next chunk's scatter covers it).
  - **Shared prefix cache** (``prefix_cache=True``): full prompt blocks
    are indexed by chained hash; a matching prefix reuses the resident
    blocks (refcounted, read-only) and prefill runs only over the
    suffix — a fleet-shared system prompt prefills once per replica,
    not once per request.

Compile discipline: there is exactly ONE jitted program per shape
bucket — decode is always ``[slots, 1]`` (one program for the whole
serve), prefill is ``[1, L]`` with L a power-of-two bucket — so
recompiles are bounded by the bucket count, counted in
``hvdtpu_serving_compiles_total``.

Correctness invariant the scheduler edge-tests pin down: per-slot
computation is independent (causal mask + disjoint block tables), so a
request's greedy output does not depend on what else is in flight, and
pool exhaustion can only delay *admission* — live sequences always
hold every block they will ever need.
"""

from __future__ import annotations

import dataclasses
import math
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models import transformer as tfm
from ..observability import flight_recorder as _flight
from ..observability import registry as _obs
from ..utils import env as _env
from ..utils.logging import get_logger
from . import qos as _qos
from . import reqtrace as _rt
from . import slo as _slo
from .kv_cache import (SCRATCH_BLOCK, BlockAllocator, PrefixCache,
                       SessionLeaseTable, blocks_needed, prefix_hashes)

_log = get_logger("serving")


class QueueFullError(RuntimeError):
    """The bounded admission queue is at capacity (HTTP 429)."""


class DrainingError(RuntimeError):
    """The engine is draining (SIGTERM received); no new admissions."""


# Error string a request fails with when its deadline passes before it
# could be served; the HTTP front maps it to 504 (and the router never
# retries an expired request).
DEADLINE_ERROR = "deadline exceeded"


def _metrics():
    r = _obs.registry()
    return {
        "requests": r.counter(
            "hvdtpu_serving_requests_total",
            "Requests by terminal status: completed, rejected (queue "
            "full), failed (draining/validation)"),
        "queue_depth": r.gauge(
            "hvdtpu_serving_queue_depth",
            "Requests waiting for admission").labels(),
        "active": r.gauge(
            "hvdtpu_serving_active_requests",
            "Requests currently holding a batch slot").labels(),
        "occupancy": r.gauge(
            "hvdtpu_serving_batch_occupancy",
            "Fraction of decode batch slots live (the continuous-"
            "batching utilization number)").labels(),
        "kv_total": r.gauge(
            "hvdtpu_serving_kv_blocks_total",
            "Allocatable KV pool blocks (scratch excluded)").labels(),
        "kv_used": r.gauge(
            "hvdtpu_serving_kv_blocks_in_use",
            "KV pool blocks held by live sequences").labels(),
        "tokens": r.counter(
            "hvdtpu_serving_tokens_total",
            "Tokens processed, kind=prompt (prefilled) or "
            "kind=generated"),
        # queue_wait/ttft/tpot are stored as FAMILIES: tenanted
        # requests observe into their {tenant=...} child, untenanted
        # ones into the unlabeled child (_observe_latency), so legacy
        # consumers of the "" series see exactly the pre-tenant shape.
        "queue_wait": r.histogram(
            "hvdtpu_serving_queue_wait_seconds",
            "Submit → admission wait — the queue share of the "
            "per-request latency budget (exemplar: trace id of the "
            "worst recent wait; tenanted requests carry a tenant "
            "label)", buckets=_obs.LATENCY_BUCKETS),
        "ttft": r.histogram(
            "hvdtpu_serving_ttft_seconds",
            "Time to first token: submit → first sampled token "
            "(includes queue wait; exemplar: trace id of the worst "
            "recent request; tenanted requests carry a tenant label)",
            buckets=_obs.LATENCY_BUCKETS),
        "tpot": r.histogram(
            "hvdtpu_serving_tpot_seconds",
            "Time per output token after the first (per live slot per "
            "decode step; tenanted requests carry a tenant label)",
            buckets=_obs.LATENCY_BUCKETS),
        "prefill": r.histogram(
            "hvdtpu_serving_prefill_seconds",
            "Prefill forward duration (per admitted request)",
            buckets=_obs.LATENCY_BUCKETS).labels(),
        "decode_step": r.histogram(
            "hvdtpu_serving_decode_step_seconds",
            "Batched decode step duration (all live slots)",
            buckets=_obs.LATENCY_BUCKETS).labels(),
        "decode_steps": r.counter(
            "hvdtpu_serving_decode_steps_total",
            "Batched decode steps executed"),
        "compiles": r.counter(
            "hvdtpu_serving_compiles_total",
            "Shape buckets compiled, phase=prefill (per length bucket) "
            "or phase=decode (once per serve)"),
        "slots": r.gauge(
            "hvdtpu_serving_batch_slots",
            "Decode batch width (max concurrent generations) — the "
            "denominator the fleet router's load score divides by"
        ).labels(),
        "qps": r.gauge(
            "hvdtpu_serving_requests_per_second",
            "Completed requests per second over the last 10 s").labels(),
        "kv_bytes": r.gauge(
            "hvdtpu_serving_kv_bytes_resident",
            "KV-pool bytes held by live sequences and the prefix "
            "cache (payload + scales, drafter pool included) — the "
            "number the quantized pool divides by ~4").labels(),
        "prefix_hits": r.counter(
            "hvdtpu_serving_prefix_cache_hits_total",
            "Prompt blocks served from the shared prefix cache "
            "(each hit skips block_size prefill positions)"),
        "prefix_misses": r.counter(
            "hvdtpu_serving_prefix_cache_misses_total",
            "Full prompt blocks that had no cached prefix entry"),
        "draft_proposed": r.counter(
            "hvdtpu_serving_draft_proposed_tokens_total",
            "Tokens proposed by the speculative drafter"),
        "draft_accepted": r.counter(
            "hvdtpu_serving_draft_accepted_tokens_total",
            "Drafter tokens accepted by the flagship's batched "
            "verification (acceptance rate = accepted/proposed)"),
        "decode_tick": r.histogram(
            "hvdtpu_serving_decode_tick_seconds",
            "Gap between consecutive batched decode ticks (start to "
            "start) while slots are decoding — the TPOT-tail bound "
            "chunked prefill holds: with interleaving, at most one "
            "prefill chunk fits in a gap, so its p99 tracks the chunk "
            "budget instead of the longest prompt",
            buckets=_obs.LATENCY_BUCKETS).labels(),
        "prefill_chunks": r.counter(
            "hvdtpu_serving_prefill_chunks_total",
            "Prefill chunks executed by the interleaved chunked-"
            "prefill path (monolithic prefills don't count here)"),
        "session_leases": r.counter(
            "hvdtpu_serving_session_leases_total",
            "Session KV leases formed at request completion "
            "(docs/serving.md#session-affinity)"),
        "session_evictions": r.counter(
            "hvdtpu_serving_session_evictions_total",
            "Session leases sacrificed under pool pressure or the "
            "lease-table cap (demoted to the prefix cache when one "
            "is configured)"),
        "session_hits": r.counter(
            "hvdtpu_serving_session_hits_total",
            "Admissions that resumed from a live session lease "
            "(prefill skipped the stored conversation context)"),
        "shed": r.counter(
            "hvdtpu_serving_shed_total",
            "Requests shed by the QoS plane before prefill, by reason "
            "(quota: over the tenant token-rate quota; deadline_pred: "
            "remaining deadline cannot cover predicted prefill + one "
            "decode step) (docs/serving.md#qos)"),
        "class_queue": r.gauge(
            "hvdtpu_serving_class_queue_depth",
            "Queued requests per QoS priority class "
            "(docs/serving.md#qos)"),
        "class_active": r.gauge(
            "hvdtpu_serving_class_active",
            "Batch slots held per QoS priority class "
            "(docs/serving.md#qos)"),
    }


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Scheduler knobs (docs/serving.md)."""

    block_size: int = 16          # tokens per KV block
    kv_blocks: int = 128          # pool size, scratch block included
    max_batch_slots: int = 8      # decode batch width
    max_queue: int = 32           # bounded admission queue (429 past it)
    max_new_tokens: int = 64      # per-request default
    eos_id: Optional[int] = None  # stop token (None: max-tokens only)
    temperature: float = 0.0      # 0 = greedy; >0 = seeded sampling
    seed: int = 0                 # sampling PRNG seed (deterministic)
    max_blocks_per_seq: Optional[int] = None  # table width; None: from
    #                                           the model's max_seq
    min_prefill_bucket: int = 16  # smallest padded prompt length
    # --- speed levers (docs/serving.md#speed-levers) ---
    kv_quant: Optional[str] = None  # "int8"/"fp8": quantized KV pool
    spec_tokens: int = 0          # speculative verify width k (the
    #                               drafter proposes k-1 tokens/step);
    #                               0 = off, requires a drafter model
    spec_adapt: bool = False      # adapt k per slot from the live
    #                               draft-acceptance rate (AIMD,
    #                               autotune.spec_adapt); spec_tokens
    #                               becomes the CAP, and a cold drafter
    #                               backs off to k=1 (plain decode)
    prefix_cache: bool = False    # shared prompt-prefix block cache
    prefix_cache_entries: Optional[int] = None  # LRU cap (None: pool-
    #                                             pressure eviction only
    prefill_chunk: Optional[int] = None  # chunked prefill: cap on the
    #                               per-chunk bucket (rounded to a
    #                               power-of-two bucket); the step loop
    #                               interleaves one chunk per decode
    #                               tick. None = monolithic prefill.
    session_leases: int = 8       # max session KV leases held between
    #                               conversation turns; 0 disables
    #                               session affinity on this replica
    reserved_slots: int = 0       # batch slots only the top QoS
    #                               priority class (interactive) may
    #                               occupy (docs/serving.md#qos);
    #                               0 = no reservation


class Request:
    """One generation request and its lifecycle record.

    ``deadline`` is an absolute ``time.monotonic()`` instant (or None):
    a queued request past it is failed with ``DEADLINE_ERROR`` instead
    of being admitted — the router's per-request deadline propagation
    maps that to HTTP 504 without retry (docs/serving.md#fleet).

    Tokens are observable *incrementally*: the engine notifies
    :meth:`next_tokens` waiters after every appended token, which is
    what the streaming HTTP path (and through it the router's
    mid-stream failover) consumes.
    """

    def __init__(self, rid: int, prompt: Sequence[int],
                 max_new_tokens: int, temperature: float,
                 deadline: Optional[float] = None,
                 trace_id: Optional[str] = None,
                 session_id: Optional[str] = None,
                 tenant: Optional[str] = None,
                 slo: Optional["_slo.SloTargets"] = None):
        self.id = rid
        # One trace id end-to-end (docs/serving.md#request-tracing):
        # the router mints it and ships it via X-Request-Id, so the
        # same id names this request in the router, every replica it
        # touches (failover re-dispatch included), the flight
        # recorder, and the metric exemplars. Locally-submitted
        # requests mint a pid-tagged one.
        self.trace_id = str(trace_id) if trace_id else \
            f"{os.getpid():x}.{rid:x}"
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.deadline = deadline          # absolute monotonic, or None
        self.tokens: List[int] = []       # generated tokens
        self.status = "queued"            # queued|active|completed|failed
        self.error: Optional[str] = None
        self.t_submit = time.perf_counter()
        self.t_submit_m = time.monotonic()   # trace-clock twin
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None
        self.slot: Optional[int] = None
        self.blocks: List[int] = []
        self.cached_tokens = 0    # prompt tokens resident via shared
        #                           prefix blocks or a session lease
        #                           (prefill skips them)
        self.session_id = str(session_id) if session_id else None
        # SLO attribution (docs/serving.md#slo): ``tenant`` is the
        # RESOLVED bounded-cardinality label (slo.resolve_tenant), or
        # None for untenanted requests (legacy metric shape); ``slo``
        # the resolved targets; ``slo_verdict`` is stamped at _finish.
        self.tenant = tenant
        self.slo = slo
        self.slo_verdict: Optional[dict] = None
        # QoS plane (docs/serving.md#qos): admission class, and
        # whether a DEADLINE_ERROR came from the predictive shed
        # (counted under reason="shed") vs an expiry in queue.
        self.qos_class = _qos.DEFAULT_CLASS
        self.shed = False
        self.prefill_pos: Optional[int] = None  # chunked prefill
        #                           cursor: next prompt position to
        #                           prefill; None = not mid-prefill
        self._prefill_s = 0.0     # accumulated chunk prefill seconds
        self._chunks = 0          # prefill chunks run so far
        self._hashes: List[bytes] = []  # prefix hashes pending insert
        self._n_shared = 0        # leading hashes already cached
        self._done = threading.Event()
        self._progress = threading.Condition()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def ttft_s(self) -> Optional[float]:
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_submit

    def _notify(self) -> None:
        """Wake next_tokens() waiters (engine-side, after appending
        tokens or reaching a terminal state)."""
        with self._progress:
            self._progress.notify_all()

    def next_tokens(self, start: int,
                    timeout: Optional[float] = None) -> List[int]:
        """Block until tokens beyond index ``start`` exist (or the
        request is terminal); returns the new slice — empty only once
        terminal. Raises :exc:`TimeoutError` if nothing happens within
        ``timeout``. The consumer side of token streaming."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        with self._progress:
            while len(self.tokens) <= start and not self._done.is_set():
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"request {self.id}: no token progress in "
                        f"{timeout}s")
                self._progress.wait(remaining)
        # list.append is atomic; len() then slice is safe outside the
        # engine lock.
        return self.tokens[start:len(self.tokens)]

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until terminal; the generated tokens, or raises the
        failure."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.id} still running")
        if self.status != "completed":
            raise RuntimeError(
                f"request {self.id} {self.status}: {self.error}")
        return list(self.tokens)


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class InferenceEngine:
    """Tensor-parallel continuous-batching engine over one model.

    ``params`` are the (mesh-sharded) transformer parameters, ``cfg``
    the *serving* variant of the model config (loader.serving_config:
    tp follows the mesh, sp/ep off), ``mesh`` the inference mesh.
    Thread-safe: ``submit`` may be called from any thread (the HTTP
    handlers); ``step`` is the single scheduler entry point, driven by
    one loop thread (or directly by tests and the bench).
    """

    def __init__(self, params: Any, cfg: tfm.TransformerConfig,
                 mesh: jax.sharding.Mesh,
                 config: Optional[ServingConfig] = None,
                 draft_params: Any = None,
                 draft_cfg: Optional[tfm.TransformerConfig] = None):
        if cfg.sp_axis or cfg.ep_axis or cfg.num_experts:
            raise ValueError(
                "serving supports dense tensor-parallel decode only; "
                "build cfg via serving.loader.serving_config()")
        self.cfg = cfg
        self.mesh = mesh
        self.config = config or ServingConfig()
        c = self.config
        bs = int(c.block_size)
        self._m = _metrics()

        from .. import quantization as _q
        self._kv_spec = _q.parse(c.kv_quant)

        if (draft_params is None) != (draft_cfg is None):
            raise ValueError(
                "speculative decoding needs BOTH draft_params and "
                "draft_cfg (a shrunk serving config sharing the vocab)")
        if c.spec_tokens and draft_params is None:
            raise ValueError(
                "spec_tokens is set but no drafter model was given — "
                "pass draft_params/draft_cfg (docs/serving.md)")
        self._draft_params = draft_params
        self._draft_cfg = draft_cfg
        self._spec_k = 0
        if draft_params is not None:
            if draft_cfg.vocab != cfg.vocab:
                raise ValueError(
                    f"drafter vocab ({draft_cfg.vocab}) must equal the "
                    f"flagship's ({cfg.vocab}) — they share the "
                    "tokenizer")
            if draft_cfg.tp_axis != cfg.tp_axis:
                raise ValueError(
                    "drafter and flagship must agree on tp_axis (both "
                    "run under the engine's one mesh)")
            self._spec_k = int(c.spec_tokens) if c.spec_tokens else 4
            if self._spec_k < 2:
                raise ValueError(
                    f"spec_tokens ({self._spec_k}) must be >= 2: the "
                    "verify chunk holds the last token plus at least "
                    "one draft")
        # Per-slot adaptive draft length (docs/autotune.md#serving):
        # spec_tokens is the cap, each slot's effective k follows its
        # own live acceptance rate.
        self._spec_ctl = None
        if c.spec_adapt:
            if draft_params is None:
                raise ValueError(
                    "spec_adapt requires a drafter model (it adapts "
                    "the speculative draft length)")
            from ..autotune.spec_adapt import SpecTokensController
            self._spec_ctl = SpecTokensController(self._spec_k)

        slots = int(c.max_batch_slots)
        if c.reserved_slots < 0 or c.reserved_slots >= slots:
            raise ValueError(
                f"reserved_slots ({c.reserved_slots}) must be in "
                f"[0, max_batch_slots) — reserving every slot would "
                "starve all non-interactive classes")
        max_tab = c.max_blocks_per_seq if c.max_blocks_per_seq \
            else -(-cfg.max_seq // bs)
        self._tab_width = int(max_tab)
        self._slots = slots
        self._alloc = BlockAllocator(c.kv_blocks)
        self._prefix = PrefixCache(self._alloc, c.prefix_cache_entries) \
            if c.prefix_cache else None
        self._sessions = SessionLeaseTable(
            self._alloc, int(c.session_leases)) \
            if c.session_leases else None
        self._m["kv_total"].set(self._alloc.total)
        self._m["slots"].set(slots)

        # Chunked prefill (docs/serving.md#chunked-prefill): the cap is
        # rounded to an existing power-of-two bucket so chunking adds
        # ZERO new compiled shapes; the budget policy below only ever
        # halves within the same bucket family.
        if c.prefill_chunk is not None and int(c.prefill_chunk) < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {c.prefill_chunk}")
        self._chunk_cap = self._bucket(int(c.prefill_chunk)) \
            if c.prefill_chunk else 0
        self._chunk_cost: Dict[int, float] = {}  # bucket -> EWMA secs
        # QoS plane (docs/serving.md#qos): per-class DWRR admission
        # queues, tenant token-rate quotas, and the measured-cost
        # models the predictive shed reads (monolithic prefill EWMA by
        # bucket — the chunked path reuses _chunk_cost — plus a decode
        # step EWMA as the minimum decode budget).
        self._qos = _qos.policy()
        self._quota = _qos.QuotaLedger(self._qos)
        self._prefill_cost: Dict[int, float] = {}  # bucket -> EWMA s
        self._decode_cost = 0.0                    # EWMA secs/step
        budget_ms = _env.serving_tick_budget_ms()
        self._tick_budget_s = None if budget_ms is None \
            else budget_ms / 1e3
        self._t_last_tick: Optional[float] = None

        # Serving fault injection (docs/adaptation.md): slow_decode /
        # slow_prefill / replica_crash_at ride the same declarative spec
        # as the training faults; resolved once, a single `is None`
        # check per step when unset.
        from ..adaptation import faults as _faults
        self._inj = _faults.injector()

        self.params = params
        self._cache = self._put_cache(
            tfm.init_cache(cfg, c.kv_blocks, bs, self._kv_spec), cfg)
        self._bytes_per_block = tfm.kv_bytes_per_block(
            cfg, bs, self._kv_spec)
        if draft_params is not None:
            self._draft_cache = self._put_cache(
                tfm.init_cache(draft_cfg, c.kv_blocks, bs,
                               self._kv_spec), draft_cfg)
            self._bytes_per_block += tfm.kv_bytes_per_block(
                draft_cfg, bs, self._kv_spec)

        # host mirrors of the device-side scheduling state
        self._tables = np.full((slots, self._tab_width), SCRATCH_BLOCK,
                               np.int32)
        self._lengths = np.zeros((slots,), np.int32)    # cached tokens
        self._last_tok = np.zeros((slots,), np.int32)   # next input
        self._reqs: List[Optional[Request]] = [None] * slots

        self._queue = _qos.ClassQueues(self._qos.class_weights())
        self._lock = threading.RLock()
        self._work = threading.Condition(self._lock)
        self._draining = False
        self._next_id = 0
        self._rng = np.random.default_rng(c.seed)
        self._completions: deque = deque()  # perf_counter stamps

        self._fwd = self._build_fwd(cfg, exact_chunk=False)
        # Prefill reads this chunk at full precision (prefill-exact
        # parity with the fp32 pool); without quantization the trace is
        # identical, so the decode program is simply reused.
        self._fwd_prefill = self._build_fwd(cfg, exact_chunk=True) \
            if self._kv_spec is not None else self._fwd
        if draft_params is not None:
            self._dfwd = self._build_fwd(draft_cfg, exact_chunk=False)
            self._dfwd_prefill = self._build_fwd(
                draft_cfg, exact_chunk=True) \
                if self._kv_spec is not None else self._dfwd
        self._buckets_seen: set = set()

    # ------------------------------------------------------- submission

    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: Optional[int] = None,
               temperature: Optional[float] = None,
               deadline_s: Optional[float] = None,
               trace_id: Optional[str] = None,
               session_id: Optional[str] = None,
               tenant: Optional[str] = None,
               slo=None) -> Request:
        """Enqueue a request; returns immediately with its ticket.
        Raises :exc:`QueueFullError` past ``max_queue`` (the HTTP 429
        path) and :exc:`DrainingError` after drain began.

        ``deadline_s`` is a *relative* budget in seconds (the router
        propagates the client's remaining deadline per hop): a request
        still queued when it expires fails with ``DEADLINE_ERROR``
        instead of occupying a slot. ``trace_id`` is the caller's
        end-to-end request identity (the router's ``X-Request-Id``);
        None mints a local one. ``session_id`` names a conversation
        (docs/serving.md#session-affinity): completion stores a KV
        lease under it, and a later turn whose prompt extends the
        stored context resumes decoding instead of re-prefilling.

        ``tenant``/``slo`` attach SLO attribution
        (docs/serving.md#slo): the tenant name is collapsed to a
        bounded-cardinality label, the targets resolve request-field >
        tenant config > env defaults, and the completed request is
        stamped with a ``slo_verdict``."""
        c = self.config
        # Resolve SLO attribution before validation: a shed (queue
        # full) request must still be attributable to its tenant.
        tlabel = _slo.resolve_tenant(tenant) if (tenant or slo) \
            else None
        targets = _slo.policy().resolve(tenant, slo)
        if targets is not None and tlabel is None:
            tlabel = _slo.resolve_tenant(tenant)
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else c.max_new_tokens)
        temp = float(temperature if temperature is not None
                     else c.temperature)
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if any(t < 0 or t >= self.cfg.vocab for t in prompt):
            raise ValueError(f"prompt token out of range "
                             f"[0, {self.cfg.vocab})")
        if len(prompt) + max_new > self.cfg.max_seq:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new}) "
                f"exceeds the model's max_seq ({self.cfg.max_seq})")
        need = blocks_needed(len(prompt), max_new, c.block_size)
        if need > min(self._alloc.total, self._tab_width):
            raise ValueError(
                f"request needs {need} KV blocks but the pool holds "
                f"{self._alloc.total} (table width {self._tab_width}) "
                "— raise kv_blocks or lower max_new_tokens")
        with self._lock:
            if self._draining:
                raise DrainingError("server is draining")
            if len(self._queue) >= c.max_queue:
                self._count_request("rejected", tlabel)
                # Shed load stays visible in goodput math
                # (docs/serving.md#slo): the 429 path attributes the
                # rejection to its tenant.
                _slo.record_shed(tlabel or _slo.DEFAULT_TENANT, "shed")
                raise QueueFullError(
                    f"admission queue full ({c.max_queue})")
            # Token-rate quota (docs/serving.md#qos): charged AFTER
            # the queue-full gate so a rejected request never burns
            # bucket tokens. Retry-After comes from the tenant's own
            # measured drain rate, not the global queue estimate.
            retry = self._quota.admit(
                tlabel, len(prompt) + max_new) if tlabel else None
            if retry is not None:
                self._count_request("rejected", tlabel)
                _slo.record_shed(tlabel or _slo.DEFAULT_TENANT, "shed")
                self._m["shed"].labels(reason="quota").inc()
                raise _qos.QuotaExceededError(retry, tenant=tlabel)
            deadline = None if deadline_s is None \
                else time.monotonic() + float(deadline_s)
            req = Request(self._next_id, prompt, max_new, temp,
                          deadline=deadline, trace_id=trace_id,
                          session_id=session_id, tenant=tlabel,
                          slo=targets)
            req.qos_class = self._qos.class_of(tlabel)
            self._next_id += 1
            self._queue.append(req)
            self._m["queue_depth"].set(len(self._queue))
            self._work.notify()
            return req

    def generate(self, prompt: Sequence[int], *,
                 max_new_tokens: Optional[int] = None,
                 temperature: Optional[float] = None) -> List[int]:
        """Synchronous single-request convenience: submit + drive the
        scheduler until THIS request finishes (single-threaded use;
        under a running serve loop, use submit().result())."""
        req = self.submit(prompt, max_new_tokens=max_new_tokens,
                          temperature=temperature)
        while not req.done:
            if not self.step():
                time.sleep(0.001)
        return req.result()

    # -------------------------------------------------------- scheduler

    @property
    def active_count(self) -> int:
        return sum(1 for r in self._reqs if r is not None)

    @property
    def _decodable_count(self) -> int:
        """Live slots past prefill — the batched decode's real width
        (mid-chunked-prefill slots are masked out of decode calls)."""
        return sum(1 for r in self._reqs
                   if r is not None and r.prefill_pos is None)

    def session_ids(self) -> List[str]:
        """Live session-lease ids (LRU-oldest first) — advertised via
        ``/healthz`` so the fleet router can pin leased sessions."""
        with self._lock:
            if self._sessions is None:
                return []
            return self._sessions.ids()

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def idle(self) -> bool:
        with self._lock:
            return self.active_count == 0 and not self._queue

    def retry_after_s(self) -> int:
        """Back-off hint for a 429: how long until the bounded queue
        has plausibly drained, from the measured completion rate (the
        same 10 s window behind ``hvdtpu_serving_requests_per_second``)
        plus — under chunked prefill — the prefill backlog itself.
        With interleaving, drain is paced by chunks-per-tick, not whole
        prefills: a queue of long prompts admits fast but takes
        ``pending_chunks × per-chunk seconds`` to actually prefill, so
        that term is added on top of the completion-rate estimate.
        Clamped to [1, 60] whole seconds — a cold server (no completions
        yet, no chunk backlog) answers 1 rather than guessing."""
        with self._lock:
            depth = len(self._queue) + self.active_count
            rate = len(self._completions) / 10.0
            chunk_s = self._chunk_backlog_s()
        if rate <= 0.0:
            if chunk_s > 0.0:
                return max(1, min(60, math.ceil(chunk_s)))
            return 1
        return max(1, min(60, math.ceil(depth / rate + chunk_s)))

    def _chunk_backlog_s(self) -> float:
        """Estimated seconds of interleaved prefill work outstanding:
        chunks still owed by mid-prefill slots plus chunks the queued
        prompts will need, priced at the measured per-chunk cost (the
        cap bucket's EWMA; the worst measured bucket as fallback).
        0 when chunking is off or nothing is pending — callers under
        the engine lock."""
        cap = self._chunk_cap
        if not cap:
            return 0.0
        cost = self._chunk_cost.get(cap)
        if cost is None:
            cost = max(self._chunk_cost.values(), default=0.0)
        if cost <= 0.0:
            return 0.0
        chunks = 0
        for r in self._reqs:
            if r is not None and r.prefill_pos is not None:
                chunks += -(-(len(r.prompt) - r.prefill_pos) // cap)
        for r in self._queue:
            chunks += -(-len(r.prompt) // cap)
        return chunks * cost

    def step(self) -> bool:
        """One scheduler iteration: admit → at most ONE prefill chunk →
        batched decode → evict. Returns True when any work was done.

        The single-chunk rule is the tentpole latency bound: a long
        prompt's prefill is spread across ticks instead of running
        start-to-finish between two decode steps, so the decode-tick
        gap every live slot experiences is bounded by one chunk (the
        budget policy sizes it under
        ``HOROVOD_TPU_SERVING_TICK_BUDGET_MS``), not by the longest
        prompt in the mix."""
        with self._lock:
            if self._inj is not None:
                for plen in self._inj.take_long_prompt_bursts():
                    self._inject_long_prompt(plen)
            admitted = self._admit()
            worked = admitted > 0
            if self._prefill_tick():
                worked = True
            if self._decodable_count:
                self._decode_step()
                worked = True
            else:
                # No decode ran: a gap across an idle stretch is not a
                # tick the histogram should count.
                self._t_last_tick = None
            self._update_gauges()
            return worked

    def wait_for_work(self, timeout: float) -> None:
        """Serve-loop parking: block until a submit arrives (or
        timeout) instead of spinning on an idle engine."""
        with self._work:
            if self.idle and not self._draining:
                self._work.wait(timeout)

    def run_until_idle(self, max_steps: int = 100000) -> None:
        for _ in range(max_steps):
            if self.idle:
                return
            self.step()
        raise RuntimeError("run_until_idle: scheduler did not converge")

    def drain(self) -> None:
        """Graceful shutdown: refuse NEW submissions, then finish every
        request already accepted — live slots decode to completion AND
        queued requests are still admitted as slots/blocks free up.

        An accepted request is a promise (its client got past the
        429/503 gate); whether the scheduler thread happened to admit it
        before SIGTERM landed must not decide its fate — the old
        fail-the-queue behavior made drain outcomes race the prefill
        phase (the regression test injects a slow_prefill fault to pin
        the window open). Zero requests dropped by a drain is the fleet
        tier's base invariant (docs/serving.md#fleet)."""
        with self._lock:
            self._draining = True
            waiting = self.active_count + len(self._queue)
        _flight.recorder().note("serving", ("drain", waiting))
        while True:
            with self._lock:
                self._admit()
                if self.active_count == 0 and not self._queue:
                    self._update_gauges()
                    break
                self._prefill_tick()
                if self._decodable_count:
                    self._decode_step()
                self._update_gauges()
        _flight.recorder().note("serving", ("drained", 0))

    # -------------------------------------------------------- internals

    def _build_fwd(self, cfg: tfm.TransformerConfig, exact_chunk: bool):
        specs = tfm.param_specs(cfg)
        cspecs = tfm.cache_specs(cfg, self._kv_spec)
        kvq = self._kv_spec
        fwd = jax.shard_map(
            lambda p, kv, t, s, bt: tfm.apply_decode(
                p, t, s, bt, kv, cfg, kv_quant=kvq,
                exact_chunk=exact_chunk),
            mesh=self.mesh, in_specs=(specs, cspecs, P(), P(), P()),
            out_specs=(P(), cspecs), check_vma=False)
        donate = () if jax.default_backend() == "cpu" else (1,)
        return jax.jit(fwd, donate_argnums=donate)

    def _put_cache(self, cache, cfg: tfm.TransformerConfig):
        cspecs = tfm.cache_specs(cfg, self._kv_spec)
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
            cache, cspecs, is_leaf=lambda x: isinstance(x, P))

    def _admit(self) -> int:
        """Move queued requests into free slots while the pool covers
        them, running each prefill immediately (this is the per-step
        admission that makes the batching *continuous*).

        Selection is deficit-weighted round robin over the per-class
        queues (docs/serving.md#qos), with ``reserved_slots`` batch
        slots only the top priority class may occupy, and a predictive
        shed at each class head: a deadline that cannot cover the
        measured prefill cost plus one decode step fails NOW (504)
        instead of burning a slot on an answer that would miss
        anyway."""
        admitted = 0
        c = self.config
        while True:
            now = time.monotonic()
            doomed = None
            for r in self._queue.heads():
                if r.deadline is None:
                    continue
                if now > r.deadline:
                    # Expired while queued: fail instead of burning a
                    # slot on an answer nobody waits for (HTTP 504).
                    doomed = r
                    break
                if _qos.shed_decision(r.deadline - now,
                                      self._predict_prefill_s(r),
                                      self._decode_cost):
                    r.shed = True
                    doomed = r
                    break
            if doomed is not None:
                self._queue.remove(doomed)
                if doomed.shed:
                    self._m["shed"].labels(
                        reason="deadline_pred").inc()
                    _flight.recorder().note(
                        "qos", ("shed", doomed.trace_id,
                                f"class={doomed.qos_class}"))
                self._finish(doomed, "failed", error=DEADLINE_ERROR)
                continue
            if not self._queue:
                break
            slot = next((i for i, r in enumerate(self._reqs)
                         if r is None), None)
            if slot is None:
                break
            # Reserved-slot invariant: non-top classes may never hold
            # more than max_batch_slots - reserved_slots slots, so a
            # full bulk backlog still leaves room for interactive.
            non_top = sum(1 for r in self._reqs if r is not None
                          and r.qos_class != _qos.TOP_CLASS)
            cap = self._slots - c.reserved_slots

            def allowed(cls, _non_top=non_top, _cap=cap):
                return cls == _qos.TOP_CLASS or _non_top < _cap

            req = self._queue.select(allowed)
            if req is None:
                break   # only reservation-blocked classes are queued
            bs = self.config.block_size
            need = blocks_needed(len(req.prompt), req.max_new_tokens,
                                 bs)
            # Session-lease probe (docs/serving.md#session-affinity):
            # a prompt that EXTENDS its session's stored conversation
            # resumes from the lease's resident blocks — the whole
            # previous context (generated tokens included, which the
            # prefix cache never indexes) skips prefill. A divergent
            # turn releases the stale lease instead: partial reuse
            # could rewrite blocks the prefix cache shares.
            lease = None
            if self._sessions is not None and req.session_id:
                peek = self._sessions.get(req.session_id)
                if peek is not None:
                    ln = peek.n_tokens
                    if len(req.prompt) >= ln \
                            and req.prompt[:ln] == peek.tokens:
                        lease = self._sessions.pop(req.session_id)
                        self._m["session_hits"].inc()
                    else:
                        self._sessions.release(
                            self._sessions.pop(req.session_id))
            lease_blocks = lease.blocks if lease is not None else []
            # Resume must re-run at least one prompt token (its forward
            # produces the first-token logits), so the cached cursor
            # stops one short of a prompt that matches end-to-end.
            lease_tokens = 0 if lease is None \
                else min(lease.n_tokens, len(req.prompt) - 1)
            # Prefix-cache probe: matching leading FULL prompt blocks
            # are shared (incref'd, read-only) instead of re-prefilled.
            # Skipped on a lease hit — the lease already covers more.
            hashes: List[bytes] = []
            shared: List[int] = []
            if lease is None and self._prefix is not None:
                hashes = prefix_hashes(req.prompt, bs)
                shared = self._prefix.lookup(hashes)
            fresh = self._alloc.alloc(
                need - len(shared) - len(lease_blocks))
            while fresh is None and self._free_pressure():
                # Pool pressure: cached-but-idle prefix blocks and then
                # parked session leases yield to a live admission.
                fresh = self._alloc.alloc(
                    need - len(shared) - len(lease_blocks))
            if fresh is None:
                for b in shared:       # roll the probe's holds back
                    self._alloc.decref(b)
                if lease is not None:  # park the consumed lease again
                    self._sessions.put(req.session_id, lease.tokens,
                                       lease.blocks)
                self._queue.pushback(req)  # DWRR deficit refunded
                break    # pool exhausted: nothing admits, nothing evicts
            if self._prefix is not None and lease is None:
                self._m["prefix_hits"].inc(len(shared))
                self._m["prefix_misses"].inc(len(hashes) - len(shared))
            t_admit_m = time.monotonic()
            self._observe_latency(
                "queue_wait", time.perf_counter() - req.t_submit,
                tenant=req.tenant, exemplar=req.trace_id)
            req.blocks = lease_blocks + shared + fresh
            req.cached_tokens = lease_tokens if lease is not None \
                else len(shared) * bs
            req._hashes = hashes
            req._n_shared = len(shared)
            req.slot = slot
            req.status = "active"
            self._reqs[slot] = req
            self._tables[slot, :] = SCRATCH_BLOCK
            self._tables[slot, :need] = req.blocks
            _flight.recorder().note(
                "request", ("admit", req.trace_id,
                            f"slot={slot} blocks={need} "
                            f"cached={req.cached_tokens}"))
            w = _rt.writer()
            if w is not None:
                w.request_span(req.trace_id, "QUEUE_WAIT",
                               req.t_submit_m, t_admit_m)
                w.request_span(req.trace_id, "ADMIT", t_admit_m,
                               time.monotonic(),
                               {"blocks": need,
                                "prefix_tokens": req.cached_tokens})
            if self._chunk_cap:
                # Chunked prefill: admission only reserves; the chunks
                # run one per tick from _prefill_tick, interleaved with
                # everyone else's decode.
                req.prefill_pos = req.cached_tokens
            else:
                self._prefill(req)
            admitted += 1
        self._m["queue_depth"].set(len(self._queue))
        return admitted

    def _bucket(self, n: int) -> int:
        b = max(self.config.min_prefill_bucket, _next_pow2(n))
        return min(b, self.cfg.max_seq)

    def _predict_prefill_s(self, req: Request) -> float:
        """Predicted prefill seconds for a queued request, from the
        measured per-bucket EWMA of whichever prefill path this engine
        runs (docs/serving.md#qos). 0.0 until the model warms up —
        the predictive shed never fires on a guess."""
        n = len(req.prompt)
        if self._chunk_cap:
            return _qos.predict_prefill_s(
                n, self._chunk_cost, self._bucket,
                chunk_tokens=self._chunk_cap)
        return _qos.predict_prefill_s(
            n, self._prefill_cost, self._bucket)

    def _record_bucket(self, phase: str, key) -> None:
        if (phase, key) not in self._buckets_seen:
            self._buckets_seen.add((phase, key))
            self._m["compiles"].labels(phase=phase).inc()

    def _free_pressure(self) -> bool:
        """Reclaim one cached-but-idle resource under pool pressure:
        prefix-cache entries first (cheapest to lose — one block each),
        then whole session leases, LRU first, demoted to the prefix
        cache as the degraded tier. True while something yielded."""
        if self._prefix is not None and self._prefix.evict_one():
            return True
        if self._sessions is not None and self._sessions.evict_one(
                self._prefix, self.config.block_size):
            self._m["session_evictions"].inc()
            return True
        return False

    def _run_prefill(self, req: Request, start: int, ns: int,
                     L: int) -> Any:
        """One prefill forward over ``prompt[start:start+ns]`` padded
        to bucket ``L`` — the shared core of monolithic and chunked
        prefill. The drafter (when present) prefills the same chunk on
        its own pool, same tables, same positions. Returns the
        flagship logits (``[1, L, vocab]``; row ``ns-1`` is the
        distribution after the last real token)."""
        self._record_bucket("prefill", L)
        toks = np.zeros((1, L), np.int32)
        toks[0, :ns] = req.prompt[start:start + ns]
        starts = jnp.full((1,), start, jnp.int32)
        tabs = jnp.asarray(self._tables[req.slot:req.slot + 1])
        logits, self._cache = self._fwd_prefill(
            self.params, self._cache, jnp.asarray(toks), starts, tabs)
        if self._draft_params is not None:
            # The drafter's pool shares the block tables, so its prefix
            # blocks are shared by the same admission decision.
            self._record_bucket("draft_prefill", L)
            _, self._draft_cache = self._dfwd_prefill(
                self._draft_params, self._draft_cache,
                jnp.asarray(toks), starts, tabs)
        self._m["tokens"].labels(kind="prompt").inc(ns)
        return logits

    def _emit_first_token(self, req: Request,
                          logits_row: np.ndarray) -> None:
        """Sample the first token from the final prefill logits row —
        TTFT ends here for both prefill shapes."""
        first = self._sample(logits_row, req)
        req.t_first_token = time.perf_counter()
        req.tokens.append(first)
        req._notify()
        self._last_tok[req.slot] = first
        self._observe_latency("ttft", req.t_first_token - req.t_submit,
                              tenant=req.tenant, exemplar=req.trace_id)
        self._m["tokens"].labels(kind="generated").inc()
        _flight.recorder().note(
            "request", ("first_token", req.trace_id,
                        f"ttft_ms={round((req.t_first_token - req.t_submit) * 1e3, 1)}"))

    def _index_prefix(self, req: Request) -> None:
        """Index this prompt's freshly-prefilled full blocks so the
        NEXT matching prompt shares them (first writer wins). Runs
        right after the last prefill forward — before _check_finished
        can evict the slot and hand the blocks back."""
        if self._prefix is None or not req._hashes:
            return
        for j in range(req._n_shared, len(req._hashes)):
            self._prefix.insert(req._hashes[j],
                                int(self._tables[req.slot, j]))
        req._hashes = []

    def _prefill(self, req: Request) -> None:
        """Monolithic prefill: the whole prompt suffix in one bucketed
        forward at admission (the chunking-off path)."""
        # Span epoch BEFORE the fault hook: an injected slow_prefill is
        # latency the request experienced — it must land INSIDE the
        # PREFILL span, or the budget report under-attributes.
        t0m = time.monotonic()
        if self._inj is not None:
            self._inj.on_serving_prefill()
        t0 = time.perf_counter()
        n = len(req.prompt)
        c = req.cached_tokens   # resident via prefix blocks or a lease
        ns = n - c
        L = self._bucket(ns)
        compile_new = ("prefill", L) not in self._buckets_seen
        logits = self._run_prefill(req, c, ns, L)
        self._lengths[req.slot] = n
        dt = time.perf_counter() - t0
        self._m["prefill"].observe(dt)
        if not compile_new:
            # Steady-state per-bucket cost for the predictive shed
            # (first-run compile time is not prefill cost).
            prev = self._prefill_cost.get(L)
            self._prefill_cost[L] = dt if prev is None \
                else 0.5 * prev + 0.5 * dt
        self._emit_first_token(req, np.asarray(logits[0, ns - 1]))
        w = _rt.writer()
        if w is not None:
            w.request_span(req.trace_id, "PREFILL", t0m,
                           time.monotonic(),
                           {"bucket": L, "tokens": ns, "cached": c,
                            "compile": compile_new})
        self._index_prefix(req)
        self._check_finished(req)

    def _chunk_len(self, remaining: int) -> int:
        """Budget policy: the next chunk's bucket. Start from the
        configured cap (or what's left of the prompt, if smaller) and
        halve while the bucket's measured cost exceeds the tick budget
        — never below the engine's smallest prefill bucket, and only
        through buckets the engine would compile anyway. Unmeasured
        buckets run optimistically (their first timed run seeds the
        cost model)."""
        L = self._bucket(min(remaining, self._chunk_cap))
        floor = self._bucket(1)
        if self._tick_budget_s is not None:
            while L > floor:
                cost = self._chunk_cost.get(L)
                if cost is None or cost <= self._tick_budget_s:
                    break
                L //= 2
            L = max(L, floor)
        return L

    def _note_chunk_cost(self, L: int, dt: float) -> None:
        prev = self._chunk_cost.get(L)
        self._chunk_cost[L] = dt if prev is None \
            else 0.5 * prev + 0.5 * dt

    def _prefill_tick(self) -> bool:
        """Run at most ONE prefill chunk — the oldest mid-prefill
        request's next chunk — between decode ticks. Returns True when
        a chunk ran. The final chunk flips the request live: lengths
        advance, the first token is sampled from its logits, and the
        next decode tick picks the slot up."""
        pending = [r for r in self._reqs
                   if r is not None and r.prefill_pos is not None]
        if not pending:
            return False
        req = min(pending, key=lambda r: r.id)
        t0m = time.monotonic()
        if self._inj is not None:
            self._inj.on_serving_prefill()
        t0 = time.perf_counter()
        n = len(req.prompt)
        pos = req.prefill_pos
        remaining = n - pos
        L = self._chunk_len(remaining)
        ns = min(remaining, L)
        compile_new = ("prefill", L) not in self._buckets_seen
        logits = self._run_prefill(req, pos, ns, L)
        dt = time.perf_counter() - t0
        if not compile_new:
            # First-run compile time is not steady-state chunk cost.
            self._note_chunk_cost(L, dt)
        req._prefill_s += dt
        req._chunks += 1
        req.prefill_pos = pos + ns
        self._m["prefill_chunks"].inc()
        w = _rt.writer()
        if w is not None:
            w.request_span(req.trace_id, "PREFILL", t0m,
                           time.monotonic(),
                           {"bucket": L, "tokens": ns, "cached": pos,
                            "compile": compile_new,
                            "chunk": req._chunks})
        if req.prefill_pos >= n:
            req.prefill_pos = None
            self._lengths[req.slot] = n
            self._m["prefill"].observe(req._prefill_s)
            self._emit_first_token(req, np.asarray(logits[0, ns - 1]))
            self._index_prefix(req)
            self._check_finished(req)
        return True

    def _inject_long_prompt(self, plen: int) -> None:
        """A ``long_prompt_burst`` fault's synthetic request:
        deterministic oversized prompt, clamped to what this model can
        hold, submitted through the ordinary admission gate (a full
        queue drops it with a warning — the burst is adversarial load,
        not a correctness obligation)."""
        vocab = self.cfg.vocab
        plen = max(1, min(int(plen), self.cfg.max_seq - 1))
        max_new = max(1, min(int(self.config.max_new_tokens),
                             self.cfg.max_seq - plen))
        prompt = [(7 + 13 * i) % vocab for i in range(plen)]
        try:
            self.submit(prompt, max_new_tokens=max_new,
                        trace_id=f"fault.burst.{self._next_id:x}")
        except (QueueFullError, DrainingError, ValueError) as e:
            _log.warning("long_prompt_burst request dropped: %s", e)

    def _decode_views(self) -> Tuple[np.ndarray, np.ndarray]:
        """Block tables / lengths for a batched decode call. Slots
        still mid-chunked-prefill are masked to the empty-slot shape
        (scratch table, length 0): a decode forward over them would
        scatter garbage K/V into their REAL blocks at the positions
        the remaining chunks are about to write."""
        if not any(r is not None and r.prefill_pos is not None
                   for r in self._reqs):
            return self._tables, self._lengths
        tabs = self._tables.copy()
        lens = self._lengths.copy()
        for s, r in enumerate(self._reqs):
            if r is not None and r.prefill_pos is not None:
                tabs[s, :] = SCRATCH_BLOCK
                lens[s] = 0
        return tabs, lens

    def _decode_step(self) -> None:
        # Tick-gap histogram: start-to-start of consecutive batched
        # decode ticks — an interleaved prefill chunk lands inside one
        # gap, which is exactly the tail this PR bounds.
        now = time.perf_counter()
        if self._t_last_tick is not None:
            self._m["decode_tick"].observe(now - self._t_last_tick)
        self._t_last_tick = now
        tabs_h, lens_h = self._decode_views()
        if self._draft_params is not None:
            ctl = self._spec_ctl
            if ctl is None:
                self._spec_decode_step()
                return
            live = [s for s, r in enumerate(self._reqs)
                    if r is not None and r.prefill_pos is None]
            width = ctl.width(live) if live else 1
            if width > 1:
                # Verify at the widest live slot's k; narrower slots
                # cap their accepted run at their own k_eff below.
                self._spec_decode_step(width)
                return
            # Every live slot backed off to k=1: take the plain decode
            # path (no verify-width tax), but keep the drafter's KV
            # cache in step with the true context — one cheap [slots,1]
            # drafter call — so a probe step's proposals are grounded,
            # and tick each slot's probe clock.
            self._record_bucket("draft", 1)
            _, self._draft_cache = self._dfwd(
                self._draft_params, self._draft_cache,
                jnp.asarray(self._last_tok[:, None]),
                jnp.asarray(lens_h),
                jnp.asarray(tabs_h))
            for s in live:
                ctl.note_plain_step(s)
        t0m = time.monotonic()   # before the fault hook (slow_decode
        #                          belongs inside the DECODE span)
        if self._inj is not None:
            self._inj.on_serving_decode()
        t0 = time.perf_counter()
        decode_warm = ("decode", self._slots) in self._buckets_seen
        self._record_bucket("decode", self._slots)
        logits, self._cache = self._fwd(
            self.params, self._cache,
            jnp.asarray(self._last_tok[:, None]),
            jnp.asarray(lens_h),
            jnp.asarray(tabs_h))
        lg = np.asarray(logits[:, 0])
        dt = time.perf_counter() - t0
        self._m["decode_step"].observe(dt)
        self._m["decode_steps"].inc()
        if decode_warm:
            # Minimum decode budget for the predictive shed
            # (docs/serving.md#qos); compile runs excluded.
            self._decode_cost = dt if self._decode_cost <= 0.0 \
                else 0.5 * self._decode_cost + 0.5 * dt
        w = _rt.writer()
        for slot, req in enumerate(self._reqs):
            if req is None or req.prefill_pos is not None:
                continue
            # the input token's K/V is cached now; its position is used
            self._lengths[slot] += 1
            tok = self._sample(lg[slot], req)
            req.tokens.append(tok)
            req._notify()
            self._last_tok[slot] = tok
            self._observe_latency("tpot", dt, tenant=req.tenant,
                                  exemplar=req.trace_id)
            self._m["tokens"].labels(kind="generated").inc()
            if w is not None:
                # The step wall as THIS request experienced it — the
                # decode share of its latency budget.
                w.request_span(req.trace_id, "DECODE", t0m,
                               time.monotonic(), {"n": 1})
            self._check_finished(req)

    def _spec_decode_step(self, k: Optional[int] = None) -> None:
        """Speculative decode step: the drafter proposes ``k-1`` greedy
        tokens per slot (k-1 cheap ``[slots, 1]`` calls on its own
        cache), the flagship verifies them in ONE batched ``[slots, k]``
        program, and each slot advances by its accepted prefix plus the
        flagship's correction token — between 1 and k tokens per
        flagship call, greedy output token-identical to the
        non-speculative path (the emitted tokens ARE the flagship's
        argmaxes under the true prefix).

        Rollback of a rejected suffix is host-side only: ``_lengths``
        simply doesn't advance over it. The garbage K/V those positions
        hold is overwritten by the next chunk's scatter before any
        query can see it (chunks are a constant k wide and start where
        the accepted prefix ended, so the rewritten span always covers
        the stale one; with spec_adapt the width can shrink between
        steps, which is equally safe — each chunk writes contiguously
        from the current length, and causal queries never read past
        their own chunk).

        With spec_adapt, ``k`` is the widest live slot's adaptive
        width; each slot caps its ACCEPTED run at its own k_eff and
        feeds its raw (uncapped) acceptance back to the controller."""
        t0m = time.monotonic()   # before the fault hook, like
        #                          _decode_step
        if self._inj is not None:
            self._inj.on_serving_decode()
        t0 = time.perf_counter()
        if k is None:
            k = self._spec_k
        ctl = self._spec_ctl
        n_live = self._decodable_count
        tabs_h, lens_h = self._decode_views()
        tabs = jnp.asarray(tabs_h)

        # Drafter proposals: greedy chain on the drafter's own pool,
        # same block tables, same positions.
        d_len = lens_h.copy()
        cur = self._last_tok.copy()
        proposals = np.zeros((self._slots, k - 1), np.int32)
        for i in range(k - 1):
            self._record_bucket("draft", 1)
            dlg, self._draft_cache = self._dfwd(
                self._draft_params, self._draft_cache,
                jnp.asarray(cur[:, None]), jnp.asarray(d_len), tabs)
            cur = np.argmax(np.asarray(dlg[:, 0]), axis=-1) \
                .astype(np.int32)
            proposals[:, i] = cur
            d_len += 1
        self._m["draft_proposed"].inc((k - 1) * n_live)

        # One batched verification: feed [last_tok, d_1..d_{k-1}]; row
        # i of the logits is the flagship's next-token distribution
        # after the first i+1 of those inputs.
        feed = np.concatenate([self._last_tok[:, None], proposals],
                              axis=1)
        self._record_bucket("decode", (self._slots, k))
        logits, self._cache = self._fwd(
            self.params, self._cache, jnp.asarray(feed),
            jnp.asarray(lens_h), tabs)
        lg = np.asarray(logits)           # [slots, k, vocab]
        greedy = lg.argmax(axis=-1)       # [slots, k]
        dt = time.perf_counter() - t0
        self._m["decode_step"].observe(dt)
        self._m["decode_steps"].inc()

        w = _rt.writer()
        for slot, req in enumerate(self._reqs):
            if req is None or req.prefill_pos is not None:
                continue
            if req.temperature > 0.0:
                # Sampled slots take one token from the true next-token
                # logits (row 0) — the exact non-speculative
                # distribution; drafts are ignored rather than biased.
                emit = [self._sample(lg[slot, 0], req)]
                accepted = 0
            else:
                d = proposals[slot]
                g = greedy[slot]
                raw = 0
                while raw < k - 1 and d[raw] == g[raw]:
                    raw += 1
                accepted = raw
                if ctl is not None:
                    # Cap the accepted run at THIS slot's adaptive k
                    # (still token-identical: every emitted token is
                    # the flagship's argmax under the true prefix),
                    # but feed the controller the raw acceptance so a
                    # recovered drafter can climb back without a probe.
                    accepted = min(raw, max(ctl.slot_k(slot) - 1, 0))
                    ctl.observe(slot, k - 1, raw)
                emit = [int(t) for t in g[:accepted + 1]]
            self._m["draft_accepted"].inc(accepted)
            # Truncate to the request's remaining budget / EOS — any
            # truncation below finishes the request, so the cache-
            # validity induction only ever continues on full chunks.
            emit = emit[:req.max_new_tokens - len(req.tokens)]
            eos = self.config.eos_id
            if eos is not None and eos in emit:
                emit = emit[:emit.index(eos) + 1]
            self._lengths[slot] += len(emit)
            self._last_tok[slot] = emit[-1]
            for tok in emit:
                req.tokens.append(int(tok))
                self._observe_latency("tpot", dt, tenant=req.tenant,
                                      exemplar=req.trace_id)
                self._m["tokens"].labels(kind="generated").inc()
            req._notify()
            if w is not None:
                w.request_span(req.trace_id, "DECODE", t0m,
                               time.monotonic(),
                               {"n": len(emit), "proposed": k - 1,
                                "accepted": accepted})
            self._check_finished(req)

    def _sample(self, logits: np.ndarray, req: Request) -> int:
        if req.temperature <= 0.0:
            return int(np.argmax(logits))
        x = logits.astype(np.float64) / req.temperature
        x -= x.max()
        p = np.exp(x)
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))

    def _check_finished(self, req: Request) -> None:
        eos = self.config.eos_id
        if (eos is not None and req.tokens
                and req.tokens[-1] == eos) \
                or len(req.tokens) >= req.max_new_tokens:
            self._evict(req, "completed")

    def _evict(self, req: Request, status: str,
               error: Optional[str] = None) -> None:
        """Free the slot mid-stream — the rest of the batch keeps
        decoding; the blocks return to the pool for the next admit
        (minus any leading blocks a session lease keeps resident)."""
        slot = req.slot
        self._tables[slot, :] = SCRATCH_BLOCK
        self._lengths[slot] = 0
        self._last_tok[slot] = 0
        self._reqs[slot] = None
        kept = 0
        if status == "completed" and self._sessions is not None \
                and req.session_id:
            kept = self._store_lease(req)
        self._alloc.release(req.blocks[kept:])
        req.blocks = []
        _flight.recorder().note(
            "request", ("evict", req.trace_id,
                        f"{status} tokens={len(req.tokens)}"))
        self._finish(req, status, error=error)

    def _store_lease(self, req: Request) -> int:
        """Park this conversation's K/V under its session id: the
        leading blocks covering ``prompt + generated[:-1]`` (every
        position actually written — the final token was output-only)
        transfer their reference from the request to the lease table.
        Returns how many blocks the lease kept."""
        tokens = req.prompt + req.tokens[:-1]
        if not tokens:
            return 0
        kept = min(-(-len(tokens) // self.config.block_size),
                   len(req.blocks))
        self._sessions.put(req.session_id, tokens, req.blocks[:kept])
        self._m["session_leases"].inc()
        while self._sessions.max_entries is not None \
                and len(self._sessions) > self._sessions.max_entries \
                and self._sessions.evict_one(self._prefix,
                                             self.config.block_size):
            self._m["session_evictions"].inc()
        return kept

    def _count_request(self, status: str,
                       tenant: Optional[str] = None) -> None:
        """Tenanted requests get a {status=, tenant=} child so per-
        tenant traffic is attributable; untenanted ones keep the
        pre-tenant {status=} shape (sum over children stays correct)."""
        if tenant:
            self._m["requests"].labels(status=status,
                                       tenant=tenant).inc()
        else:
            self._m["requests"].labels(status=status).inc()

    def _observe_latency(self, key: str, value: float,
                         tenant: Optional[str] = None,
                         exemplar: Optional[str] = None) -> None:
        fam = self._m[key]
        child = fam.labels(tenant=tenant) if tenant else fam.labels()
        child.observe(value, exemplar=exemplar)

    def _judge_slo(self, req: Request) -> None:
        """Stamp a completed SLO-attached request with its verdict and
        count it into the hvdtpu_slo_* families."""
        ttft_s = req.ttft_s
        tpot_s = None
        if (req.t_first_token is not None and req.t_done is not None
                and len(req.tokens) > 1):
            tpot_s = ((req.t_done - req.t_first_token)
                      / (len(req.tokens) - 1))
        verdict = _slo.judge(req.slo, ttft_s, tpot_s)
        req.slo_verdict = verdict
        _slo.record_completion(
            req.tenant or _slo.DEFAULT_TENANT, verdict,
            req.t_done - req.t_submit, ttft_s, tpot_s,
            len(req.tokens), trace_id=req.trace_id)

    def _finish(self, req: Request, status: str,
                error: Optional[str] = None) -> None:
        req.status = status
        req.error = error
        req.t_done = time.perf_counter()
        if status == "completed" and req.slo is not None:
            self._judge_slo(req)
        elif error == DEADLINE_ERROR and (req.tenant
                                          or req.slo is not None):
            # Predictive sheds count under reason="shed" (the request
            # was turned away, not served late); queue expiries stay
            # under "deadline" (docs/serving.md#qos).
            _slo.record_shed(req.tenant or _slo.DEFAULT_TENANT,
                             "shed" if req.shed else "deadline")
        note = status if error is None else f"{status}: {error}"[:200]
        if req.tenant:
            note += (f" tenant={req.tenant}"
                     f" slo={_slo.verdict_summary(req.slo_verdict)}")
        _flight.recorder().note(
            "request", ("finish", req.trace_id, note))
        self._count_request(status, req.tenant)
        if status == "completed":
            now = req.t_done
            self._completions.append(now)
            while self._completions and now - self._completions[0] > 10:
                self._completions.popleft()
            self._m["qps"].set(len(self._completions) / 10.0)
            if req.tenant:
                # Tenant drain rate: what quota Retry-After quotes
                # instead of the global queue estimate.
                self._quota.note_completion(
                    req.tenant, len(req.prompt) + len(req.tokens))
        req._done.set()
        req._notify()

    def _update_gauges(self) -> None:
        self._m["active"].set(self.active_count)
        self._m["occupancy"].set(self.active_count / self._slots)
        self._m["kv_used"].set(self._alloc.in_use)
        self._m["kv_bytes"].set(self._alloc.in_use
                                * self._bytes_per_block)
        depths = self._queue.depths()
        active = {c: 0 for c in _qos.PRIORITY_CLASSES}
        for r in self._reqs:
            if r is not None:
                active[r.qos_class] = active.get(r.qos_class, 0) + 1
        for cls in _qos.PRIORITY_CLASSES:
            self._m["class_queue"].labels(qos_class=cls).set(
                depths.get(cls, 0))
            self._m["class_active"].labels(qos_class=cls).set(
                active.get(cls, 0))

    def class_counts(self) -> Dict[str, Dict[str, int]]:
        """Per-QoS-class queued/active counts — advertised via
        ``/healthz`` so the fleet router's class-aware scoring sees
        each replica's interactive backlog (docs/serving.md#qos)."""
        with self._lock:
            depths = self._queue.depths()
            active = {c: 0 for c in _qos.PRIORITY_CLASSES}
            for r in self._reqs:
                if r is not None:
                    active[r.qos_class] = \
                        active.get(r.qos_class, 0) + 1
        return {c: {"queued": depths.get(c, 0),
                    "active": active.get(c, 0)}
                for c in _qos.PRIORITY_CLASSES}
