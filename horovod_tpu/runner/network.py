"""HMAC-authenticated pickle RPC over TCP — the launcher's control wire.

Parity: horovod/spark/util/network.py (reference :44-142). The reference
wraps socket streams in an HMAC check before cloudpickle-deserializing
requests; a ``BasicService`` dispatches request objects to handlers and a
``BasicClient`` sends them with retries. This is the same design with an
explicit length-prefixed frame:

    [4-byte big-endian payload length][32-byte HMAC-SHA256(key, payload)][payload]

The digest is verified *before* unpickling — unauthenticated bytes are never
deserialized (the reference's ``check_digest`` wrapper, network.py:44-79).
"""

from __future__ import annotations

import hashlib
import hmac
import pickle
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

_LEN = struct.Struct(">I")
_DIGEST_BYTES = hashlib.sha256().digest_size
_MAX_FRAME = 1 << 30


class AuthenticationError(RuntimeError):
    """A frame failed HMAC verification (wrong or missing secret key)."""


class Wire:
    """Frame codec over a connected socket."""

    def __init__(self, key: bytes):
        self._key = key

    def write(self, sock: socket.socket, obj: Any) -> None:
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        digest = hmac.new(self._key, payload, hashlib.sha256).digest()
        sock.sendall(_LEN.pack(len(payload)) + digest + payload)

    def read(self, sock: socket.socket) -> Any:
        header = self._read_exact(sock, _LEN.size + _DIGEST_BYTES)
        (n,) = _LEN.unpack(header[:_LEN.size])
        if n > _MAX_FRAME:
            raise AuthenticationError(f"oversized frame ({n} bytes)")
        digest = header[_LEN.size:]
        payload = self._read_exact(sock, n)
        expected = hmac.new(self._key, payload, hashlib.sha256).digest()
        if not hmac.compare_digest(digest, expected):
            raise AuthenticationError(
                "HMAC verification failed; refusing to deserialize")
        return pickle.loads(payload)

    @staticmethod
    def _read_exact(sock: socket.socket, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("connection closed mid-frame")
            buf.extend(chunk)
        return bytes(buf)


class PingRequest:
    pass


class PingResponse:
    pass


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        service: "BasicService" = self.server.service  # type: ignore
        wire = service._wire
        sock = self.request
        sock.settimeout(service.conn_timeout)
        try:
            while True:
                try:
                    req = wire.read(sock)
                except (ConnectionError, socket.timeout, OSError):
                    return
                except AuthenticationError:
                    return  # drop unauthenticated peers silently
                resp = service._dispatch(req, self.client_address)
                wire.write(sock, resp)
        except (ConnectionError, BrokenPipeError, OSError):
            return


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def routable_addresses(
        probes: Tuple[str, ...] = ("8.8.8.8", "10.255.255.255"),
) -> List[str]:
    """Candidate non-loopback addresses other hosts may reach us on.

    Combines hostname resolution with the UDP-connect trick (no packet is
    sent; the kernel's route selection picks the outbound interface per
    probe target). Multiple probe targets matter: on a host with a VPN or
    overlay route covering 10.0.0.0/8, the 10.x probe resolves to the
    tunnel IP while 8.8.8.8 resolves to the LAN IP — every candidate is
    returned so peers can pick the one they can actually dial (the
    reference probes all NICs for the same reason, network.py:93-107)."""
    out: List[str] = []

    def _add(ip: str) -> None:
        if ip and not ip.startswith("127.") and ip not in out:
            out.append(ip)

    try:
        for info in socket.getaddrinfo(socket.gethostname(), None,
                                       socket.AF_INET):
            _add(info[4][0])
    except OSError:
        pass
    for probe in probes:
        try:
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                s.connect((probe, 1))
                _add(s.getsockname()[0])
        except OSError:
            continue
    return out


class BasicService:
    """Threaded TCP service dispatching authenticated request objects.

    Subclasses override :meth:`_handle`. Mirrors the reference's
    ``network.BasicService`` (spark/util/network.py:81-142).
    """

    conn_timeout = 3600.0

    def __init__(self, name: str, key: bytes, host: str = "0.0.0.0",
                 port: int = 0):
        self.name = name
        self._wire = Wire(key)
        self._server = _Server((host, port), _Handler)
        self._server.service = self  # type: ignore[attr-defined]
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name=f"{name}-rpc",
            daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._port

    def addresses(self) -> List[Tuple[str, int]]:
        """All (ip, port) pairs this service answers on — the reference
        collects every NIC's address so the driver can find a mutually
        routable interface (network.py:93-107).

        ``getaddrinfo(gethostname())`` alone is not enough: Debian-style
        /etc/hosts maps the hostname to 127.0.1.1, leaving only loopback
        candidates. The UDP-connect trick recovers the outbound interface's
        address without sending a packet (kernel route selection only)."""
        addrs = [("127.0.0.1", self._port)]
        for ip in routable_addresses():
            if (ip, self._port) not in addrs:
                addrs.append((ip, self._port))
        return addrs

    def _dispatch(self, req: Any, client_address) -> Any:
        if isinstance(req, PingRequest):
            return PingResponse()
        return self._handle(req, client_address)

    def _handle(self, req: Any, client_address) -> Any:
        raise NotImplementedError(f"{self.name}: unknown request {req!r}")

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class BasicClient:
    """RPC client with retries (network.py:~150+).

    The connection is persistent: the server handler loops over framed
    requests on one socket, so keeping it open avoids per-call TCP
    setup/teardown and handler-thread churn (the eager engine issues RPCs
    every ~1 ms cycle). Reconnects transparently on failure. Thread-safe:
    one in-flight request at a time per client.
    """

    def __init__(self, addresses, key: bytes, attempts: int = 3,
                 timeout: float = 60.0,
                 connect_attempts: Optional[int] = None):
        """``connect_attempts`` applies only until the FIRST successful
        connection (rendezvous patience — the peer may come up seconds
        later); once connected, failures retry ``attempts`` times so a
        dead peer surfaces fast instead of being masked for minutes."""
        if isinstance(addresses, tuple) and len(addresses) == 2 \
                and isinstance(addresses[0], str):
            addresses = [addresses]
        self._addresses: List[Tuple[str, int]] = list(addresses)
        self._wire = Wire(key)
        self._attempts = attempts
        self._connect_attempts = (connect_attempts
                                  if connect_attempts is not None
                                  else attempts)
        self._ever_connected = False
        self._timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._mu = threading.Lock()

    def _connect(self) -> socket.socket:
        last: Optional[Exception] = None
        for host, port in self._addresses:
            try:
                sock = socket.create_connection((host, port),
                                                timeout=self._timeout)
                sock.settimeout(self._timeout)
                return sock
            except (OSError, ConnectionError) as e:
                last = e
        raise ConnectionError(
            f"could not reach service at {self._addresses}: {last}")

    def close(self) -> None:
        with self._mu:
            if self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None

    def request(self, req: Any) -> Any:
        last: Optional[Exception] = None
        with self._mu:
            # Rendezvous patience is a wall-clock deadline, not an attempt
            # count: dropped SYNs block each connect() for up to the full
            # socket timeout, so counting attempts would multiply that
            # into hours. ~0.2 s/attempt of refused-connection pacing sets
            # the budget; once connected, the short attempt count governs.
            deadline = (None if self._ever_connected
                        else time.monotonic() + 0.2 * self._connect_attempts)
            attempt = 0
            while True:
                try:
                    if self._sock is None:
                        self._sock = self._connect()
                        self._ever_connected = True
                        deadline = None
                    self._wire.write(self._sock, req)
                    return self._wire.read(self._sock)
                except (OSError, ConnectionError) as e:
                    last = e
                    if self._sock is not None:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                    attempt += 1
                    if deadline is not None:
                        if time.monotonic() > deadline:
                            break
                    elif attempt >= self._attempts:
                        break
                    time.sleep(0.2)
        raise ConnectionError(
            f"could not reach service at {self._addresses}: {last}")

    def ping(self) -> bool:
        try:
            return isinstance(self.request(PingRequest()), PingResponse)
        except ConnectionError:
            return False


def find_free_port(host: str = "") -> int:
    """Ask the OS for an ephemeral port (used for the JAX coordinator)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s.getsockname()[1]
