"""Deadline helper with a helpful message — parity with
horovod/spark/util/timeout.py (the reference raises a descriptive exception
when registration does not complete in time, spark/__init__.py:112-114)."""

from __future__ import annotations

import time


class TimeoutException(RuntimeError):
    pass


class Timeout:
    def __init__(self, seconds: float, message: str):
        self._deadline = time.monotonic() + seconds
        self._message = message
        self._seconds = seconds

    def remaining(self) -> float:
        return max(0.0, self._deadline - time.monotonic())

    def timed_out(self) -> bool:
        return time.monotonic() > self._deadline

    def check(self) -> None:
        if self.timed_out():
            raise TimeoutException(
                self._message.format(timeout=self._seconds))
