"""Programmatic launch API: ``runner.run(fn, args=..., np=...)``.

Parity: ``horovod.spark.run`` (reference horovod/spark/__init__.py:80-196) —
run a Python function on every rank of a fresh distributed job and return
the per-rank results in rank order. Where the reference rides Spark
executors + mpirun, this spawns workers directly (subprocess/ssh) and wires
them with the JAX distributed coordinator.
"""

from __future__ import annotations

import contextlib
import os
import sys
from typing import Any, Callable, Dict, List, Optional

from .driver_service import DriverService
from .launcher import launch
from .secret import SECRET_ENV, encode_key, make_secret_key
from .timeout import Timeout

START_TIMEOUT_ENV = "HOROVOD_TPU_START_TIMEOUT"


def run(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
        np: int = 1, hosts: Optional[str] = None,
        extra_env: Optional[Dict[str, str]] = None,
        start_timeout: Optional[float] = None,
        run_timeout: Optional[float] = None,
        stdout=None, stderr=None, verbose: bool = False) -> List[Any]:
    """Execute ``fn(*args, **kwargs)`` on ``np`` ranks; return results in
    rank order.

    The launched workers may freely call :func:`horovod_tpu.init` and the
    collective API — the driver pre-wires the JAX coordinator and the TCP
    control plane through the environment.
    """
    kwargs = kwargs or {}
    if start_timeout is None:
        start_timeout = float(os.environ.get(START_TIMEOUT_ENV, 600))

    try:
        import cloudpickle as pickler
    except ImportError:  # pragma: no cover
        import pickle as pickler
    fn_bytes = pickler.dumps((fn, args, kwargs))

    key = make_secret_key()
    driver = DriverService(np, key, fn_bytes)
    try:
        env = dict(extra_env or {})
        env[SECRET_ENV] = encode_key(key)
        # Advertise every interface the driver answers on; remote workers
        # pick the first one they can reach (the reference probes NICs for
        # mutually routable interfaces, spark/util/network.py:93-107).
        env["HOROVOD_TPU_DRIVER"] = ",".join(
            f"{h}:{p}" for h, p in driver.addresses())

        job = launch([sys.executable, "-m",
                      "horovod_tpu.runner.task_exec"],
                     np=np, hosts=hosts, extra_env=env,
                     stdout=stdout, stderr=stderr)
        try:
            reg_timeout = Timeout(
                start_timeout,
                "Timed out waiting for {timeout} s for all ranks to "
                "register with the driver. Check worker logs for startup "
                "failures.")
            driver.wait_for_registration(reg_timeout,
                                         failfast=job.failfast_check)
            total = Timeout(
                run_timeout if run_timeout is not None else 10 ** 9,
                "Timed out after {timeout} s waiting for results.")
            results = driver.wait_for_results(total,
                                              failfast=job.failfast_check)
            # Results are already in hand: a worker lingering in teardown
            # (profiler flush, TPU runtime exit) past the grace period is
            # not a reason to discard a successful job — wait() already
            # terminates stragglers before raising.
            with contextlib.suppress(TimeoutError):
                job.wait(timeout=60)
            return results
        finally:
            job.terminate()
    finally:
        driver.shutdown()
