"""Remote worker bootstrap (``python -m horovod_tpu.runner.remote_bootstrap``).

The rsh/orted hop of the stack (reference: horovod/spark/driver/
mpirun_rsh.py:24-37 bridging orted launches through remote agents). The
launcher ssh-es to the host and pipes ONE JSON line on stdin:

    {"env": {...}, "cmd": ["python", "train.py", ...]}

Env (including the HMAC secret) and command travel over ssh's encrypted
stdin rather than the remote argv, so values with spaces survive and
secrets never show up in ``ps`` output. The child is exec'd directly —
no shell interprets any of it.
"""

from __future__ import annotations

import json
import os
import sys


def main() -> int:
    line = sys.stdin.readline()
    spec = json.loads(line)
    env = dict(os.environ)
    env.update(spec["env"])
    cmd = spec["cmd"]
    os.execvpe(cmd[0], cmd, env)
    return 127  # unreachable


if __name__ == "__main__":
    sys.exit(main())
