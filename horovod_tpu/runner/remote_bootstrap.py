"""Remote worker bootstrap (``python -m horovod_tpu.runner.remote_bootstrap``).

The rsh/orted hop of the stack (reference: horovod/spark/driver/
mpirun_rsh.py:24-37 bridging orted launches through remote agents). The
launcher ssh-es to the host and pipes ONE JSON line on stdin:

    {"env": {...}, "cmd": ["python", "train.py", ...]}

Env (including the HMAC secret) and command travel over ssh's encrypted
stdin rather than the remote argv, so values with spaces survive and
secrets never show up in ``ps`` output. The child is exec'd directly —
no shell interprets any of it.

Probe mode (``--probe PORT [PORT ...]``): bind-checks the given ports on
this host and prints one JSON line ``{"free": [...], "busy": [...]}``.
The launcher uses this before starting a job whose rank 0 is remote, so
coordinator/control ports are verified free on the machine that will
actually bind them instead of being drawn blind from the high range.
"""

from __future__ import annotations

import json
import os
import socket
import sys


def probe_ports(ports) -> dict:
    """Try binding each port on all interfaces; report free vs busy."""
    free, busy = [], []
    for p in ports:
        p = int(p)
        try:
            with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
                # No SO_REUSEADDR: a TIME_WAIT remnant should count as
                # busy — the coordinator binds immediately after this.
                s.bind(("", p))
            free.append(p)
        except OSError:
            busy.append(p)
    return {"free": free, "busy": busy}


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--probe":
        print(json.dumps(probe_ports(sys.argv[2:])), flush=True)
        return 0
    line = sys.stdin.readline()
    spec = json.loads(line)
    env = dict(os.environ)
    env.update(spec["env"])
    cmd = spec["cmd"]
    os.execvpe(cmd[0], cmd, env)
    return 127  # unreachable


if __name__ == "__main__":
    sys.exit(main())
