"""CLI launcher: ``python -m horovod_tpu.runner -np 4 python train.py``.

The reference has no dedicated CLI (bare ``mpirun`` per docs/running.md:
1-45); this plays mpirun's role for the TPU-native stack. Slots follow
mpirun's ``-H host:slots`` syntax; output is tag-prefixed per rank.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.runner",
        description="Launch a distributed horovod_tpu job "
                    "(the mpirun of the TPU-native stack).")
    parser.add_argument("-np", "--num-proc", type=int, required=True,
                        help="total number of worker processes")
    parser.add_argument("-H", "--hosts", default=None,
                        help="host slots, mpirun syntax: host1:2,host2:2 "
                             "(default: localhost)")
    parser.add_argument("--timeout", type=float, default=None,
                        help="overall job timeout in seconds")
    parser.add_argument("--no-tag-output", action="store_true",
                        help="do not prefix worker output with [rank]")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="worker command, e.g. python train.py")
    args = parser.parse_args(argv)

    if not args.command:
        parser.error("missing worker command")
    command = args.command
    if command and command[0] == "--":
        command = command[1:]

    from .launcher import launch

    job = launch(command, np=args.num_proc, hosts=args.hosts,
                 tag_output=not args.no_tag_output)
    try:
        return job.wait(timeout=args.timeout)
    except KeyboardInterrupt:
        job.terminate()
        return 130


if __name__ == "__main__":
    sys.exit(main())
