"""CLI launcher: ``python -m horovod_tpu.runner -np 4 python train.py``.

The reference has no dedicated CLI (bare ``mpirun`` per docs/running.md:
1-45); this plays mpirun's role for the TPU-native stack. Slots follow
mpirun's ``-H host:slots`` syntax; output is tag-prefixed per rank.

Worker discovery (``--discovery {hostfile,ssh,tpu-pod}``) resolves the
host list through the :class:`horovod_tpu.elastic.HostProvider`
interface instead of a literal ``-H`` string — the cluster-manager
integration the reference delegates to Spark (SURVEY M7). ``--elastic``
additionally survives worker loss: the job shrinks to the surviving
hosts (never below ``--min-np``), relaunches, and grows back when
replacements appear (docs/elastic.md).
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.runner",
        description="Launch a distributed horovod_tpu job "
                    "(the mpirun of the TPU-native stack).")
    parser.add_argument("-np", "--num-proc", type=int, default=None,
                        help="total number of worker processes (default "
                             "with --discovery: every discovered slot)")
    parser.add_argument("-H", "--hosts", default=None,
                        help="host slots, mpirun syntax: host1:2,host2:2 "
                             "(default: localhost); with --discovery ssh "
                             "these are the candidates to probe")
    parser.add_argument("--discovery", default=None,
                        choices=["hostfile", "ssh", "tpu-pod"],
                        help="resolve workers through a HostProvider: "
                             "a hostfile, ssh-probed -H candidates, or "
                             "the GCE metadata server of a TPU pod")
    parser.add_argument("--hostfile", default=None,
                        help="hostfile path for --discovery hostfile "
                             "(lines: 'host slots=N', 'host:N', 'host')")
    parser.add_argument("--metadata-addr", default=None,
                        help="metadata server base URL for --discovery "
                             "tpu-pod (default: $HOROVOD_TPU_METADATA_ADDR "
                             "or the real GCE endpoint)")
    parser.add_argument("--elastic", action="store_true",
                        help="survive worker loss: shrink to the "
                             "remaining hosts (>= --min-np), relaunch, "
                             "grow back when hosts return")
    parser.add_argument("--min-np", type=int, default=1,
                        help="elastic: smallest world size to continue "
                             "with (default 1)")
    parser.add_argument("--max-np", type=int, default=None,
                        help="elastic: largest world size to grow to "
                             "(default: -np, else all discovered slots)")
    parser.add_argument("--max-restarts", type=int, default=3,
                        help="elastic: relaunch budget (default 3)")
    parser.add_argument("--failure-timeout", type=float, default=30.0,
                        help="elastic: seconds before stalls/heartbeat "
                             "loss escalate to WorkerFailure (default 30)")
    parser.add_argument("--state-dir", default=None,
                        help="elastic: ElasticState commit directory, "
                             "exported as HOROVOD_TPU_ELASTIC_DIR")
    parser.add_argument("--timeline", default=None,
                        help="write collective timelines: a plain path "
                             "traces rank 0 only; a path with a {rank} "
                             "placeholder (e.g. /tmp/trace.{rank}.json) "
                             "traces EVERY rank with clock-alignment "
                             "headers for `python -m "
                             "horovod_tpu.tools.trace merge` "
                             "(docs/tracing.md); exported as "
                             "HOROVOD_TPU_TIMELINE")
    parser.add_argument("--fault-spec", default=None,
                        help="deterministic fault injection "
                             "(docs/adaptation.md), e.g. "
                             "'rank=2:delay=80ms:from_step=50; "
                             "rank=1:crash_at=30'; exported as "
                             "HOROVOD_TPU_FAULT_SPEC to every worker "
                             "generation")
    parser.add_argument("--adaptation", action="store_true",
                        help="arm the rank-0 adaptation policy "
                             "(docs/adaptation.md): on sustained "
                             "straggler lateness, shrink fused groups, "
                             "escalate wire compression, and (with "
                             "--elastic) evict the slow rank; exported "
                             "as HOROVOD_TPU_ADAPTATION=1")
    parser.add_argument("--autotune", action="store_true",
                        help="arm the GLOBAL online autotuner "
                             "(docs/autotune.md): one search space "
                             "over every perf knob, scored on "
                             "measured step time, each move guarded "
                             "by the health plane's step-time "
                             "regression detector with automatic "
                             "rollback; exported as "
                             "HOROVOD_TPU_AUTOTUNE=1 (distinct from "
                             "the legacy HOROVOD_AUTOTUNE tuner)")
    parser.add_argument("--blackbox-dir", default=None,
                        help="flight-recorder crash-dump directory "
                             "(docs/postmortem.md): on a crash, "
                             "SIGTERM, stall escalation or eviction "
                             "each rank writes blackbox-rank{rank}"
                             ".jsonl here for `python -m "
                             "horovod_tpu.tools.postmortem`; exported "
                             "as HOROVOD_TPU_BLACKBOX")
    parser.add_argument("--history-dir", default=None,
                        help="telemetry history directory "
                             "(docs/health.md): each rank appends "
                             "windowed registry deltas to "
                             "history-rank{rank}.jsonl here every "
                             "HOROVOD_TPU_HISTORY_INTERVAL (5 s) and "
                             "the online health detectors run over "
                             "the live window; read with `python -m "
                             "horovod_tpu.tools.health`; exported as "
                             "HOROVOD_TPU_HISTORY")
    parser.add_argument("--serve", action="store_true",
                        help="serving mode (docs/serving.md): the "
                             "worker command becomes `python -m "
                             "horovod_tpu.serving` and remaining "
                             "arguments are passed to it, e.g. "
                             "`python -m horovod_tpu.runner --serve -- "
                             "--checkpoint-dir /ckpts --tp 4`")
    parser.add_argument("--fleet", type=int, default=None,
                        help="with --serve: supervise N serving "
                             "replicas behind the failover router "
                             "(docs/serving.md#fleet) — shorthand for "
                             "passing --fleet N to `python -m "
                             "horovod_tpu.serving`")
    parser.add_argument("--timeout", type=float, default=None,
                        help="overall job timeout in seconds")
    parser.add_argument("--no-tag-output", action="store_true",
                        help="do not prefix worker output with [rank]")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="worker command, e.g. python train.py")
    args = parser.parse_args(argv)

    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if args.fleet is not None and not args.serve:
        parser.error("--fleet requires --serve")
    if args.serve:
        # Serving is one front-end process per host (which itself
        # supervises --fleet N replica processes); the remaining argv
        # belongs to `python -m horovod_tpu.serving`.
        fleet = ["--fleet", str(args.fleet)] \
            if args.fleet is not None else []
        command = [sys.executable, "-m", "horovod_tpu.serving"] \
            + fleet + command
        if args.num_proc is None and not args.discovery:
            args.num_proc = 1
    elif not command:
        parser.error("missing worker command")

    extra_env = {}
    if args.fault_spec:
        # Validate at launch: a typo'd fault harness must fail here, not
        # silently inject nothing in the workers.
        from ..adaptation.faults import parse_spec
        parse_spec(args.fault_spec)
        extra_env["HOROVOD_TPU_FAULT_SPEC"] = args.fault_spec
    if args.adaptation:
        extra_env["HOROVOD_TPU_ADAPTATION"] = "1"
    if args.autotune:
        extra_env["HOROVOD_TPU_AUTOTUNE"] = "1"
    if args.timeline:
        # Propagated UNEXPANDED: each worker resolves its own {rank}
        # (utils/env.resolved_timeline_path), so the same value serves
        # the single-writer and all-ranks capture modes — and elastic
        # relaunches keep rank-correct paths across generations.
        extra_env["HOROVOD_TPU_TIMELINE"] = args.timeline
    if args.blackbox_dir:
        extra_env["HOROVOD_TPU_BLACKBOX"] = args.blackbox_dir
    if args.history_dir:
        extra_env["HOROVOD_TPU_HISTORY"] = args.history_dir

    provider = None
    hosts = args.hosts
    np = args.num_proc
    if args.discovery:
        from ..elastic.discovery import get_provider
        provider = get_provider(args.discovery, hosts=args.hosts,
                                hostfile=args.hostfile,
                                metadata_addr=args.metadata_addr)
        slots = provider.discover()
        if not slots:
            parser.error(f"--discovery {args.discovery} found no workers")
        hosts = ",".join(f"{h}:{s}" for h, s in slots)
        if np is None:
            np = sum(s for _, s in slots)
        print(f"[discovery:{args.discovery}] "
              f"{len(slots)} host(s), {sum(s for _, s in slots)} slot(s): "
              f"{hosts}", file=sys.stderr)

    if args.elastic:
        from ..elastic.driver import run_elastic_command
        from ..elastic.failure import FailureConfig
        config = FailureConfig(failure_timeout_s=args.failure_timeout,
                               max_restarts=args.max_restarts)
        try:
            return run_elastic_command(
                command, min_np=args.min_np,
                max_np=args.max_np if args.max_np is not None else np,
                provider=provider, hosts=hosts,
                state_dir=args.state_dir, config=config,
                extra_env=extra_env or None,
                tag_output=not args.no_tag_output,
                run_timeout=args.timeout)
        except KeyboardInterrupt:
            return 130

    if np is None:
        parser.error("-np is required (or use --discovery to size the "
                     "job from the discovered slots)")

    from .launcher import launch

    job = launch(command, np=np, hosts=hosts,
                 extra_env=extra_env or None,
                 tag_output=not args.no_tag_output)
    try:
        return job.wait(timeout=args.timeout)
    except KeyboardInterrupt:
        job.terminate()
        return 130


if __name__ == "__main__":
    sys.exit(main())
