"""Worker-side entry for function-mode launches
(``python -m horovod_tpu.runner.task_exec``).

Parity: horovod/spark/task/mpirun_exec_fn.py (reference :1-55) — start a
parent watchdog, read the driver address + own index from env, fetch the
pickled function and world assignment, execute, register the result (or the
error) back with the driver.
"""

from __future__ import annotations

import os
import sys
import traceback


def main() -> int:
    from .driver_service import DriverClient
    from .host_hash import host_hash
    from .safe_exec import start_parent_watchdog
    from .secret import key_from_env

    start_parent_watchdog()

    # Make JAX_PLATFORMS authoritative again: a site customization (e.g. a
    # TPU-tunnel plugin) may have pinned jax.config's platform list at import
    # time, which outranks the env var the launcher set for this worker.
    jax_platforms = os.environ.get("JAX_PLATFORMS")
    if jax_platforms:
        try:
            import jax
            jax.config.update("jax_platforms", jax_platforms)
        except Exception:
            pass

    # Comma-separated host:port candidates — every interface the driver
    # answers on; the client tries them in order.
    addresses = []
    for hp in os.environ["HOROVOD_TPU_DRIVER"].split(","):
        host, port = hp.rsplit(":", 1)
        addresses.append((host, int(port)))
    index = int(os.environ["HOROVOD_TPU_PROCESS_ID"])
    client = DriverClient(addresses, key_from_env())

    client.register_task(index, host_hash())
    info = client.world_info(index)

    try:
        try:
            import cloudpickle as pickler
        except ImportError:  # pragma: no cover
            import pickle as pickler
        fn, args, kwargs = pickler.loads(info.fn_bytes)
        result = fn(*args, **kwargs)
        client.register_result(info.rank, result, None)
        return 0
    except BaseException as e:
        # Exit 0 once the traceback is registered: the driver raises the
        # real exception from wait_for_results; a nonzero exit here would
        # race failfast into masking it with a generic "exited with code 1".
        # Final gasp FIRST (docs/postmortem.md): function-mode workers
        # catch the exception here — sys.excepthook never fires — so
        # this is the flight recorder's last chance to dump the ring
        # and flush the metrics file.
        try:
            from ..observability import flight_recorder as _flight
            _flight.dump_on("exception", exc=e)
        except Exception:
            pass
        error = traceback.format_exc()
        try:
            # A typed WorkerFailure (e.g. a slow_rank eviction from the
            # adaptation policy) travels as the OBJECT, not flattened
            # text: the elastic driver dispatches on its class/fields to
            # recover instead of aborting (docs/adaptation.md).
            from ..elastic.failure import WorkerFailure
            if isinstance(e, WorkerFailure):
                error = e
        except Exception:
            pass
        client.register_result(info.rank, None, error)
        return 0


if __name__ == "__main__":
    sys.exit(main())
