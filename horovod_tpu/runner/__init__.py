"""Cluster launcher — the TPU-native equivalent of the reference's L4 layer
(horovod/spark/ + bare mpirun, docs/running.md).

Pieces:
  - :mod:`.network`   HMAC-authenticated pickle RPC (spark/util/network.py)
  - :mod:`.secret`    shared-secret handling (spark/util/secret.py)
  - :mod:`.host_hash` host grouping (spark/util/host_hash.py)
  - :mod:`.safe_exec` process management + watchdogs (safe_shell_exec.py,
                      mpirun_exec_fn.py)
  - :mod:`.launcher`  rank spawning, local + ssh (mpirun / mpirun_rsh.py)
  - :mod:`.driver_service` rendezvous + result collection
                      (driver/driver_service.py)
  - :mod:`.api`       ``run(fn)`` (horovod.spark.run, spark/__init__.py)
  - CLI: ``python -m horovod_tpu.runner -np 4 python train.py``
        (``--discovery {hostfile,ssh,tpu-pod}`` resolves workers through
        the elastic subsystem's HostProvider; ``--elastic`` survives
        worker loss — see horovod_tpu/elastic/ and docs/elastic.md)
"""

from .api import run
from .launcher import launch, parse_hosts
from .network import find_free_port

__all__ = ["run", "run_elastic", "launch", "parse_hosts",
           "find_free_port"]


def __getattr__(name):
    # Lazy: the elastic driver imports this package's submodules, so a
    # top-level import here would be circular.
    if name == "run_elastic":
        from ..elastic.driver import run_elastic
        return run_elastic
    raise AttributeError(name)
