"""Driver-side rendezvous service for function-mode launches.

Parity: horovod/spark/driver/driver_service.py (reference :1-234) and the
result-collection flow of horovod/spark/__init__.py:80-196 — the driver runs
an HMAC RPC service; each worker registers on start, fetches the pickled
function plus its world assignment, executes, and registers its result; the
driver collects results in rank order.

TPU-native redesign: the Spark scheduler is replaced by direct process
spawning (local subprocess or ssh — :mod:`horovod_tpu.runner.launcher`), and
the mpirun wire-up is replaced by handing every worker the JAX distributed
coordinator address (``jax.distributed.initialize`` is the MPI_Init
equivalent, see horovod_tpu/topology.py).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from .network import BasicClient, BasicService
from .timeout import Timeout


class RegisterTaskRequest:
    def __init__(self, index: int, host_hash: str):
        self.index = index
        self.host_hash = host_hash


class RegisterTaskResponse:
    pass


class WorldInfoRequest:
    """Worker asks for its world assignment + the pickled function."""

    def __init__(self, index: int):
        self.index = index


class WorldInfoResponse:
    """Rank/size + the function to run. The distributed wire-up
    (coordinator, control plane) travels exclusively through the
    ``HOROVOD_TPU_*`` env vars set by the launcher — one authoritative
    channel, consumed by :func:`horovod_tpu.init`."""

    def __init__(self, rank: int, size: int, fn_bytes: bytes):
        self.rank = rank
        self.size = size
        self.fn_bytes = fn_bytes


class RegisterResultRequest:
    def __init__(self, rank: int, result: Any, error: Optional[str] = None):
        self.rank = rank
        self.result = result
        self.error = error


class RegisterResultResponse:
    pass


class DriverService(BasicService):
    """Rendezvous + result collection for ``runner.run(fn)``."""

    def __init__(self, num_proc: int, key: bytes, fn_bytes: bytes):
        self._num_proc = num_proc
        self._fn_bytes = fn_bytes
        self._lock = threading.Lock()
        self._registered: Dict[int, str] = {}
        self._results: Dict[int, Tuple[Any, Optional[str]]] = {}
        self._all_registered = threading.Event()
        self._all_done = threading.Event()
        super().__init__("horovod-tpu-driver", key)

    # ------------------------------------------------------------- dispatch

    def _handle(self, req, client_address):
        if isinstance(req, RegisterTaskRequest):
            with self._lock:
                self._registered[req.index] = req.host_hash
                if len(self._registered) == self._num_proc:
                    self._all_registered.set()
            return RegisterTaskResponse()
        if isinstance(req, WorldInfoRequest):
            # index == rank: slot assignment happens at spawn time (the
            # launcher already grouped slots by host, mirroring the
            # reference's host ordering, spark/__init__.py:123-152).
            return WorldInfoResponse(
                rank=req.index, size=self._num_proc,
                fn_bytes=self._fn_bytes)
        if isinstance(req, RegisterResultRequest):
            with self._lock:
                self._results[req.rank] = (req.result, req.error)
                if len(self._results) == self._num_proc:
                    self._all_done.set()
            return RegisterResultResponse()
        return super()._handle(req, client_address)

    # -------------------------------------------------------------- waiting

    def wait_for_registration(self, timeout: Timeout, failfast=None) -> None:
        while not self._all_registered.wait(timeout=1.0):
            timeout.check()
            if failfast is not None:
                failfast()

    def wait_for_results(self, timeout: Timeout,
                         failfast=None) -> List[Any]:
        """Block until every rank registered a result; raise if any worker
        reported an error (or ``failfast()`` flags a dead worker)."""
        while not self._all_done.wait(timeout=1.0):
            timeout.check()
            if failfast is not None:
                failfast()
        out: List[Any] = []
        errors = []
        typed = None
        for r in range(self._num_proc):
            result, error = self._results[r]
            if error is not None:
                errors.append(f"rank {r}: {error}")
                if typed is None and isinstance(error, BaseException):
                    typed = error
            out.append(result)
        if typed is not None:
            # A worker registered a typed failure object (WorkerFailure
            # from a slow-rank eviction / escalated stall): re-raise IT
            # so the elastic driver can dispatch on rank/host/kind and
            # recover, instead of burying it in a generic RuntimeError.
            raise typed
        if errors:
            raise RuntimeError("worker function failed on "
                               + "; ".join(errors))
        return out

    def results_so_far(self) -> int:
        with self._lock:
            return len(self._results)


class DriverClient(BasicClient):
    def register_task(self, index: int, hh: str) -> None:
        self.request(RegisterTaskRequest(index, hh))

    def world_info(self, index: int) -> WorldInfoResponse:
        return self.request(WorldInfoRequest(index))

    def register_result(self, rank: int, result: Any,
                        error: Optional[str] = None) -> None:
        self.request(RegisterResultRequest(rank, result, error))
