"""Subprocess execution with output forwarding and orphan watchdog.

Parity:
  - horovod/spark/util/safe_shell_exec.py (reference :1-148): run a command,
    stream its stdout/stderr to the parent, kill the whole process group on
    failure or parent exit.
  - horovod/spark/task/mpirun_exec_fn.py:26-31: the worker-side watchdog
    thread that exits when the parent process dies (re-parented to init).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import IO, Dict, List, Optional


def _forward(stream: IO[bytes], sink, prefix: str = "") -> threading.Thread:
    def pump():
        try:
            for raw in iter(stream.readline, b""):
                line = raw.decode("utf-8", "replace")
                sink.write(f"{prefix}{line}" if prefix else line)
                sink.flush()
        except ValueError:
            pass  # stream closed

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    return t


class ManagedProcess:
    """A spawned worker whose output is streamed with a rank prefix
    (``[rank]<stdout>:`` — the convention mpirun's ``-tag-output`` uses)."""

    def __init__(self, args: List[str], env: Dict[str, str],
                 prefix: Optional[str] = None,
                 stdout=None, stderr=None,
                 stdin_data: Optional[bytes] = None):
        self.args = args
        self.proc = subprocess.Popen(
            args, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            stdin=subprocess.PIPE if stdin_data is not None else None,
            start_new_session=True)
        if stdin_data is not None:
            # Hand secrets/config to the child over stdin, never argv
            # (argv is world-readable via ps).
            def feed():
                try:
                    self.proc.stdin.write(stdin_data)
                    self.proc.stdin.close()
                except (BrokenPipeError, OSError):
                    pass
            threading.Thread(target=feed, daemon=True).start()
        out_sink = stdout if stdout is not None else sys.stdout
        err_sink = stderr if stderr is not None else sys.stderr
        p_out = f"{prefix}<stdout>:" if prefix else ""
        p_err = f"{prefix}<stderr>:" if prefix else ""
        self._pumps = [
            _forward(self.proc.stdout, out_sink, p_out),
            _forward(self.proc.stderr, err_sink, p_err),
        ]

    def poll(self) -> Optional[int]:
        return self.proc.poll()

    def wait(self, timeout: Optional[float] = None) -> int:
        rc = self.proc.wait(timeout)
        for t in self._pumps:
            t.join(timeout=2.0)
        return rc

    def terminate(self) -> None:
        """Kill the worker's whole process group (safe_shell_exec kills the
        session it created, reference :60-90)."""
        if self.proc.poll() is None:
            try:
                os.killpg(self.proc.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                self.proc.wait(timeout=3.0)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(self.proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass


def start_parent_watchdog(parent_pid: Optional[int] = None,
                          interval: float = 1.0) -> threading.Thread:
    """Exit this process when its launcher dies (mpirun_exec_fn.py:26-31)."""
    ppid = parent_pid if parent_pid is not None else os.getppid()

    def watch():
        while True:
            time.sleep(interval)
            # Re-parented to init/reaper ⇒ launcher is gone.
            if os.getppid() != ppid:
                os._exit(1)

    t = threading.Thread(target=watch, daemon=True, name="parent-watchdog")
    t.start()
    return t
