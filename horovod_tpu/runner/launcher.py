"""Process launcher — the ``mpirun`` of the TPU-native stack.

Parity: the reference launches ranks with bare ``mpirun -H host:slots``
(docs/running.md:1-45) or through Spark executors bridged by an rsh agent
(horovod/spark/__init__.py:160-178, driver/mpirun_rsh.py:24-37). Here the
launcher itself spawns the workers:

  - slots are parsed from ``-H host1:2,host2:2`` (mpirun's syntax) or
    default to ``localhost:np``;
  - local slots become subprocesses; remote slots become ``ssh`` commands
    (the orted/rsh role);
  - every worker gets the JAX distributed coordinator address and its
    process id via ``HOROVOD_TPU_*`` env vars, which
    :func:`horovod_tpu.init` consumes (the MPI_Init equivalent);
  - output is streamed with ``[rank]<stdout>:`` prefixes and the whole job
    is torn down fail-fast when any rank dies (safe_shell_exec semantics).
"""

from __future__ import annotations

import os
import socket
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

from .network import find_free_port, routable_addresses
from .safe_exec import ManagedProcess

_LOCAL_NAMES = {"localhost", "127.0.0.1", "::1"}


def parse_hosts(hosts: str) -> List[Tuple[str, int]]:
    """Parse mpirun-style ``host:slots[,host:slots...]``."""
    out: List[Tuple[str, int]] = []
    for part in hosts.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            host, slots = part.rsplit(":", 1)
            out.append((host, int(slots)))
        else:
            out.append((part, 1))
    return out


def expand_slots(host_slots: Sequence[Tuple[str, int]], np: int
                 ) -> List[str]:
    """One host entry per rank, hosts grouped contiguously (ranks on the
    same host are adjacent — the reference orders hosts the same way,
    spark/__init__.py:123-152)."""
    ranks: List[str] = []
    for host, slots in host_slots:
        ranks.extend([host] * slots)
    if len(ranks) < np:
        raise ValueError(
            f"host list provides {len(ranks)} slots but -np is {np}")
    return ranks[:np]


def is_local_host(host: str) -> bool:
    if host in _LOCAL_NAMES:
        return True
    try:
        return host == socket.gethostname()
    except OSError:
        return False


def routable_local_address() -> str:
    """Best-effort address OTHER hosts can reach this machine on (the
    reference eliminates non-routable NAT/loopback interfaces the same
    way, spark/__init__.py:134-159). Delegates to the shared probe in
    :mod:`.network`; first candidate wins."""
    candidates = routable_addresses()
    if candidates:
        return candidates[0]
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


def _ssh_spawn_spec(host: str, env: Dict[str, str], args: List[str],
                    extra_keys: Sequence[str] = ()
                    ) -> Tuple[List[str], bytes]:
    """Remote spawn via ssh — the rsh-agent role (mpirun_rsh.py:24-37).

    Returns (ssh argv, stdin payload). Env and command are shipped as one
    JSON line over ssh's stdin to :mod:`.remote_bootstrap`: no shell
    quoting pitfalls, and the HMAC secret stays off the remote argv.
    HOROVOD_TPU_*/JAX/XLA/TPU env plus every caller-supplied ``extra_env``
    key is forwarded across the hop, so the ``run(fn, extra_env=...)``
    contract holds on remote workers too."""
    import json
    fwd = {k: v for k, v in env.items()
           if k.startswith(("HOROVOD_TPU_", "JAX_", "XLA_", "TPU_"))
           or k in extra_keys}
    payload = json.dumps({"env": fwd, "cmd": args}).encode() + b"\n"
    argv = ["ssh", "-o", "StrictHostKeyChecking=no", host,
            "python3", "-m", "horovod_tpu.runner.remote_bootstrap"]
    return argv, payload


def _probe_remote_ports(host: str, ports: List[int],
                        timeout: float = 20.0) -> Optional[List[int]]:
    """Bind-check ``ports`` on ``host`` via the bootstrap's --probe mode.
    Returns the free subset, or None when the probe could not run (no
    ssh / no python on the remote) — callers then fall back to a blind
    pick, the pre-probe behavior."""
    import json as _json
    import subprocess
    argv = (["ssh", "-o", "StrictHostKeyChecking=no", host, "python3",
             "-m", "horovod_tpu.runner.remote_bootstrap", "--probe"]
            + [str(p) for p in ports])
    try:
        out = subprocess.run(argv, capture_output=True, timeout=timeout)
        if out.returncode != 0:
            return None
        return list(_json.loads(out.stdout.decode().strip())["free"])
    except Exception:
        return None


def _pick_remote_ports(host: str, coordinator_port: Optional[int]
                       ) -> Tuple[int, int]:
    """Choose (coordinator, control) ports for a remote rank-0 host,
    probing candidates over ssh. A pinned ``coordinator_port`` that turns
    out busy raises with a message naming the knob."""
    import random
    rnd = random.SystemRandom()
    for _ in range(3):
        coord = (coordinator_port if coordinator_port is not None
                 else rnd.randrange(20000, 60000))
        ctrl = rnd.randrange(20000, 60000)
        while ctrl == coord:
            ctrl = rnd.randrange(20000, 60000)
        free = _probe_remote_ports(host, [coord, ctrl])
        if free is None:
            return coord, ctrl  # probe unavailable: keep the blind pick
        if coord in free and ctrl in free:
            return coord, ctrl
        if coordinator_port is not None and coord not in free:
            raise RuntimeError(
                f"coordinator_port {coordinator_port} is already in use "
                f"on {host}; pick a different coordinator_port or free "
                "the port")
    raise RuntimeError(
        f"could not find free coordinator/control ports on {host} after "
        "3 probe attempts; pass coordinator_port to pin a known-free one")


class LaunchedJob:
    def __init__(self, workers: List[ManagedProcess]):
        self.workers = workers

    def failfast_check(self) -> None:
        """Raise if any worker exited nonzero (and kill the rest)."""
        for rank, w in enumerate(self.workers):
            rc = w.poll()
            if rc is not None and rc != 0:
                self.terminate()
                raise RuntimeError(
                    f"worker rank {rank} exited with code {rc}")

    def wait(self, timeout: Optional[float] = None) -> int:
        """Wait for all workers; fail-fast on the first nonzero exit.
        Returns 0 on full success."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            rcs = [w.poll() for w in self.workers]
            for rank, rc in enumerate(rcs):
                if rc is not None and rc != 0:
                    self.terminate()
                    self._drain()
                    return rc
            if all(rc == 0 for rc in rcs):
                self._drain()
                return 0
            if deadline is not None and time.monotonic() > deadline:
                self.terminate()
                self._drain()
                raise TimeoutError("job did not finish in time")
            time.sleep(0.1)

    def _drain(self) -> None:
        """Join output pumps of exited workers so their last lines are
        flushed before the launcher returns (poll() can report exit while
        output still sits in the pipe buffer)."""
        for w in self.workers:
            if w.poll() is not None:
                try:
                    w.wait(timeout=5.0)
                except Exception:
                    pass

    def terminate(self) -> None:
        for w in self.workers:
            w.terminate()


def launch(command: List[str], np: int, hosts: Optional[str] = None,
           extra_env: Optional[Dict[str, str]] = None,
           stdout=None, stderr=None, tag_output: bool = True,
           coordinator_port: Optional[int] = None) -> LaunchedJob:
    """Spawn ``np`` copies of ``command`` with the distributed env wired up.

    Env contract consumed by :func:`horovod_tpu.init`
    (horovod_tpu/topology.py:136-176):
      HOROVOD_TPU_COORDINATOR       host:port of the JAX coordinator (rank 0)
      HOROVOD_TPU_NUM_PROCESSES     world size
      HOROVOD_TPU_PROCESS_ID        this worker's process id
    Consumed by the eager collective engine (ops/control_plane.py) for
    cross-process fusion negotiation:
      HOROVOD_TPU_CONTROL           host:port of the rank-0 TCP coordinator
      HOROVOD_TPU_SECRET_KEY        HMAC key for the control plane (created
                                    here unless the caller already set one)
    Informational, for user scripts (the OMPI_COMM_WORLD_LOCAL_RANK
    equivalent, test/common.py:25-57):
      HOROVOD_TPU_LOCAL_PROCESS_ID  rank within its host
    """
    host_slots = parse_hosts(hosts) if hosts else [("localhost", np)]
    rank_hosts = expand_slots(host_slots, np)
    any_remote = any(not is_local_host(h) for h in rank_hosts)

    # The coordinator (JAX distributed service) binds on rank 0's host.
    # All-local jobs use loopback; once any worker is remote, loopback is
    # unreachable from it, so advertise a routable address of rank 0's
    # machine instead (the launcher's own when rank 0 is local).
    first_host = rank_hosts[0]
    if not any_remote:
        coord_host = "127.0.0.1"
    elif is_local_host(first_host):
        coord_host = routable_local_address()
    else:
        coord_host = first_host
    if is_local_host(first_host):
        # Probing only tells us the port is free HERE — valid exactly when
        # the coordinator binds here.
        coord_port = (coordinator_port if coordinator_port is not None
                      else find_free_port())
        ctrl_port = find_free_port()
        while ctrl_port == coord_port:
            ctrl_port = find_free_port()
    else:
        # Rank 0 binds on a remote machine: verify candidate ports over
        # the ssh hop (remote_bootstrap --probe) before committing, so a
        # collision with an existing listener fails HERE with a clear
        # message instead of as a confusing startup error (or the control
        # plane dialing a stranger's service). Falls back to the blind
        # entropy-backed pick only if the probe itself cannot run.
        coord_port, ctrl_port = _pick_remote_ports(first_host,
                                                   coordinator_port)

    # Local workers must be able to import horovod_tpu (and task_exec)
    # regardless of the caller's cwd — e.g. a script run from examples/
    # with the package importable only via the caller's sys.path. Remote
    # hosts need the package installed; PYTHONPATH is not shipped there.
    import horovod_tpu
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(horovod_tpu.__file__)))

    # The eager engine's control plane authenticates with a shared HMAC
    # key (secret.py, reference spark/util/secret.py:21-36); mint one per
    # launch unless the caller (e.g. api.run) already provided it.
    from .secret import SECRET_ENV, encode_key, make_secret_key
    secret = ((extra_env or {}).get(SECRET_ENV)
              or os.environ.get(SECRET_ENV) or encode_key(make_secret_key()))

    extra_keys = tuple(extra_env.keys()) if extra_env else ()
    workers: List[ManagedProcess] = []
    local_counts: Dict[str, int] = {}
    for rank, host in enumerate(rank_hosts):
        env = dict(os.environ)
        if extra_env:
            env.update(extra_env)
        prev_pp = env.get("PYTHONPATH", "")
        if pkg_root not in prev_pp.split(os.pathsep):
            env["PYTHONPATH"] = (f"{pkg_root}{os.pathsep}{prev_pp}"
                                 if prev_pp else pkg_root)
        env["HOROVOD_TPU_COORDINATOR"] = f"{coord_host}:{coord_port}"
        env["HOROVOD_TPU_NUM_PROCESSES"] = str(np)
        env["HOROVOD_TPU_PROCESS_ID"] = str(rank)
        # Single-host jobs may use the shared-memory data plane for eager
        # host-staged collectives (the reference's MPI shared-memory CPU
        # path); the launcher is the authority on placement.
        env["HOROVOD_TPU_ALL_LOCAL"] = "0" if any_remote else "1"
        env["HOROVOD_TPU_CONTROL"] = f"{coord_host}:{ctrl_port}"
        env[SECRET_ENV] = secret
        local_rank = local_counts.get(host, 0)
        local_counts[host] = local_rank + 1
        env["HOROVOD_TPU_LOCAL_PROCESS_ID"] = str(local_rank)

        prefix = f"[{rank}]" if tag_output else None
        if is_local_host(host):
            workers.append(ManagedProcess(list(command), env, prefix=prefix,
                                          stdout=stdout, stderr=stderr))
        else:
            args, stdin_data = _ssh_spawn_spec(host, env, list(command),
                                               extra_keys)
            workers.append(ManagedProcess(args, env, prefix=prefix,
                                          stdout=stdout, stderr=stderr,
                                          stdin_data=stdin_data))
    return LaunchedJob(workers)
