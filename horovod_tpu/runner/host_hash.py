"""Host identity hashing for slot grouping.

Parity: horovod/spark/util/host_hash.py (reference :15-36) — tasks on the
same physical host must be grouped so ranks land contiguously per host (the
reference feeds ``-H host_hash:count`` to mpirun). The hash combines
hostname with an optional namespace salt for containerized environments
where hostnames collide.
"""

from __future__ import annotations

import hashlib
import os
import socket


def host_hash(salt: str | None = None) -> str:
    """Stable identifier for this host."""
    parts = [socket.gethostname()]
    # Containers may share hostnames across nodes; a namespace env
    # disambiguates (the reference mixes in the mount namespace).
    ns = os.environ.get("HOROVOD_TPU_HOST_NAMESPACE")
    if ns:
        parts.append(ns)
    if salt:
        parts.append(salt)
    joined = "-".join(parts)
    return "%s-%s" % (parts[0],
                      hashlib.md5(joined.encode("utf-8")).hexdigest()[:8])
