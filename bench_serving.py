#!/usr/bin/env python
"""Serving-tier benchmark — continuous-batched vs sequential decode
throughput, and latency percentiles from the live registry histograms
(docs/serving.md, docs/benchmarks.md).

``--fleet`` instead measures AVAILABILITY: a 3-replica fleet behind
the failover router, with replica 1 hard-crashed mid-load by a
deterministic ``replica_crash_at`` fault — requests
attempted/succeeded/retried, failover latency p50/p99 (from the
router's ``hvdtpu_fleet_failover_seconds`` histogram), and the
output-token checksum, which is identical to an uncrashed run because
greedy decode makes the router's re-prefill resume byte-exact. Writes
BENCH_FLEET.json.

Each arm runs in a fresh subprocess on the CPU platform (fresh jit
cache, fresh metrics registry — the TTFT/TPOT percentiles reported for
an arm come from ITS OWN registry snapshot through the same
``histogram_percentiles`` estimator the /metrics.json endpoint uses).

Arms:
  - ``batched``    one engine with 8 batch slots; c ∈ {1, 2, 4, 8}
                   concurrent requests submitted at once (the
                   continuous-batching scheduler interleaves them per
                   decode step).
  - ``sequential`` the same 8 requests through a 1-slot engine — every
                   request waits for the previous one's last token.

Deterministic fields (seeded params, seeded prompts, greedy decode):
request/token counts and the output-token checksum — identical across
runs, byte-compared by the slow-tier reproducibility test. Wall-clock
fields (*_ms, tokens_per_s) are informational except the headline they
support: batched decode throughput at 8 concurrent requests is ≥ 2x
sequential (``batched_vs_sequential_ratio``).

Prints ONE JSON line and writes BENCH_SERVING.json with --out.
"""

import argparse
import json
import os
import subprocess
import sys

N_REQUESTS = 8
MAX_NEW = 16

WORKER = r"""
import json, sys, time
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.models import transformer as tfm
from horovod_tpu.parallel.mesh import create_mesh
from horovod_tpu.serving import InferenceEngine, ServingConfig
from horovod_tpu.observability import histogram_percentiles

slots = int(sys.argv[1])
concurrency = int(sys.argv[2])
max_new = int(sys.argv[3])

cfg = tfm.TransformerConfig(
    vocab=256, d_model=64, n_heads=2, n_layers=2, d_ff=128,
    max_seq=128, dtype=jnp.float32, remat=False)
params = tfm.init_params(cfg, jax.random.PRNGKey(42))
mesh = create_mesh(devices=jax.devices()[:1], tp=1)
engine = InferenceEngine(params, cfg, mesh, ServingConfig(
    block_size=8, kv_blocks=64, max_batch_slots=slots,
    max_queue=32, max_new_tokens=max_new, min_prefill_bucket=8))

rng = np.random.RandomState(7)
prompts = [list(rng.randint(0, 256, int(n)))
           for n in rng.randint(8, 25, concurrency)]

# Warmup: compile every prefill bucket + the decode program once, on a
# throwaway request per distinct bucket, so the measured wall is
# scheduling + forward — not XLA compiles.
for L in sorted({max(8, 1 << (len(p) - 1).bit_length()) for p in prompts}):
    engine.generate([1] * min(L, 24), max_new_tokens=2)

snap0 = hvd.metrics_snapshot()   # warmup baseline: histograms diffed out
t0 = time.perf_counter()
reqs = [engine.submit(p) for p in prompts]
engine.run_until_idle()
wall = time.perf_counter() - t0
outputs = [r.result() for r in reqs]

generated = sum(len(o) for o in outputs)
prompt_tokens = sum(len(p) for p in prompts)
checksum = int(sum((i + 1) * t for o in outputs
               for i, t in enumerate(o)) % (1 << 31))

snap = hvd.metrics_snapshot()
def pct(name):
    # Cumulative-histogram diff against the warmup baseline, so the
    # percentiles describe the measured requests only (warmup carries
    # the XLA compiles).
    h1 = snap[name]["values"][""]
    h0 = snap0[name]["values"].get("", {"buckets": [], "count": 0,
                                        "sum": 0.0})
    prev = {le: c for le, c in h0["buckets"]}
    diff = {"buckets": [[le, c - prev.get(le, 0)]
                        for le, c in h1["buckets"]],
            "count": h1["count"] - h0["count"],
            "sum": h1["sum"] - h0["sum"]}
    return {k: round(v * 1e3, 3)
            for k, v in histogram_percentiles(diff).items()}

print(json.dumps({
    "wall_ms": round(wall * 1e3, 3),
    "tokens_per_s": round(generated / wall, 2),
    "requests": concurrency,
    "prompt_tokens": prompt_tokens,
    "generated_tokens": generated,
    "output_checksum": checksum,
    "outputs": outputs,
    "ttft_ms": pct("hvdtpu_serving_ttft_seconds"),
    "tpot_ms": pct("hvdtpu_serving_tpot_seconds"),
    "decode_steps": snap["hvdtpu_serving_decode_steps_total"]
        ["values"][""],
}))
"""


FLEET_WORKER = r"""
import json, os, sys, tempfile, time
from concurrent.futures import ThreadPoolExecutor
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from horovod_tpu.checkpoint import CheckpointEngine
from horovod_tpu.models import transformer as tfm
from horovod_tpu.parallel.mesh import create_mesh
from horovod_tpu.serving import (InferenceEngine, Router, ServingConfig,
                                 config_from_manifest, load_params,
                                 serving_config, transformer_extra)
from horovod_tpu.serving.fleet import Fleet
from horovod_tpu.observability import (histogram_percentiles,
                                       metrics_snapshot)

n_replicas = int(sys.argv[1])
n_requests = int(sys.argv[2])
max_new = int(sys.argv[3])
crash_tick = int(sys.argv[4])

tmp = tempfile.mkdtemp(prefix="bench_fleet_")
ckpt = os.path.join(tmp, "ckpt")
cfg = tfm.TransformerConfig(
    vocab=256, d_model=64, n_heads=2, n_layers=2, d_ff=128,
    max_seq=128, dtype=jnp.float32, remat=False)
params = tfm.init_params(cfg, jax.random.PRNGKey(42))
CheckpointEngine(ckpt, process_count=1, barrier=lambda n: None).save(
    params, 1, block=True, extra=transformer_extra(cfg))

# Uncontended reference (seeded prompts, greedy): the availability
# claim is not just "200 OK" but token-identical output through the
# crash.
mesh1 = create_mesh(devices=jax.devices()[:1], tp=1)
man = CheckpointEngine(ckpt).restore_manifest()
scfg = serving_config(config_from_manifest(man), mesh1)
ref = InferenceEngine(load_params(ckpt, scfg, mesh1), scfg, mesh1,
                      ServingConfig(block_size=8, kv_blocks=64,
                                    max_batch_slots=4,
                                    max_new_tokens=max_new))
rng = np.random.RandomState(7)
prompts = [[int(t) for t in rng.randint(0, 256, int(n))]
           for n in rng.randint(8, 25, n_requests)]
expected = [ref.generate(p) for p in prompts]

env = dict(os.environ)
env["HOROVOD_TPU_FAULT_SPEC"] = (
    "rank=1:replica_crash_at=%d:gen=0" % crash_tick)
fleet = Fleet(n_replicas,
              ["--checkpoint-dir", ckpt, "--tp", "1",
               "--block-size", "8", "--kv-blocks", "64",
               "--slots", "4", "--max-new-tokens", str(max_new)],
              env=env)
router = Router(fleet, port=0, host="127.0.0.1",
                scrape_interval_s=0.1)
fleet.start()
fleet.wait_ready(600.0)
router.start()

import http.client

def one(i):
    conn = http.client.HTTPConnection("127.0.0.1", router.port,
                                      timeout=300)
    conn.request("POST", "/generate",
                 json.dumps({"tokens": prompts[i],
                             "max_new_tokens": max_new}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    return resp.status, json.loads(resp.read())

t0 = time.perf_counter()
with ThreadPoolExecutor(max_workers=6) as pool:
    results = list(pool.map(one, range(n_requests)))
wall = time.perf_counter() - t0
fleet_stop_ok = True
try:
    router.shutdown()
    fleet.stop()
except Exception:
    fleet_stop_ok = False

succeeded = sum(1 for s, _ in results if s == 200)
outputs_equal = all(
    s == 200 and b["tokens"] == expected[i]
    for i, (s, b) in enumerate(results))
checksum = int(sum((i + 1) * t
                   for _, b in results if isinstance(b, dict)
                   for i, t in enumerate(b.get("tokens", [])))
               % (1 << 31))

snap = metrics_snapshot()
def count(name, labels=None):
    vals = snap.get(name, {"values": {}})["values"]
    if labels is None:
        return {k: v for k, v in vals.items()}
    return vals.get(labels, 0)

fo = snap.get("hvdtpu_fleet_failover_seconds",
              {"values": {}})["values"].get("")
fo_pct = ({k: round(v * 1e3, 3)
           for k, v in histogram_percentiles(fo).items()}
          if fo else None)

print(json.dumps({
    "wall_ms": round(wall * 1e3, 3),
    "replicas": n_replicas,
    "requests_attempted": n_requests,
    "requests_succeeded": succeeded,
    "requests_failed": n_requests - succeeded,
    "outputs_equal_uncontended": outputs_equal,
    "output_checksum": checksum,
    "retries_by_reason": count("hvdtpu_fleet_retries_total"),
    "failovers_by_phase": count("hvdtpu_fleet_failovers_total"),
    "replica_restarts": sum(r.restarts for r in fleet.replicas),
    "failover_ms": fo_pct,
    "clean_stop": fleet_stop_ok,
}))
"""


def run_fleet(out_path):
    """The --fleet availability arm, in a fresh subprocess (its own
    registry, its own jit cache) like every other arm."""
    env = dict(os.environ)
    env.pop("HOROVOD_TPU_METRICS", None)
    proc = subprocess.run(
        [sys.executable, "-c", FLEET_WORKER, "3", "32", "16", "25"],
        env=env, capture_output=True, text=True, timeout=900,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    if proc.returncode != 0:
        raise RuntimeError(
            f"fleet bench worker failed:\n{proc.stderr[-3000:]}")
    r = json.loads(proc.stdout.strip().splitlines()[-1])
    result = {
        "metric": "fleet_availability_under_replica_crash",
        "model": {"d_model": 64, "n_layers": 2, "n_heads": 2,
                  "vocab": 256, "dtype": "float32"},
        "fault": "rank=1:replica_crash_at=25:gen=0",
        "note": ("3-replica fleet behind the failover router; replica "
                 "1 is SIGKILLed by a deterministic fault mid-load. "
                 "requests_*, outputs_equal_uncontended and "
                 "output_checksum are seeded-deterministic (greedy "
                 "decode; the router's re-prefill resume is "
                 "token-exact, so the crash is invisible in the "
                 "checksum). retries/failover counts and *_ms are "
                 "run-dependent (which requests sat on the dying "
                 "replica is a scheduling accident)."),
        **r,
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
    print(json.dumps(result))


def run_arm(slots: int, concurrency: int) -> dict:
    env = dict(os.environ)
    env.pop("HOROVOD_TPU_METRICS", None)   # percentiles need recording
    proc = subprocess.run(
        [sys.executable, "-c", WORKER, str(slots), str(concurrency),
         str(MAX_NEW)],
        env=env, capture_output=True, text=True, timeout=900,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    if proc.returncode != 0:
        raise RuntimeError(
            f"serving bench worker failed (slots={slots}, "
            f"c={concurrency}):\n{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None,
                    help="write BENCH_SERVING.json (or, with --fleet, "
                         "BENCH_FLEET.json) here")
    ap.add_argument("--fleet", action="store_true",
                    help="measure fleet availability under an injected "
                         "replica crash instead of single-replica "
                         "throughput")
    args = ap.parse_args()

    if args.fleet:
        run_fleet(args.out)
        return

    sweep = {}
    for c in (1, 2, 4, 8):
        r = run_arm(slots=8, concurrency=c)
        sweep[str(c)] = {k: r[k] for k in
                         ("wall_ms", "tokens_per_s", "generated_tokens")}
    batched = run_arm(slots=8, concurrency=N_REQUESTS)
    sequential = run_arm(slots=1, concurrency=N_REQUESTS)

    ratio = round(batched["tokens_per_s"]
                  / sequential["tokens_per_s"], 3)
    result = {
        "metric": "serving_batched_vs_sequential_tokens_per_sec",
        "model": {"d_model": 64, "n_layers": 2, "n_heads": 2,
                  "vocab": 256, "dtype": "float32"},
        "requests": N_REQUESTS,
        "max_new_tokens": MAX_NEW,
        "note": ("Token/request counts and output_checksum are seeded "
                 "and deterministic (greedy decode); *_ms and "
                 "tokens_per_s are wall-clock. Headline: continuous "
                 "batching at 8 concurrent requests sustains >= 2x the "
                 "sequential (1-slot) decode throughput — "
                 "batched_vs_sequential_ratio. TTFT/TPOT percentiles "
                 "come from each arm's own "
                 "hvdtpu_serving_{ttft,tpot}_seconds registry "
                 "histograms."),
        "sweep_batched_by_concurrency": sweep,
        "batched": {k: batched[k] for k in
                    ("wall_ms", "tokens_per_s", "prompt_tokens",
                     "generated_tokens", "output_checksum",
                     "decode_steps", "ttft_ms", "tpot_ms")},
        "sequential": {k: sequential[k] for k in
                       ("wall_ms", "tokens_per_s", "prompt_tokens",
                        "generated_tokens", "output_checksum",
                        "decode_steps", "ttft_ms", "tpot_ms")},
        "outputs_equal": batched["outputs"] == sequential["outputs"],
        "batched_vs_sequential_ratio": ratio,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
