#!/usr/bin/env python
"""Serving-tier benchmark — continuous-batched vs sequential decode
throughput, and latency percentiles from the live registry histograms
(docs/serving.md, docs/benchmarks.md).

``--fleet`` instead measures AVAILABILITY: a 3-replica fleet behind
the failover router, with replica 1 hard-crashed mid-load by a
deterministic ``replica_crash_at`` fault — requests
attempted/succeeded/retried, failover latency p50/p99 (from the
router's ``hvdtpu_fleet_failover_seconds`` histogram), and the
output-token checksum, which is identical to an uncrashed run because
greedy decode makes the router's re-prefill resume byte-exact. Writes
BENCH_FLEET.json.

Each arm runs in a fresh subprocess on the CPU platform (fresh jit
cache, fresh metrics registry — the TTFT/TPOT percentiles reported for
an arm come from ITS OWN registry snapshot through the same
``histogram_percentiles`` estimator the /metrics.json endpoint uses).

Arms:
  - ``batched``    one engine with 8 batch slots; c ∈ {1, 2, 4, 8}
                   concurrent requests submitted at once (the
                   continuous-batching scheduler interleaves them per
                   decode step).
  - ``sequential`` the same 8 requests through a 1-slot engine — every
                   request waits for the previous one's last token.

Deterministic fields (seeded params, seeded prompts, greedy decode):
request/token counts and the output-token checksum — identical across
runs, byte-compared by the slow-tier reproducibility test. Wall-clock
fields (*_ms, tokens_per_s) are informational except the headline they
support: batched decode throughput at 8 concurrent requests is ≥ 2x
sequential (``batched_vs_sequential_ratio``).

``--speed`` measures the three raw-speed levers
(docs/serving.md#speed-levers) on a purpose-built bench model: a
flagship (256d x 4L) and a shrunk drafter (64d x 1L) are first TRAINED
(seeded, deterministic) on the cyclic-successor task — the drafter must
actually agree with the flagship for speculation to pay, and random
weights agree on nothing — then five arms serve the same 8 requests
sharing a 48-token system prompt: baseline / quantized-KV (int8 pool) /
speculative (k=8 verify chunks) / prefix-cache / all-on. Each arm
records tok/s, TTFT/TPOT percentiles, KV bytes resident at full
admission, and the lever's own counters (draft acceptance, prefix
hits). Headlines: speculative ≥ 1.5x tok/s and token-identical under
greedy decode; prefix-cache TTFT p50 below baseline with the prefill
token count to prove why; quantized pool < 0.30x resident KV bytes.
Writes BENCH_SPEED.json.

``--slo`` runs the open-loop SLO sweep (docs/serving.md#slo): a seeded
Poisson arrival schedule fires at 4/10/25 req/s against the 3-replica
fleet — past its ~12 req/s pinned capacity — with fixed TTFT/TPOT
targets attached to every request, and goodput (SLO-met over OFFERED
load) develops the knee closed-loop benches structurally hide. A
two-tenant arm replays the identical interactive schedule with and
without an overlapping bulk burst and reports the interactive p99
inflation. Writes BENCH_SLO.json.

``--reqtrace`` A/Bs the per-request serving trace capture
(docs/serving.md#request-tracing) on vs off under the same load —
in-process toggle, alternating-order paired rounds, pooled per-request
latencies, p25 (the BENCH_TRACE methodology) — and writes
BENCH_REQTRACE.json; the slow-tier guard holds the overhead under 3%.

Prints ONE JSON line and writes BENCH_SERVING.json with --out.
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile

N_REQUESTS = 8
MAX_NEW = 16

WORKER = r"""
import json, sys, time
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.models import transformer as tfm
from horovod_tpu.parallel.mesh import create_mesh
from horovod_tpu.serving import InferenceEngine, ServingConfig
from horovod_tpu.observability import histogram_percentiles

slots = int(sys.argv[1])
concurrency = int(sys.argv[2])
max_new = int(sys.argv[3])

cfg = tfm.TransformerConfig(
    vocab=256, d_model=64, n_heads=2, n_layers=2, d_ff=128,
    max_seq=128, dtype=jnp.float32, remat=False)
params = tfm.init_params(cfg, jax.random.PRNGKey(42))
mesh = create_mesh(devices=jax.devices()[:1], tp=1)
engine = InferenceEngine(params, cfg, mesh, ServingConfig(
    block_size=8, kv_blocks=64, max_batch_slots=slots,
    max_queue=32, max_new_tokens=max_new, min_prefill_bucket=8))

rng = np.random.RandomState(7)
prompts = [list(rng.randint(0, 256, int(n)))
           for n in rng.randint(8, 25, concurrency)]

# Warmup: compile every prefill bucket + the decode program once, on a
# throwaway request per distinct bucket, so the measured wall is
# scheduling + forward — not XLA compiles.
for L in sorted({max(8, 1 << (len(p) - 1).bit_length()) for p in prompts}):
    engine.generate([1] * min(L, 24), max_new_tokens=2)

snap0 = hvd.metrics_snapshot()   # warmup baseline: histograms diffed out
t0 = time.perf_counter()
reqs = [engine.submit(p) for p in prompts]
engine.run_until_idle()
wall = time.perf_counter() - t0
outputs = [r.result() for r in reqs]

generated = sum(len(o) for o in outputs)
prompt_tokens = sum(len(p) for p in prompts)
checksum = int(sum((i + 1) * t for o in outputs
               for i, t in enumerate(o)) % (1 << 31))

snap = hvd.metrics_snapshot()
def pct(name):
    # Cumulative-histogram diff against the warmup baseline, so the
    # percentiles describe the measured requests only (warmup carries
    # the XLA compiles).
    h1 = snap[name]["values"][""]
    h0 = snap0[name]["values"].get("", {"buckets": [], "count": 0,
                                        "sum": 0.0})
    prev = {le: c for le, c in h0["buckets"]}
    diff = {"buckets": [[le, c - prev.get(le, 0)]
                        for le, c in h1["buckets"]],
            "count": h1["count"] - h0["count"],
            "sum": h1["sum"] - h0["sum"]}
    return {k: round(v * 1e3, 3)
            for k, v in histogram_percentiles(diff).items()}

print(json.dumps({
    "wall_ms": round(wall * 1e3, 3),
    "tokens_per_s": round(generated / wall, 2),
    "requests": concurrency,
    "prompt_tokens": prompt_tokens,
    "generated_tokens": generated,
    "output_checksum": checksum,
    "outputs": outputs,
    "ttft_ms": pct("hvdtpu_serving_ttft_seconds"),
    "tpot_ms": pct("hvdtpu_serving_tpot_seconds"),
    "decode_steps": snap["hvdtpu_serving_decode_steps_total"]
        ["values"][""],
}))
"""


FLEET_WORKER = r"""
import json, os, sys, tempfile, time
from concurrent.futures import ThreadPoolExecutor
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from horovod_tpu.checkpoint import CheckpointEngine
from horovod_tpu.models import transformer as tfm
from horovod_tpu.parallel.mesh import create_mesh
from horovod_tpu.serving import (InferenceEngine, Router, ServingConfig,
                                 config_from_manifest, load_params,
                                 serving_config, transformer_extra)
from horovod_tpu.serving.fleet import Fleet
from horovod_tpu.observability import (histogram_percentiles,
                                       metrics_snapshot)

n_replicas = int(sys.argv[1])
n_requests = int(sys.argv[2])
max_new = int(sys.argv[3])
crash_tick = int(sys.argv[4])

tmp = tempfile.mkdtemp(prefix="bench_fleet_")
ckpt = os.path.join(tmp, "ckpt")
cfg = tfm.TransformerConfig(
    vocab=256, d_model=64, n_heads=2, n_layers=2, d_ff=128,
    max_seq=128, dtype=jnp.float32, remat=False)
params = tfm.init_params(cfg, jax.random.PRNGKey(42))
CheckpointEngine(ckpt, process_count=1, barrier=lambda n: None).save(
    params, 1, block=True, extra=transformer_extra(cfg))

# Uncontended reference (seeded prompts, greedy): the availability
# claim is not just "200 OK" but token-identical output through the
# crash.
mesh1 = create_mesh(devices=jax.devices()[:1], tp=1)
man = CheckpointEngine(ckpt).restore_manifest()
scfg = serving_config(config_from_manifest(man), mesh1)
ref = InferenceEngine(load_params(ckpt, scfg, mesh1), scfg, mesh1,
                      ServingConfig(block_size=8, kv_blocks=64,
                                    max_batch_slots=4,
                                    max_new_tokens=max_new))
rng = np.random.RandomState(7)
prompts = [[int(t) for t in rng.randint(0, 256, int(n))]
           for n in rng.randint(8, 25, n_requests)]
expected = [ref.generate(p) for p in prompts]

env = dict(os.environ)
env["HOROVOD_TPU_FAULT_SPEC"] = (
    "rank=1:replica_crash_at=%d:gen=0" % crash_tick)
fleet = Fleet(n_replicas,
              ["--checkpoint-dir", ckpt, "--tp", "1",
               "--block-size", "8", "--kv-blocks", "64",
               "--slots", "4", "--max-new-tokens", str(max_new)],
              env=env)
router = Router(fleet, port=0, host="127.0.0.1",
                scrape_interval_s=0.1)
fleet.start()
fleet.wait_ready(600.0)
router.start()

import http.client

def one(i):
    conn = http.client.HTTPConnection("127.0.0.1", router.port,
                                      timeout=300)
    conn.request("POST", "/generate",
                 json.dumps({"tokens": prompts[i],
                             "max_new_tokens": max_new}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    return resp.status, json.loads(resp.read())

t0 = time.perf_counter()
with ThreadPoolExecutor(max_workers=6) as pool:
    results = list(pool.map(one, range(n_requests)))
wall = time.perf_counter() - t0
fleet_stop_ok = True
try:
    router.shutdown()
    fleet.stop()
except Exception:
    fleet_stop_ok = False

succeeded = sum(1 for s, _ in results if s == 200)
outputs_equal = all(
    s == 200 and b["tokens"] == expected[i]
    for i, (s, b) in enumerate(results))
checksum = int(sum((i + 1) * t
                   for _, b in results if isinstance(b, dict)
                   for i, t in enumerate(b.get("tokens", [])))
               % (1 << 31))

snap = metrics_snapshot()
def count(name, labels=None):
    vals = snap.get(name, {"values": {}})["values"]
    if labels is None:
        return {k: v for k, v in vals.items()}
    return vals.get(labels, 0)

fo = snap.get("hvdtpu_fleet_failover_seconds",
              {"values": {}})["values"].get("")
fo_pct = ({k: round(v * 1e3, 3)
           for k, v in histogram_percentiles(fo).items()}
          if fo else None)

print(json.dumps({
    "wall_ms": round(wall * 1e3, 3),
    "replicas": n_replicas,
    "requests_attempted": n_requests,
    "requests_succeeded": succeeded,
    "requests_failed": n_requests - succeeded,
    "outputs_equal_uncontended": outputs_equal,
    "output_checksum": checksum,
    "retries_by_reason": count("hvdtpu_fleet_retries_total"),
    "failovers_by_phase": count("hvdtpu_fleet_failovers_total"),
    "replica_restarts": sum(r.restarts for r in fleet.replicas),
    "failover_ms": fo_pct,
    "clean_stop": fleet_stop_ok,
}))
"""


SLO_WORKER = r"""
import json, os, sys, tempfile, time
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from horovod_tpu.checkpoint import CheckpointEngine
from horovod_tpu.models import transformer as tfm
from horovod_tpu.serving import Router, transformer_extra
from horovod_tpu.serving import loadgen
from horovod_tpu.serving.fleet import Fleet
from horovod_tpu.tools.slo import _arm_from_run

n_replicas = int(sys.argv[1])
max_new = int(sys.argv[2])
duration_s = float(sys.argv[3])
seed = int(sys.argv[4])

SLO = {"ttft_ms": 500.0, "tpot_ms": 100.0}
SWEEP_RPS = (4, 10, 25)

tmp = tempfile.mkdtemp(prefix="bench_slo_")
ckpt = os.path.join(tmp, "ckpt")
cfg = tfm.TransformerConfig(
    vocab=256, d_model=64, n_heads=2, n_layers=2, d_ff=128,
    max_seq=128, dtype=jnp.float32, remat=False)
params = tfm.init_params(cfg, jax.random.PRNGKey(42))
CheckpointEngine(ckpt, process_count=1, barrier=lambda n: None).save(
    params, 1, block=True, extra=transformer_extra(cfg))

env = dict(os.environ)
# CPU decode speed is machine-dependent; pinning the per-token cost
# with a deterministic slow_decode fault makes fleet capacity — and
# therefore where the knee lands — an experimental constant
# (~2 slots x 3 replicas / (max_new x 20ms) ~= 12 req/s).
env["HOROVOD_TPU_FAULT_SPEC"] = "rank=*:slow_decode=20ms"
fleet = Fleet(n_replicas,
              ["--checkpoint-dir", ckpt, "--tp", "1",
               "--block-size", "8", "--kv-blocks", "64",
               "--slots", "2", "--max-new-tokens", str(max_new)],
              env=env)
router = Router(fleet, port=0, host="127.0.0.1",
                scrape_interval_s=0.1)
fleet.start()
fleet.wait_ready(600.0)
router.start()

import http.client

def warm(n_tokens, rounds, port):
    # Distinct prompts (no prefix-cache shortcut) so every replica
    # compiles this prefill bucket before the clock starts.
    for i in range(rounds):
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=300)
        conn.request("POST", "/generate",
                     json.dumps({"tokens": [2 + i] * n_tokens,
                                 "max_new_tokens": 2}),
                     {"Content-Type": "application/json"})
        conn.getresponse().read()
        conn.close()

for n_tokens in (6, 12, 48):   # buckets 8 / 16 / 64
    warm(n_tokens, 2 * n_replicas, router.port)

sweep = {}
for rps in SWEEP_RPS:
    tenant = loadgen.TenantSpec("sweep", prompt_len=(8, 16),
                                max_new_tokens=max_new, slo=SLO)
    sched = loadgen.build_schedule(rps, duration_s, seed + rps,
                                   [tenant])
    run = loadgen.run_schedule(sched, "127.0.0.1", router.port,
                               max_inflight=256, timeout_s=120.0)
    arm = _arm_from_run("rps%d" % rps, run, offered_rps=rps)
    arm["schedule_checksum"] = loadgen.schedule_checksum(sched)
    arm["duration_s"] = duration_s
    sweep["rps%d" % rps] = arm

# Two-tenant arm: the interactive tenant keeps the SAME seeded
# schedule in both runs; the only difference is the bulk burst
# overlapping the first half. Whatever its p99 does is the bulk
# tenant's doing.
interactive = loadgen.TenantSpec("interactive", prompt_len=(8, 16),
                                 max_new_tokens=8, slo=SLO)
bulk = loadgen.TenantSpec("bulk", prompt_len=(48, 64),
                          max_new_tokens=max_new)
ia = loadgen.build_schedule(3.0, duration_s, seed, [interactive])
bb = loadgen.build_schedule(6.0, duration_s / 2, seed + 1, [bulk])
ia_checksum = loadgen.schedule_checksum(ia)

run_a = loadgen.run_schedule(ia, "127.0.0.1", router.port,
                             max_inflight=256, timeout_s=120.0)
arm_a = _arm_from_run("interactive_only", run_a, offered_rps=3.0)
arm_a["schedule_checksum"] = ia_checksum

merged = sorted(ia + bb, key=lambda a: a.t_s)
run_b = loadgen.run_schedule(merged, "127.0.0.1", router.port,
                             max_inflight=256, timeout_s=120.0)
arm_b = _arm_from_run("with_bulk_burst", run_b,
                      offered_rps=3.0 + 6.0 * 0.5)
arm_b["interactive_schedule_checksum"] = loadgen.schedule_checksum(
    [a for a in merged if a.tenant == "interactive"])
arm_b["bulk_schedule_checksum"] = loadgen.schedule_checksum(bb)

clean_stop = True
try:
    router.shutdown()
    fleet.stop()
except Exception:
    clean_stop = False

p99_alone = arm_a["tenants"]["interactive"]["ttft_p99_ms"]
p99_burst = arm_b["tenants"]["interactive"]["ttft_p99_ms"]

# ---- QoS arm (docs/serving.md#qos): the SAME two-tenant replay —
# byte-identical interactive schedule, checksum-asserted — against a
# fleet with priority classes, DWRR weights and a reserved interactive
# slot. The A/B against the plain fleet above isolates what the QoS
# plane buys the interactive tenant under the same bulk burst.
QOS_CLASSES = {"interactive": "interactive", "bulk": "bulk"}
qos_cfg_path = os.path.join(tmp, "slo_config.json")
qos_policy = {"tenants": {
    "interactive": {"priority": "interactive", "weight": 8},
    "bulk": {"priority": "bulk", "weight": 1}}}
with open(qos_cfg_path, "w") as f:
    json.dump(qos_policy, f)
os.environ["HOROVOD_TPU_SLO_CONFIG"] = qos_cfg_path
from horovod_tpu.serving import qos as _qosmod
from horovod_tpu.serving import slo as _slomod
_qosmod._reset_policy()
_slomod._reset_policy()
env_qos = dict(env)
env_qos["HOROVOD_TPU_SLO_CONFIG"] = qos_cfg_path

fleet2 = Fleet(n_replicas,
               ["--checkpoint-dir", ckpt, "--tp", "1",
                "--block-size", "8", "--kv-blocks", "64",
                "--slots", "2", "--max-new-tokens", str(max_new),
                "--reserved-slots", "1"],
               env=env_qos)
router2 = Router(fleet2, port=0, host="127.0.0.1",
                 scrape_interval_s=0.1)
fleet2.start()
fleet2.wait_ready(600.0)
router2.start()
for n_tokens in (6, 12, 48):
    warm(n_tokens, 2 * n_replicas, router2.port)

run_qa = loadgen.run_schedule(ia, "127.0.0.1", router2.port,
                              max_inflight=256, timeout_s=120.0)
run_qa["summary"] = loadgen.summarize(run_qa, classes=QOS_CLASSES)
arm_qa = _arm_from_run("qos_interactive_only", run_qa,
                       offered_rps=3.0)
arm_qa["schedule_checksum"] = loadgen.schedule_checksum(ia)

run_qb = loadgen.run_schedule(merged, "127.0.0.1", router2.port,
                              max_inflight=256, timeout_s=120.0)
run_qb["summary"] = loadgen.summarize(run_qb, classes=QOS_CLASSES)
arm_qb = _arm_from_run("qos_with_bulk_burst", run_qb,
                       offered_rps=3.0 + 6.0 * 0.5)
arm_qb["interactive_schedule_checksum"] = loadgen.schedule_checksum(
    [a for a in merged if a.tenant == "interactive"])
arm_qb["bulk_schedule_checksum"] = loadgen.schedule_checksum(bb)
try:
    router2.shutdown()
    fleet2.stop()
except Exception:
    clean_stop = False

# ---- Autoscaling knee sweep: the same offered-load ladder against a
# 2-replica fleet allowed to grow to 4 on sustained pressure (and
# drain back once load clears). Scale decisions land in the artifact.
from horovod_tpu.serving import AutoscalerConfig, FleetAutoscaler
fleet3 = Fleet(2,
               ["--checkpoint-dir", ckpt, "--tp", "1",
                "--block-size", "8", "--kv-blocks", "64",
                "--slots", "2", "--max-new-tokens", str(max_new),
                "--reserved-slots", "1"],
               env=env_qos)
router3 = Router(fleet3, port=0, host="127.0.0.1",
                 scrape_interval_s=0.1)
fleet3.start()
fleet3.wait_ready(600.0)
router3.start()
for n_tokens in (6, 12, 48):
    warm(n_tokens, 2 * 2, router3.port)
scaler = FleetAutoscaler(
    fleet3,
    AutoscalerConfig(2, 4, high_load=1.2, low_load=0.3,
                     sustain_s=1.0, cooldown_s=3.0),
    signals=router3.qos_signals, interval_s=0.25)
fleet3.on_alert = scaler.note_alert
scaler.start()
auto_sweep = {}
for rps in SWEEP_RPS:
    tenant = loadgen.TenantSpec("sweep", prompt_len=(8, 16),
                                max_new_tokens=max_new, slo=SLO)
    sched = loadgen.build_schedule(rps, duration_s, seed + rps,
                                   [tenant])
    run = loadgen.run_schedule(sched, "127.0.0.1", router3.port,
                               max_inflight=256, timeout_s=120.0)
    arm = _arm_from_run("auto_rps%d" % rps, run, offered_rps=rps)
    arm["schedule_checksum"] = loadgen.schedule_checksum(sched)
    arm["duration_s"] = duration_s
    arm["replicas_after"] = fleet3.live_count()
    auto_sweep["rps%d" % rps] = arm
# Let any scale-up finish coming online, then re-offer the past-knee
# rate: goodput with the grown fleet vs the first pass.
deadline = time.time() + 45.0
while time.time() < deadline and any(
        not r.up for r in list(fleet3.replicas) if not r.retiring):
    time.sleep(0.5)
sched25 = loadgen.build_schedule(25, duration_s, seed + 25,
    [loadgen.TenantSpec("sweep", prompt_len=(8, 16),
                        max_new_tokens=max_new, slo=SLO)])
run25b = loadgen.run_schedule(sched25, "127.0.0.1", router3.port,
                              max_inflight=256, timeout_s=120.0)
arm25b = _arm_from_run("auto_rps25_scaled", run25b, offered_rps=25)
arm25b["schedule_checksum"] = loadgen.schedule_checksum(sched25)
arm25b["duration_s"] = duration_s
arm25b["replicas_after"] = fleet3.live_count()
auto_sweep["rps25_scaled"] = arm25b
# Idle: the cooldown drains the fleet back toward the floor.
deadline = time.time() + 25.0
while time.time() < deadline and not any(
        d["direction"] == "down" for d in scaler.decisions):
    time.sleep(0.5)
scaler.stop()
scale_events = [{"direction": d["direction"], "why": d["why"],
                 "n": d["n"]} for d in scaler.decisions]
replicas_final = fleet3.live_count()
try:
    router3.shutdown()
    fleet3.stop()
except Exception:
    clean_stop = False

qp99_alone = arm_qa["tenants"]["interactive"]["ttft_p99_ms"]
qp99_burst = arm_qb["tenants"]["interactive"]["ttft_p99_ms"]
print(json.dumps({
    "sweep": sweep,
    "two_tenant": {
        "interactive_only": arm_a,
        "with_bulk_burst": arm_b,
        "interactive_schedules_identical": (
            arm_b["interactive_schedule_checksum"] == ia_checksum),
        "interactive_ttft_p99_alone_ms": p99_alone,
        "interactive_ttft_p99_under_burst_ms": p99_burst,
        "interactive_p99_inflation": round(
            p99_burst / max(p99_alone, 1e-9), 3),
    },
    "qos": {
        "policy": qos_policy["tenants"],
        "reserved_slots": 1,
        "interactive_only": arm_qa,
        "with_bulk_burst": arm_qb,
        "interactive_schedules_identical": (
            arm_qb["interactive_schedule_checksum"] == ia_checksum),
        "interactive_ttft_p99_alone_ms": qp99_alone,
        "interactive_ttft_p99_under_burst_ms": qp99_burst,
        "interactive_p99_inflation_qos": round(
            qp99_burst / max(qp99_alone, 1e-9), 3),
        "interactive_p99_inflation_baseline": round(
            p99_burst / max(p99_alone, 1e-9), 3),
        "autoscale": {
            "config": {"min": 2, "max": 4, "high_load": 1.2,
                       "low_load": 0.3, "sustain_s": 1.0,
                       "cooldown_s": 3.0},
            "sweep": auto_sweep,
            "scale_events": scale_events,
            "scaled_up": any(e["direction"] == "up"
                             for e in scale_events),
            "scaled_back_down": any(e["direction"] == "down"
                                    for e in scale_events),
            "replicas_final": replicas_final,
        },
    },
    "clean_stop": clean_stop,
}))
"""


SPEED_PREP = r"""
import json, os, sys, time
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import optax
from horovod_tpu.checkpoint import CheckpointEngine
from horovod_tpu.models import transformer as tfm
from horovod_tpu.serving import transformer_extra

out_dir = sys.argv[1]
VOCAB = 512

def cfg_of(d, l, h, ff):
    return tfm.TransformerConfig(vocab=VOCAB, d_model=d, n_heads=h,
                                 n_layers=l, d_ff=ff, max_seq=160,
                                 dtype=jnp.float32, remat=False)

def train(cfg, seed, phases, lr):
    # Cyclic-successor task (next = (t + 1) % vocab): trivially
    # learnable, so BOTH models converge to the same argmax map and
    # the drafter's proposals genuinely agree with the flagship —
    # random-weight pairs agree on nothing and would only ever measure
    # the rejection path. Curriculum: converge cheaply on short
    # windows, then a brief full-length phase so the positional rows
    # the decode actually visits (prompt 128 + 32 generated) are
    # trained for both models — untrained positions degrade the two
    # models DIFFERENTLY and tank acceptance. Seeded end to end.
    params = tfm.init_params(cfg, jax.random.PRNGKey(seed))
    opt = optax.adam(lr)
    st = opt.init(params)

    @jax.jit
    def step(p, s, tok, tgt):
        loss, g = jax.value_and_grad(tfm.loss_fn)(p, tok, tgt, cfg)
        up, s = opt.update(g, s)
        return optax.apply_updates(p, up), s, loss

    rng = np.random.RandomState(seed)
    for steps, bsz, seq in phases:
        for _ in range(steps):
            start = rng.randint(0, VOCAB, (bsz, 1))
            tok = (start + np.arange(seq)[None, :]) % VOCAB
            tgt = (tok + 1) % VOCAB
            params, st, loss = step(params, st, jnp.asarray(tok),
                                    jnp.asarray(tgt))
    return params, float(loss)

t0 = time.perf_counter()
flag_cfg = cfg_of(256, 4, 4, 512)
draft_cfg = cfg_of(64, 1, 1, 128)
flag, flag_loss = train(flag_cfg, 0, [(180, 8, 32), (70, 2, 160)], 3e-3)
draft, draft_loss = train(draft_cfg, 1, [(350, 8, 32), (120, 2, 160)],
                          5e-3)
for sub, cfg, params in (("flagship", flag_cfg, flag),
                         ("drafter", draft_cfg, draft)):
    CheckpointEngine(os.path.join(out_dir, sub), process_count=1,
                     barrier=lambda n: None).save(
        params, 1, block=True, extra=transformer_extra(cfg))
print(json.dumps({"train_s": round(time.perf_counter() - t0, 1),
                  "flagship_loss": round(flag_loss, 5),
                  "drafter_loss": round(draft_loss, 5)}))
"""


SPEED_WORKER = r"""
import json, os, sys, time
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.checkpoint import CheckpointEngine
from horovod_tpu.parallel.mesh import create_mesh
from horovod_tpu.serving import (InferenceEngine, ServingConfig,
                                 config_from_manifest, load_params,
                                 serving_config)
from horovod_tpu.observability import histogram_percentiles

ckpt_root = sys.argv[1]
arm = sys.argv[2]
n_requests = int(sys.argv[3])
max_new = int(sys.argv[4])
spec_k = int(sys.argv[5])

quant = arm in ("quantized_kv", "all_on")
spec = arm in ("speculative", "all_on")
prefix = arm in ("prefix_cache", "all_on")

mesh = create_mesh(devices=jax.devices()[:1], tp=1)

def load(sub):
    d = os.path.join(ckpt_root, sub)
    man = CheckpointEngine(d).restore_manifest()
    cfg = serving_config(config_from_manifest(man), mesh)
    return cfg, load_params(d, cfg, mesh)

cfg, params = load("flagship")
draft_cfg = draft_params = None
if spec:
    draft_cfg, draft_params = load("drafter")

engine = InferenceEngine(
    params, cfg, mesh,
    ServingConfig(block_size=16, kv_blocks=96, max_batch_slots=8,
                  max_queue=32, max_new_tokens=max_new,
                  min_prefill_bucket=16,
                  kv_quant="int8" if quant else None,
                  spec_tokens=spec_k if spec else 0,
                  prefix_cache=prefix),
    draft_params=draft_params, draft_cfg=draft_cfg)

VOCAB = cfg.vocab
# One shared 112-token system prompt (7 full KV blocks) + a 16-token
# unique tail per request — the fleet-shared-system-prompt shape the
# prefix cache exists for.
system = [(100 + i) % VOCAB for i in range(112)]
prompts = [system + [(250 + 16 * j + i) % VOCAB for i in range(16)]
           for j in range(n_requests)]

# Warmup: compile the prefill buckets (full prompt AND suffix-after-
# prefix-hit) plus the decode/draft programs on throwaway requests
# with a DIFFERENT system prefix, so the measured arm pays scheduling
# + forwards, not XLA compiles.
warm_sys = [(400 + i) % VOCAB for i in range(112)]
engine.generate(warm_sys + list(range(1, 17)), max_new_tokens=2)
engine.generate(warm_sys + list(range(17, 33)), max_new_tokens=2)

snap0 = hvd.metrics_snapshot()
t0 = time.perf_counter()
reqs = [engine.submit(p) for p in prompts]
engine.step()            # admit + prefill all 8 (slots == requests)
kv_bytes = int(engine._alloc.in_use * engine._bytes_per_block)
engine.run_until_idle()
wall = time.perf_counter() - t0
outputs = [r.result() for r in reqs]
snap = hvd.metrics_snapshot()

generated = sum(len(o) for o in outputs)
checksum = int(sum((i + 1) * t for o in outputs
               for i, t in enumerate(o)) % (1 << 31))

def cnt(name, labels=""):
    v1 = snap.get(name, {"values": {}})["values"].get(labels, 0)
    v0 = snap0.get(name, {"values": {}})["values"].get(labels, 0)
    return v1 - v0

def pct(name):
    h1 = snap[name]["values"][""]
    h0 = snap0[name]["values"].get("", {"buckets": [], "count": 0,
                                        "sum": 0.0})
    prev = {le: c for le, c in h0["buckets"]}
    diff = {"buckets": [[le, c - prev.get(le, 0)]
                        for le, c in h1["buckets"]],
            "count": h1["count"] - h0["count"],
            "sum": h1["sum"] - h0["sum"]}
    return {k: round(v * 1e3, 3)
            for k, v in histogram_percentiles(diff).items()}

print(json.dumps({
    "arm": arm,
    "wall_ms": round(wall * 1e3, 3),
    "tokens_per_s": round(generated / wall, 2),
    "generated_tokens": generated,
    "prefill_tokens": int(cnt("hvdtpu_serving_tokens_total",
                              'kind="prompt"')),
    "output_checksum": checksum,
    "outputs": outputs,
    "decode_steps": int(cnt("hvdtpu_serving_decode_steps_total")),
    "kv_bytes_resident": kv_bytes,
    "ttft_ms": pct("hvdtpu_serving_ttft_seconds"),
    "tpot_ms": pct("hvdtpu_serving_tpot_seconds"),
    "prefix_hits": int(cnt("hvdtpu_serving_prefix_cache_hits_total")),
    "prefix_misses": int(cnt(
        "hvdtpu_serving_prefix_cache_misses_total")),
    "draft_proposed": int(cnt(
        "hvdtpu_serving_draft_proposed_tokens_total")),
    "draft_accepted": int(cnt(
        "hvdtpu_serving_draft_accepted_tokens_total")),
}))
"""

REQTRACE_WORKER = r"""
import json, os, sys, tempfile, time
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from horovod_tpu.models import transformer as tfm
from horovod_tpu.parallel.mesh import create_mesh
from horovod_tpu.serving import InferenceEngine, ServingConfig
from horovod_tpu.serving import reqtrace as _rt

rounds = int(sys.argv[1])          # paired rounds (one on + one off)
max_new = int(sys.argv[2])

cfg = tfm.TransformerConfig(
    vocab=256, d_model=64, n_heads=2, n_layers=2, d_ff=128,
    max_seq=128, dtype=jnp.float32, remat=False)
params = tfm.init_params(cfg, jax.random.PRNGKey(42))
mesh = create_mesh(devices=jax.devices()[:1], tp=1)
engine = InferenceEngine(params, cfg, mesh, ServingConfig(
    block_size=8, kv_blocks=64, max_batch_slots=8,
    max_queue=32, max_new_tokens=max_new, min_prefill_bucket=8))

rng = np.random.RandomState(7)
prompts = [list(rng.randint(0, 256, int(n)))
           for n in rng.randint(8, 25, 8)]

# Warmup compiles every bucket + decode once (BENCH_SERVING recipe).
for L in sorted({max(8, 1 << (len(p) - 1).bit_length()) for p in prompts}):
    engine.generate([1] * min(L, 24), max_new_tokens=2)

# BENCH_TRACE methodology: tracing toggled IN-process, paired rounds in
# alternating order (on/off, off/on, ...) so slow drift cancels; pooled
# per-REQUEST latencies; 25th percentile (the steady-state floor,
# robust to CI-box noise spikes).
tdir = tempfile.mkdtemp(prefix="bench_reqtrace_")
lat = {"on": [], "off": []}
trace_files = 0

def one_round(arm, i):
    global trace_files
    if arm == "on":
        _rt.start(os.path.join(tdir, "r%d.trace.json" % i),
                  rank=0, proc="bench")
        trace_files += 1
    reqs = [engine.submit(p) for p in prompts]
    engine.run_until_idle()
    for r in reqs:
        r.result()
        lat[arm].append(r.t_done - r.t_submit)
    if arm == "on":
        _rt.stop()

i = 0
for pair in range(rounds):
    order = ("on", "off") if pair % 2 == 0 else ("off", "on")
    for arm in order:
        one_round(arm, i)
        i += 1

def p25(xs):
    xs = sorted(xs)
    return xs[len(xs) // 4]

on, off = p25(lat["on"]), p25(lat["off"])
print(json.dumps({
    "rows": {
        "tracing_on": {"request_p25_ms": round(on * 1e3, 3),
                       "requests": len(lat["on"])},
        "tracing_off": {"request_p25_ms": round(off * 1e3, 3),
                        "requests": len(lat["off"])},
    },
    "trace_files": trace_files,
    "overhead_frac": round(on / off - 1.0, 4),
}))
"""


SPEC_ADAPT_WORKER = r"""
import json, os, sys, time
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.checkpoint import CheckpointEngine
from horovod_tpu.observability import flight_recorder as _fr
from horovod_tpu.parallel.mesh import create_mesh
from horovod_tpu.serving import (InferenceEngine, ServingConfig,
                                 config_from_manifest, load_params,
                                 serving_config)

ckpt_root = sys.argv[1]
arm = sys.argv[2]                       # "adaptive" | "static"
n_requests = int(sys.argv[3])
spec_k = int(sys.argv[4])

mesh = create_mesh(devices=jax.devices()[:1], tp=1)

def load(sub):
    d = os.path.join(ckpt_root, sub)
    man = CheckpointEngine(d).restore_manifest()
    cfg = serving_config(config_from_manifest(man), mesh)
    return cfg, load_params(d, cfg, mesh)

cfg, params = load("flagship")
draft_cfg, draft_params = load("drafter")

engine = InferenceEngine(
    params, cfg, mesh,
    ServingConfig(block_size=16, kv_blocks=96, max_batch_slots=8,
                  max_queue=32, max_new_tokens=64,
                  min_prefill_bucket=16, spec_tokens=spec_k,
                  spec_adapt=(arm == "adaptive")),
    draft_params=draft_params, draft_cfg=draft_cfg)

good_draft = engine._draft_params
# The deliberately degraded drafter: zeroed weights give all-zero
# logits (argmax = token 0 at every position), so proposals essentially
# never match the flagship — a deterministic worst-case acceptance
# rate, which is what the controller must survive.
bad_draft = jax.tree_util.tree_map(lambda x: x * 0.0, good_draft)

VOCAB = cfg.vocab

def prompts(base):
    return [[(base + 16 * j + i) % VOCAB for i in range(16)]
            for j in range(n_requests)]

# Warmup compiles (prefill bucket + the k-wide verify + plain decode).
engine.generate([1] * 16, max_new_tokens=2)

def cnt(snap0, snap, name, labels=""):
    v1 = snap.get(name, {"values": {}})["values"].get(labels, 0)
    v0 = snap0.get(name, {"values": {}})["values"].get(labels, 0)
    return v1 - v0

def slots_backed_off_to_1():
    # Flight-recorder evidence: per-slot spec_backoff notes that landed
    # at k=1 (docs/autotune.md#serving).
    hit = set()
    for _, kind, p in _fr.recorder()._snapshot():
        if kind == "autotune" and p[0] == "spec_backoff" and p[2] == "1":
            hit.add(p[5])
    return len(hit)

def run_phase(name, base, max_new):
    snap0 = hvd.metrics_snapshot()
    t0 = time.perf_counter()
    reqs = [engine.submit(p, max_new_tokens=max_new)
            for p in prompts(base)]
    engine.run_until_idle()
    wall = time.perf_counter() - t0
    outputs = [r.result() for r in reqs]
    snap = hvd.metrics_snapshot()
    ctl = engine._spec_ctl
    ks = sorted(s.k_eff for s in ctl._slots.values()) if ctl else None
    proposed = cnt(snap0, snap,
                   "hvdtpu_serving_draft_proposed_tokens_total")
    accepted = cnt(snap0, snap,
                   "hvdtpu_serving_draft_accepted_tokens_total")
    return {
        "phase": name,
        "wall_ms": round(wall * 1e3, 3),
        "generated_tokens": sum(len(o) for o in outputs),
        "decode_steps": int(cnt(snap0, snap,
                                "hvdtpu_serving_decode_steps_total")),
        "draft_proposed": int(proposed),
        "draft_accepted": int(accepted),
        "acceptance": round(accepted / proposed, 4) if proposed else None,
        "k_slots_end": ks,
        "spec_moves": {d: int(cnt(snap0, snap,
                                  "hvdtpu_autotune_spec_moves_total",
                                  'direction="%s"' % d))
                       for d in ("down", "up", "probe")},
        "output_checksum": int(sum((i + 1) * t for o in outputs
                               for i, t in enumerate(o)) % (1 << 31)),
        "outputs": outputs,
    }

# healthy -> degraded (drafter swapped mid-run) -> recovered (restored;
# the longer budget gives the k=1 probe clock room to climb back).
phases = []
phases.append(run_phase("healthy", 250, 32))
engine._draft_params = bad_draft
phases.append(run_phase("degraded", 1000, 32))
engine._draft_params = good_draft
phases.append(run_phase("recovered", 2000, 64))

print(json.dumps({
    "arm": arm,
    "spec_tokens_cap": spec_k,
    "phases": phases,
    "slots_backed_off_to_1": slots_backed_off_to_1(),
}))
"""


CHUNKED_WORKER = r"""
import json, os, sys, time
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"

arm = sys.argv[1]            # baseline_no_burst|unchunked_burst|chunked_burst
n_requests = int(sys.argv[2])
max_new = int(sys.argv[3])
chunk = int(sys.argv[4])

# The adversarial mix arrives through the declarative fault grammar
# (docs/adaptation.md): one burst of two 1024-token prompts, fired once
# the serving tick clears the warmup window. Env must be set before
# the engine constructs its injector.
if arm != "baseline_no_burst":
    os.environ["HOROVOD_TPU_FAULT_SPEC"] = \
        "rank=*:long_prompt_burst=2x1024:from_step=20"
if arm == "chunked_burst":
    os.environ["HOROVOD_TPU_SERVING_TICK_BUDGET_MS"] = "100"

import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.models import transformer as tfm
from horovod_tpu.parallel.mesh import create_mesh
from horovod_tpu.serving import InferenceEngine, ServingConfig
from horovod_tpu.observability import histogram_percentiles

# d_model/seq sized so a monolithic 1024-bucket prefill costs several
# decode ticks even on CPU — the stall the chunked arm must not show.
cfg = tfm.TransformerConfig(
    vocab=256, d_model=256, n_heads=2, n_layers=4, d_ff=512,
    max_seq=1088, dtype=jnp.float32, remat=False)
params = tfm.init_params(cfg, jax.random.PRNGKey(42))
mesh = create_mesh(devices=jax.devices()[:1], tp=1)
engine = InferenceEngine(params, cfg, mesh, ServingConfig(
    block_size=8, kv_blocks=200, max_batch_slots=8,
    max_queue=32, max_new_tokens=max_new, min_prefill_bucket=8,
    prefill_chunk=chunk if arm == "chunked_burst" else None))

rng = np.random.RandomState(7)
prompts = [list(int(t) for t in rng.randint(0, 256, int(n)))
           for n in rng.randint(10, 17, n_requests)]

# Warmup compiles every bucket either arm touches — the steady 16
# bucket, the chunk buckets (32 cap plus the 8/16 the budget policy
# could halve to), and (unchunked) the 1024 monolithic bucket — so
# measured tick gaps are scheduling + forwards, not XLA compiles.
engine.generate([1] * 12, max_new_tokens=2)
engine.generate([3] * 8, max_new_tokens=2)
engine.generate([2] * 1024, max_new_tokens=2)

snap0 = hvd.metrics_snapshot()
t0 = time.perf_counter()
# Steady arrivals are paced one per tick (open-loop load, not a
# thundering herd) so the baseline's tick gap reflects steady-state
# decode + at most one short prefill — the bound the burst arms are
# measured against. The burst still lands all at once via the fault.
reqs = []
for p in prompts:
    reqs.append(engine.submit(p))
    engine.step()
engine.run_until_idle()      # the burst fires and completes mid-run
wall = time.perf_counter() - t0
outputs = [r.result() for r in reqs]
snap = hvd.metrics_snapshot()

def cnt(name, labels=""):
    v1 = snap.get(name, {"values": {}})["values"].get(labels, 0)
    v0 = snap0.get(name, {"values": {}})["values"].get(labels, 0)
    return v1 - v0

def pct(name):
    h1 = snap[name]["values"][""]
    h0 = snap0[name]["values"].get("", {"buckets": [], "count": 0,
                                        "sum": 0.0})
    prev = {le: c for le, c in h0["buckets"]}
    diff = {"buckets": [[le, c - prev.get(le, 0)]
                        for le, c in h1["buckets"]],
            "count": h1["count"] - h0["count"],
            "sum": h1["sum"] - h0["sum"]}
    return {k: round(v * 1e3, 3)
            for k, v in histogram_percentiles(diff).items()}

checksum = int(sum((i + 1) * t for o in outputs
               for i, t in enumerate(o)) % (1 << 31))
print(json.dumps({
    "arm": arm,
    "wall_ms": round(wall * 1e3, 3),
    "steady_outputs_checksum": checksum,
    "steady_outputs": outputs,
    "generated_tokens": int(cnt("hvdtpu_serving_tokens_total",
                                'kind="generated"')),
    "decode_tick_ms": pct("hvdtpu_serving_decode_tick_seconds"),
    "decode_ticks": int(snap["hvdtpu_serving_decode_tick_seconds"]
                        ["values"][""]["count"]
                        - snap0["hvdtpu_serving_decode_tick_seconds"]
                        ["values"].get("", {"count": 0})["count"]),
    "prefill_chunks": int(cnt("hvdtpu_serving_prefill_chunks_total")),
    "bursts_injected": int(cnt("hvdtpu_fault_injections_total",
                               'kind="long_prompt_burst"')),
}))
"""


SESSION_WORKER = r"""
import json, os, sys, time
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.models import transformer as tfm
from horovod_tpu.parallel.mesh import create_mesh
from horovod_tpu.serving import InferenceEngine, ServingConfig

arm = sys.argv[1]            # "prefix_cache_only" | "session_affinity"
n_sessions = int(sys.argv[2])
n_turns = int(sys.argv[3])
max_new = int(sys.argv[4])

cfg = tfm.TransformerConfig(
    vocab=256, d_model=64, n_heads=2, n_layers=2, d_ff=128,
    max_seq=160, dtype=jnp.float32, remat=False)
params = tfm.init_params(cfg, jax.random.PRNGKey(42))
mesh = create_mesh(devices=jax.devices()[:1], tp=1)
engine = InferenceEngine(params, cfg, mesh, ServingConfig(
    block_size=8, kv_blocks=160, max_batch_slots=8,
    max_queue=32, max_new_tokens=max_new, min_prefill_bucket=8,
    prefix_cache=True,
    session_leases=n_sessions if arm == "session_affinity" else 0))

def replay(base, collect=None):
    # Multi-turn conversations: every turn's prompt is the FULL prior
    # context (prompt + reply) plus a fresh user utterance — the shape
    # where the prefix cache cannot help (it never indexes generated
    # tokens) but a session lease resumes in place.
    ctx = {s: [(base + 7 * s + i) % 256 for i in range(24)]
           for s in range(n_sessions)}
    for turn in range(n_turns):
        reqs = {}
        for s in range(n_sessions):
            reqs[s] = engine.submit(ctx[s], max_new_tokens=max_new,
                                    session_id="sess-%d-%d" % (base, s))
        engine.run_until_idle()
        for s, r in reqs.items():
            if collect is not None and turn > 0:
                collect.append(r.ttft_s)
            ctx[s] = ctx[s] + r.result() + \
                [(base + 31 * s + 13 * turn + i) % 256 for i in range(8)]
    return ctx

replay(100)                   # warmup: compiles every turn's buckets
snap0 = hvd.metrics_snapshot()
ttfts = []
t0 = time.perf_counter()
final_ctx = replay(200, collect=ttfts)
wall = time.perf_counter() - t0
snap = hvd.metrics_snapshot()

def cnt(name, labels=""):
    v1 = snap.get(name, {"values": {}})["values"].get(labels, 0)
    v0 = snap0.get(name, {"values": {}})["values"].get(labels, 0)
    return v1 - v0

ttfts.sort()
outputs = [final_ctx[s] for s in range(n_sessions)]
checksum = int(sum((i + 1) * t for o in outputs
               for i, t in enumerate(o)) % (1 << 31))
print(json.dumps({
    "arm": arm,
    "wall_ms": round(wall * 1e3, 3),
    "sessions": n_sessions,
    "turns": n_turns,
    "followup_ttft_p50_ms": round(
        ttfts[len(ttfts) // 2] * 1e3, 3),
    "followup_turns_measured": len(ttfts),
    "prefill_tokens": int(cnt("hvdtpu_serving_tokens_total",
                              'kind="prompt"')),
    "session_hits": int(cnt("hvdtpu_serving_session_hits_total")),
    "session_leases": int(cnt("hvdtpu_serving_session_leases_total")),
    "prefix_hits": int(cnt("hvdtpu_serving_prefix_cache_hits_total")),
    "final_context_checksum": checksum,
    "final_contexts": outputs,
}))
"""


SPEED_ARMS = ("baseline", "quantized_kv", "speculative", "prefix_cache",
              "all_on")
SPEED_REQUESTS = 8
SPEED_MAX_NEW = 32
SPEC_TOKENS = 8

CHUNKED_ARMS = ("baseline_no_burst", "unchunked_burst", "chunked_burst")
CHUNKED_REQUESTS = 12
CHUNKED_MAX_NEW = 24
CHUNKED_CHUNK = 32

SESSION_ARMS = ("prefix_cache_only", "session_affinity")
SESSION_SESSIONS = 4
SESSION_TURNS = 4
SESSION_MAX_NEW = 16


def run_speed(out_path):
    """The --speed arms: train the bench pair once, then one fresh
    subprocess per arm (its own registry + jit cache, like every other
    arm in this file)."""
    env = dict(os.environ)
    env.pop("HOROVOD_TPU_METRICS", None)
    repo = os.path.dirname(os.path.abspath(__file__))
    with tempfile.TemporaryDirectory(prefix="bench_speed_") as tmp:
        prep = subprocess.run(
            [sys.executable, "-c", SPEED_PREP, tmp], env=env,
            capture_output=True, text=True, timeout=900, cwd=repo)
        if prep.returncode != 0:
            raise RuntimeError(
                f"speed bench prep failed:\n{prep.stderr[-3000:]}")
        train_meta = json.loads(prep.stdout.strip().splitlines()[-1])

        arms = {}
        for arm in SPEED_ARMS:
            proc = subprocess.run(
                [sys.executable, "-c", SPEED_WORKER, tmp, arm,
                 str(SPEED_REQUESTS), str(SPEED_MAX_NEW),
                 str(SPEC_TOKENS)],
                env=env, capture_output=True, text=True, timeout=900,
                cwd=repo)
            if proc.returncode != 0:
                raise RuntimeError(f"speed bench arm {arm} failed:\n"
                                   f"{proc.stderr[-3000:]}")
            arms[arm] = json.loads(proc.stdout.strip().splitlines()[-1])

    base = arms["baseline"]
    spec = arms["speculative"]
    pfx = arms["prefix_cache"]
    quant = arms["quantized_kv"]
    outputs = {a: arms[a].pop("outputs") for a in arms}
    headlines = {
        "speculative_speedup": round(
            spec["tokens_per_s"] / base["tokens_per_s"], 3),
        "speculative_outputs_equal_baseline":
            outputs["speculative"] == outputs["baseline"],
        "draft_acceptance": round(
            spec["draft_accepted"] / max(1, spec["draft_proposed"]), 3),
        "prefix_ttft_p50_ratio": round(
            pfx["ttft_ms"]["p50"] / base["ttft_ms"]["p50"], 3),
        "prefix_prefill_tokens_ratio": round(
            pfx["prefill_tokens"] / base["prefill_tokens"], 3),
        "quantized_kv_bytes_ratio": round(
            quant["kv_bytes_resident"] / base["kv_bytes_resident"], 3),
        "quantized_outputs_equal_fp32":
            outputs["quantized_kv"] == outputs["baseline"],
        "all_on_outputs_equal_quantized":
            outputs["all_on"] == outputs["quantized_kv"],
    }
    result = {
        "metric": "serving_speed_levers",
        "model": {"d_model": 256, "n_layers": 4, "n_heads": 4,
                  "vocab": 512, "dtype": "float32"},
        "drafter": {"d_model": 64, "n_layers": 1, "n_heads": 1,
                    "vocab": 512},
        "task": "cyclic successor (seeded training, greedy decode)",
        "train": train_meta,
        "requests": SPEED_REQUESTS,
        "max_new_tokens": SPEED_MAX_NEW,
        "spec_tokens": SPEC_TOKENS,
        "shared_system_prompt_tokens": 112,
        "arms": arms,
        "headlines": headlines,
        "note": ("Token counts, checksums, decode_steps, prefix/draft "
                 "counters and kv_bytes_resident are seeded-"
                 "deterministic (greedy decode over trained-to-"
                 "convergence seeded weights); *_ms and tokens_per_s "
                 "are wall-clock. Headlines: speculative decode >= "
                 "1.5x baseline tok/s AND token-identical (the "
                 "emitted tokens are the flagship's own argmaxes); "
                 "prefix-cache TTFT p50 below baseline with "
                 "prefill_tokens showing the prompt work skipped; "
                 "quantized pool < 0.30x resident KV bytes at "
                 "identical admission. kv_bytes_resident is read at "
                 "full admission (8/8 slots); the all_on arm includes "
                 "the drafter's (also quantized) pool."),
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
    print(json.dumps(result))


def run_spec_adapt(out_path):
    """The --spec-adapt A/B: per-slot adaptive spec_tokens
    (docs/autotune.md#serving) vs the static k, on the trained bench
    pair, with the drafter deliberately degraded mid-run (zeroed
    weights) and then restored. The adaptive arm must back every slot
    off to k=1 under the cold drafter and climb back after the probe
    rediscovers it; both arms stay token-identical throughout (every
    emitted token is the flagship's own argmax). Writes/updates the
    ``spec_adapt`` row in BENCH_SPEED.json."""
    env = dict(os.environ)
    env.pop("HOROVOD_TPU_METRICS", None)
    repo = os.path.dirname(os.path.abspath(__file__))
    with tempfile.TemporaryDirectory(prefix="bench_specadapt_") as tmp:
        prep = subprocess.run(
            [sys.executable, "-c", SPEED_PREP, tmp], env=env,
            capture_output=True, text=True, timeout=900, cwd=repo)
        if prep.returncode != 0:
            raise RuntimeError(
                f"spec-adapt bench prep failed:\n{prep.stderr[-3000:]}")
        arms = {}
        for arm in ("adaptive", "static"):
            proc = subprocess.run(
                [sys.executable, "-c", SPEC_ADAPT_WORKER, tmp, arm,
                 str(SPEED_REQUESTS), str(SPEC_TOKENS)],
                env=env, capture_output=True, text=True, timeout=900,
                cwd=repo)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"spec-adapt bench arm {arm} failed:\n"
                    f"{proc.stderr[-3000:]}")
            arms[arm] = json.loads(proc.stdout.strip().splitlines()[-1])

    outputs = {a: [p.pop("outputs") for p in arms[a]["phases"]]
               for a in arms}
    ph = {a: {p["phase"]: p for p in arms[a]["phases"]} for a in arms}
    ad, st = ph["adaptive"], ph["static"]
    headlines = {
        "adaptive_backed_off_to_1":
            arms["adaptive"]["slots_backed_off_to_1"] >= SPEED_REQUESTS,
        "degraded_k_slots_end": ad["degraded"]["k_slots_end"],
        "adaptive_recovered_k": max(ad["recovered"]["k_slots_end"]),
        "adaptive_recovered": (max(ad["recovered"]["k_slots_end"])
                               >= SPEC_TOKENS // 2),
        # Wasted draft work the backoff saves while the drafter is cold.
        "degraded_proposed_ratio": round(
            ad["degraded"]["draft_proposed"]
            / max(1, st["degraded"]["draft_proposed"]), 3),
        "outputs_equal_static": outputs["adaptive"] == outputs["static"],
    }
    row = {
        "spec_tokens_cap": SPEC_TOKENS,
        "requests_per_phase": SPEED_REQUESTS,
        "arms": arms,
        "headlines": headlines,
        "note": ("adaptive (spec_adapt=True) vs static spec_tokens, "
                 "three phases: trained drafter, zero-weight drafter "
                 "swapped in mid-run, trained drafter restored. "
                 "Counters, k timelines and checksums are seeded-"
                 "deterministic (greedy decode, deterministic "
                 "scheduler); *_ms are wall-clock. Headlines: every "
                 "slot backs off to k=1 under the cold drafter "
                 "(flight-recorder spec_backoff evidence), climbs "
                 "back to >= cap/2 after restore via the k=1 probe, "
                 "proposes a fraction of the static arm's draft "
                 "tokens while degraded, and stays token-identical "
                 "with the static arm in every phase."),
    }
    result = None
    if out_path and os.path.exists(out_path):
        # Ride along in BENCH_SPEED.json next to the other levers.
        with open(out_path) as f:
            result = json.load(f)
        if result.get("metric") != "serving_speed_levers":
            result = None
    if result is None:
        result = {"metric": "serving_speed_levers"}
    result["spec_adapt"] = row
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
    print(json.dumps({"spec_adapt_headlines": headlines}))


def _ride_along(out_path, key, row):
    """Insert ``row`` under ``key`` in BENCH_SPEED.json, preserving the
    other rows (the spec_adapt pattern: the levers file accretes arms)."""
    result = None
    if out_path and os.path.exists(out_path):
        with open(out_path) as f:
            result = json.load(f)
        if result.get("metric") != "serving_speed_levers":
            result = None
    if result is None:
        result = {"metric": "serving_speed_levers"}
    result[key] = row
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")


def run_chunked_prefill(out_path):
    """The --chunked-prefill A/B/C: decode-tick tail latency under an
    adversarial long-prompt burst (the ``long_prompt_burst`` fault
    clause), three arms on the same seeded steady load:

      - ``baseline_no_burst``: no long prompts — the clean tick gap.
      - ``unchunked_burst``: two 1024-token prompts land mid-run and
        each monolithic prefill stalls every decoding slot.
      - ``chunked_burst``: same burst with ``prefill_chunk=32`` — at
        most one chunk runs between ticks, so the gap stays near
        baseline.

    Headlines: chunked holds decode-tick p99 within 2x the no-burst
    baseline while unchunked exceeds 2x, and the steady requests stay
    token-identical across all three arms (greedy decode; chunking
    only reorders prefill work). Writes/updates the
    ``chunked_prefill`` row in BENCH_SPEED.json."""
    env = dict(os.environ)
    env.pop("HOROVOD_TPU_METRICS", None)
    env.pop("HOROVOD_TPU_FAULT_SPEC", None)     # the worker sets it
    env.pop("HOROVOD_TPU_SERVING_TICK_BUDGET_MS", None)
    repo = os.path.dirname(os.path.abspath(__file__))
    arms = {}
    for arm in CHUNKED_ARMS:
        proc = subprocess.run(
            [sys.executable, "-c", CHUNKED_WORKER, arm,
             str(CHUNKED_REQUESTS), str(CHUNKED_MAX_NEW),
             str(CHUNKED_CHUNK)],
            env=env, capture_output=True, text=True, timeout=900,
            cwd=repo)
        if proc.returncode != 0:
            raise RuntimeError(
                f"chunked-prefill bench arm {arm} failed:\n"
                f"{proc.stderr[-3000:]}")
        arms[arm] = json.loads(proc.stdout.strip().splitlines()[-1])

    outputs = {a: arms[a].pop("steady_outputs") for a in arms}
    base_p99 = arms["baseline_no_burst"]["decode_tick_ms"]["p99"]
    unchunked_p99 = arms["unchunked_burst"]["decode_tick_ms"]["p99"]
    chunked_p99 = arms["chunked_burst"]["decode_tick_ms"]["p99"]
    headlines = {
        "baseline_tick_p99_ms": base_p99,
        "unchunked_tick_p99_ms": unchunked_p99,
        "chunked_tick_p99_ms": chunked_p99,
        "unchunked_p99_vs_baseline": round(
            unchunked_p99 / max(base_p99, 1e-9), 3),
        "chunked_p99_vs_baseline": round(
            chunked_p99 / max(base_p99, 1e-9), 3),
        "chunked_holds_2x_baseline": chunked_p99 <= 2.0 * base_p99,
        "unchunked_exceeds_2x_baseline": unchunked_p99 > 2.0 * base_p99,
        "steady_outputs_equal_across_arms": (
            outputs["baseline_no_burst"] == outputs["unchunked_burst"]
            == outputs["chunked_burst"]),
    }
    row = {
        "requests": CHUNKED_REQUESTS,
        "max_new_tokens": CHUNKED_MAX_NEW,
        "prefill_chunk": CHUNKED_CHUNK,
        "fault": "rank=*:long_prompt_burst=2x1024:from_step=20",
        "arms": arms,
        "headlines": headlines,
        "note": ("Decode-tick gap (hvdtpu_serving_decode_tick_seconds) "
                 "p99 under an adversarial long-prompt burst. "
                 "Checksums, token/chunk/burst counts are seeded-"
                 "deterministic (greedy decode, deterministic "
                 "scheduler); *_ms are wall-clock. Headlines: with "
                 "prefill_chunk=32 the burst's 1024-token prefills "
                 "interleave one bucket-shaped chunk per tick, holding "
                 "decode-tick p99 within 2x the no-burst baseline, "
                 "while the unchunked arm's monolithic prefill blows "
                 "past 2x; the steady requests are token-identical "
                 "across all arms."),
    }
    _ride_along(out_path, "chunked_prefill", row)
    print(json.dumps({"chunked_prefill_headlines": headlines}))


def run_session_affinity(out_path):
    """The --session-affinity A/B: multi-turn conversation replay,
    session KV leases (session_leases=4) vs the prefix cache alone.
    Every follow-up turn resends the full conversation so far plus a
    fresh utterance; the prefix cache can only re-serve *prompt*
    blocks from earlier turns (it never indexes generated tokens),
    while a session lease resumes from the stored context and skips
    the re-prefill entirely. Headlines: follow-up TTFT p50 below the
    prefix-only arm, fewer prompt tokens prefilled, and final
    conversation contexts token-identical. Writes/updates the
    ``session_affinity`` row in BENCH_SPEED.json."""
    env = dict(os.environ)
    env.pop("HOROVOD_TPU_METRICS", None)
    env.pop("HOROVOD_TPU_FAULT_SPEC", None)
    repo = os.path.dirname(os.path.abspath(__file__))
    arms = {}
    for arm in SESSION_ARMS:
        proc = subprocess.run(
            [sys.executable, "-c", SESSION_WORKER, arm,
             str(SESSION_SESSIONS), str(SESSION_TURNS),
             str(SESSION_MAX_NEW)],
            env=env, capture_output=True, text=True, timeout=900,
            cwd=repo)
        if proc.returncode != 0:
            raise RuntimeError(
                f"session-affinity bench arm {arm} failed:\n"
                f"{proc.stderr[-3000:]}")
        arms[arm] = json.loads(proc.stdout.strip().splitlines()[-1])

    contexts = {a: arms[a].pop("final_contexts") for a in arms}
    sess = arms["session_affinity"]
    pfx = arms["prefix_cache_only"]
    headlines = {
        "session_ttft_p50_ms": sess["followup_ttft_p50_ms"],
        "prefix_only_ttft_p50_ms": pfx["followup_ttft_p50_ms"],
        "session_beats_prefix_ttft": (sess["followup_ttft_p50_ms"]
                                      < pfx["followup_ttft_p50_ms"]),
        "prefill_tokens_ratio": round(
            sess["prefill_tokens"] / max(1, pfx["prefill_tokens"]), 3),
        "session_hits": sess["session_hits"],
        "contexts_equal_across_arms": (
            contexts["session_affinity"]
            == contexts["prefix_cache_only"]),
    }
    row = {
        "sessions": SESSION_SESSIONS,
        "turns": SESSION_TURNS,
        "max_new_tokens": SESSION_MAX_NEW,
        "arms": arms,
        "headlines": headlines,
        "note": ("Multi-turn replay (4 conversations x 4 turns, each "
                 "turn resends the full context + 8 new tokens). "
                 "Token counts, hit counts and checksums are seeded-"
                 "deterministic (greedy decode); *_ms are wall-clock. "
                 "Headlines: session leases beat the prefix-cache-only "
                 "arm on follow-up TTFT p50 (the lease resumes past "
                 "the generated tokens the prefix cache cannot index), "
                 "prefill a fraction of the prompt tokens, and the "
                 "final conversation contexts are token-identical "
                 "across arms."),
    }
    _ride_along(out_path, "session_affinity", row)
    print(json.dumps({"session_affinity_headlines": headlines}))


def run_reqtrace(out_path, rounds=6):
    """The --reqtrace A/B: request tracing on vs off under the
    BENCH_SERVING load (8 slots, 8 concurrent requests), toggled
    in-process with alternating-order paired rounds (the BENCH_TRACE
    methodology). Headline: per-request latency overhead < 3%."""
    env = dict(os.environ)
    env.pop("HOROVOD_TPU_METRICS", None)
    env.pop("HOROVOD_TPU_REQTRACE", None)   # the worker toggles itself
    proc = subprocess.run(
        [sys.executable, "-c", REQTRACE_WORKER, str(rounds),
         str(MAX_NEW)],
        env=env, capture_output=True, text=True, timeout=900,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    if proc.returncode != 0:
        raise RuntimeError(
            f"reqtrace bench worker failed:\n{proc.stderr[-3000:]}")
    r = json.loads(proc.stdout.strip().splitlines()[-1])
    result = {
        "metric": "serving_reqtrace_overhead",
        "model": {"d_model": 64, "n_layers": 2, "n_heads": 2,
                  "vocab": 256, "dtype": "float32"},
        "requests_per_round": 8,
        "max_new_tokens": MAX_NEW,
        "paired_rounds": rounds,
        "note": ("Per-request serving trace capture "
                 "(docs/serving.md#request-tracing) A/B'd on/off "
                 "in-process under the BENCH_SERVING load: paired "
                 "alternating-order rounds, pooled per-request "
                 "latencies, p25 (the BENCH_TRACE methodology). "
                 "Headline: overhead_frac < 0.03 — span emission is "
                 "one tuple append per request-phase on the scheduler "
                 "thread; formatting happens on the writer's drain "
                 "thread."),
        **r,
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
    print(json.dumps(result))


def run_fleet(out_path):
    """The --fleet availability arm, in a fresh subprocess (its own
    registry, its own jit cache) like every other arm."""
    env = dict(os.environ)
    env.pop("HOROVOD_TPU_METRICS", None)
    proc = subprocess.run(
        [sys.executable, "-c", FLEET_WORKER, "3", "32", "16", "25"],
        env=env, capture_output=True, text=True, timeout=900,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    if proc.returncode != 0:
        raise RuntimeError(
            f"fleet bench worker failed:\n{proc.stderr[-3000:]}")
    r = json.loads(proc.stdout.strip().splitlines()[-1])
    result = {
        "metric": "fleet_availability_under_replica_crash",
        "model": {"d_model": 64, "n_layers": 2, "n_heads": 2,
                  "vocab": 256, "dtype": "float32"},
        "fault": "rank=1:replica_crash_at=25:gen=0",
        "note": ("3-replica fleet behind the failover router; replica "
                 "1 is SIGKILLed by a deterministic fault mid-load. "
                 "requests_*, outputs_equal_uncontended and "
                 "output_checksum are seeded-deterministic (greedy "
                 "decode; the router's re-prefill resume is "
                 "token-exact, so the crash is invisible in the "
                 "checksum). retries/failover counts and *_ms are "
                 "run-dependent (which requests sat on the dying "
                 "replica is a scheduling accident)."),
        **r,
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
    print(json.dumps(result))


SLO_MAX_NEW = 24
SLO_DURATION_S = 6.0
SLO_SEED = 1000


def run_slo(out_path):
    """The --slo arm: open-loop offered-load sweep against the
    3-replica fleet at fixed TTFT/TPOT SLOs (writes BENCH_SLO.json).
    Closed-loop benches adapt their arrival rate to whatever the fleet
    absorbs, so queueing collapse never shows; the seeded Poisson
    schedule here keeps firing past saturation, and goodput (requests
    meeting their SLO, over OFFERED load) develops a measurable knee."""
    env = dict(os.environ)
    env.pop("HOROVOD_TPU_METRICS", None)
    env.pop("HOROVOD_TPU_FAULT_SPEC", None)   # the worker sets its own
    proc = subprocess.run(
        [sys.executable, "-c", SLO_WORKER, "3", str(SLO_MAX_NEW),
         str(SLO_DURATION_S), str(SLO_SEED)],
        env=env, capture_output=True, text=True, timeout=1800,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    if proc.returncode != 0:
        raise RuntimeError(
            f"slo bench worker failed:\n{proc.stderr[-3000:]}")
    r = json.loads(proc.stdout.strip().splitlines()[-1])

    from horovod_tpu.tools.slo import find_knee
    arms = sorted(r["sweep"].values(),
                  key=lambda a: a.get("offered_rps") or 0.0)
    knee = find_knee(arms, target_ttft_ms=500.0)
    result = {
        "metric": "slo_goodput_vs_offered_load",
        "model": {"d_model": 64, "n_layers": 2, "n_heads": 2,
                  "vocab": 256, "dtype": "float32"},
        "config": {
            "replicas": 3, "slots_per_replica": 2,
            "max_new_tokens": SLO_MAX_NEW,
            "duration_s": SLO_DURATION_S, "seed": SLO_SEED,
            "arrival_process": "poisson",
            "slo": {"ttft_ms": 500.0, "tpot_ms": 100.0},
            "fault": "rank=*:slow_decode=20ms",
            "sweep_rps": [4, 10, 25],
            "max_inflight": 256,
            "qos": {"reserved_slots": 1,
                    "weights": {"interactive": 8, "bulk": 1},
                    "autoscale": {"min": 2, "max": 4}},
        },
        "note": ("Open-loop (MLPerf-style, arXiv 1909.09756) offered-"
                 "load sweep on the 3-replica fleet with per-token "
                 "cost pinned by a deterministic slow_decode fault "
                 "(capacity ~12 req/s). Arm names, schedule checksums "
                 "and offered counts are seeded-deterministic; "
                 "goodput/percentiles are wall-clock. Headlines: "
                 "goodput tracks offered load until the knee, then "
                 "falls below it (has_knee); the two-tenant arm "
                 "replays the IDENTICAL interactive schedule with and "
                 "without an overlapping bulk burst and reports the "
                 "interactive tenant's TTFT p99 inflation — the "
                 "before-picture. The qos section replays the SAME "
                 "two-tenant schedules (checksum-asserted) against a "
                 "fleet with priority classes, DWRR weights 8:1 and a "
                 "reserved interactive slot (docs/serving.md#qos), "
                 "then reruns the ladder on a 2-replica fleet allowed "
                 "to autoscale to 4 on sustained pressure."),
        "sweep": r["sweep"],
        "two_tenant": r["two_tenant"],
        "qos": r["qos"],
        "clean_stop": r["clean_stop"],
        "headlines": {
            "has_knee": knee is not None,
            "knee_rps": None if knee is None
            else knee.get("offered_rps"),
            "goodput_frac_at_knee": None if knee is None
            else knee.get("goodput_frac"),
            "interactive_schedules_identical":
                r["two_tenant"]["interactive_schedules_identical"],
            "interactive_p99_inflation":
                r["two_tenant"]["interactive_p99_inflation"],
            "interactive_p99_inflation_qos":
                r["qos"]["interactive_p99_inflation_qos"],
            "qos_schedules_identical":
                r["qos"]["interactive_schedules_identical"],
            "fleet_scaled_up": r["qos"]["autoscale"]["scaled_up"],
            "fleet_scaled_back_down":
                r["qos"]["autoscale"]["scaled_back_down"],
        },
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
    print(json.dumps(result))


def run_arm(slots: int, concurrency: int) -> dict:
    env = dict(os.environ)
    env.pop("HOROVOD_TPU_METRICS", None)   # percentiles need recording
    proc = subprocess.run(
        [sys.executable, "-c", WORKER, str(slots), str(concurrency),
         str(MAX_NEW)],
        env=env, capture_output=True, text=True, timeout=900,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    if proc.returncode != 0:
        raise RuntimeError(
            f"serving bench worker failed (slots={slots}, "
            f"c={concurrency}):\n{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None,
                    help="write BENCH_SERVING.json (or, with --fleet, "
                         "BENCH_FLEET.json) here")
    ap.add_argument("--fleet", action="store_true",
                    help="measure fleet availability under an injected "
                         "replica crash instead of single-replica "
                         "throughput")
    ap.add_argument("--speed", action="store_true",
                    help="measure the raw-speed levers (quantized KV / "
                         "speculative decode / prefix cache) on the "
                         "trained bench pair; writes BENCH_SPEED.json "
                         "with --out")
    ap.add_argument("--spec-adapt", action="store_true",
                    help="A/B per-slot adaptive spec_tokens vs static "
                         "k with the drafter degraded mid-run; "
                         "writes/updates the spec_adapt row in "
                         "BENCH_SPEED.json (--out)")
    ap.add_argument("--chunked-prefill", action="store_true",
                    help="A/B/C decode-tick tail latency under a "
                         "long_prompt_burst fault: no burst vs "
                         "monolithic vs chunked prefill; "
                         "writes/updates the chunked_prefill row in "
                         "BENCH_SPEED.json (--out)")
    ap.add_argument("--session-affinity", action="store_true",
                    help="A/B multi-turn replay: session KV leases vs "
                         "prefix cache alone; writes/updates the "
                         "session_affinity row in BENCH_SPEED.json "
                         "(--out)")
    ap.add_argument("--slo", action="store_true",
                    help="open-loop offered-load sweep at fixed "
                         "TTFT/TPOT SLOs on the 3-replica fleet, plus "
                         "a two-tenant bulk-burst arm; writes "
                         "BENCH_SLO.json with --out")
    ap.add_argument("--reqtrace", action="store_true",
                    help="A/B per-request tracing on/off under the "
                         "BENCH_SERVING load; writes "
                         "BENCH_REQTRACE.json with --out")
    ap.add_argument("--reqtrace-rounds", type=int, default=6,
                    help="alternating on/off paired rounds for "
                         "--reqtrace")
    args = ap.parse_args()

    if args.fleet:
        run_fleet(args.out)
        return
    if args.speed:
        run_speed(args.out)
        return
    if args.spec_adapt:
        run_spec_adapt(args.out)
        return
    if args.chunked_prefill:
        run_chunked_prefill(args.out)
        return
    if args.session_affinity:
        run_session_affinity(args.out)
        return
    if args.slo:
        run_slo(args.out)
        return
    if args.reqtrace:
        run_reqtrace(args.out, rounds=args.reqtrace_rounds)
        return

    sweep = {}
    for c in (1, 2, 4, 8):
        r = run_arm(slots=8, concurrency=c)
        sweep[str(c)] = {k: r[k] for k in
                         ("wall_ms", "tokens_per_s", "generated_tokens")}
    batched = run_arm(slots=8, concurrency=N_REQUESTS)
    sequential = run_arm(slots=1, concurrency=N_REQUESTS)

    ratio = round(batched["tokens_per_s"]
                  / sequential["tokens_per_s"], 3)
    result = {
        "metric": "serving_batched_vs_sequential_tokens_per_sec",
        "model": {"d_model": 64, "n_layers": 2, "n_heads": 2,
                  "vocab": 256, "dtype": "float32"},
        "requests": N_REQUESTS,
        "max_new_tokens": MAX_NEW,
        "note": ("Token/request counts and output_checksum are seeded "
                 "and deterministic (greedy decode); *_ms and "
                 "tokens_per_s are wall-clock. Headline: continuous "
                 "batching at 8 concurrent requests sustains >= 2x the "
                 "sequential (1-slot) decode throughput — "
                 "batched_vs_sequential_ratio. TTFT/TPOT percentiles "
                 "come from each arm's own "
                 "hvdtpu_serving_{ttft,tpot}_seconds registry "
                 "histograms."),
        "sweep_batched_by_concurrency": sweep,
        "batched": {k: batched[k] for k in
                    ("wall_ms", "tokens_per_s", "prompt_tokens",
                     "generated_tokens", "output_checksum",
                     "decode_steps", "ttft_ms", "tpot_ms")},
        "sequential": {k: sequential[k] for k in
                       ("wall_ms", "tokens_per_s", "prompt_tokens",
                        "generated_tokens", "output_checksum",
                        "decode_steps", "ttft_ms", "tpot_ms")},
        "outputs_equal": batched["outputs"] == sequential["outputs"],
        "batched_vs_sequential_ratio": ratio,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
