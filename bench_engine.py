#!/usr/bin/env python
"""Eager-engine microbenchmark — allreduce throughput vs tensor size with
fusion on/off and native vs Python planner (VERDICT r1 #8).

This is the regression guard for the engine/control-plane stack: the
autotuner scores the same quantity (bytes/µs over the cycle,
parameter_manager.cc:144-170), so a regression here is a regression in
exactly what the reference's tuner optimizes.

Each configuration runs in a fresh subprocess (engine knobs are read once
at engine start, mirroring the reference's read-once env handling,
operations.cc:1824-1909) on the CPU platform, so CI needs no TPU.

Prints ONE JSON line:
  {"metric": "engine_allreduce_bytes_per_us", "value": <best>, ...,
   "sweep": {"<size>B": {"fused_native": bytes/us, "fused_python": ...,
             "unfused_native": ..., "single_native": ...}}}
"""

import json
import os
import subprocess
import sys

SIZES = [4 * 1024, 256 * 1024, 4 * 1024 * 1024]  # bytes, fp32 tensors
TENSORS_PER_BURST = 8
BURSTS = int(os.environ.get("HVD_BENCH_ENGINE_BURSTS", 10))

WORKER = r"""
import json, os, sys, time
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd

size_bytes = int(sys.argv[1])
per_burst = int(sys.argv[2])
bursts = int(sys.argv[3])

hvd.init()
n = size_bytes // 4
xs = [jnp.ones((n,), jnp.float32) for _ in range(per_burst)]

# Warmup: compile the fused program(s) + prime the engine.
for w in range(2):
    hs = [hvd.allreduce_async(x, average=False, name=f"warm{w}.{i}")
          for i, x in enumerate(xs)]
    [h.wait() for h in hs]

t0 = time.perf_counter()
for b in range(bursts):
    hs = [hvd.allreduce_async(x, average=False, name=f"b{b}.{i}")
          for i, x in enumerate(xs)]
    [h.wait() for h in hs]
dt = time.perf_counter() - t0
total_bytes = size_bytes * per_burst * bursts
print(json.dumps({"bytes_per_us": total_bytes / (dt * 1e6)}))
"""


def run_config(size_bytes, per_burst, *, native, fusion):
    env = dict(os.environ)
    env["HOROVOD_TPU_DISABLE_NATIVE"] = "0" if native else "1"
    # Fusion off == threshold too small for any pair (the reference's
    # HOROVOD_FUSION_THRESHOLD=0 semantics).
    env["HOROVOD_FUSION_THRESHOLD"] = (
        str(64 * 1024 * 1024) if fusion else "1")
    env["HOROVOD_CYCLE_TIME"] = "1"
    proc = subprocess.run(
        [sys.executable, "-c", WORKER, str(size_bytes), str(per_burst),
         str(BURSTS)],
        env=env, capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    if proc.returncode != 0:
        raise RuntimeError(
            f"engine bench worker failed (size={size_bytes}, "
            f"native={native}, fusion={fusion}):\n{proc.stderr[-2000:]}")
    return float(json.loads(proc.stdout.strip().splitlines()[-1])
                 ["bytes_per_us"])


def main():
    sweep = {}
    best = 0.0
    for size in SIZES:
        row = {
            "fused_native": run_config(size, TENSORS_PER_BURST,
                                       native=True, fusion=True),
            "fused_python": run_config(size, TENSORS_PER_BURST,
                                       native=False, fusion=True),
            "unfused_native": run_config(size, TENSORS_PER_BURST,
                                         native=True, fusion=False),
            "single_native": run_config(size, 1, native=True, fusion=True),
        }
        sweep[f"{size}B"] = {k: round(v, 3) for k, v in row.items()}
        best = max(best, row["fused_native"])
    print(json.dumps({
        "metric": "engine_allreduce_bytes_per_us",
        "value": round(best, 3),
        "unit": "bytes/us",
        "sweep": sweep,
    }))


if __name__ == "__main__":
    main()
