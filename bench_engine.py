#!/usr/bin/env python
"""Eager-engine microbenchmark — allreduce throughput vs tensor size with
fusion on/off and native vs Python planner (VERDICT r1 #8).

This is the regression guard for the engine/control-plane stack: the
autotuner scores the same quantity (bytes/µs over the cycle,
parameter_manager.cc:144-170), so a regression here is a regression in
exactly what the reference's tuner optimizes.

Each configuration runs in a fresh subprocess (engine knobs are read once
at engine start, mirroring the reference's read-once env handling,
operations.cc:1824-1909) on the CPU platform, so CI needs no TPU.

Prints ONE JSON line:
  {"metric": "engine_allreduce_bytes_per_us", "value": <best>, ...,
   "sweep": {"<size>B": {"fused_native": bytes/us, "fused_python": ...,
             "unfused_native": ..., "single_native": ...}}}
"""

import argparse
import json
import os
import subprocess
import sys

SIZES = [4 * 1024, 256 * 1024, 4 * 1024 * 1024]  # bytes, fp32 tensors
TENSORS_PER_BURST = 8
BURSTS = int(os.environ.get("HVD_BENCH_ENGINE_BURSTS", 10))

WORKER = r"""
import json, os, sys, time
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd

size_bytes = int(sys.argv[1])
per_burst = int(sys.argv[2])
bursts = int(sys.argv[3])

hvd.init()
n = size_bytes // 4
xs = [jnp.ones((n,), jnp.float32) for _ in range(per_burst)]

# Warmup: compile the fused program(s) + prime the engine.
for w in range(2):
    hs = [hvd.allreduce_async(x, average=False, name=f"warm{w}.{i}")
          for i, x in enumerate(xs)]
    [h.wait() for h in hs]

t0 = time.perf_counter()
for b in range(bursts):
    hs = [hvd.allreduce_async(x, average=False, name=f"b{b}.{i}")
          for i, x in enumerate(xs)]
    [h.wait() for h in hs]
dt = time.perf_counter() - t0
total_bytes = size_bytes * per_burst * bursts
print(json.dumps({"bytes_per_us": total_bytes / (dt * 1e6)}))
"""


def run_config(size_bytes, per_burst, *, native, fusion):
    env = dict(os.environ)
    env["HOROVOD_TPU_DISABLE_NATIVE"] = "0" if native else "1"
    # Fusion off == threshold too small for any pair (the reference's
    # HOROVOD_FUSION_THRESHOLD=0 semantics).
    env["HOROVOD_FUSION_THRESHOLD"] = (
        str(64 * 1024 * 1024) if fusion else "1")
    env["HOROVOD_CYCLE_TIME"] = "1"
    proc = subprocess.run(
        [sys.executable, "-c", WORKER, str(size_bytes), str(per_burst),
         str(BURSTS)],
        env=env, capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    if proc.returncode != 0:
        raise RuntimeError(
            f"engine bench worker failed (size={size_bytes}, "
            f"native={native}, fusion={fusion}):\n{proc.stderr[-2000:]}")
    return float(json.loads(proc.stdout.strip().splitlines()[-1])
                 ["bytes_per_us"])


OVERLAP_WORKER = r"""
import json, os, sys, time
import jax
import jax.numpy as jnp
import horovod_tpu as hvd

bursts = int(sys.argv[1])

hvd.init()
assert jax.local_device_count() == 1, "overlap A/B is a 1-device workload"

@jax.jit
def producer(x, i):
    # a real compute chain standing in for a backward segment
    for _ in range(8):
        x = jnp.tanh(x @ x)
    return x * 0 + i

x = jnp.ones((512, 512), jnp.float32)

# warmup: compile producer + the fused allreduce program
for w in range(3):
    ys = [producer(x, float(i)) for i in range(4)]
    hs = [hvd.allreduce_async(y, average=False, name=f"w{w}.{i}")
          for i, y in enumerate(ys)]
    [wh.wait() for wh in hs]

# async-submitter (hook-style) flow: dispatch producer, enqueue its
# allreduce, immediately dispatch the next producer — the engine's
# launch policy decides whether the collective waits out the producer
# (fence on) or enqueues behind it in the device FIFO (fence off).
t0 = time.perf_counter()
all_hs = []
for b in range(bursts):
    for i in range(4):
        y = producer(x, float(b * 4 + i))
        all_hs.append(hvd.allreduce_async(y, average=False,
                                          name=f"b{b}.{i}"))
[h.wait(timeout=300.0) for h in all_hs]
dt = time.perf_counter() - t0
print(json.dumps({"wall_s": dt,
                  "chains": bursts * 4,
                  "ms_per_chain": dt * 1e3 / (bursts * 4)}))
"""


def run_overlap(*, fence: bool, bursts: int = 8):
    """Async-submitter chain timing with the producer fence forced on
    (the pre-round-4 behavior) vs off (the 1-device default): the delta
    is the restored compute/collective overlap (VERDICT r3 #2)."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["JAX_PLATFORMS"] = "cpu"
    env["HOROVOD_TPU_PRODUCER_FENCE"] = "1" if fence else "0"
    env["HOROVOD_CYCLE_TIME"] = "1"
    proc = subprocess.run(
        [sys.executable, "-c", OVERLAP_WORKER, str(bursts)],
        env=env, capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    if proc.returncode != 0:
        raise RuntimeError(
            f"overlap worker failed (fence={fence}):\n"
            f"{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


# --------------------------------------------------------------------------
# Wire-compression bench (--compression): bytes-on-wire, roundtrip error,
# step time, and seeded convergence per wire format. All recorded DELTAS
# (wire-byte ratios, error, loss-vs-fp32) are deterministic — seeded data,
# CPU backend — so BENCH_COMPRESSION.json regenerates reproducibly; only
# the *_ms fields are wall-clock and informational.
# --------------------------------------------------------------------------

COMPRESSION_MODES = ["fp32", "bf16_cast", "fp8_cast", "int8_blockwise",
                     "fp8_blockwise"]

COMPRESSION_WORKER = r"""
import json, os, sys, time
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import optax
import horovod_tpu as hvd
from horovod_tpu.compression import Compression
from horovod_tpu.ops import collective as _coll

mode = sys.argv[1]
steps = int(sys.argv[2])

COMP = {"fp32": Compression.none, "bf16_cast": Compression.bf16,
        "fp8_cast": Compression.fp8,
        "int8_blockwise": Compression.int8_blockwise,
        "fp8_blockwise": Compression.fp8_blockwise}[mode]

hvd.init()
rng = np.random.RandomState(0)

# Synthetic gradient pytree: mixed sizes/magnitudes like a real model's
# layer gradients (large near-zero embedding tail, small active head).
tree = {
    "embed": jnp.asarray(rng.standard_normal(8192).astype(np.float32) * 1e-3),
    "w1": jnp.asarray(rng.standard_normal(2048).astype(np.float32) * 1e-2),
    "w2": jnp.asarray(rng.standard_normal(777).astype(np.float32) * 1e-1),
    "b": jnp.asarray(rng.standard_normal(65).astype(np.float32)),
}
logical = sum(int(v.size) * 4 for v in tree.values())

eng = _coll.engine()
base = eng.wire_bytes_enqueued
out = hvd.allreduce_gradients(tree, average=True, compression=COMP)
wire = eng.wire_bytes_enqueued - base

# Max relative error per tensor (normalized by the tensor's absmax —
# averaging replicated copies is the identity, so the input is the
# reference), worst tensor reported.
max_rel = 0.0
for k in tree:
    ref = np.asarray(tree[k], np.float32)
    got = np.asarray(out[k], np.float32)
    max_rel = max(max_rel,
                  float(np.max(np.abs(got - ref)) / np.max(np.abs(ref))))

# Seeded quadratic-model convergence: `steps` eager engine steps (the
# fused — and for blockwise, quantized — XLA collective path each step).
X = rng.standard_normal((64, 16)).astype(np.float32)
w_true = rng.standard_normal((16,)).astype(np.float32)
y = X @ w_true
Xj, yj = jnp.asarray(X), jnp.asarray(y)

def loss(w):
    return jnp.mean((Xj @ w - yj) ** 2)

opt = hvd.DistributedOptimizer(optax.sgd(0.05), compression=COMP)
w = jnp.zeros((16,))
state = opt.init(w)
grad = jax.grad(loss)
t0 = time.perf_counter()
for _ in range(steps):
    g = grad(w)
    u, state = opt.update(g, state, w)
    w = optax.apply_updates(w, u)
dt = time.perf_counter() - t0
print(json.dumps({
    "mode": mode,
    "logical_bytes": logical,
    "wire_bytes": int(wire),
    "max_rel_err": max_rel,
    "final_loss": float(loss(w)),
    "steps": steps,
    "step_time_ms": dt * 1e3 / steps,
}))
"""


def run_compression_mode(mode: str, steps: int) -> dict:
    env = dict(os.environ)
    env["HOROVOD_CYCLE_TIME"] = "1"
    proc = subprocess.run(
        [sys.executable, "-c", COMPRESSION_WORKER, mode, str(steps)],
        env=env, capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    if proc.returncode != 0:
        raise RuntimeError(
            f"compression bench worker failed (mode={mode}):\n"
            f"{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main_compression(steps: int, out_path: str) -> None:
    rows = {}
    fp32 = None
    for mode in COMPRESSION_MODES:
        r = run_compression_mode(mode, steps)
        if mode == "fp32":
            fp32 = r
        rows[mode] = {
            "wire_bytes": r["wire_bytes"],
            "wire_ratio_vs_fp32": round(
                r["wire_bytes"] / fp32["wire_bytes"], 4),
            "max_rel_err": round(r["max_rel_err"], 6),
            "final_loss": r["final_loss"],
            "loss_ratio_vs_fp32": round(
                r["final_loss"] / fp32["final_loss"], 6)
            if fp32["final_loss"] else None,
            "step_time_ms": round(r["step_time_ms"], 3),
        }
    result = {
        "metric": "compression_allreduce",
        "steps": steps,
        "logical_bytes": fp32["logical_bytes"],
        "note": ("deltas (wire_ratio/max_rel_err/loss_ratio) are seeded "
                 "and deterministic; step_time_ms is wall-clock and "
                 "informational only"),
        "rows": rows,
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(result))


# --------------------------------------------------------------------------
# Checkpoint bench (--checkpoint): rank-0 pickle vs sharded-async engine on
# a ZeRO-like seeded state. Deterministic fields: logical bytes, per-rank
# bytes written, shard counts (seeded data, fixed layouts). Wall-clock
# fields (*_ms) are informational except the headline claim they support:
# the sharded-async save blocks the training loop for less time than the
# rank-0 pickle (the *_ratio row; guarded by the slow-tier bench test).
# --------------------------------------------------------------------------

CHECKPOINT_WORKER = r"""
import json, os, sys, time
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
import horovod_tpu as hvd
from horovod_tpu.checkpoint import CheckpointEngine, read_manifest, tree_layout
from horovod_tpu.utils.checkpoint import save_checkpoint

commits = int(sys.argv[1])
world = 4                                  # simulated hosts (8 devs / 2)

hvd.init()
rng = np.random.RandomState(0)
mesh = Mesh(np.asarray(jax.devices(), dtype=object).reshape(8), ("dp",))
shard = NamedSharding(mesh, P("dp"))

# ZeRO-1-shaped state: two dp-sharded flat moment vectors (the state that
# is ALREADY sharded across ranks and should never transit one host) plus
# a replicated parameter block. ~48 MB fp32 total.
state = {
    "mu": jax.device_put(
        jnp.asarray(rng.standard_normal(8 * 1024 * 1024), jnp.float32),
        shard),
    "nu": jax.device_put(
        jnp.asarray(rng.standard_normal(2 * 1024 * 1024), jnp.float32),
        shard),
    "params": jnp.asarray(rng.standard_normal(2 * 1024 * 1024),
                          jnp.float32),
}
logical = sum(int(np.shape(v)[0]) * 4 for v in state.values())

outdir = sys.argv[2]

# --- rank-0 pickle convention: the loop blocks for the whole device_get
# + serialize + fsync of the full state.
pk_dir = os.path.join(outdir, "pickle")
pk_blocked = []
for c in range(commits):
    t0 = time.perf_counter()
    save_checkpoint(state, pk_dir, step=c)
    pk_blocked.append(time.perf_counter() - t0)
pk_bytes = os.path.getsize(os.path.join(pk_dir, "0.pkl"))

# --- sharded-async engine, simulated 4-host layout: each rank's save()
# returns after snapshotting ITS shards; writes/commit run in background.
proc_fn = lambda d: d.id // 2
sh_dir = os.path.join(outdir, "sharded")
engines = [CheckpointEngine(sh_dir, process_index=p, process_count=world,
                            process_fn=proc_fn, barrier=lambda n: None)
           for p in range(world)]
sh_blocked = []
for c in range(commits):
    per_rank = []
    for p in list(range(1, world)) + [0]:
        t0 = time.perf_counter()
        engines[p].save(state, c)
        per_rank.append(time.perf_counter() - t0)
    # the loop blocks on the slowest rank's snapshot
    sh_blocked.append(max(per_rank))
    for p in range(world):
        engines[p].wait()

man = read_manifest(sh_dir, commits - 1)
rank_bytes = {p: 0 for p in range(world)}
rank_shards = {p: 0 for p in range(world)}
for entry in man["leaves"]:
    for s in entry["shards"]:
        rank_bytes[s["process"]] += s["nbytes"]
        rank_shards[s["process"]] += 1

med = lambda xs: sorted(xs)[len(xs) // 2]
print(json.dumps({
    "logical_bytes": logical,
    "commits": commits,
    "pickle": {"bytes_rank0": pk_bytes,
               "bytes_other_ranks": 0,
               "blocked_ms_per_commit": round(med(pk_blocked) * 1e3, 3)},
    "sharded": {"bytes_per_rank": {str(p): rank_bytes[p]
                                   for p in range(world)},
                "shards_per_rank": {str(p): rank_shards[p]
                                    for p in range(world)},
                "process_count": man["process_count"],
                "blocked_ms_per_commit": round(med(sh_blocked) * 1e3, 3)},
}))
"""


def run_checkpoint_bench(commits: int, workdir: str) -> dict:
    env = dict(os.environ)
    proc = subprocess.run(
        [sys.executable, "-c", CHECKPOINT_WORKER, str(commits), workdir],
        env=env, capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    if proc.returncode != 0:
        raise RuntimeError(
            f"checkpoint bench worker failed:\n{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main_checkpoint(commits: int, out_path: str) -> None:
    import tempfile
    with tempfile.TemporaryDirectory() as workdir:
        r = run_checkpoint_bench(commits, workdir)
    pk_ms = r["pickle"]["blocked_ms_per_commit"]
    sh_ms = r["sharded"]["blocked_ms_per_commit"]
    result = {
        "metric": "checkpoint_blocked_seconds",
        "commits": r["commits"],
        "logical_bytes": r["logical_bytes"],
        "note": ("byte/shard counts are seeded and deterministic; "
                 "*_ms are wall-clock. The headline delta — sharded-"
                 "async blocks the loop less than the rank-0 pickle — "
                 "is blocked_ratio_sharded_vs_pickle < 1."),
        "pickle": r["pickle"],
        "sharded": r["sharded"],
        "blocked_ratio_sharded_vs_pickle": round(sh_ms / pk_ms, 4)
        if pk_ms else None,
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(result))


# --------------------------------------------------------------------------
# Trace overhead bench (--trace): all-ranks tracing (HOROVOD_TPU_TIMELINE
# with a {rank} placeholder, docs/tracing.md) on vs off inside ONE
# 2-process control-plane job — the same p25-of-per-step A/B method as
# BENCH_METRICS: interleaved repeats with ALTERNATING order toggled
# in-process (the writer is detached between bursts, so both modes share
# one process, one warmup, one socket set — separate jobs were measured
# to differ by ±5% job-to-job, swamping a 3% budget), each step timed
# individually, per-mode estimate = 25th percentile of the pooled
# per-step times (hiccups land in the upper tail; a systematic writer
# cost shifts the whole distribution). Writes BENCH_TRACE.json; the
# slow-tier guard (tests/test_trace_overhead.py) asserts < 3%.
# --------------------------------------------------------------------------

TRACE_STEPS = 40           # steps per mode per round
TRACE_ROUNDS = 6           # alternating-order on/off rounds
TRACE_WARMUP = 8


def run_trace_job(steps: int, warmup: int, rounds: int,
                  tmpdir: str) -> dict:
    """One 2-process job with per-rank tracing configured; returns
    {"on": [...], "off": [...]} per-step wall times pooled over both
    ranks."""
    from horovod_tpu.runner.api import run as hvd_run

    def worker(steps, warmup, rounds):
        import time

        import jax.numpy as jnp

        import horovod_tpu as hvd
        from horovod_tpu.ops import collective as _coll

        hvd.init()
        eng = _coll.engine()
        xs = [jnp.ones((256,), jnp.float32) for _ in range(8)]

        def hot(tag, n):
            out = []
            for step in range(n):
                t0 = time.perf_counter()
                with eng.burst():
                    hs = [hvd.allreduce_async(x, average=False,
                                              name=f"tr.{tag}.{step}.{i}")
                          for i, x in enumerate(xs)]
                for h in hs:
                    h.wait()
                out.append(time.perf_counter() - t0)
            return out

        hot("w", warmup)               # compile + engine + trace bring-up
        tl = eng.timeline              # created during warmup (per-rank)
        times = {"on": [], "off": []}
        for rep in range(rounds):
            order = (("on", "off") if rep % 2 == 0 else ("off", "on"))
            for mode in order:
                # Toggle BETWEEN bursts only: every handle is waited, so
                # no span is torn. The off mode still pays the
                # `timeline is None` checks — that IS the disabled cost.
                eng.timeline = tl if mode == "on" else None
                times[mode].extend(hot(f"{rep}.{mode}", steps))
        eng.timeline = tl
        eng.shutdown()
        return times

    env = {"JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
           "HOROVOD_TPU_DISABLE_NATIVE": "1",
           "HOROVOD_CYCLE_TIME": "1",
           "HOROVOD_TPU_TIMELINE": os.path.join(tmpdir,
                                                "bench.{rank}.json")}
    results = hvd_run(worker, args=(steps, warmup, rounds), np=2,
                      extra_env=env, start_timeout=300)
    pooled = {"on": [], "off": []}
    for r in results:
        pooled["on"].extend(r["on"])
        pooled["off"].extend(r["off"])
    return pooled


def main_trace(out_path: str, rounds: int = TRACE_ROUNDS) -> dict:
    import tempfile
    with tempfile.TemporaryDirectory() as tmpdir:
        times = run_trace_job(TRACE_STEPS, TRACE_WARMUP, rounds, tmpdir)
    p25 = lambda xs: sorted(xs)[len(xs) // 4]  # noqa: E731
    t_on, t_off = p25(times["on"]), p25(times["off"])
    overhead = t_on / t_off - 1.0
    result = {
        "metric": "trace_overhead",
        "note": ("2-process fused-allreduce loop, all-ranks tracing "
                 "({rank} placeholder) on vs off, toggled in-process "
                 "with alternating order per round (the BENCH_METRICS "
                 "method); p25 of pooled per-step wall times "
                 "(wall-clock, informational); the slow-tier guard "
                 "asserts on < 1.03 * off"),
        "steps_per_mode_per_round": TRACE_STEPS,
        "rounds": rounds,
        "tensors_per_step": 8,
        "rows": {
            "tracing_on": {"step_time_ms": round(t_on * 1e3, 4)},
            "tracing_off": {"step_time_ms": round(t_off * 1e3, 4)},
        },
        "overhead_frac": round(overhead, 6),
        "budget_frac": 0.03,
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(result))
    return result


# --------------------------------------------------------------------------
# Flight-recorder overhead A/B (--recorder): the black-box ring buffer
# (observability/flight_recorder.py) is ALWAYS ON — every fused group
# appends deliver/done tuples, every StepTimer step appends begin/end.
# This bench proves that stays invisible: a 2-process fused-allreduce +
# StepTimer loop with recording enabled vs disabled (toggled in-process
# with alternating order per round, the BENCH_METRICS method), p25 of
# pooled per-step wall times. Budget: < 1% of step time.
# --------------------------------------------------------------------------

RECORDER_STEPS = 40
RECORDER_ROUNDS = 6
RECORDER_WARMUP = 8
RECORDER_BUDGET = 0.01


def run_recorder_job(steps: int, warmup: int, rounds: int) -> dict:
    """One 2-process job; returns {"on": [...], "off": [...]} per-step
    wall times pooled over both ranks."""
    from horovod_tpu.runner.api import run as hvd_run

    def worker(steps, warmup, rounds):
        import time

        import jax.numpy as jnp

        import horovod_tpu as hvd
        from horovod_tpu.observability import StepTimer
        from horovod_tpu.observability import flight_recorder as _fr
        from horovod_tpu.ops import collective as _coll

        hvd.init()
        eng = _coll.engine()
        timer = StepTimer("bench", batch_size=32)
        xs = [jnp.ones((256,), jnp.float32) for _ in range(8)]

        def hot(tag, n):
            out = []
            for step in range(n):
                t0 = time.perf_counter()
                with timer:
                    with eng.burst():
                        hs = [hvd.allreduce_async(
                            x, average=False,
                            name=f"rec.{tag}.{step}.{i}")
                            for i, x in enumerate(xs)]
                    for h in hs:
                        h.wait()
                out.append(time.perf_counter() - t0)
            return out

        hot("w", warmup)               # compile + engine bring-up
        times = {"on": [], "off": []}
        for rep in range(rounds):
            order = (("on", "off") if rep % 2 == 0 else ("off", "on"))
            for mode in order:
                _fr.set_enabled(mode == "on")
                times[mode].extend(hot(f"{rep}.{mode}", steps))
        _fr.set_enabled(True)
        eng.shutdown()
        return times

    env = {"JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
           "HOROVOD_TPU_DISABLE_NATIVE": "1",
           "HOROVOD_CYCLE_TIME": "1"}
    results = hvd_run(worker, args=(steps, warmup, rounds), np=2,
                      extra_env=env, start_timeout=300)
    pooled = {"on": [], "off": []}
    for r in results:
        pooled["on"].extend(r["on"])
        pooled["off"].extend(r["off"])
    return pooled


def main_recorder(out_path: str, rounds: int = RECORDER_ROUNDS) -> dict:
    times = run_recorder_job(RECORDER_STEPS, RECORDER_WARMUP, rounds)
    p25 = lambda xs: sorted(xs)[len(xs) // 4]  # noqa: E731
    t_on, t_off = p25(times["on"]), p25(times["off"])
    overhead = t_on / t_off - 1.0
    result = {
        "metric": "flight_recorder_overhead",
        "note": ("2-process fused-allreduce + StepTimer loop, flight "
                 "recorder always-on vs disabled, toggled in-process "
                 "with alternating order per round (the BENCH_METRICS "
                 "method); p25 of pooled per-step wall times "
                 "(wall-clock, informational); the slow-tier guard "
                 "asserts on < 1.01 * off"),
        "steps_per_mode_per_round": RECORDER_STEPS,
        "rounds": rounds,
        "tensors_per_step": 8,
        "rows": {
            "recorder_on": {"step_time_ms": round(t_on * 1e3, 4)},
            "recorder_off": {"step_time_ms": round(t_off * 1e3, 4)},
        },
        "overhead_frac": round(overhead, 6),
        "budget_frac": RECORDER_BUDGET,
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(result))
    return result


# --------------------------------------------------------------------------
# Telemetry-history + detector overhead A/B (--health): the history
# sampler + online anomaly detectors (docs/health.md) run OFF the hot
# path (one task on the shared telemetry timer thread), so their step
# cost must be indistinguishable from zero. A 2-process fused-allreduce
# + StepTimer loop runs with the sampler ticking at a deliberately
# aggressive 100 ms cadence (50x the production default — a worst case)
# vs disabled, toggled in-process with alternating order per round (the
# BENCH_METRICS method), p25 of pooled per-step wall times. Budget: the
# acceptance bar is < 1% of step time. A deterministic detector-smoke
# section also pins the plane's headline behaviours (leak trips, noisy
# flat does not) so the artifact documents more than a timing.
# --------------------------------------------------------------------------

HEALTH_STEPS = 40
HEALTH_ROUNDS = 6
HEALTH_WARMUP = 8
HEALTH_BUDGET = 0.01


def run_health_job(steps: int, warmup: int, rounds: int) -> dict:
    """One 2-process job; returns pooled per-step wall times per mode
    plus rank-0's sampler/alert counters."""
    from horovod_tpu.runner.api import run as hvd_run

    def worker(steps, warmup, rounds):
        import os
        import time

        import jax.numpy as jnp

        import horovod_tpu as hvd
        from horovod_tpu.observability import StepTimer
        from horovod_tpu.observability import history as _history
        from horovod_tpu.ops import collective as _coll

        hvd.init()
        eng = _coll.engine()
        timer = StepTimer("bench", batch_size=32)
        xs = [jnp.ones((256,), jnp.float32) for _ in range(8)]
        sampler = _history.maybe_start_sampler()

        def hot(tag, n):
            out = []
            for step in range(n):
                t0 = time.perf_counter()
                with timer:
                    with eng.burst():
                        hs = [hvd.allreduce_async(
                            x, average=False,
                            name=f"hl.{tag}.{step}.{i}")
                            for i, x in enumerate(xs)]
                    for h in hs:
                        h.wait()
                out.append(time.perf_counter() - t0)
            return out

        hot("w", warmup)               # compile + engine bring-up
        times = {"on": [], "off": []}
        for rep in range(rounds):
            order = (("on", "off") if rep % 2 == 0 else ("off", "on"))
            for mode in order:
                _history.set_enabled(mode == "on")
                times[mode].extend(hot(f"{rep}.{mode}", steps))
        _history.set_enabled(True)
        if sampler is not None:
            sampler.final_flush()
        snap = hvd.metrics_snapshot(prefix="hvdtpu_history_")
        times["samples"] = sum(
            (snap.get("hvdtpu_history_samples_total") or
             {"values": {}})["values"].values())
        times["rank"] = int(os.environ.get("HOROVOD_TPU_PROCESS_ID",
                                           "0") or 0)
        eng.shutdown()
        return times

    import tempfile
    hist_dir = tempfile.mkdtemp(prefix="bench_health_")
    env = {"JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
           "HOROVOD_TPU_DISABLE_NATIVE": "1",
           "HOROVOD_CYCLE_TIME": "1",
           # Worst-case cadence: 50x faster than the 5 s default.
           "HOROVOD_TPU_HISTORY": hist_dir,
           "HOROVOD_TPU_HISTORY_INTERVAL": "0.1"}
    results = hvd_run(worker, args=(steps, warmup, rounds), np=2,
                      extra_env=env, start_timeout=300)
    pooled = {"on": [], "off": [], "samples": 0}
    for r in results:
        pooled["on"].extend(r["on"])
        pooled["off"].extend(r["off"])
        pooled["samples"] += r["samples"]
    return pooled


def run_health_detector_smoke() -> dict:
    """Seeded, deterministic detector behaviour pinned into the
    artifact: a synthetic monotone leak must trip the trend detector, a
    noisy-but-flat gauge must not (the false-positive guard), and a
    20% level shift must trip the EWMA regression detector."""
    import random

    from horovod_tpu.observability import health as _health

    rng = random.Random(1234)
    leak = _health.TrendDetector()
    flat = _health.TrendDetector()
    # The STOCK step-time-regression detector (same factory the live
    # plane uses): a 20% shift must fire within a few windows.
    shift = next(s for s in _health.default_specs()
                 if s.kind == "step_time_regression").factory()
    leak_fired = flat_fired = 0
    shift_fired_at = None
    for t in range(60):
        if leak.update(float(t), 1e6 + 5e4 * t + rng.gauss(0, 1e3)):
            leak_fired += 1
        if flat.update(float(t), 1e6 + rng.gauss(0, 1e5)):
            flat_fired += 1
        v = 0.010 if t < 30 else 0.012
        if shift.update(float(t), v + rng.gauss(0, 2e-4)) \
                and shift_fired_at is None:
            shift_fired_at = t
    return {
        "leak_windows_fired": leak_fired,
        "noisy_flat_windows_fired": flat_fired,
        "regression_first_fired_at_sample": shift_fired_at,
        "regression_onset_sample": 30,
    }


def main_health(out_path: str, rounds: int = HEALTH_ROUNDS) -> dict:
    times = run_health_job(HEALTH_STEPS, HEALTH_WARMUP, rounds)
    p25 = lambda xs: sorted(xs)[len(xs) // 4]  # noqa: E731
    t_on, t_off = p25(times["on"]), p25(times["off"])
    overhead = t_on / t_off - 1.0
    result = {
        "metric": "history_sampler_detector_overhead",
        "note": ("2-process fused-allreduce + StepTimer loop, history "
                 "sampler + online detectors at a 100 ms cadence (50x "
                 "the 5 s production default) vs disabled, toggled "
                 "in-process with alternating order per round (the "
                 "BENCH_METRICS method); p25 of pooled per-step wall "
                 "times (wall-clock, informational); the slow-tier "
                 "guard asserts on < 1.01 * off; detector_smoke "
                 "fields are seeded-deterministic"),
        "steps_per_mode_per_round": HEALTH_STEPS,
        "rounds": rounds,
        "tensors_per_step": 8,
        "history_samples_written": times["samples"],
        "rows": {
            "health_on": {"step_time_ms": round(t_on * 1e3, 4)},
            "health_off": {"step_time_ms": round(t_off * 1e3, 4)},
        },
        "overhead_frac": round(overhead, 6),
        "budget_frac": HEALTH_BUDGET,
        "detector_smoke": run_health_detector_smoke(),
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(result))
    return result


# --------------------------------------------------------------------------
# Numerics-plane overhead A/B (--numerics): the nonfinite payload
# sentinel (docs/numerics.md) adds one np.isfinite pass over each fused
# collective buffer — bytes the pack loop just touched, so the pass
# should ride the cache — plus a single flag check everywhere else.
# A 2-process fused-allreduce loop runs with the plane enabled vs
# disabled, toggled in-process with alternating order per round (the
# BENCH_METRICS method), p25 of pooled per-step wall times. Budget: the
# acceptance bar is < 1% of step time. A seeded numerics_smoke section
# pins the plane's headline behaviours (a crafted NaN/Inf buffer counts
# exactly, a single flipped mantissa bit changes the value fingerprint
# and the majority-compare names the flipped rank, the nonfinite-rate
# detector fires on the first event) so the artifact documents more
# than a timing.
# --------------------------------------------------------------------------

NUMERICS_STEPS = 40
NUMERICS_ROUNDS = 6
NUMERICS_WARMUP = 8
NUMERICS_BUDGET = 0.01


def run_numerics_job(steps: int, warmup: int, rounds: int) -> dict:
    """One 2-process job; returns pooled per-step wall times per mode
    plus the nonfinite counter total (must stay 0 on an all-ones
    payload — a nonzero count here means the sentinel miscounts)."""
    from horovod_tpu.runner.api import run as hvd_run

    def worker(steps, warmup, rounds):
        import time

        import jax.numpy as jnp

        import horovod_tpu as hvd
        from horovod_tpu.observability import numerics as _numerics
        from horovod_tpu.ops import collective as _coll

        hvd.init()
        eng = _coll.engine()
        xs = [jnp.ones((256,), jnp.float32) for _ in range(8)]

        def hot(tag, n):
            out = []
            for step in range(n):
                t0 = time.perf_counter()
                with eng.burst():
                    hs = [hvd.allreduce_async(
                        x, average=False,
                        name=f"nm.{tag}.{step}.{i}")
                        for i, x in enumerate(xs)]
                for h in hs:
                    h.wait()
                out.append(time.perf_counter() - t0)
            return out

        hot("w", warmup)               # compile + engine bring-up
        # STEP-level interleave, not the --health block interleave: the
        # plane toggles with one module flag, so each on-step can run
        # back-to-back with its off-step twin ~4 ms later — any load
        # swing on a shared box hits both halves of a pair and cancels
        # in the per-pair ratio. Order flips each round.
        times = {"rounds": []}
        for rep in range(rounds):
            order = (("on", "off") if rep % 2 == 0 else ("off", "on"))
            row = {"on": [], "off": []}
            for step in range(steps):
                for mode in order:
                    _numerics.set_enabled(mode == "on")
                    row[mode].extend(hot(f"{rep}.{mode}.{step}", 1))
            times["rounds"].append(row)
        _numerics.set_enabled(False)
        snap = hvd.metrics_snapshot(prefix="hvdtpu_numerics_")
        times["nonfinite"] = sum(
            (snap.get("hvdtpu_numerics_nonfinite_total") or
             {"values": {}})["values"].values())
        eng.shutdown()
        return times

    env = {"JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
           "HOROVOD_TPU_DISABLE_NATIVE": "1",
           "HOROVOD_CYCLE_TIME": "1"}
    results = hvd_run(worker, args=(steps, warmup, rounds), np=2,
                      extra_env=env, start_timeout=300)
    # Pool the two ranks' samples round-by-round: collectives step in
    # lockstep, so round r on rank 0 and round r on rank 1 are the same
    # wall-clock window.
    pooled = {"rounds": [], "nonfinite": 0}
    for i in range(rounds):
        row = {"on": [], "off": []}
        for r in results:
            row["on"].extend(r["rounds"][i]["on"])
            row["off"].extend(r["rounds"][i]["off"])
        pooled["rounds"].append(row)
    for r in results:
        pooled["nonfinite"] += r["nonfinite"]
    return pooled


def run_numerics_smoke() -> dict:
    """Seeded, deterministic numerics behaviour pinned into the
    artifact: exact nonfinite accounting, single-bitflip fingerprint
    sensitivity + majority blame, and the windowed nonfinite-rate
    detector's time-to-fire."""
    import numpy as np

    from horovod_tpu.observability import health as _health
    from horovod_tpu.observability import numerics as _numerics

    bad = np.arange(64, dtype=np.float32)
    bad[3] = np.nan
    bad[10], bad[11] = np.inf, -np.inf
    counted = int(_numerics.count_nonfinite(bad))

    clean = np.arange(4096, dtype=np.float32) / 7.0
    fp = _numerics.fingerprint_leaf("w", clean)
    fp_flipped = _numerics.fingerprint_leaf(
        "w", _numerics.flip_mantissa_bit(clean, index=2048, bit=3))
    divergent = _numerics.compare_fingerprints(
        {0: {"w": fp}, 1: {"w": fp_flipped}, 2: {"w": fp}})

    det = next(s for s in _health.default_specs()
               if s.kind == "nonfinite_rate").factory()
    fired_at = None
    for t in range(10):
        # A counter-rate series that records one nonfinite event at
        # t=3s and is otherwise silent.
        if det.update(float(t), 1.0 if t == 3 else 0.0) \
                and fired_at is None:
            fired_at = t
    return {
        "nonfinite_elements_counted": counted,
        "nonfinite_elements_expected": 3,
        "bitflip_changes_fingerprint": fp != fp_flipped,
        "bitflip_blamed": [[leaf, rank] for leaf, rank in divergent],
        "nonfinite_rate_first_fired_at_sample": fired_at,
        "nonfinite_event_at_sample": 3,
    }


def main_numerics(out_path: str, rounds: int = NUMERICS_ROUNDS) -> dict:
    times = run_numerics_job(NUMERICS_STEPS, NUMERICS_WARMUP, rounds)
    p25 = lambda xs: sorted(xs)[len(xs) // 4]  # noqa: E731
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    # Paired estimator: each on-step ran back-to-back with its
    # off-step twin, so the per-pair ratio cancels whatever the box
    # was doing at that instant; the median over all pairs rejects the
    # pairs a load spike still split. Block-level A/B (the --health
    # method) was tried first and wandered ±4% on a busy box — 50x the
    # plane's true measured cost (~3 us of np.isfinite per fused
    # buffer).
    ratios = [on / off
              for r in times["rounds"]
              for on, off in zip(r["on"], r["off"])]
    overhead = med(ratios) - 1.0
    per_round = [round(med([on / off
                            for on, off in zip(r["on"], r["off"])]), 5)
                 for r in times["rounds"]]
    all_on = [t for r in times["rounds"] for t in r["on"]]
    all_off = [t for r in times["rounds"] for t in r["off"]]
    t_on, t_off = p25(all_on), p25(all_off)
    result = {
        "metric": "numerics_plane_overhead",
        "note": ("2-process fused-allreduce loop, nonfinite payload "
                 "sentinel + numerics plane enabled vs disabled, "
                 "toggled in-process PER STEP so each on-step runs "
                 "back-to-back with its off-step twin (order flips "
                 "each round); overhead_frac is the median over all "
                 "paired on/off step-time ratios (wall-clock, "
                 "informational); the slow-tier guard asserts "
                 "overhead_frac < 0.01; numerics_smoke fields are "
                 "seeded-deterministic"),
        "steps_per_mode_per_round": NUMERICS_STEPS,
        "rounds": rounds,
        "tensors_per_step": 8,
        "nonfinite_false_positives": times["nonfinite"],
        "rows": {
            "numerics_on": {"step_time_ms": round(t_on * 1e3, 4)},
            "numerics_off": {"step_time_ms": round(t_off * 1e3, 4)},
        },
        "round_ratios": per_round,
        "overhead_frac": round(overhead, 6),
        "budget_frac": NUMERICS_BUDGET,
        "numerics_smoke": run_numerics_smoke(),
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(result))
    return result


# --------------------------------------------------------------------------
# Straggler A/B (--straggler): a 4-process job with one rank delayed via
# HOROVOD_TPU_FAULT_SPEC, run WITHOUT adaptation (every fused collective
# stalls behind the slow rank for the whole job) and WITH the adaptation
# policy + elastic eviction (docs/adaptation.md): the policy escalates
# degradation tiers, evicts the slow rank, and the job re-rendezvouses at
# np=3 and recovers. Writes BENCH_STRAGGLER.json: the per-step step-time
# timeline, the recovered-throughput ratio (unmitigated stalled step time
# over post-recovery step time), time-to-recover, and the adaptation
# events from the hvdtpu_adaptation_* metrics. Deterministic fields:
# world sizes, generations, tier/transition names, eviction target, step
# counts (seeded faults, fixed spec); *_ms / *_s fields are wall-clock —
# the slow-tier reproducibility test asserts only their sign-stable
# headline, recovered_throughput_ratio > 1.
# --------------------------------------------------------------------------

STRAGGLER_NP = 4
STRAGGLER_RANK = 2
STRAGGLER_DELAY_MS = 100
STRAGGLER_STEPS = 24
STRAGGLER_COMMIT_EVERY = 2


def _make_straggler_worker():
    """Nested so cloudpickle ships it by value (see tests/test_elastic)."""

    def worker(outdir, total_steps, commit_every):
        import json
        import os
        import time

        import jax.numpy as jnp

        import horovod_tpu as hvd

        hvd.init()
        r = hvd.process_rank()
        gen = hvd.generation()
        state = hvd.ElasticState(params={"w": jnp.zeros((64,))})
        state.restore()
        w = jnp.asarray(state.params["w"])

        def dump_adapt():
            if r != 0:
                return
            snap = hvd.metrics_snapshot()
            keep = {k: v for k, v in snap.items()
                    if k.startswith("hvdtpu_adaptation")
                    or k.startswith("hvdtpu_fault")}
            tmp = os.path.join(outdir, f"adapt.g{gen}.json.tmp")
            with open(tmp, "w") as af:
                json.dump(keep, af)
            os.replace(tmp, os.path.join(outdir, f"adapt.g{gen}.json"))

        path = os.path.join(outdir, f"steps.g{gen}.r{r}.jsonl")
        try:
            with open(path, "a") as f:
                for step in range(int(state.step), total_steps):
                    t0 = time.perf_counter()
                    g = hvd.allreduce(w * 0 + (r + 1.0), average=True,
                                      name=f"g.{step}")
                    w = w - 0.01 * g
                    f.write(json.dumps(
                        {"step": step, "gen": gen,
                         "t_ms": (time.perf_counter() - t0) * 1e3,
                         "ts": time.time()}) + "\n")
                    f.flush()
                    state.params = {"w": w}
                    if (step + 1) % commit_every == 0:
                        state.commit(step + 1)
        except BaseException:
            # Eviction path: persist the adaptation metrics BEFORE the
            # typed failure propagates (the post-eviction snapshot is
            # the one that records the eviction counter).
            dump_adapt()
            raise
        dump_adapt()
        return {"rank": r, "gen": gen, "size": hvd.size(),
                "w0": float(w[0])}

    return worker


def _straggler_env(adaptation: bool) -> dict:
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "HOROVOD_TPU_DISABLE_NATIVE": "1",
        "HOROVOD_CYCLE_TIME": "1",
        "HOROVOD_TPU_STALL_CHECK_DISABLE": "1",
        "HOROVOD_TPU_FAULT_SPEC": (
            f"rank={STRAGGLER_RANK}:delay={STRAGGLER_DELAY_MS}ms:gen=0"),
    }
    if adaptation:
        env.update({
            "HOROVOD_TPU_ADAPTATION": "1",
            "HOROVOD_TPU_ADAPT_THRESHOLD": "0.03",
            "HOROVOD_TPU_ADAPT_SUSTAIN": "0.4",
            "HOROVOD_TPU_ADAPT_COOLDOWN": "10",
            "HOROVOD_TPU_ADAPT_INTERVAL": "0.1",
        })
    return env


def _read_steps(outdir: str, gen: int, rank: int = 0):
    path = os.path.join(outdir, f"steps.g{gen}.r{rank}.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def run_straggler_pair(workdir: str, steps: int, commit_every: int) -> dict:
    """Both arms of the A/B; returns the raw per-arm data."""
    from horovod_tpu.elastic import FailureConfig, run_elastic
    from horovod_tpu.runner.api import run as hvd_run

    un_dir = os.path.join(workdir, "unmitigated")
    ad_dir = os.path.join(workdir, "adaptive")
    os.makedirs(un_dir)
    os.makedirs(ad_dir)

    hvd_run(_make_straggler_worker(), args=(un_dir, steps, commit_every),
            np=STRAGGLER_NP, extra_env=_straggler_env(adaptation=False),
            start_timeout=300)

    cfg = FailureConfig(failure_timeout_s=60.0, max_restarts=2,
                        backoff_s=0.2, slow_blacklist_s=600.0)
    results = run_elastic(
        _make_straggler_worker(), args=(ad_dir, steps, commit_every),
        min_np=1, max_np=STRAGGLER_NP, hosts=f"localhost:{STRAGGLER_NP}",
        state_dir=os.path.join(ad_dir, "estate"), config=cfg,
        extra_env=_straggler_env(adaptation=True), start_timeout=300)

    # Merged adaptive timeline: per-step rows keyed by step index, the
    # highest generation's execution winning (a resumed step replays
    # from the last commit).
    merged = {}
    for gen in range(4):
        for row in _read_steps(ad_dir, gen):
            prev = merged.get(row["step"])
            if prev is None or row["gen"] >= prev["gen"]:
                merged[row["step"]] = row
    adapt = {}
    for gen in range(4):
        p = os.path.join(ad_dir, f"adapt.g{gen}.json")
        if os.path.exists(p):
            adapt[f"g{gen}"] = json.load(open(p))
    return {
        "unmitigated_steps": _read_steps(un_dir, 0),
        "adaptive_timeline": [merged[s] for s in sorted(merged)],
        "adaptation_metrics": adapt,
        "final_world_size": results[0]["size"] if results else None,
        "final_generation": results[0]["gen"] if results else None,
    }


def main_straggler(out_path: str, steps: int = STRAGGLER_STEPS) -> dict:
    import tempfile
    with tempfile.TemporaryDirectory() as workdir:
        raw = run_straggler_pair(workdir, steps, STRAGGLER_COMMIT_EVERY)
    med = lambda xs: sorted(xs)[len(xs) // 2] if xs else None  # noqa: E731
    un = raw["unmitigated_steps"]
    tl = raw["adaptive_timeline"]
    un_steady = med([r["t_ms"] for r in un[len(un) // 2:]])
    tail = [r["t_ms"] for r in tl if r["gen"] > 0] or [r["t_ms"] for r in tl]
    rec_steady = med(tail[len(tail) // 2:])
    # Time-to-recover: first step at least 2x faster than the stalled
    # steady state, measured from the adaptive run's first step.
    t_rec = None
    for r in tl:
        if un_steady and r["t_ms"] < un_steady / 2.0:
            t_rec = r["ts"] - tl[0]["ts"]
            break
    g0 = raw["adaptation_metrics"].get("g0", {})
    transitions = g0.get("hvdtpu_adaptation_transitions_total",
                         {}).get("values", {})
    evictions = g0.get("hvdtpu_adaptation_evictions_total",
                       {}).get("values", {})
    result = {
        "metric": "straggler_recovery",
        "np": STRAGGLER_NP,
        "straggler_rank": STRAGGLER_RANK,
        "injected_delay_ms": STRAGGLER_DELAY_MS,
        "steps": steps,
        "note": ("4-proc fused-allreduce loop, rank "
                 f"{STRAGGLER_RANK} delayed {STRAGGLER_DELAY_MS}ms/step "
                 "via HOROVOD_TPU_FAULT_SPEC. Unmitigated: the whole "
                 "fleet runs at the straggler's pace forever. Adaptive: "
                 "the policy escalates degradation tiers then evicts the "
                 "rank; the elastic driver re-rendezvouses at np=3 and "
                 "resumes from the last commit. World sizes / "
                 "generations / transition names / eviction target are "
                 "deterministic; *_ms and *_s are wall-clock — the "
                 "slow-tier guard asserts recovered_throughput_ratio "
                 "> 1."),
        "rows": {
            "unmitigated": {"steady_step_ms": round(un_steady, 3),
                            "steps_completed": len(un)},
            "adaptive": {
                "recovered_steady_step_ms": round(rec_steady, 3),
                "steps_completed": len(tl),
                "final_world_size": raw["final_world_size"],
                "final_generation": raw["final_generation"],
            },
        },
        "recovered_throughput_ratio": round(un_steady / rec_steady, 3)
        if un_steady and rec_steady else None,
        "time_to_recover_s": round(t_rec, 3) if t_rec is not None else None,
        "adaptation_events": {"transitions": transitions,
                              "evictions": evictions},
        "step_timeline": [{"step": r["step"], "gen": r["gen"],
                           "t_ms": round(r["t_ms"], 3)} for r in tl],
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(result))
    return result


# --------------------------------------------------------------------------
# Pipeline-schedule bench (--pipeline): static bubble share + numerics
# parity per schedule (gpipe / 1f1b / interleaved / zb-h1) over a
# microbatch sweep,
# plus the hierarchical (in-slice ICI, then cross-slice DCN) gradient
# reduction vs the flat allreduce — cross-slice bytes/step and gradient
# equality. All recorded DELTAS (bubble shares, tick budgets, parity
# errors, dcn bytes, grad diffs) are deterministic — seeded data, static
# schedule math, CPU backend — so BENCH_PIPELINE.json regenerates
# reproducibly; only the *_ms fields are wall-clock and informational.
# --------------------------------------------------------------------------

PIPELINE_WORKER = r"""
import json, os, sys, time
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["HOROVOD_TPU_DCN_AXES"] = "dcn"
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from horovod_tpu.parallel import create_mesh
from horovod_tpu.parallel.collectives import (cross_slice_bytes,
                                              hierarchical_psum)
from horovod_tpu.parallel.pipeline import (pipeline_value_and_grad,
                                           schedule_info)
from horovod_tpu.quantization import wire_nbytes

microbatches = [int(x) for x in sys.argv[1].split(",")]
PP, V, D, MB = 4, 2, 32, 4

def stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])

def loss_fn(y):
    return jnp.mean(y.astype(jnp.float32) ** 2)

def make_stages(n_total, seed=0):
    rng = np.random.RandomState(seed)
    return [{"w": jnp.asarray(rng.randn(D, D), jnp.float32) * 0.5,
             "b": jnp.asarray(rng.randn(D), jnp.float32) * 0.1}
            for _ in range(n_total)]

def reference(stages, x_mb):
    def total(stages):
        losses = []
        for j in range(x_mb.shape[0]):
            h = x_mb[j]
            for p in stages:
                h = stage_fn(p, h)
            losses.append(loss_fn(h))
        return jnp.mean(jnp.asarray(losses))
    return jax.value_and_grad(total)(stages)

def pack(stages, n, v):
    def f(*ls):
        arr = jnp.stack(ls)
        if v == 1:
            return arr
        return arr.reshape((v, n) + arr.shape[1:]).swapaxes(0, 1)
    return jax.tree_util.tree_map(f, *stages)

mesh_pp = create_mesh(devices=jax.devices()[:PP], pp=PP)

def run_schedule(schedule, m):
    v = V if schedule == "interleaved" else 1
    stages = make_stages(PP * v)
    x = jnp.asarray(np.random.RandomState(1).randn(m, MB, D), jnp.float32)
    packed = pack(stages, PP, v)
    def run(p_local, x):
        p = jax.tree_util.tree_map(lambda l: l[0], p_local)
        loss, g = pipeline_value_and_grad(
            stage_fn, loss_fn, p, x, axis_name="pp", schedule=schedule,
            num_virtual=v)
        return loss, jax.tree_util.tree_map(lambda l: l[None], g)
    f = jax.jit(jax.shard_map(
        run, mesh=mesh_pp,
        in_specs=(jax.tree_util.tree_map(lambda _: P("pp"), packed), P()),
        out_specs=(P(), P("pp")), check_vma=False))
    loss, grads = f(packed, x)             # compile + first run
    jax.block_until_ready(grads)
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        out = f(packed, x)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    ref_loss, ref_grads = reference(stages, x)
    err = abs(float(loss) - float(ref_loss)) / max(abs(float(ref_loss)),
                                                   1e-9)
    for c in range(PP * v):
        r_, v_ = c % PP, c // PP
        got = jax.tree_util.tree_map(
            lambda l: l[r_] if v == 1 else l[r_][v_], grads)
        for a, b in zip(jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(ref_grads[c])):
            denom = max(float(jnp.max(jnp.abs(b))), 1e-9)
            err = max(err, float(jnp.max(jnp.abs(a - b))) / denom)
    sched = schedule_info(schedule, PP, m, num_virtual=v)
    return {
        "bubble_share": round(sched.bubble_share, 6),
        "ticks": sched.ticks,
        "num_virtual": v,
        "parity_max_rel_err": round(err, 9),
        "step_ms": round(sorted(times)[len(times) // 2] * 1e3, 3),
    }

bubble = {s: {str(m): run_schedule(s, m) for m in microbatches}
          for s in ("gpipe", "1f1b", "interleaved", "zb-h1")}

# --- hierarchical vs flat reduction on a dcn(2) x dp(4) mesh -------------
mesh_dp = create_mesh(dcn=2, dp=4)
rng = np.random.RandomState(2)
tree = {
    "embed": jnp.asarray(rng.standard_normal(262144).astype(np.float32)
                         * 1e-3),
    "w1": jnp.asarray(rng.standard_normal(65536).astype(np.float32)
                      * 1e-2),
    "w2": jnp.asarray(rng.standard_normal(16384).astype(np.float32)
                      * 1e-1),
    "b": jnp.asarray(rng.standard_normal(333).astype(np.float32)),
}
n_total = sum(int(v.size) for v in tree.values())
ICI = 4

def reduce_with(kind):
    def shard(t):
        if kind == "flat":
            return jax.tree_util.tree_map(
                lambda g: lax.psum(g, ("dcn", "dp")), t)
        wire = "int8x256" if kind == "hier_int8" else None
        return jax.tree_util.tree_map(
            lambda g: hierarchical_psum(g, "dp", "dcn", wire=wire), t)
    return jax.jit(jax.shard_map(shard, mesh=mesh_dp, in_specs=(P(),),
                                 out_specs=P(), check_vma=False))

results = {}
flat_out = None
for kind in ("flat", "hier", "hier_int8"):
    f = reduce_with(kind)
    out = f(tree)
    jax.block_until_ready(out)
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        o = f(tree)
        jax.block_until_ready(o)
        times.append(time.perf_counter() - t0)
    wire = "int8x256" if kind == "hier_int8" else None
    dcn_bytes = sum(
        cross_slice_bytes(int(v.size), ICI,
                          hierarchical=(kind != "flat"), wire=wire)
        for v in tree.values())
    row = {"dcn_bytes_per_step": int(dcn_bytes),
           "step_ms": round(sorted(times)[len(times) // 2] * 1e3, 3)}
    if kind == "flat":
        flat_out = out
    else:
        diff = max(float(jnp.max(jnp.abs(a - b)))
                   for a, b in zip(jax.tree_util.tree_leaves(out),
                                   jax.tree_util.tree_leaves(flat_out)))
        scale = max(float(jnp.max(jnp.abs(b)))
                    for b in jax.tree_util.tree_leaves(flat_out))
        row["grad_max_abs_diff_vs_flat"] = round(diff, 9)
        row["grad_max_rel_diff_vs_flat"] = round(diff / scale, 9)
    results[kind] = row

print(json.dumps({
    "bubble": bubble,
    "hierarchical": results,
    "gradient_elements": n_total,
    "ici_size": ICI,
    "pp": PP,
}))
"""


def run_pipeline_bench(microbatches: str) -> dict:
    env = dict(os.environ)
    proc = subprocess.run(
        [sys.executable, "-c", PIPELINE_WORKER, microbatches],
        env=env, capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    if proc.returncode != 0:
        raise RuntimeError(
            f"pipeline bench worker failed:\n{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main_pipeline(out_path: str, microbatches: str = "4,8,16") -> dict:
    r = run_pipeline_bench(microbatches)
    result = {
        "metric": "pipeline_schedules",
        "note": ("bubble_share/ticks are the schedules' static budgets "
                 "(docs/pipeline.md: gpipe = activation stash + "
                 "recompute backward, 1f1b/interleaved = residual-stash "
                 "ring, cost_bwd=2; zb-h1 splits backward into "
                 "input-grad and weight-grad ticks, cost cF+cB/2 per "
                 "pipelined tick + m weight ticks off the critical "
                 "path); parity is vs the single-program autodiff "
                 "reference; dcn bytes count one rank's cross-slice "
                 "leg per reduction. step_ms fields are wall-clock and "
                 "informational only"),
        "bubble": r["bubble"],
        "hierarchical": r["hierarchical"],
        "gradient_elements": r["gradient_elements"],
        "ici_size": r["ici_size"],
        "pp": r["pp"],
        "microbatches": [int(x) for x in microbatches.split(",")],
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(result))
    return result


# --------------------------------------------------------------------------
# Global-autotuner bench (--autotune): cold-start successive-halving
# search over the rebuild knobs (pipeline schedule x microbatch count)
# on a small flagship transformer at pp=4, vs the hand-picked best a
# human would read off BENCH_PIPELINE (1f1b at the deepest microbatch
# sweep point) — writes BENCH_AUTOTUNE.json with the trial ledger and
# the gap-to-best fraction. Deterministic fields: the search space,
# candidate count, rung/budget schedule, trial count, and the
# hand-picked reference config (all independent of measured step time).
# Measured fields: the converged config, step times, the gap, and the
# flight-recorder convergence evidence — wall-clock on a shared CPU, so
# the reproducibility guard (tests/test_autotune_e2e.py) diffs only the
# deterministic block.
# --------------------------------------------------------------------------

AUTOTUNE_WORKER = r"""
import json, os, sys, time
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import optax
from horovod_tpu.autotune import (AutoTuner, default_registry,
                                  enumerate_configs, rungs_for)
from horovod_tpu.models.transformer import TransformerConfig, init_params
from horovod_tpu.observability import flight_recorder as _fr
from horovod_tpu.parallel import create_mesh
from horovod_tpu.parallel.train import (build_pipeline_train_step,
                                        to_pipeline_params)

PP = 4
B = 32          # fixed global batch: micro_batch = B / num_microbatches
S = 16
BASE_BUDGET = int(sys.argv[1]) if len(sys.argv) > 1 else 2
cfg = TransformerConfig(vocab=128, d_model=32, n_heads=4, n_layers=8,
                        d_ff=64, max_seq=S, dtype=jnp.float32,
                        use_flash=False, remat=False)
mesh = create_mesh(devices=jax.devices()[:PP], pp=PP)
optimizer = optax.sgd(1e-2)
base_params = init_params(cfg, jax.random.PRNGKey(0))
tok = np.random.RandomState(3).randint(0, cfg.vocab, size=(B, S))

_cache = {}

def setup(config):
    # One compile per (schedule, m); rungs re-use the cached executable
    # so a survivor's later, longer windows time pure steps.
    key = (config["pipeline_schedule"], config["num_microbatches"])
    if key not in _cache:
        schedule, m = key
        v = 2 if schedule == "interleaved" else 1
        make, shard_params, shard_batch = build_pipeline_train_step(
            cfg, mesh, optimizer, schedule=schedule, num_virtual=v)
        params = to_pipeline_params(cfg, base_params, PP, v)
        opt_state = optimizer.init(params)
        step, _ = make(params, opt_state)
        params = shard_params(params)
        mb = B // m
        tokens = shard_batch(jnp.asarray(tok.reshape(m, mb, S),
                                         jnp.int32))
        targets = shard_batch(jnp.asarray(
            np.roll(tok, -1, axis=1).reshape(m, mb, S), jnp.int32))
        out = step(params, opt_state, tokens, targets)   # compile
        jax.block_until_ready(out[2])
        _cache[key] = (step, params, opt_state, tokens, targets)
    return _cache[key]

def measure_s(config, budget):
    step, params, opt_state, tokens, targets = setup(config)
    times = []
    for _ in range(max(3, int(budget))):
        t0 = time.perf_counter()
        out = step(params, opt_state, tokens, targets)
        jax.block_until_ready(out[2])
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2]

def constraint(c):
    # zb-h1's uniform weight-grad drain needs m >= n stages.
    return (c["pipeline_schedule"] != "zb-h1"
            or c["num_microbatches"] >= PP)

reg = default_registry(include=("pipeline_schedule",
                                "num_microbatches"))
knobs = [reg.get("pipeline_schedule"), reg.get("num_microbatches")]
candidates = enumerate_configs(knobs, constraint=constraint)

tuner = AutoTuner(reg, trial_budget=BASE_BUDGET)
t0 = time.perf_counter()
best, trials = tuner.tune_rebuild(lambda c, b: -measure_s(c, b),
                                  constraint=constraint)
search_s = time.perf_counter() - t0

# The trial ledger's rung sizes depend only on the candidate count and
# eta, never on measured scores — deterministic bench metadata.
sizes, alive = [], len(candidates)
while alive > 1:
    sizes.append(alive)
    alive = max(1, alive // 2)
sizes.append(alive)
budgets = [BASE_BUDGET * 2 ** r for r in range(len(sizes))]

# Re-measure the converged config and the hand-picked reference (what a
# human reads off BENCH_PIPELINE: 1f1b at the deepest sweep point) in
# the SAME process at the final rung's budget, so the gap compares two
# long windows under identical conditions.
HAND_PICKED = {"pipeline_schedule": "1f1b", "num_microbatches": 16}
final_budget = budgets[-1]
best_s = measure_s(best, final_budget)
hand_s = measure_s(HAND_PICKED, final_budget)
gap = (best_s - hand_s) / hand_s

snap = _fr.recorder()._snapshot()
conv = [p for _, kind, p in snap
        if kind == "autotune" and p[0] == "converged"]

print(json.dumps({
    "deterministic": {
        "search_space": {k.name: list(k.domain) for k in knobs},
        "constraint": "zb-h1 requires num_microbatches >= pp",
        "n_candidates": len(candidates),
        "eta": 2,
        "base_budget": BASE_BUDGET,
        "rungs": rungs_for(len(candidates)),
        "trials_per_rung": sizes,
        "budget_per_rung": budgets,
        "n_trials": len(trials),
        "hand_picked": HAND_PICKED,
        "workload": {"pp": PP, "global_batch": B, "seq": S,
                     "vocab": cfg.vocab, "d_model": cfg.d_model,
                     "n_layers": cfg.n_layers, "dtype": "float32"},
    },
    "measured": {
        "converged": best,
        "converged_step_ms": round(best_s * 1e3, 3),
        "hand_picked_step_ms": round(hand_s * 1e3, 3),
        "gap_to_best_frac": round(gap, 4),
        "within_5pct_of_hand_picked": bool(gap <= 0.05),
        "search_s": round(search_s, 3),
        "flight_converged": bool(conv),
        "flight_converged_config": conv[-1][2] if conv else None,
        "trials": [{"config": t.config, "rung": t.rung,
                    "budget": t.budget,
                    "step_ms": round(-t.score * 1e3, 3)}
                   for t in trials],
    },
}))
"""


def run_autotune_bench(base_budget: int = 2) -> dict:
    env = dict(os.environ)
    proc = subprocess.run(
        [sys.executable, "-c", AUTOTUNE_WORKER, str(base_budget)],
        env=env, capture_output=True, text=True, timeout=900,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    if proc.returncode != 0:
        raise RuntimeError(
            f"autotune bench worker failed:\n{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main_autotune(out_path: str, base_budget: int = 2) -> dict:
    r = run_autotune_bench(base_budget)
    result = {
        "metric": "autotune_gap_to_best_frac",
        "value": r["measured"]["gap_to_best_frac"],
        "unit": "frac",
        "note": ("cold-start successive halving over pipeline schedule "
                 "x microbatch count (docs/autotune.md), scored on "
                 "measured step time via build_pipeline_train_step "
                 "rebuilds; gap compares the converged config vs the "
                 "hand-picked BENCH_PIPELINE best, both re-measured at "
                 "the final rung's budget in one process. Only the "
                 "'deterministic' block is stable across runs — "
                 "everything under 'measured' is wall-clock"),
        "deterministic": r["deterministic"],
        "measured": r["measured"],
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps({"metric": result["metric"],
                      "value": result["value"],
                      "converged": r["measured"]["converged"],
                      "n_trials": r["deterministic"]["n_trials"]}))
    return result


# --------------------------------------------------------------------------
# Input-pipeline bench (--data): prefetch-to-device on/off step-time A/B on
# a deliberately slow synthetic source, plus the exactly-once resume count
# across a 2 -> 1 -> 2 world-size path — writes BENCH_DATA.json
# (docs/data.md, docs/benchmarks.md). Seeded-deterministic fields: sample-id
# checksums and every count; wall-clock fields are excluded from the
# reproducibility compare (tests/test_data_e2e.py).
# --------------------------------------------------------------------------

DATA_STEPS = int(os.environ.get("HVD_BENCH_DATA_STEPS", 40))
_DATA_BATCH = 32
_DATA_N = 4096
_DATA_SEED = 13
_DATA_DELAY_S = 0.004     # per-batch source cost the prefetch must hide


def _ids_checksum(ids) -> int:
    import zlib

    import numpy as _np
    return zlib.crc32(_np.asarray(sorted(int(i) for i in ids),
                                  dtype="<i8").tobytes())


def run_data_arm(prefetch: bool, steps: int) -> dict:
    """One arm: `steps` training steps drawing real batches through the
    loader, source throttled by _DATA_DELAY_S per batch. Returns wall
    stats + the delivered-id checksum (deterministic)."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as _np

    from horovod_tpu import data

    def slow(arrays):
        _time.sleep(_DATA_DELAY_S)
        return arrays

    src = data.synthetic("image", n=_DATA_N, image_size=16,
                         num_classes=10, seed=_DATA_SEED)
    loader = data.build_loader(src, batch_size=_DATA_BATCH, rank=0,
                               world_size=1, seed=_DATA_SEED,
                               transform=slow)

    # A two-layer MLP sized so the step's compute is comparable to the
    # source delay — the regime where overlap actually pays (a trivial
    # step would leave both arms producer-bound and flatten the A/B).
    hidden = 1024

    @jax.jit
    def step(w, x, y):
        onehot = jax.nn.one_hot(y, 10)

        def loss(ws):
            h = jax.nn.relu(x.reshape(x.shape[0], -1) @ ws["w1"])
            return jnp.mean((h @ ws["w2"] - onehot) ** 2)

        g = jax.grad(loss)(w)
        return {k: w[k] - 0.01 * g[k] for k in w}

    import numpy as _rngnp
    rng = _rngnp.random.RandomState(_DATA_SEED)
    w = {"w1": jnp.asarray(rng.randn(16 * 16 * 3, hidden).astype(
            "float32") * 0.02),
         "w2": jnp.asarray(rng.randn(hidden, 10).astype("float32")
                           * 0.02)}
    it = data.prefetch_to_device(loader, depth=2) if prefetch \
        else iter(loader)
    ids = []
    # Warmup: one staged batch to compile the step outside the window.
    b0 = next(it)
    b0 = b0 if prefetch else data.stage(b0)
    ids.extend(b0.ids.tolist())
    w = step(w, b0.data[0], b0.data[1])
    jax.block_until_ready(w)
    t0 = _time.perf_counter()
    for _ in range(steps):
        b = next(it)
        if not prefetch:
            b = data.stage(b)
        ids.extend(b.ids.tolist())
        w = step(w, b.data[0], b.data[1])
        jax.block_until_ready(w)
    wall = _time.perf_counter() - t0
    if prefetch:
        it.close()
    return {"ms_per_step": round(wall / steps * 1e3, 3),
            "samples": len(ids),
            "ids_checksum": _ids_checksum(ids),
            "weights_sum": float(_np.asarray(jnp.sum(w["w2"])))}


def run_data_exactly_once() -> dict:
    """Exactly-once across a world-size change, in-process: 2 ranks
    consume and commit, 1 rank resumes and commits, 2 ranks finish the
    epoch — the multiset must be one clean epoch (docs/data.md)."""
    from horovod_tpu import data

    src = data.synthetic("image", n=_DATA_N, image_size=8,
                         num_classes=10, seed=_DATA_SEED)
    ds = data.ShardedDataset(src, batch_size=_DATA_BATCH,
                             seed=_DATA_SEED)
    consumed = []
    l2 = [data.build_loader(src, batch_size=_DATA_BATCH, rank=r,
                            world_size=2, seed=_DATA_SEED)
          for r in range(2)]
    for _ in range(20):
        for ld in l2:
            consumed.extend(next(ld).ids.tolist())
    cur = l2[0].commit_cursor()
    l1 = data.build_loader(src, batch_size=_DATA_BATCH, rank=0,
                           world_size=1, seed=_DATA_SEED).restore(cur)
    for _ in range(15):
        consumed.extend(next(l1).ids.tolist())
    cur = l1.commit_cursor()
    l2b = [data.build_loader(src, batch_size=_DATA_BATCH, rank=r,
                             world_size=2, seed=_DATA_SEED, epochs=1
                             ).restore(cur) for r in range(2)]
    for ld in l2b:
        for b in ld:
            consumed.extend(b.ids.tolist())
    clean = sorted(ds.epoch_ids(0).tolist())
    got = sorted(consumed)
    dup = len(consumed) - len(set(consumed))
    gaps = len(set(clean) - set(consumed))
    return {"epoch_samples": ds.usable,
            "consumed": len(consumed),
            "duplicates": dup,
            "gaps": gaps,
            "world_path": [2, 1, 2],
            "ids_match_clean_epoch": got == clean,
            "ids_checksum": _ids_checksum(consumed),
            "resume_skips": (20 * 2 + 15) * _DATA_BATCH}


def main_data(steps: int, out_path: str) -> dict:
    off = run_data_arm(prefetch=False, steps=steps)
    on = run_data_arm(prefetch=True, steps=steps)
    exactly = run_data_exactly_once()
    out = {
        "metric": "data_prefetch_step_ms_ratio",
        "value": round(on["ms_per_step"] / off["ms_per_step"], 3),
        "unit": "prefetch_on/prefetch_off (lower is better)",
        "steps": steps,
        "batch": _DATA_BATCH,
        "source_delay_ms": _DATA_DELAY_S * 1e3,
        "prefetch": {"off": off, "on": on},
        "exactly_once": exactly,
    }
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({k: out[k] for k in
                      ("metric", "value", "unit")} |
                     {"exactly_once_ok":
                      exactly["ids_match_clean_epoch"]}))
    return out


def main():
    sweep = {}
    best = 0.0
    for size in SIZES:
        row = {
            "fused_native": run_config(size, TENSORS_PER_BURST,
                                       native=True, fusion=True),
            "fused_python": run_config(size, TENSORS_PER_BURST,
                                       native=False, fusion=True),
            "unfused_native": run_config(size, TENSORS_PER_BURST,
                                         native=True, fusion=False),
            "single_native": run_config(size, 1, native=True, fusion=True),
        }
        sweep[f"{size}B"] = {k: round(v, 3) for k, v in row.items()}
        best = max(best, row["fused_native"])
    # Overlap A/B (interleaved rounds, medians): hook-style async
    # submitter on one device, producer fence forced on vs off. Guarded:
    # a wedged/failed A/B must not discard the primary sweep above.
    overlap_ab = None
    try:
        fenced_ms, unfenced_ms = [], []
        for _ in range(3):
            fenced_ms.append(run_overlap(fence=True)["ms_per_chain"])
            unfenced_ms.append(run_overlap(fence=False)["ms_per_chain"])
        med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
        overlap_ab = {
            "fenced_ms_per_chain": round(med(fenced_ms), 3),
            "unfenced_ms_per_chain": round(med(unfenced_ms), 3),
            "fenced_over_unfenced": round(
                med(fenced_ms) / med(unfenced_ms), 3),
        }
    except Exception as e:  # pragma: no cover - keep the primary metric
        overlap_ab = {"error": str(e)[:200]}
    print(json.dumps({
        "metric": "engine_allreduce_bytes_per_us",
        "value": round(best, 3),
        "unit": "bytes/us",
        "sweep": sweep,
        "overlap_ab": overlap_ab,
    }))


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--compression", action="store_true",
                    help="run the wire-compression bench and write "
                         "BENCH_COMPRESSION.json instead of the "
                         "throughput sweep")
    ap.add_argument("--checkpoint", action="store_true",
                    help="run the rank-0-pickle vs sharded-async "
                         "checkpoint bench and write "
                         "BENCH_CHECKPOINT.json")
    ap.add_argument("--trace", action="store_true",
                    help="run the all-ranks-tracing overhead A/B and "
                         "write BENCH_TRACE.json")
    ap.add_argument("--straggler", action="store_true",
                    help="run the injected-slow-rank A/B (no adaptation "
                         "vs adaptation + eviction) and write "
                         "BENCH_STRAGGLER.json")
    ap.add_argument("--recorder", action="store_true",
                    help="run the flight-recorder overhead A/B "
                         "(always-on ring buffer vs disabled) and "
                         "write BENCH_RECORDER.json")
    ap.add_argument("--pipeline", action="store_true",
                    help="run the pipeline-schedule bench (bubble share "
                         "vs microbatch count for gpipe/1f1b/"
                         "interleaved/zb-h1 + hierarchical vs flat "
                         "cross-slice reduction) and write "
                         "BENCH_PIPELINE.json")
    ap.add_argument("--pipeline-microbatches", default="4,8,16",
                    help="comma-separated microbatch counts for "
                         "--pipeline")
    ap.add_argument("--autotune", action="store_true",
                    help="run the global-autotuner bench (cold-start "
                         "successive halving over pipeline schedule x "
                         "microbatch count vs the hand-picked "
                         "BENCH_PIPELINE best) and write "
                         "BENCH_AUTOTUNE.json")
    ap.add_argument("--autotune-budget", type=int, default=2,
                    help="rung-0 measurement budget (timed steps per "
                         "candidate) for --autotune")
    ap.add_argument("--data", action="store_true",
                    help="run the input-pipeline bench (prefetch on/off "
                         "step-time A/B on a throttled source + "
                         "exactly-once resume counts) and write "
                         "BENCH_DATA.json")
    ap.add_argument("--data-steps", type=int, default=DATA_STEPS,
                    help="training steps per arm for --data")
    ap.add_argument("--health", action="store_true",
                    help="run the history-sampler + anomaly-detector "
                         "overhead A/B (sampler at 100 ms cadence vs "
                         "disabled) plus the seeded detector smoke, "
                         "and write BENCH_HEALTH.json")
    ap.add_argument("--health-rounds", type=int, default=HEALTH_ROUNDS,
                    help="alternating on/off rounds for --health")
    ap.add_argument("--numerics", action="store_true",
                    help="run the numerics-plane overhead A/B "
                         "(nonfinite payload sentinel enabled vs "
                         "disabled) plus the seeded fingerprint/"
                         "detector smoke, and write BENCH_NUMERICS.json")
    ap.add_argument("--numerics-rounds", type=int,
                    default=NUMERICS_ROUNDS,
                    help="alternating on/off rounds for --numerics")
    ap.add_argument("--recorder-rounds", type=int,
                    default=RECORDER_ROUNDS,
                    help="alternating on/off rounds for --recorder")
    ap.add_argument("--straggler-steps", type=int, default=STRAGGLER_STEPS,
                    help="training steps per arm for --straggler")
    ap.add_argument("--trace-rounds", type=int, default=TRACE_ROUNDS,
                    help="alternating on/off rounds for --trace")
    ap.add_argument("--steps", type=int, default=50,
                    help="convergence-run steps for --compression")
    ap.add_argument("--commits", type=int, default=5,
                    help="checkpoint commits per mode for --checkpoint")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    here = os.path.dirname(os.path.abspath(__file__))
    if args.compression:
        main_compression(args.steps, args.out or os.path.join(
            here, "BENCH_COMPRESSION.json"))
    elif args.checkpoint:
        main_checkpoint(args.commits, args.out or os.path.join(
            here, "BENCH_CHECKPOINT.json"))
    elif args.trace:
        main_trace(args.out or os.path.join(here, "BENCH_TRACE.json"),
                   rounds=args.trace_rounds)
    elif args.straggler:
        main_straggler(args.out or os.path.join(here,
                                                "BENCH_STRAGGLER.json"),
                       steps=args.straggler_steps)
    elif args.recorder:
        main_recorder(args.out or os.path.join(here,
                                               "BENCH_RECORDER.json"),
                      rounds=args.recorder_rounds)
    elif args.health:
        main_health(args.out or os.path.join(here, "BENCH_HEALTH.json"),
                    rounds=args.health_rounds)
    elif args.numerics:
        main_numerics(args.out or os.path.join(here,
                                               "BENCH_NUMERICS.json"),
                      rounds=args.numerics_rounds)
    elif args.pipeline:
        main_pipeline(args.out or os.path.join(here,
                                               "BENCH_PIPELINE.json"),
                      microbatches=args.pipeline_microbatches)
    elif args.autotune:
        main_autotune(args.out or os.path.join(here,
                                               "BENCH_AUTOTUNE.json"),
                      base_budget=args.autotune_budget)
    elif args.data:
        main_data(args.data_steps, args.out or os.path.join(
            here, "BENCH_DATA.json"))
    else:
        main()
