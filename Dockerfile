# horovod_tpu container — the reference's Dockerfile role (a ready-to-run
# training image) for TPU VMs. Build args select the JAX flavor:
#   docker build --build-arg JAX_PACKAGE="jax[tpu]" .     # TPU VM
#   docker build --build-arg JAX_PACKAGE="jax" .          # CPU (CI/tests)
FROM python:3.12-slim

ARG JAX_PACKAGE="jax[tpu]"
ARG EXTRAS="all"

# g++ builds the native control-plane core at install time; ssh is the
# launcher's remote-spawn transport (the rsh-agent role).
RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ openssh-client && \
    rm -rf /var/lib/apt/lists/*

WORKDIR /opt/horovod_tpu

# Dependency layers first: a source edit must not invalidate the
# multi-gigabyte framework installs (.dockerignore keeps .git/tests out).
RUN pip install --no-cache-dir "${JAX_PACKAGE}" numpy flax optax \
        cloudpickle
RUN if [ "${EXTRAS}" = "all" ]; then \
        pip install --no-cache-dir torch "keras>=3" tensorflow; fi

COPY pyproject.toml setup.py README.md ./
COPY horovod_tpu ./horovod_tpu
# Full resolve (no --no-deps): arbitrary EXTRAS values stay correct; the
# pre-layers above just keep the big downloads cached across source edits.
RUN pip install --no-cache-dir ".[${EXTRAS}]"

# Smoke: import, init on whatever devices exist, one collective.
RUN JAX_PLATFORMS=cpu python -c "\
import horovod_tpu as hvd, jax.numpy as jnp; \
hvd.init(); \
assert float(hvd.allreduce(jnp.ones(()), average=False)) == hvd.size()"

ENTRYPOINT ["python", "-m", "horovod_tpu.runner"]
