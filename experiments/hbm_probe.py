"""Is HBM bandwidth really ~136 GB/s here, or is there fixed per-iter
overhead? Time y=x+1 across tensor sizes and loop lengths."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

C = 256


def loop(k):
    @jax.jit
    def run(x, g):
        def body(_, carry):
            x, g = carry
            return x + jnp.bfloat16(1.0), x
        x, g = jax.lax.fori_loop(0, k, body, (x, g))
        return x
    return run


def timed(fn, args, k, reps=3):
    out = fn(*args)
    _ = float(jnp.sum(out[:8, :8].astype(jnp.float32)))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        _ = float(jnp.sum(out[:8, :8].astype(jnp.float32)))
        ts.append((time.perf_counter() - t0) / k)
    return float(np.median(ts))


def main():
    print("device:", jax.devices()[0].device_kind, flush=True)
    key = jax.random.PRNGKey(0)
    for m2 in (100352, 200704, 401408, 802816, 1605632):
        x = jax.random.normal(key, (m2, C), jnp.bfloat16)
        g = x + 0
        mb = m2 * C * 2 / 1e6
        for k in (20, 100):
            t = timed(loop(k), (x, g), k)
            gbps = 2 * mb / 1e3 / t
            print(f"size {mb:6.0f} MB k={k:4d}: {t*1e3:7.3f} ms/iter "
                  f"= {gbps:6.0f} GB/s", flush=True)


if __name__ == "__main__":
    main()
