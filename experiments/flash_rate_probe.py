"""Measure the flash kernel's standalone sustained FLOP rate (fwd and
fwd+bwd) on the real chip, to test the round-4 decomposition's ~33%
inferred flash rate and locate where the time goes.

Timing follows bench_lm.py: K chained steps inside one jitted fori_loop
(amortizes the ~90-100 ms per-call tunnel overhead) and host readback of
a scalar for sync (block_until_ready is unreliable through the tunnel —
the first version of this probe "measured" 47,000% MFU without it).

Useful model FLOPs (causal): fwd = 2 matmuls * 2*B*H*S^2*D, halved by
causality; bwd = 2.5x fwd (5 useful matmuls vs fwd's 2).
"""
import time, sys
from functools import partial

import jax, jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from horovod_tpu.ops.flash_attention import flash_attention

PEAK = 197e12  # v5e bf16
K = 100


_tunnel = None


def tunnel_overhead():
    """Median wall time of an (almost) empty chained call + readback —
    the per-call axon tunnel cost to subtract from every measurement."""
    global _tunnel
    if _tunnel is None:
        x = jnp.zeros((8, 128), jnp.float32)

        @jax.jit
        def empty(c):
            return jax.lax.fori_loop(0, K, lambda _, y: y + 1.0, c)

        for _ in range(3):
            x = empty(x)
        float(jnp.sum(x))
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            x = empty(x)
            float(jnp.sum(x))
            ts.append(time.perf_counter() - t0)
        _tunnel = float(np.median(ts))
        print(f"tunnel overhead per call: {_tunnel*1e3:.1f} ms")
    return _tunnel


def timed(fn, carry, flops_per_step):
    for _ in range(3):
        carry = fn(carry)
    float(jnp.sum(carry[0][0, 0, 0].astype(jnp.float32)))
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        carry = fn(carry)
        float(jnp.sum(carry[0][0, 0, 0].astype(jnp.float32)))
        dt = time.perf_counter() - t0 - tunnel_overhead()
        rates.append(flops_per_step * K / dt)
    return float(np.median(rates))


def main():
    B, H, D = 8, 16, 128
    for S in (2048, 8192):
        key = jax.random.PRNGKey(0)
        q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                     (B, S, H, D), jnp.bfloat16)
                   for i in range(3))
        f_fwd = 4 * B * H * S * S * D / 2
        f_bwd = 2.5 * f_fwd

        @jax.jit
        def fwd_k(carry):
            def body(_, c):
                q, k, v = c
                o = flash_attention(q, k, v, True)
                return (o, k, v)
            return jax.lax.fori_loop(0, K, body, carry)

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, True)
                           .astype(jnp.float32))

        @jax.jit
        def fb_k(carry):
            def body(_, c):
                q, k, v = c
                dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
                eps = jnp.bfloat16(1e-4)
                return (q + eps * dq, k + eps * dk, v + eps * dv)
            return jax.lax.fori_loop(0, K, body, carry)

        r_f = timed(fwd_k, (q, k, v), f_fwd)
        r_fb = timed(fb_k, (q, k, v), f_fwd + f_bwd)
        t_f = f_fwd / r_f
        t_fb = (f_fwd + f_bwd) / r_fb
        t_b = t_fb - t_f
        print(f"S={S}: fwd {t_f*1e3:.2f} ms ({r_f/PEAK*100:.1f}% MFU), "
              f"fwd+bwd {t_fb*1e3:.2f} ms ({r_fb/PEAK*100:.1f}% MFU), "
              f"bwd-only {t_b*1e3:.2f} ms ({f_bwd/t_b/PEAK*100:.1f}% MFU)")


if __name__ == "__main__":
    main()
