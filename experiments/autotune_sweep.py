"""Autotune pay-rent sweep (VERDICT r4 next #3).

Round 4 measured tuned/default = 0.951 on the np=2 real training
workload — the tuner wasn't earning its ~1.1k LoC. Before retiring it,
sweep the regimes where the knobs PLAUSIBLY matter: multiprocess eager
with many small tensors (per-group control-plane round trips dominate;
cycle time and fusion threshold set the batching), np=2/4, shm plane
on. Grid over (cycle_ms, threshold_MB) with interleaved defaults, then
an HOROVOD_AUTOTUNE=1 arm on the same workload: if the grid shows a
>=1.1x pocket the tuner must find it; a flat grid is the documented
negative (the knobs themselves have no headroom on this plane, so no
tuner could).

Run: python experiments/autotune_sweep.py > experiments/autotune_sweep.log
(one JSON line on stdout; progress markers on stderr)
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

NP = int(os.environ.get("SWEEP_NP", 4))
STEPS = int(os.environ.get("SWEEP_STEPS", 6))
ROUNDS = int(os.environ.get("SWEEP_ROUNDS", 2))

# Many-small-tensors step: 120 tensors, 4 KB - 1 MB (the torch-hook /
# fine-tune-head regime the 64 MiB threshold was NOT chosen for; total
# ~12 MB so cycle batching, not bandwidth, decides group count).
N_SMALL, SMALL_MAX = 120, 1 << 18


def _small_tensor_worker():
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    rng = np.random.RandomState(0)
    sizes = rng.randint(1 << 10, SMALL_MAX, size=N_SMALL)
    tensors = [rng.randn(s).astype(np.float32) for s in sizes]

    def step(tag):
        hs = [hvd.allreduce_async(t, average=True, name=f"{tag}.{i}")
              for i, t in enumerate(tensors)]
        for h in hs:
            h.wait()

    for w in range(2):
        step(f"w{w}")
    t0 = time.perf_counter()
    for i in range(STEPS):
        step(f"s{i}")
    return STEPS / (time.perf_counter() - t0)


def run_job(extra_env):
    from horovod_tpu.runner.api import run as hvd_run
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    }
    env.update(extra_env)
    out = hvd_run(_small_tensor_worker, np=NP, extra_env=env,
                  start_timeout=600)
    return float(np.median(out))


def main():
    grid = []
    for cyc in ("1", "5", "20"):
        for thr_mb in ("8", "64"):
            grid.append((cyc, thr_mb))
    results = {}
    defaults = []
    for rnd in range(ROUNDS):
        defaults.append(run_job({}))
        for cyc, thr in grid:
            key = f"cycle{cyc}ms_thr{thr}mb"
            results.setdefault(key, []).append(run_job({
                "HOROVOD_TPU_CYCLE_TIME": cyc,
                "HOROVOD_TPU_FUSION_THRESHOLD": str(int(thr) << 20),
            }))
        print(f"# round {rnd} done", file=sys.stderr, flush=True)
    tuned = [run_job({"HOROVOD_AUTOTUNE": "1"}) for _ in range(ROUNDS)]

    base = float(np.median(defaults))
    table = {k: round(float(np.median(v)) / base, 3)
             for k, v in sorted(results.items())}
    best_key = max(table, key=table.get)
    print(json.dumps({
        "metric": "autotune_knob_headroom",
        "value": table[best_key],
        "unit": "best-grid/default step rate "
                f"(np={NP}, {N_SMALL} small tensors)",
        "best": best_key,
        "grid_vs_default": table,
        "autotune_vs_default": round(float(np.median(tuned)) / base, 3),
        "default_steps_per_s": round(base, 3),
        "rounds": ROUNDS,
    }))


if __name__ == "__main__":
    main()
