"""Ordered-launch prototype A/B + hazard record (VERDICT r4 next #4).

Three measurements on the 8-device CPU mesh:

1. HAZARD (the reason the fence exists): an unrelated mesh-wide jit
   stream concurrent with eager collectives — with the fence OFF and
   every Python-level launch serialized under one lock, XLA CPU still
   aborts at the collective rendezvous (7-of-8). PJRT's cross-device
   fan-out happens on its own threadpool AFTER the Python execute call
   returns, so no host-side ordering (token-threading included — a
   data-dependency token cannot reorder FIFO device queues) can close
   the inversion window on this backend. Run with MODE=hazard to
   reproduce (the process ABORTS — that is the result).

2. A/B (async-submitter / producer-feeding workload): mesh-wide jit
   producers feeding eager async allreduces, fence (default) vs
   ordered-launch (HOROVOD_TPU_ORDERED_LAUNCH=1 + launch_lock around
   producers). Interleaved rounds, median ratio.

3. REGRESSION: the 4-of-8 producer-feeding scenario must complete with
   ordered-launch on (it does — also pinned in
   tests/test_engine_overlap.py::test_ordered_launch_*).

Conclusion recorded in docs/concepts.md + utils/env.py: the fence stays
the default on multi-device processes; ordered-launch is an opt-in
prototype for platforms whose per-device enqueue is host-call-ordered
(real TPU PJRT — unverifiable on this 1-chip box).
"""
import json
import os
import subprocess
import sys
import time

MODE = os.environ.get("MODE", "ab")

WORKER = r"""
import os, sys, time, threading
import numpy as np
sys.path.insert(0, ".")
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
mode = sys.argv[1]          # "fence" | "ordered"
if mode == "ordered":
    os.environ["HOROVOD_TPU_ORDERED_LAUNCH"] = "1"
    os.environ["HOROVOD_TPU_PRODUCER_FENCE"] = "0"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
import horovod_tpu as hvd
from horovod_tpu.ops import launch_lock
hvd.init()
mesh = hvd.mesh()

@jax.jit
def producer(x, i):
    for _ in range(6):
        x = jnp.tanh(x) @ jnp.eye(x.shape[-1], dtype=x.dtype)
    return x * 0 + i

x = jax.device_put(jnp.ones((512, 512), jnp.float32),
                   NamedSharding(mesh, P()))
ITERS = int(os.environ.get("AB_ITERS", 25))
WARM = 5

def step(r):
    if mode == "ordered":
        with launch_lock():
            ys = [producer(x, float(i)) for i in range(8)]
    else:
        ys = [producer(x, float(i)) for i in range(8)]
    hs = [hvd.allreduce_async(y, name=f"ol.{r}.{i}", average=False)
          for i, y in enumerate(ys)]
    for i, h in enumerate(hs):
        np.testing.assert_allclose(
            np.asarray(h.wait(timeout=60.0))[0, 0], float(i) * hvd.size())

for w in range(WARM):
    step(f"w{w}")
t0 = time.perf_counter()
for r in range(ITERS):
    step(r)
print(ITERS / (time.perf_counter() - t0))
"""


def run_arm(mode: str) -> float:
    out = subprocess.run([sys.executable, "-c", WORKER, mode],
                         capture_output=True, text=True, timeout=900,
                         cwd=os.path.dirname(os.path.dirname(
                             os.path.abspath(__file__))))
    if out.returncode != 0:
        raise RuntimeError(f"{mode} arm failed:\n{out.stderr[-2000:]}")
    return float(out.stdout.strip().splitlines()[-1])


HAZARD = r"""
import os, sys, time, threading
import numpy as np
sys.path.insert(0, ".")
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["HOROVOD_TPU_ORDERED_LAUNCH"] = "1"
os.environ["HOROVOD_TPU_PRODUCER_FENCE"] = "0"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
import horovod_tpu as hvd
from horovod_tpu.ops import launch_lock
hvd.init()
mesh = hvd.mesh()

@jax.jit
def unrelated(x):
    for _ in range(8):
        x = jnp.tanh(x) @ jnp.eye(x.shape[-1], dtype=x.dtype)
    return x

stop = [False]
def background():
    y = jax.device_put(jnp.ones((64, 64), jnp.float32),
                       NamedSharding(mesh, P()))
    while not stop[0]:
        with launch_lock():   # even fully locked: still aborts
            y = unrelated(y)
threading.Thread(target=background, daemon=True).start()
for r in range(40):
    hs = [hvd.allreduce_async(np.full(4096, float(i), np.float32),
                              name=f"hz.{r}.{i}", average=False)
          for i in range(4)]
    for h in hs:
        h.wait(timeout=60.0)
stop[0] = True
print("NO-ABORT (hazard did not reproduce this run)")
"""


def main():
    import numpy as np
    if MODE == "hazard":
        out = subprocess.run([sys.executable, "-c", HAZARD],
                             capture_output=True, text=True, timeout=900,
                             cwd=os.path.dirname(os.path.dirname(
                                 os.path.abspath(__file__))))
        print(json.dumps({
            "metric": "ordered_launch_hazard_repro",
            "aborted": out.returncode != 0,
            "returncode": out.returncode,
            "tail": out.stderr[-400:],
        }))
        return
    rounds = int(os.environ.get("AB_ROUNDS", 3))
    fence_r, ordered_r, ratios = [], [], []
    for _ in range(rounds):
        f = run_arm("fence")
        o = run_arm("ordered")
        fence_r.append(f)
        ordered_r.append(o)
        ratios.append(o / f)
    print(json.dumps({
        "metric": "ordered_launch_vs_fence",
        "value": round(float(np.median(ratios)), 3),
        "unit": "ordered/fence step-rate ratio (producer-feeding "
                "workload, 8-dev CPU mesh)",
        "ordered_steps_per_s": round(float(np.median(ordered_r)), 3),
        "fence_steps_per_s": round(float(np.median(fence_r)), 3),
        "rounds": [round(r, 3) for r in ratios],
        "hazard_note": "unrelated-stream scenario still aborts at XLA "
                       "rendezvous under full Python-side launch "
                       "locking (MODE=hazard); fence remains default",
    }))


if __name__ == "__main__":
    main()
