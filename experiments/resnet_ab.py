"""Full ResNet-50 train-step A/B on the chip: flax BN vs fused custom-VJP
BN ('jnp' = XLA-fused passes, 'pallas' = Mosaic kernels). In-process
interleaved rounds; k steps per call amortize the ~100 ms per-call
tunnel overhead."""
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, ".")
from horovod_tpu.models.resnet import ResNet  # noqa: E402

import os
BATCH = 256
K = int(os.environ.get("AB_K", 10))
REPS = int(os.environ.get("AB_REPS", 3))


def build(bn_impl):
    model = ResNet(stage_sizes=[3, 4, 6, 3], num_classes=1000,
                   bn_impl=bn_impl)
    opt = optax.sgd(0.01, momentum=0.9)

    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def train_k(params, batch_stats, opt_state, images, labels):
        def body(_, carry):
            params, batch_stats, opt_state = carry

            def loss_fn(p):
                logits, new_state = model.apply(
                    {"params": p, "batch_stats": batch_stats}, images,
                    train=True, mutable=["batch_stats"])
                loss = optax.softmax_cross_entropy_with_integer_labels(
                    logits, labels).mean()
                return loss, new_state["batch_stats"]

            (_, new_bs), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, new_opt = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_bs, new_opt

        return jax.lax.fori_loop(0, K, body,
                                 (params, batch_stats, opt_state))

    return model, opt, train_k


def main():
    impls = sys.argv[1].split(",") if len(sys.argv) > 1 else [
        "flax", "jnp", "pallas"]
    print("device:", jax.devices()[0].device_kind, flush=True)
    rng = jax.random.PRNGKey(0)
    images = jax.random.normal(rng, (BATCH, 224, 224, 3), jnp.float32)
    labels = jax.random.randint(rng, (BATCH,), 0, 1000)

    states = {}
    for impl in impls:
        model, opt, train_k = build(impl)
        variables = model.init(rng, images[:2], train=True)
        params, bs = variables["params"], variables["batch_stats"]
        opt_state = opt.init(params)
        states[impl] = [train_k, params, bs, opt_state]
        print(f"built {impl}", flush=True)

    def run(impl):
        st = states[impl]
        train_k, params, bs, opt_state = st
        params, bs, opt_state = train_k(params, bs, opt_state, images,
                                        labels)
        st[1], st[2], st[3] = params, bs, opt_state
        return float(jnp.sum(jax.tree_util.tree_leaves(params)[0]))

    for impl in impls:  # warmup/compile, 2 calls for jit fixpoint
        run(impl)
        run(impl)
        print(f"warm {impl}", flush=True)

    results = {}
    for rnd in range(3):
        for impl in impls:
            t0 = time.perf_counter()
            for _ in range(REPS):
                run(impl)
            dt = (time.perf_counter() - t0) / (REPS * K)
            results.setdefault(impl, []).append(dt)
            print(f"[{rnd}] {impl}: {dt*1e3:.2f} ms/step "
                  f"= {BATCH/dt:.0f} img/s", flush=True)
    print("--- medians ---")
    for impl, ts in results.items():
        t = float(np.median(ts))
        print(f"{impl}: {t*1e3:.2f} ms/step = {BATCH/t:.0f} img/s")


if __name__ == "__main__":
    main()
