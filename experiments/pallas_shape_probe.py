"""Why is a trivial Pallas copy 2x slower than XLA's y=x+1? Sweep block
geometry (lane width x sublane count) at constant total bytes."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

K = 100
TOTAL = 802816 * 256  # elements (411 MB bf16)


def loop(step):
    @jax.jit
    def run(x, g):
        def body(_, carry):
            x, g = carry
            return step(x), x
        x, g = jax.lax.fori_loop(0, K, body, (x, g))
        return x
    return loop_ret(run)


def loop_ret(run):
    return run


def timed(fn, args, reps=3):
    out = fn(*args)
    _ = float(jnp.sum(out[:8, :8].astype(jnp.float32)))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        _ = float(jnp.sum(out[:8, :8].astype(jnp.float32)))
        ts.append((time.perf_counter() - t0) / K)
    return float(np.median(ts))


def copy_kernel(x_ref, y_ref):
    y_ref[:] = x_ref[:]


def make_copy(c2, bm):
    m2 = TOTAL // c2
    f = pl.pallas_call(
        copy_kernel, grid=(m2 // bm,),
        in_specs=[pl.BlockSpec((bm, c2), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bm, c2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m2, c2), jnp.bfloat16))
    return f, m2


def main():
    print("device:", jax.devices()[0].device_kind, flush=True)
    key = jax.random.PRNGKey(0)
    base = TOTAL * 2 * 2 / 1e9 / 0.819  # ms at 819 GB/s (R+W)
    print(f"R+W at 819 GB/s = {base:.2f} ms", flush=True)

    def xla_add(x):
        return x + jnp.bfloat16(1.0)

    cases = []
    for c2, bm in ((256, 512), (256, 1024), (256, 4096),
                   (2048, 128), (2048, 512), (2048, 1024),
                   (8192, 128), (8192, 256), (512, 2048)):
        if (TOTAL // c2) % bm == 0:
            cases.append((c2, bm))

    x0 = jax.random.normal(key, (802816, 256), jnp.bfloat16)
    progs = {"xla y=x+1": (loop(xla_add), x0)}
    for c2, bm in cases:
        f, m2 = make_copy(c2, bm)
        xs = x0.reshape(m2, c2)
        progs[f"pallas copy c2={c2} bm={bm} ({bm*c2*2//1024} KB)"] = (
            loop(f), xs)

    for rnd in range(2):
        for name, (prog, xin) in progs.items():
            t = timed(prog, (xin, xin))
            gbps = TOTAL * 2 * 2 / 1e9 / t
            print(f"[{rnd}] {name}: {t*1e3:.2f} ms = {gbps:.0f} GB/s",
                  flush=True)


if __name__ == "__main__":
    main()
