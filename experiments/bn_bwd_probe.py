"""Probe: what does BN(+relu) fwd+bwd actually cost on the chip, and can
a fused backward beat XLA's fusion? (VERDICT r3 item 1 — measure before
building.)

Method: k=20 chained iterations inside one jitted lax.fori_loop (per-call
dispatch through the axon tunnel costs ~12 ms — measured — so per-call
timing is meaningless); the loop carry feeds each iteration's dx back in
as the next x so XLA cannot CSE the iterations. In-process interleaved
A/B per tpu-bench-pitfalls.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

N, H, W, C = 256, 56, 56, 256
M = N * H * W
EPS = 1e-5
K = 100


def bn_relu_ref(x, gamma, beta):
    """Plain jnp train-mode BN + relu, flax numerics (fp32 stats)."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=(0, 1, 2))
    var = jnp.mean(jnp.square(xf), axis=(0, 1, 2)) - jnp.square(mean)
    rstd = jax.lax.rsqrt(var + EPS)
    y = (xf - mean) * (rstd * gamma) + beta
    return jax.nn.relu(y).astype(x.dtype)


@jax.custom_vjp
def bn_relu_manual(x, gamma, beta):
    return bn_relu_ref(x, gamma, beta)


def _fwd(x, gamma, beta):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=(0, 1, 2))
    var = jnp.mean(jnp.square(xf), axis=(0, 1, 2)) - jnp.square(mean)
    rstd = jax.lax.rsqrt(var + EPS)
    y = (xf - mean) * (rstd * gamma) + beta
    return jax.nn.relu(y).astype(x.dtype), (x, mean, rstd, gamma, beta)


def _bwd(res, da):
    x, mean, rstd, gamma, beta = res
    xf = x.astype(jnp.float32)
    daf = da.astype(jnp.float32)
    xhat = (xf - mean) * rstd
    mask = (xhat * gamma + beta) > 0  # recompute pre-relu sign from x
    dy = jnp.where(mask, daf, 0.0)
    s1 = jnp.sum(dy, axis=(0, 1, 2))
    s2 = jnp.sum(dy * xhat, axis=(0, 1, 2))
    m = float(M)
    dx = (gamma * rstd) * (dy - s1 / m - xhat * (s2 / m))
    return dx.astype(x.dtype), s2, s1


bn_relu_manual.defvjp(_fwd, _bwd)


def loop_program(step):
    """jit(fori_loop(k, step)) with an (x, g) carry chained through dx."""

    @jax.jit
    def run(x, g, gamma, beta):
        def body(_, carry):
            x, g = carry
            dx = step(x, g, gamma, beta)
            # chain: next x depends on this dx; swap roles to vary data
            return dx, x

        x, g = jax.lax.fori_loop(0, K, body, (x, g))
        return x

    return run


def timed(fn, args, reps=5):
    out = fn(*args)
    _ = float(jnp.sum(out))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        _ = float(jnp.sum(out))
        ts.append((time.perf_counter() - t0) / K)
    return float(np.median(ts))


def main():
    key = jax.random.PRNGKey(0)
    kx, kg, ks, kb = jax.random.split(key, 4)
    x = jax.random.normal(kx, (N, H, W, C), jnp.bfloat16)
    g = jax.random.normal(kg, (N, H, W, C), jnp.bfloat16)
    gamma = jax.random.uniform(ks, (C,), jnp.float32, 0.5, 1.5)
    beta = jax.random.normal(kb, (C,), jnp.float32) * 0.1
    print("device:", jax.devices()[0].device_kind, flush=True)
    size_mb = N * H * W * C * 2 / 1e6
    print(f"tensor [{N},{H},{W},{C}] bf16 = {size_mb:.0f} MB", flush=True)

    def fwd_only(x, g, gamma, beta):
        return bn_relu_ref(x, gamma, beta)

    def grad_ref(x, g, gamma, beta):
        def loss(x):
            return jnp.sum((bn_relu_ref(x, gamma, beta) * g)
                           .astype(jnp.float32))
        return jax.grad(loss)(x)

    def grad_man(x, g, gamma, beta):
        def loss(x):
            return jnp.sum((bn_relu_manual(x, gamma, beta) * g)
                           .astype(jnp.float32))
        return jax.grad(loss)(x)

    progs = {
        "fwd only (xla)": loop_program(fwd_only),
        "fwd+bwd (xla autodiff)": loop_program(grad_ref),
        "fwd+bwd (manual 2-pass vjp)": loop_program(grad_man),
    }

    # parity check (single call each)
    r = jax.jit(grad_ref)(x, g, gamma, beta)
    m = jax.jit(grad_man)(x, g, gamma, beta)
    d = float(jnp.max(jnp.abs(r.astype(jnp.float32) -
                              m.astype(jnp.float32))))
    print(f"parity dx: max|diff| = {d:.3e}", flush=True)

    bw = 819e9
    base = size_mb * 1e6 / bw * 1e3
    print(f"one tensor pass at HBM peak: {base:.2f} ms", flush=True)
    results = {}
    for rnd in range(2):  # interleaved rounds
        for name, prog in progs.items():
            t = timed(prog, (x, g, gamma, beta))
            results.setdefault(name, []).append(t)
            print(f"[round {rnd}] {name}: {t*1e3:.2f} ms "
                  f"(~{t*1e3/base:.1f} passes)", flush=True)
    print("--- medians ---")
    for name, ts in results.items():
        t = float(np.median(ts)) * 1e3
        print(f"{name}: {t:.2f} ms (~{t/base:.1f} passes)")



def main2():
    """A/B the Pallas fused op vs XLA on the chip."""
    import sys
    sys.path.insert(0, ".")
    from horovod_tpu.ops import fused_bn

    key = jax.random.PRNGKey(0)
    kx, kg, ks, kb = jax.random.split(key, 4)
    x = jax.random.normal(kx, (N, H, W, C), jnp.bfloat16)
    g = jax.random.normal(kg, (N, H, W, C), jnp.bfloat16)
    gamma = jax.random.uniform(ks, (C,), jnp.float32, 0.5, 1.5)
    beta = jax.random.normal(kb, (C,), jnp.float32) * 0.1
    print("device:", jax.devices()[0].device_kind, flush=True)
    size_mb = N * H * W * C * 2 / 1e6

    def grad_ref(x, g, gamma, beta):
        def loss(x):
            return jnp.sum((bn_relu_ref(x, gamma, beta) * g)
                           .astype(jnp.float32))
        return jax.grad(loss)(x)

    def grad_fused(x, g, gamma, beta):
        def loss(x):
            y, _, _ = fused_bn.bn_act(x, gamma, beta, relu=True)
            return jnp.sum((y * g).astype(jnp.float32))
        return jax.grad(loss)(x)

    def fwd_fused(x, g, gamma, beta):
        y, _, _ = fused_bn.bn_act(x, gamma, beta, relu=True)
        return y

    def fwd_ref(x, g, gamma, beta):
        return bn_relu_ref(x, gamma, beta)

    # parity on chip
    r = jax.jit(grad_ref)(x, g, gamma, beta)
    m = jax.jit(grad_fused)(x, g, gamma, beta)
    d = float(jnp.max(jnp.abs(r.astype(jnp.float32) -
                              m.astype(jnp.float32))))
    print(f"chip parity dx: max|diff| = {d:.3e}", flush=True)

    progs = {
        "fwd xla": loop_program(fwd_ref),
        "fwd pallas": loop_program(fwd_fused),
        "fwd+bwd xla": loop_program(grad_ref),
        "fwd+bwd pallas": loop_program(grad_fused),
    }
    bw = 819e9
    base = size_mb * 1e6 / bw * 1e3
    results = {}
    for rnd in range(2):
        for name, prog in progs.items():
            t = timed(prog, (x, g, gamma, beta))
            results.setdefault(name, []).append(t)
            print(f"[round {rnd}] {name}: {t*1e3:.2f} ms "
                  f"(~{t*1e3/base:.1f} passes)", flush=True)
    print("--- medians ---")
    for name, ts in results.items():
        t = float(np.median(ts)) * 1e3
        print(f"{name}: {t:.2f} ms (~{t/base:.1f} passes)")


if __name__ == "__main__":
    import sys
    main2() if "--fused" in sys.argv else main()
