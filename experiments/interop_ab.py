"""Shim-tax A/B: DLPack zero-copy boundary vs numpy fallback.

Pushes a ResNet-50-shaped gradient set (~170 tensors, ~24M params,
~90 MB fp32) through the torch shim's async allreduce path — the
DistributedOptimizer hook flow — with HOROVOD_TPU_DLPACK toggled
in-process (interop reads the env per call), interleaved rounds.

Isolation: on a multi-device mesh the fused collective itself costs
1.5-5 s/step (measured) and drowns a ~90 MB boundary copy, so the
default arm runs a 1-DEVICE CPU mesh where allreduce over one rank is
near-identity and step time ≈ the shim boundary cost — the tax the
VERDICT item names. AB_DEVICES=8 measures the end-to-end (diluted)
ratio instead.

  JAX_PLATFORMS=cpu python experiments/interop_ab.py            # tax
  AB_DEVICES=8 JAX_PLATFORMS=cpu python experiments/interop_ab.py

Prints one JSON line with both modes' step rates and the ratio.
"""
import json
import os
import sys
import time

sys.path.insert(0, ".")

if __name__ == "__main__":
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count="
        + os.environ.get("AB_DEVICES", "1"))
    if os.environ.get("JAX_PLATFORMS"):
        # The axon sitecustomize re-forces JAX_PLATFORMS=axon; config
        # update (the conftest trick) is what actually sticks.
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np  # noqa: E402
import torch  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
import horovod_tpu.torch as hvd_torch  # noqa: E402
from horovod_tpu.utils import interop  # noqa: E402

ITERS = int(os.environ.get("AB_ITERS", 30))
WARMUP = int(os.environ.get("AB_WARMUP", 5))
ROUNDS = int(os.environ.get("AB_ROUNDS", 3))

# ResNet-50 parameter-shape histogram (conv kernels + BN pairs + fc),
# close enough for boundary-cost purposes: dominated by a few large
# tensors with a long tail of small ones, 25.5M params total.
SHAPES = (
    [(2048, 512, 1, 1)] * 3 + [(512, 2048, 1, 1)] * 3
    + [(512, 512, 3, 3)] * 3 + [(1024, 256, 1, 1)] * 6
    + [(256, 1024, 1, 1)] * 6 + [(256, 256, 3, 3)] * 6
    + [(512, 128, 1, 1)] * 4 + [(128, 512, 1, 1)] * 4
    + [(128, 128, 3, 3)] * 4 + [(256, 64, 1, 1)] * 3
    + [(64, 256, 1, 1)] * 3 + [(64, 64, 3, 3)] * 3
    + [(1000, 2048)] + [(64, 3, 7, 7)]
    + [(512,)] * 30 + [(256,)] * 30 + [(1024,)] * 20 + [(2048,)] * 10
    + [(128,)] * 20 + (lambda: [(64,)] * 10)()
)


def step(grads):
    handles = [hvd_torch.allreduce_async_(g, average=True,
                                          name=f"ab.grad.{i}")
               for i, g in enumerate(grads)]
    for h in handles:
        hvd_torch.synchronize(h)


def run_mode(dlpack_on: bool, grads) -> float:
    os.environ["HOROVOD_TPU_DLPACK"] = "1" if dlpack_on else "0"
    for _ in range(WARMUP):
        step(grads)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        step(grads)
    dt = time.perf_counter() - t0
    return ITERS / dt


def main():
    hvd.init()
    grads = [torch.randn(*s, dtype=torch.float32) for s in SHAPES]
    nbytes = sum(g.numel() * 4 for g in grads)
    print(f"# {len(grads)} tensors, {nbytes/2**20:.1f} MiB/step, "
          f"size={hvd.size()}", file=sys.stderr)

    on, off = [], []
    for r in range(ROUNDS):
        off.append(run_mode(False, grads))
        on.append(run_mode(True, grads))
    interop.reset_stats()
    os.environ["HOROVOD_TPU_DLPACK"] = "1"
    step(grads)
    s = interop.stats()

    on_m, off_m = float(np.median(on)), float(np.median(off))
    print(json.dumps({
        "metric": "interop_dlpack_speedup",
        "value": round(on_m / off_m, 4),
        "unit": "dlpack/numpy step-rate ratio",
        "dlpack_steps_per_s": round(on_m, 3),
        "numpy_steps_per_s": round(off_m, 3),
        "mb_per_step": round(nbytes / 2**20, 1),
        "rounds_on": [round(x, 3) for x in on],
        "rounds_off": [round(x, 3) for x in off],
        "fastpath_stats_one_step": s,
        "platform": __import__("jax").default_backend(),
    }))


if __name__ == "__main__":
    main()
