"""Sweep flash block sizes for fwd and fwd+bwd separately (calibrated
against the per-call tunnel overhead). Decides the compiled defaults."""
import time, sys
import jax, jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from horovod_tpu.ops.flash_attention import flash_attention

PEAK = 197e12
K = 100
_tunnel = None


def tunnel_overhead():
    global _tunnel
    if _tunnel is None:
        x = jnp.zeros((8, 128), jnp.float32)

        @jax.jit
        def empty(c):
            return jax.lax.fori_loop(0, K, lambda _, y: y + 1.0, c)

        for _ in range(3):
            x = empty(x)
        float(jnp.sum(x))
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            x = empty(x)
            float(jnp.sum(x))
            ts.append(time.perf_counter() - t0)
        _tunnel = float(np.median(ts))
        print(f"tunnel overhead per call: {_tunnel*1e3:.1f} ms")
    return _tunnel


def timed(fn, carry, flops):
    for _ in range(3):
        carry = fn(carry)
    float(jnp.sum(carry[0][0, 0, 0].astype(jnp.float32)))
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        carry = fn(carry)
        float(jnp.sum(carry[0][0, 0, 0].astype(jnp.float32)))
        dt = time.perf_counter() - t0 - tunnel_overhead()
        rates.append(flops * K / dt)
    return float(np.median(rates))


def main():
    B, H, D = 8, 16, 128
    for S in (2048, 8192):
        key = jax.random.PRNGKey(0)
        q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                     (B, S, H, D), jnp.bfloat16)
                   for i in range(3))
        f_fwd = 4 * B * H * S * S * D / 2
        f_bwd = 2.5 * f_fwd
        for (bq, bk) in [(512, 512), (1024, 512), (512, 1024),
                         (1024, 1024), (2048, 1024)]:
            if bq > S or bk > S:
                continue
            try:
                @jax.jit
                def fwd_k(c, bq=bq, bk=bk):
                    def body(_, c):
                        q, k, v = c
                        o = flash_attention(q, k, v, True, None, bq, bk)
                        return (o, k, v)
                    return jax.lax.fori_loop(0, K, body, c)

                r_f = timed(fwd_k, (q, k, v), f_fwd)
                msg = f"S={S} b({bq},{bk}): fwd {r_f/PEAK*100:.1f}%"
            except Exception as e:
                print(f"S={S} b({bq},{bk}): fwd FAIL {str(e)[:100]}")
                continue
            try:
                def loss(q, k, v, bq=bq, bk=bk):
                    return jnp.sum(
                        flash_attention(q, k, v, True, None, bq, bk)
                        .astype(jnp.float32))

                @jax.jit
                def fb_k(c, loss=loss):
                    def body(_, c):
                        q, k, v = c
                        dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(
                            q, k, v)
                        eps = jnp.bfloat16(1e-4)
                        return (q + eps * dq, k + eps * dk, v + eps * dv)
                    return jax.lax.fori_loop(0, K, body, c)

                r_fb = timed(fb_k, (q, k, v), f_fwd + f_bwd)
                msg += f"  fwd+bwd {r_fb/PEAK*100:.1f}%"
            except Exception as e:
                msg += f"  bwd FAIL {str(e)[:100]}"
            print(msg, flush=True)


if __name__ == "__main__":
    main()
