"""Ablate the flash forward kernel to locate the cost center.

Variants at fixed shapes:
  full      — the real fwd kernel (via flash_attention fwd-only)
  matmul    — same grid/blockspecs, but body is just the two matmuls
              (s = qk^T, acc += s_bf16 @ v): isolates MXU + HBM streaming
  nosoft    — matmuls + running accumulator scale, no exp/max/sum
  stream    — body only reads blocks and writes acc (no matmul): HBM only
Sweeps: causal on/off, block sizes, batch scaling (fixed-overhead test).
"""
import time, sys, functools
import jax, jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, "/root/repo")
from horovod_tpu.ops.flash_attention import flash_attention

PEAK = 197e12
K = 20


def variant_kernel(q_ref, k_ref, v_ref, o_ref, acc_sc, *, mode, causal,
                   block_q, block_k, n_k):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)

    def _update():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        if mode == "stream":
            acc_sc[:] += q.astype(jnp.float32) + k.astype(jnp.float32) \
                + v.astype(jnp.float32)
            return
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if mode == "matmul":
            p = s.astype(v.dtype)
            acc_sc[:] += jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        elif mode == "nosoft":
            m = s.max(axis=-1)
            p = (s - m[:, None]).astype(v.dtype)
            acc_sc[:] = acc_sc[:] * 0.5 + jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    if causal:
        @pl.when(kj * block_k <= (qi + 1) * block_q - 1)
        def _():
            _update()
    else:
        _update()

    @pl.when(kj == n_k - 1)
    def _fin():
        o_ref[0] = acc_sc[:].astype(o_ref.dtype)


def run_variant(q, k, v, mode, causal, bq, bk):
    bh, s, d = q.shape
    n_q = pl.cdiv(s, bq)
    n_k = pl.cdiv(s, bk)
    kern = functools.partial(variant_kernel, mode=mode, causal=causal,
                             block_q=bq, block_k=bk, n_k=n_k)
    call = pl.pallas_call(
        kern,
        grid=(bh, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
    )

    @jax.jit
    def chain(c):
        def body(_, c):
            q, k, v = c
            o = call(q, k, v)
            return (o, k, v)
        return jax.lax.fori_loop(0, K, body, c)

    c = (q, k, v)
    for _ in range(3):
        c = chain(c)
    float(jnp.sum(c[0][0, 0].astype(jnp.float32)))
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        c = chain(c)
        float(jnp.sum(c[0][0, 0].astype(jnp.float32)))
        ts.append((time.perf_counter() - t0) / K)
    return float(np.median(ts))


def run_full(q4, causal, bq, bk):
    @jax.jit
    def chain(c):
        def body(_, c):
            q, k, v = c
            o = flash_attention(q, k, v, causal, None, bq, bk)
            return (o, k, v)
        return jax.lax.fori_loop(0, K, body, c)

    c = (q4, q4, q4)
    for _ in range(3):
        c = chain(c)
    float(jnp.sum(c[0][0, 0, 0].astype(jnp.float32)))
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        c = chain(c)
        float(jnp.sum(c[0][0, 0, 0].astype(jnp.float32)))
        ts.append((time.perf_counter() - t0) / K)
    return float(np.median(ts))


def main():
    D = 128
    for (B, H, S) in [(8, 16, 2048), (8, 16, 8192), (16, 16, 2048)]:
        bh = B * H
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (bh, S, D), jnp.bfloat16)
        q4 = jax.random.normal(key, (B, S, H, D), jnp.bfloat16)
        f_causal = 4 * bh * S * S * D / 2

        for causal in (True, False):
            f = f_causal if causal else 2 * f_causal
            rows = []
            for mode in ("stream", "matmul", "nosoft"):
                t = run_variant(q, q, q, mode, causal, 512, 512)
                rows.append(f"{mode} {t*1e3:.2f}ms ({f/t/PEAK*100:.0f}%)")
            t = run_full(q4, causal, 512, 512)
            rows.append(f"full {t*1e3:.2f}ms ({f/t/PEAK*100:.0f}%)")
            print(f"B{B} S{S} causal={int(causal)} b512: "
                  + "  ".join(rows))

        # block sweep, causal, full kernel
        for (bq, bk) in [(1024, 512), (1024, 1024), (2048, 512)]:
            try:
                t = run_full(q4, True, bq, bk)
                print(f"B{B} S{S} causal=1 b({bq},{bk}): full "
                      f"{t*1e3:.2f}ms ({f_causal/t/PEAK*100:.0f}%)")
            except Exception as e:
                print(f"B{B} S{S} b({bq},{bk}): FAIL "
                      f"{type(e).__name__}: {str(e)[:120]}")


if __name__ == "__main__":
    main()
